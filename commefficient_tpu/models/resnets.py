"""The generic ResNet family (resnet18 ... wide_resnet101_2), as the
reference forked it from torchvision for EMNIST (models/resnets.py):

- the stem conv takes **1 input channel** (28x28 grayscale EMNIST;
  reference resnets.py:155-156),
- every norm site can be **LayerNorm** instead of BatchNorm
  (``norm="layer"``; reference resnets.py:79-97, 157-161 hardcodes
  per-site (C, hw, hw) shapes — here flax resolves the normalized
  shape from the activation, so any input size works),
- ``ResNet101LN`` = resnet101 + LayerNorm + 62 classes (reference
  resnet101ln.py:7-13).

TPU notes: NHWC; LayerNorm normalizes over (H, W, C) with elementwise
affine over the same axes, matching torch ``LayerNorm((C, hw, hw))``.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp

from commefficient_tpu.models import register_model
from commefficient_tpu.models.norms import BatchStatNorm

_he = nn.initializers.he_normal()


def _norm(kind: str):
    if kind == "batch":
        # stateless batch-stat norm (see models/norms.py docstring)
        return BatchStatNorm
    if kind == "layer":
        return partial(nn.LayerNorm, reduction_axes=(-3, -2, -1),
                       feature_axes=(-3, -2, -1))
    raise ValueError(f"unknown norm {kind!r}")


class BasicBlock(nn.Module):
    """reference resnets.py:34-73."""
    planes: int
    norm: str = "batch"
    stride: int = 1
    expansion: int = 1

    @nn.compact
    def __call__(self, x):
        norm = _norm(self.norm)
        out = nn.Conv(self.planes, (3, 3), strides=(self.stride,) * 2,
                      padding=1, use_bias=False, kernel_init=_he)(x)
        out = nn.relu(norm()(out))
        out = nn.Conv(self.planes, (3, 3), padding=1, use_bias=False,
                      kernel_init=_he)(out)
        out = norm()(out)
        if self.stride != 1 or x.shape[-1] != self.planes:
            x = norm()(nn.Conv(self.planes, (1, 1),
                               strides=(self.stride,) * 2,
                               use_bias=False, kernel_init=_he)(x))
        return nn.relu(out + x)


class Bottleneck(nn.Module):
    """reference resnets.py:76-130."""
    planes: int
    norm: str = "batch"
    stride: int = 1
    base_width: int = 64
    groups: int = 1
    expansion: int = 4

    @nn.compact
    def __call__(self, x):
        norm = _norm(self.norm)
        width = int(self.planes * (self.base_width / 64.0)) * self.groups
        out_ch = self.planes * self.expansion
        out = nn.Conv(width, (1, 1), use_bias=False, kernel_init=_he)(x)
        out = nn.relu(norm()(out))
        out = nn.Conv(width, (3, 3), strides=(self.stride,) * 2,
                      padding=1, use_bias=False, kernel_init=_he,
                      feature_group_count=self.groups)(out)
        out = nn.relu(norm()(out))
        out = nn.Conv(out_ch, (1, 1), use_bias=False, kernel_init=_he)(out)
        out = norm()(out)
        if self.stride != 1 or x.shape[-1] != out_ch:
            x = norm()(nn.Conv(out_ch, (1, 1),
                               strides=(self.stride,) * 2,
                               use_bias=False, kernel_init=_he)(x))
        return nn.relu(out + x)


class ResNet(nn.Module):
    """reference resnets.py:133-237 (1-channel 7x7/2 stem, 3x3/2
    max-pool, four stages, global avg-pool, fc)."""
    block: Any  # BasicBlock or Bottleneck class
    layers: Sequence[int]
    num_classes: int = 1000
    norm: str = "batch"
    width_per_group: int = 64
    groups: int = 1

    @nn.compact
    def __call__(self, x, train: bool = True):
        norm = _norm(self.norm)
        x = nn.Conv(64, (7, 7), strides=(2, 2), padding=3,
                    use_bias=False, kernel_init=_he)(x)
        x = nn.relu(norm()(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2),
                        padding=((1, 1), (1, 1)))
        planes = 64
        for stage, n_blocks in enumerate(self.layers):
            stride = 1 if stage == 0 else 2
            for b in range(n_blocks):
                kw = {}
                if self.block is Bottleneck:
                    kw["base_width"] = self.width_per_group
                    kw["groups"] = self.groups
                x = self.block(planes, self.norm,
                               stride if b == 0 else 1, **kw)(x)
            planes *= 2
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, kernel_init=_he)(x)


def _factory(layers, block, **preset) -> Callable[..., ResNet]:
    def make(**kwargs):
        merged = {**preset, **kwargs}
        return ResNet(block=block, layers=layers, **merged)
    return make


# reference resnets.py:249-370 factory surface; registered so every
# family member is a valid --model choice (the reference discovers
# them by reflection over its models package, utils.py:114-118)
resnet18 = register_model("resnet18")(_factory([2, 2, 2, 2], BasicBlock))
resnet34 = register_model("resnet34")(_factory([3, 4, 6, 3], BasicBlock))
resnet50 = register_model("resnet50")(_factory([3, 4, 6, 3], Bottleneck))
resnet101 = register_model("resnet101")(
    _factory([3, 4, 23, 3], Bottleneck))
resnet152 = register_model("resnet152")(
    _factory([3, 8, 36, 3], Bottleneck))
resnext50_32x4d = register_model("resnext50_32x4d")(
    _factory([3, 4, 6, 3], Bottleneck, groups=32, width_per_group=4))
resnext101_32x8d = register_model("resnext101_32x8d")(
    _factory([3, 4, 23, 3], Bottleneck, groups=32, width_per_group=8))
wide_resnet50_2 = register_model("wide_resnet50_2")(
    _factory([3, 4, 6, 3], Bottleneck, width_per_group=128))
wide_resnet101_2 = register_model("wide_resnet101_2")(
    _factory([3, 4, 23, 3], Bottleneck, width_per_group=128))


def ResNet101LN(num_classes: int = 62, **kwargs) -> ResNet:
    """resnet101 with LayerNorm, 62 classes = EMNIST byclass
    (reference resnet101ln.py:7-13)."""
    return resnet101(num_classes=num_classes, norm="layer", **kwargs)


register_model("ResNet101LN")(ResNet101LN)
