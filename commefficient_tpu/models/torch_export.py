"""Torch-format ``state_dict`` export for the CV model families.

The reference's final CV artifact is ``torch.save(model.state_dict(),
checkpoint_path + model + '.pt')`` (reference cv_train.py:420-423),
with the key names of its torch modules (models/resnet9.py,
fixup_resnet9.py, fixup_resnet18.py, resnets.py). This module maps
each flax model family onto exactly those names so the saved file is
consumable by the torch ecosystem the reference lives in:

- conv kernels  (kh, kw, cin, cout) -> (cout, cin, kh, kw)
- dense kernels (in, out)           -> (out, in)
- LayerNorm over (H, W, C)          -> torch ``LayerNorm((C, h, w))``
  affine layout (C, h, w)
- BatchStatNorm scale/bias          -> ``bn.weight``/``bn.bias``, with
  the server's running stats (``batch_stats`` collection) as
  ``bn.running_mean``/``bn.running_var`` (+ ``num_batches_tracked``,
  torch's bookkeeping scalar)
- fixup scalars keep their reference names (``bias1a`` ...); the
  ResNet18 family wraps them in ``Add``/``Mul`` submodules, so they
  export as ``addXx.bias`` / ``mul.scale`` (reference
  fixup_resnet18.py:8-21)

The same name map drives the inverse (``load_state_dict``), used to
round-trip-test losslessness without torchvision in the image.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

__all__ = ["cv_state_dict", "cv_load_state_dict", "build_name_map",
           "supports_torch_export", "save_torch_state_dict"]

# leaf-tensor layout transforms, keyed by tag; (export, import) pairs
_TRANSFORMS = {
    "conv": (lambda a: np.transpose(a, (3, 2, 0, 1)),
             lambda a: np.transpose(a, (2, 3, 1, 0))),
    "dense": (lambda a: np.transpose(a),
              lambda a: np.transpose(a)),
    "ln": (lambda a: np.transpose(a, (2, 0, 1)),
           lambda a: np.transpose(a, (1, 2, 0))),
    "id": (lambda a: a, lambda a: a),
}


def _leaf(torch_prefix: str, seg: str, leaf: str):
    """(torch_name, transform_tag) for one flax leaf under a module
    segment like Conv_0 / Dense_0 / BatchStatNorm_0 / LayerNorm_0."""
    if seg.startswith("Conv_"):
        assert leaf == "kernel", leaf
        return f"{torch_prefix}.weight", "conv"
    if seg.startswith("Dense_"):
        return (f"{torch_prefix}.weight", "dense") \
            if leaf == "kernel" else (f"{torch_prefix}.bias", "id")
    if seg.startswith("BatchStatNorm_"):
        name = {"scale": "weight", "bias": "bias",
                "mean": "running_mean", "var": "running_var"}[leaf]
        return f"{torch_prefix}.{name}", "id"
    if seg.startswith("LayerNorm_"):
        name = {"scale": "weight", "bias": "bias"}[leaf]
        return f"{torch_prefix}.{name}", "ln"
    raise KeyError(f"unmapped module segment {seg!r}")


def _walk(tree, rename: Dict[str, Any], prefix: str, out, path=()):
    """Recursive renamer: ``rename`` maps flax child segment ->
    (torch segment, child rename map | None). A None child map means
    the segment is a primitive flax module handled by ``_leaf``;
    scalar fixup params appear as direct leaves and pass through a
    '' mapping or their own (name, "leaf") entries."""
    for seg, sub in tree.items():
        if not isinstance(sub, dict):
            # scalar fixup param leaf at this level (renamed when the
            # reference wraps it in an Add/Mul submodule)
            t = rename[seg][0] if seg in rename else seg
            tname = f"{prefix}.{t}" if prefix else t
            out[tname] = (path + (seg,), "id")
            continue
        if seg not in rename:
            raise KeyError(f"unmapped segment {seg!r} under "
                           f"{prefix or '<root>'!r}")
        tseg, child = rename[seg]
        tprefix = f"{prefix}.{tseg}" if prefix else tseg
        if child is None:
            for leaf in sub:
                tname, tag = _leaf(tprefix, seg, leaf)
                out[tname] = (path + (seg, leaf), tag)
        else:
            _walk(sub, child, tprefix, out, path + (seg,))


# --- family rename tables (reference module attribute names) ---------

_CONVBN = {"Conv_0": ("conv", None), "BatchStatNorm_0": ("bn", None)}
_RESIDUAL9 = {"ConvBN_0": ("res1", _CONVBN),
              "ConvBN_1": ("res2", _CONVBN)}
# reference resnet9.py:74-124: the net lives under the ``n`` attribute
_RESNET9 = {
    "ConvBN_0": ("n.prep", _CONVBN),
    "ConvBN_1": ("n.layer1", _CONVBN),
    "Residual_0": ("n.res1", _RESIDUAL9),
    "ConvBN_2": ("n.layer2", _CONVBN),
    "ConvBN_3": ("n.layer3", _CONVBN),
    "Residual_1": ("n.res3", _RESIDUAL9),
    "Dense_0": ("n.linear", None),
}

# reference fixup_resnet9.py:10-56 (+ the fixup submodule's cifar
# FixupBasicBlock naming: conv1/conv2 + bias/scale scalars)
_FIXUP_BLOCK9 = {"Conv_0": ("conv1", None), "Conv_1": ("conv2", None)}
_FIXUP_LAYER9 = {"Conv_0": ("conv", None)}
for _i in range(4):
    _FIXUP_LAYER9[f"FixupBasicBlock_{_i}"] = (f"blocks.{_i}",
                                              _FIXUP_BLOCK9)
_FIXUPRESNET9 = {
    "Conv_0": ("conv1", None),
    "FixupLayer_0": ("layer1", _FIXUP_LAYER9),
    "FixupLayer_1": ("layer2", _FIXUP_LAYER9),
    "FixupLayer_2": ("layer3", _FIXUP_LAYER9),
    "Dense_0": ("linear", None),
}

# reference fixup_resnet18.py:24-63, 66-133: a flat ``layers``
# Sequential over all blocks; scalars live in Add/Mul submodules.
# FixupBlock's map is built per block in build_name_map — flax creates
# the shortcut conv BEFORE conv1 when present (models/resnet18.py:
# 67-69), so the Conv_i labels shift per block.

_PREACT_BLOCK = {"Conv_0": ("conv1", None),
                 "BatchStatNorm_0": ("bn1", None),
                 "Conv_1": ("conv2", None),
                 "BatchStatNorm_1": ("bn2", None),
                 "Conv_2": ("shortcut.0", None)}

# reference resnets.py (torchvision fork) block naming
_BASIC_BLOCK = {"Conv_0": ("conv1", None), "Conv_1": ("conv2", None),
                "Conv_2": ("downsample.0", None)}
_BOTTLENECK = {"Conv_0": ("conv1", None), "Conv_1": ("conv2", None),
               "Conv_2": ("conv3", None),
               "Conv_3": ("downsample.0", None)}


def _with_norms(base: Dict, n_norms: int, norm_seg: str,
                names) -> Dict:
    d = dict(base)
    for i in range(n_norms):
        d[f"{norm_seg}_{i}"] = (names[i], None)
    return d


def _stage_layout(stage_sizes) -> Dict[int, str]:
    """Flat block index -> ``layer{stage}.{i}`` (torch Sequential)."""
    out, idx = {}, 0
    for s, n in enumerate(stage_sizes):
        for b in range(n):
            out[idx] = f"layer{s + 1}.{b}"
            idx += 1
    return out


def supports_torch_export(module) -> bool:
    return type(module).__name__ in ("ResNet9", "FixupResNet9",
                                     "FixupResNet50", "ResNet18",
                                     "FixupResNet18", "ResNet")


def build_name_map(module, params,
                   model_state: Optional[dict] = None
                   ) -> Dict[str, Tuple[Tuple[str, ...], str, str]]:
    """torch_name -> (flax_path, transform_tag, collection). The map
    is derived from the actual param tree (block/downsample presence
    varies with geometry), so it is exact for the instance exported."""
    fam = type(module).__name__
    out: Dict[str, Tuple[Tuple[str, ...], str]] = {}

    def walk(rename):
        _walk(params, rename, "", out)

    if fam == "ResNet9":
        walk(_RESNET9)
    elif fam == "FixupResNet9":
        walk(_FIXUPRESNET9)
    elif fam == "FixupResNet50":
        layout = _stage_layout(module.stage_sizes)
        fb = {"Conv_0": ("conv1", None), "Conv_1": ("conv2", None),
              "Conv_2": ("conv3", None), "Conv_3": ("downsample", None)}
        rename = {"Conv_0": ("conv1", None), "Dense_0": ("fc", None)}
        for i, tseg in layout.items():
            rename[f"FixupBottleneck_{i}"] = (tseg, fb)
        walk(rename)
    elif fam in ("ResNet18", "FixupResNet18"):
        n_blocks = sum(module.num_blocks)
        rename = {"Conv_0": ("prep" if fam == "FixupResNet18"
                             else "prep.0", None),
                  "Dense_0": ("classifier", None)}
        for i in range(n_blocks):
            if fam == "ResNet18":
                rename[f"PreActBlock_{i}"] = (f"layers.{i}",
                                              _PREACT_BLOCK)
            else:
                # flax created the shortcut conv FIRST when present
                # (models/resnet18.py:67-75): relabel per block
                blk = params.get(f"FixupBlock_{i}", {})
                has_sc = "Conv_2" in blk
                m = {("Conv_0" if not has_sc else "Conv_1"):
                     ("conv1", None),
                     ("Conv_1" if not has_sc else "Conv_2"):
                     ("conv2", None)}
                if has_sc:
                    m["Conv_0"] = ("shortcut", None)
                for s, t in (("add1a", "add1a.bias"),
                             ("add1b", "add1b.bias"),
                             ("add2a", "add2a.bias"),
                             ("add2b", "add2b.bias"),
                             ("mul", "mul.scale")):
                    m[s] = (t, "leaf")
                rename[f"FixupBlock_{i}"] = (f"layers.{i}", m)
        walk(rename)
    elif fam == "ResNet":
        layout = _stage_layout(module.layers)
        norm_seg = ("BatchStatNorm" if module.norm == "batch"
                    else "LayerNorm")
        rename = {"Conv_0": ("conv1", None),
                  f"{norm_seg}_0": ("bn1", None),
                  "Dense_0": ("fc", None)}
        from commefficient_tpu.models.resnets import Bottleneck
        bottleneck = module.block is Bottleneck
        for i, tseg in layout.items():
            bseg = ("Bottleneck" if bottleneck else "BasicBlock") \
                + f"_{i}"
            blk = params.get(bseg, {})
            n_convs = sum(1 for s in blk if s.startswith("Conv_"))
            base = dict(_BOTTLENECK if bottleneck else _BASIC_BLOCK)
            norm_names = (["bn1", "bn2", "bn3", "downsample.1"]
                          if bottleneck
                          else ["bn1", "bn2", "downsample.1"])
            bmap = _with_norms(base, n_convs, norm_seg, norm_names)
            rename[bseg] = (tseg, bmap)
        walk(rename)
    else:
        raise ValueError(
            f"torch-format export is not defined for {fam}; "
            "families: ResNet9/Fixup*/ResNet18/ResNet (use "
            "hf_format for GPT-2)")

    full = {name: (path, tag, "params") for name, (path, tag)
            in out.items()}
    if model_state:
        stats: Dict[str, Tuple[Tuple[str, ...], str]] = {}
        # reuse the same rename walk on the batch_stats tree: its
        # paths are a sub-tree of the params paths (norm sites only)
        def visit(tree, path=()):
            for seg, sub in tree.items():
                if isinstance(sub, dict):
                    visit(sub, path + (seg,))
                else:
                    stats[path + (seg,)] = sub
        visit(model_state)
        # invert the params map at the norm-module level to place
        # running stats beside their scale/bias
        prefix_of = {}
        for name, (path, tag) in out.items():
            if path[-1] in ("scale", "bias") \
                    and path[-2].startswith("BatchStatNorm_"):
                prefix_of[path[:-1]] = name.rsplit(".", 1)[0]
        for spath in stats:
            mod_path, leaf = spath[:-1], spath[-1]
            if mod_path in prefix_of:
                tname = {"mean": "running_mean",
                         "var": "running_var"}[leaf]
                full[f"{prefix_of[mod_path]}.{tname}"] = (
                    spath, "id", "batch_stats")
    return full


def _get(tree, path):
    for seg in path:
        tree = tree[seg]
    return tree


def cv_state_dict(module, params,
                  model_state: Optional[dict] = None) -> Dict[str, Any]:
    """Flax params (+ optional running stats) -> reference-named torch
    ``state_dict`` of numpy arrays (callers torch.save after
    torch.from_numpy; kept numpy here so the mapping is testable
    without torch)."""
    nm = build_name_map(module, params, model_state)
    sd = {}
    bn_sites = {}  # torch prefix -> channel count
    for tname, (path, tag, coll) in nm.items():
        src = params if coll == "params" else model_state
        arr = _TRANSFORMS[tag][0](np.asarray(_get(src, path)))
        sd[tname] = arr
        if len(path) >= 2 and path[-1] == "scale" \
                and path[-2].startswith("BatchStatNorm_"):
            bn_sites[tname.rsplit(".", 1)[0]] = arr.shape[0]
    for p, c in bn_sites.items():
        # torch nn.BatchNorm2d always carries running buffers; a
        # batch-stats-only site (track_stats=False) exports identity
        # stats so the file strict-loads into the reference module
        sd.setdefault(f"{p}.running_mean", np.zeros((c,), np.float32))
        sd.setdefault(f"{p}.running_var", np.ones((c,), np.float32))
        sd[f"{p}.num_batches_tracked"] = np.asarray(0, np.int64)
    return sd


def save_torch_state_dict(module, params, model_state, path: str):
    """``torch.save`` the reference-named state_dict to ``path`` — the
    one shared recipe behind FedModel.save_pretrained(torch_format)
    and cv_train's ``--checkpoint`` artifact (reference
    cv_train.py:420-423)."""
    import jax
    import torch

    sd = cv_state_dict(
        module, jax.tree_util.tree_map(np.asarray, params),
        jax.tree_util.tree_map(np.asarray, model_state)
        if model_state else None)
    torch.save({k: torch.from_numpy(np.array(v, copy=True))
                for k, v in sd.items()}, path)


def cv_load_state_dict(module, params, sd,
                       model_state: Optional[dict] = None):
    """Inverse mapping: a reference-named state_dict back into a flax
    params pytree (+ running stats if ``model_state`` given) — proves
    the export lossless and gives the reference's torch checkpoints a
    way IN, not just out."""
    import jax

    nm = build_name_map(module, params, model_state)
    new_params = jax.tree_util.tree_map(np.asarray, params)
    new_state = (jax.tree_util.tree_map(np.asarray, model_state)
                 if model_state else None)

    def set_(tree, path, val):
        for seg in path[:-1]:
            tree = tree[seg]
        old = tree[path[-1]]
        assert old.shape == val.shape, (path, old.shape, val.shape)
        tree[path[-1]] = val.astype(old.dtype)

    for tname, (path, tag, coll) in nm.items():
        arr = _TRANSFORMS[tag][1](np.asarray(sd[tname]))
        set_(new_params if coll == "params" else new_state, path, arr)
    return (new_params, new_state) if model_state else new_params
