"""Model registry.

The reference discovers model classes by reflection over the models
package (utils.py:114-118: every public CamelCase name). Here models
register explicitly; ``model_names()`` feeds the ``--model`` choices.
"""

from __future__ import annotations

_REGISTRY = {}


def register_model(name: str):
    def deco(cls):
        _REGISTRY[name] = cls
        return cls
    return deco


def get_model(name: str):
    _ensure_loaded()
    return _REGISTRY[name]


def model_names():
    _ensure_loaded()
    return sorted(_REGISTRY)


_loaded = False


def _ensure_loaded():
    global _loaded
    if _loaded:
        return
    _loaded = True
    # import for registration side effects; keep lazy so `ops`-only
    # users never pay for flax imports
    import importlib
    import importlib.util
    for mod in ("resnet9", "fixup_resnet9", "resnet18", "resnets", "gpt2"):
        name = f"commefficient_tpu.models.{mod}"
        # skip modules not yet written, but let real import errors
        # inside existing ones propagate
        if importlib.util.find_spec(name) is not None:
            importlib.import_module(name)
