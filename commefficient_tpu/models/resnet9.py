"""ResNet9 — the cifar10_fast-style 9-layer ResNet (default CV model).

Flax re-design of reference models/resnet9.py:32-159: ConvBN blocks
(3x3 conv, optional BatchNorm, ReLU, optional 2x2 max-pool), two
residual blocks, a bias-free linear head scaled by 0.125 (``Mul``).

TPU notes:
- NHWC layout (XLA's native conv layout on TPU).
- BatchNorm ("--batchnorm") trains on current-batch statistics
  (masked to real rows — padded ragged-client rows are excluded, as
  the reference's dynamically-sized torch batches naturally are) and
  records them into a ``batch_stats`` collection; the server blends
  participating clients' stats into one running-stats state and eval
  normalizes with it (``train=False``), so eval metrics don't depend
  on eval batch composition — running-stats parity with the
  reference's torch BN (models/resnet9.py:32-59). The BN-free default
  path is identical to the reference's default (do_batchnorm=False,
  utils.py:138).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import flax.linen as nn
import jax.numpy as jnp

from commefficient_tpu.models import register_model
from commefficient_tpu.models.norms import BatchStatNorm

_conv_init = nn.initializers.he_normal()


class ConvBN(nn.Module):
    """(reference resnet9.py:32-50)"""
    c_out: int
    do_batchnorm: bool = False
    pool: bool = False
    bn_weight_init: float = 1.0
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True, mask=None):
        x = nn.Conv(self.c_out, (3, 3), padding=1, use_bias=False,
                    kernel_init=_conv_init, dtype=self.dtype)(x)
        if self.do_batchnorm:
            # train: current batch statistics (recorded raw into the
            # batch_stats collection; the server blends them into its
            # running stats). eval: the server's running stats, so
            # metrics don't depend on eval batch composition — the
            # running-stats parity mode for the reference's torch BN
            # eval (models/resnet9.py:32-59).
            x = BatchStatNorm(
                scale_init=self.bn_weight_init,
                use_running_average=not train,
                track_stats=True,
            )(x, mask)
        x = nn.relu(x)
        if self.pool:
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        return x


class Residual(nn.Module):
    """x + relu(ConvBN(ConvBN(x))) (reference resnet9.py:61-68)"""
    c: int
    do_batchnorm: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True, mask=None):
        y = ConvBN(self.c, self.do_batchnorm,
                   dtype=self.dtype)(x, train, mask)
        y = ConvBN(self.c, self.do_batchnorm,
                   dtype=self.dtype)(y, train, mask)
        return x + nn.relu(y)


@register_model("ResNet9")
class ResNet9(nn.Module):
    """(reference resnet9.py:74-159; channel plan at 147-148)"""
    num_classes: int = 10
    do_batchnorm: bool = False
    initial_channels: int = 3
    channels: Optional[Dict[str, int]] = None
    weight: float = 0.125
    # computation dtype (params stay float32): bfloat16 feeds the MXU
    # at full rate — the TPU analogue of cifar10_fast's fp16 training
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True, mask=None):
        ch = self.channels or {"prep": 64, "layer1": 128,
                               "layer2": 256, "layer3": 512}
        x = x.astype(self.dtype)
        x = ConvBN(ch["prep"], self.do_batchnorm,
                   dtype=self.dtype)(x, train, mask)
        x = ConvBN(ch["layer1"], self.do_batchnorm, pool=True,
                   dtype=self.dtype)(x, train, mask)
        x = Residual(ch["layer1"], self.do_batchnorm,
                     dtype=self.dtype)(x, train, mask)
        x = ConvBN(ch["layer2"], self.do_batchnorm, pool=True,
                   dtype=self.dtype)(x, train, mask)
        x = ConvBN(ch["layer3"], self.do_batchnorm, pool=True,
                   dtype=self.dtype)(x, train, mask)
        x = Residual(ch["layer3"], self.do_batchnorm,
                     dtype=self.dtype)(x, train, mask)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(self.num_classes, use_bias=False,
                     kernel_init=_conv_init, dtype=self.dtype)(x)
        return (x * self.weight).astype(jnp.float32)

    @staticmethod
    def test_config(num_classes: int = 10) -> Dict[str, Any]:
        """--test shrink: 1 channel per layer (cv_train.py:329-336)."""
        return dict(channels={"prep": 1, "layer1": 1,
                              "layer2": 1, "layer3": 1},
                    num_classes=num_classes)
