"""GPT-2 with double heads (LM + multiple-choice), in flax.

The reference imports ``GPT2DoubleHeadsModel`` from pytorch_transformers
(gpt2_train.py:4-6, 262-273); here the transformer is in-tree and
TPU-shaped:

- causal attention via a single fused qkv projection feeding
  ``jax.nn.dot_product_attention`` (lowered to a fused TPU kernel);
- weight-tied LM head (logits = h @ wte.T), like GPT-2;
- MC head: take the hidden state at ``mc_token_ids`` per candidate,
  project to a scalar (the pytorch_transformers SequenceSummary with
  cls_index behavior);
- all shapes static; works under vmap over federated clients.

Double-heads batch layout (matching the reference collate,
fed_persona.py:360-392): input_ids / token_type_ids / lm_labels are
(B, num_candidates, T), mc_token_ids (B, num_candidates),
mc_labels (B,).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from commefficient_tpu.compat import axis_size
from commefficient_tpu.models import register_model


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    n_positions: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    layer_norm_epsilon: float = 1e-5
    initializer_range: float = 0.02
    # computation dtype (params stay float32); bfloat16 runs the MXU
    # at full rate. LayerNorm statistics and logits stay float32.
    dtype: Any = jnp.float32
    # Sequence/context parallelism (a capability the reference lacks,
    # SURVEY.md §2.8): set to a mesh axis name and call the model
    # inside shard_map with input_ids sharded on T over that axis.
    # Attention runs as ring attention ("ring") or all-to-all Ulysses
    # ("ulysses", needs n_head % axis_size == 0); position embeddings
    # and the MC-head gather become global-position aware. Hidden
    # states / LM logits stay sequence-sharded inside the model — use
    # an out_spec partitioned on T to reassemble, or keep them sharded
    # for a distributed loss.
    seq_axis: Optional[str] = None
    seq_impl: str = "ring"
    # single-chip attention lowering: "xla" = jax.nn.dot_product_
    # attention (XLA fusion), "flash" = the Pallas TPU flash-attention
    # kernel (jax.experimental.pallas.ops.tpu.flash_attention) — the
    # model-side kernel experiment; measured head-to-head in
    # BENCHMARKS.md (scripts/gpt2_bench.py --attn_impl)
    attn_impl: str = "xla"
    # rematerialise each transformer block's activations in the
    # backward pass (jax.checkpoint): peak activation memory drops
    # from O(n_layer * B * T * n_embd) to O(B * T * n_embd) + one
    # block's internals, at ~1/3 extra FLOPs — the standard lever for
    # long-context training on HBM-bound chips
    remat: bool = False

    @staticmethod
    def tiny() -> "GPT2Config":
        """Test-scale config (the moral equivalent of --test's model
        shrink, cv_train.py:329-336)."""
        return GPT2Config(vocab_size=256, n_positions=64, n_embd=32,
                          n_layer=2, n_head=2)


def _dense_init(cfg):
    return nn.initializers.normal(stddev=cfg.initializer_range)


class MLP(nn.Module):
    cfg: GPT2Config

    @nn.compact
    def __call__(self, x):
        h = nn.Dense(4 * self.cfg.n_embd, dtype=self.cfg.dtype,
                     kernel_init=_dense_init(self.cfg), name="c_fc")(x)
        h = jax.nn.gelu(h, approximate=True)
        return nn.Dense(self.cfg.n_embd, dtype=self.cfg.dtype,
                        kernel_init=_dense_init(self.cfg),
                        name="c_proj")(h)


class CausalSelfAttention(nn.Module):
    cfg: GPT2Config

    @nn.compact
    def __call__(self, x, attn_mask=None):
        B, T, C = x.shape
        H = self.cfg.n_head
        qkv = nn.Dense(3 * C, dtype=self.cfg.dtype,
                       kernel_init=_dense_init(self.cfg),
                       name="c_attn")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, T, H, C // H)
        k = k.reshape(B, T, H, C // H)
        v = v.reshape(B, T, H, C // H)
        if self.cfg.seq_axis is not None:
            from commefficient_tpu.parallel.ring_attention import (
                ring_attention, ulysses_attention)
            attn = (ring_attention if self.cfg.seq_impl == "ring"
                    else ulysses_attention)
            out = attn(q, k, v, self.cfg.seq_axis, causal=True)
        elif self.cfg.attn_impl == "flash" and T % 128 == 0:
            # T % 128 != 0 (shape-probe inits, odd batch tails) falls
            # through to the XLA path: the flash BACKWARD kernel tiles
            # by block // 128 and traces to a broadcasting error at
            # unaligned T (reproduced at T=8/64/200 on jax 0.9.0) —
            # and at short T the XLA lowering wins anyway
            # (BENCHMARKS.md flash table)
            from jax.experimental.pallas.ops.tpu.flash_attention import (
                BlockSizes, flash_attention)
            # kernel layout is (B, H, T, hd); scale explicitly — the
            # kernel's default sm_scale is 1.0, XLA's is hd^-0.5.
            # Block size must DIVIDE the sequence, not just bound it
            # (T=768 with block 512 raises in the kernel); the T % 128
            # guard above guarantees a divisor exists in this list
            b = next(x for x in (512, 256, 128) if T % x == 0)
            blocks = BlockSizes(
                block_q=b, block_k_major=b, block_k=b, block_b=1,
                block_q_major_dkv=b, block_k_major_dkv=b,
                block_k_dkv=b, block_q_dkv=b,
                block_k_major_dq=b, block_k_dq=b, block_q_dq=b)
            out = flash_attention(
                q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
                v.transpose(0, 2, 1, 3), causal=True,
                sm_scale=float((C // H) ** -0.5),
                block_sizes=blocks)
            out = out.transpose(0, 2, 1, 3)
        else:
            out = jax.nn.dot_product_attention(q, k, v, is_causal=True)
        out = out.reshape(B, T, C)
        return nn.Dense(C, dtype=self.cfg.dtype,
                        kernel_init=_dense_init(self.cfg),
                        name="c_proj")(out)


class Block(nn.Module):
    cfg: GPT2Config

    @nn.compact
    def __call__(self, x):
        eps = self.cfg.layer_norm_epsilon
        x = x + CausalSelfAttention(self.cfg, name="attn")(
            nn.LayerNorm(epsilon=eps, name="ln_1")(x)
            .astype(self.cfg.dtype))
        x = x + MLP(self.cfg, name="mlp")(
            nn.LayerNorm(epsilon=eps, name="ln_2")(x)
            .astype(self.cfg.dtype))
        return x


class GPT2Transformer(nn.Module):
    cfg: GPT2Config

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None):
        cfg = self.cfg
        B, T = input_ids.shape
        wte = self.param("wte", _dense_init(cfg),
                         (cfg.vocab_size, cfg.n_embd))
        wpe = self.param("wpe", _dense_init(cfg),
                         (cfg.n_positions, cfg.n_embd))
        pos = jnp.arange(T)
        if cfg.seq_axis is not None:
            # T here is the local shard; offset to global positions
            pos = pos + jax.lax.axis_index(cfg.seq_axis) * T
        h = wte[input_ids] + wpe[pos][None]
        if token_type_ids is not None:
            # token types index the same embedding table, GPT-2 style
            h = h + wte[token_type_ids]
        block_cls = nn.remat(Block) if cfg.remat else Block
        for i in range(cfg.n_layer):
            h = block_cls(cfg, name=f"h_{i}")(h)
        h = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, name="ln_f")(h)
        return h, wte


@register_model("GPT2DoubleHeads")
class GPT2DoubleHeads(nn.Module):
    """LM logits + per-candidate MC logits.

    ``return_hidden=True`` skips the LM head matmul and returns the
    final hidden states + tied embedding instead of lm_logits — the
    training loss then computes the LM cross-entropy in token chunks
    (``lm_nll_sums_chunked``) so the (tokens, vocab) logits tensor is
    never materialised (f32 it is ~6.6 GB at 65k tokens; its
    store/reload chain dominated the large-batch profile)."""
    cfg: GPT2Config = GPT2Config()

    @nn.compact
    def __call__(self, input_ids, mc_token_ids, token_type_ids=None,
                 return_hidden=False):
        # flatten candidates into the batch axis
        B, N, T = input_ids.shape
        flat_ids = input_ids.reshape(B * N, T)
        flat_tt = (token_type_ids.reshape(B * N, T)
                   if token_type_ids is not None else None)
        h, wte = GPT2Transformer(self.cfg, name="transformer")(
            flat_ids, flat_tt)
        flat_h = h
        if not return_hidden:
            # tied weights; logits accumulate in float32
            lm_logits = jnp.einsum("btc,vc->btv",
                                   h.astype(self.cfg.dtype),
                                   wte.astype(self.cfg.dtype),
                                   preferred_element_type=jnp.float32)
            lm_logits = lm_logits.reshape(B, N, T, -1)

        h = h.reshape(B, N, T, -1)
        if self.cfg.seq_axis is not None:
            # mc_token_ids are GLOBAL positions; the owning shard
            # contributes its hidden state, psum broadcasts it
            ax = self.cfg.seq_axis
            n_shards = axis_size(ax)
            gpos = jax.lax.axis_index(ax) * T + jnp.arange(T)
            idx = jnp.clip(mc_token_ids, 0, n_shards * T - 1)
            sel = (gpos[None, None, :] == idx[..., None]).astype(h.dtype)
            cls_h = jax.lax.psum(
                jnp.einsum("bnt,bntc->bnc", sel, h), ax)
        else:
            idx = jnp.clip(mc_token_ids, 0, T - 1)
            cls_h = jnp.take_along_axis(
                h, idx[..., None, None], axis=2)[:, :, 0]  # (B, N, C)
        mc_logits = nn.Dense(1, kernel_init=_dense_init(self.cfg),
                             name="mc_head")(cls_h)[..., 0]  # (B, N)
        if return_hidden:
            return flat_h, wte, mc_logits
        return lm_logits, mc_logits


def token_nll(logits, labels, ignore_index=-100):
    """(..., T, V) logits + (..., T) labels -> ((..., T) f32 NLL,
    (..., T) f32 validity). Logsumexp formulation: the (..., T, V)
    log-softmax tensor is never materialised (at GPT-2 vocab size that
    buffer is ~800 MB f32 per training round, and a per-example vmap
    of it lowers to a serial scan — measured 10x the loss cost)."""
    valid = labels != ignore_index
    safe = jnp.where(valid, labels, 0)
    lse = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
    tok = jnp.take_along_axis(logits, safe[..., None],
                              axis=-1)[..., 0].astype(jnp.float32)
    return lse - tok, valid.astype(jnp.float32)


def lm_nll_sums_chunked(h, wte, labels, dtype, ignore_index=-100,
                        tokens_per_chunk=1024):
    """Per-example (Σ nll, Σ valid) of the tied-head LM cross-entropy
    without materialising the (E, T, V) logits tensor.

    ``h`` (E, Tm, C) are the final hidden states at the *predicting*
    positions (callers pass ``h[:, :-1]``), ``labels`` (E, Tm) the
    shifted targets. A ``lax.scan`` over token chunks computes each
    chunk's logits, logsumexp and label gather in one compiler-fused
    region; ``jax.checkpoint`` makes the backward recompute the chunk
    logits instead of storing them, so peak logits memory is one chunk
    (~200 MB f32 at 1024 tokens x 50k vocab) and the fwd+bwd HBM
    traffic of the vocab head drops by the full-logits store/reload
    chain. Same math as ``token_nll`` of the full logits (fp summation
    order aside)."""
    E, Tm, C = h.shape
    tc = max(1, min(Tm, tokens_per_chunk // max(E, 1)))
    num_chunks = -(-Tm // tc)
    pad = num_chunks * tc - Tm
    # cast ONCE before chunking (the transformer's final hidden may be
    # f32 out of the last LayerNorm) and slice inside the scan rather
    # than pre-transposing to a (chunks, E, tc, C) copy — the copy
    # measured ~15 ms at 65k tokens
    hp = jnp.pad(h.astype(dtype), ((0, 0), (0, pad), (0, 0)))
    lp = jnp.pad(labels, ((0, 0), (0, pad)),
                 constant_values=ignore_index)
    wte_c = wte.astype(dtype)  # cast once, outside the scan

    @jax.checkpoint
    def chunk_sums(hc, lc, w):
        logits = jnp.einsum("etc,vc->etv", hc, w,
                            preferred_element_type=jnp.float32)
        nll, valid = token_nll(logits, lc, ignore_index)
        return jnp.sum(nll * valid, -1), jnp.sum(valid, -1)

    def body(carry, i):
        sn, sv = carry
        hc = jax.lax.dynamic_slice_in_dim(hp, i * tc, tc, axis=1)
        lc = jax.lax.dynamic_slice_in_dim(lp, i * tc, tc, axis=1)
        n, v = chunk_sums(hc, lc, wte_c)
        return (sn + n, sv + v), None

    # the zero init is derived from the inputs (x*0 sums) rather than
    # jnp.zeros so that under shard_map it carries the same varying
    # mesh axes as the body's output — a plain-zeros carry trips the
    # scan carry-type check when this runs on a sequence shard
    init = (jnp.sum(hp[:, :, 0] * 0.0, axis=1, dtype=jnp.float32),
            jnp.sum(lp * 0, axis=1).astype(jnp.float32))
    (sn, sv), _ = jax.lax.scan(body, init,
                               jnp.arange(num_chunks, dtype=jnp.int32))
    return sn, sv


def gpt2_double_heads_loss(lm_logits, mc_logits, lm_labels, mc_labels,
                           lm_coef=1.0, mc_coef=1.0,
                           ignore_index=-100):
    """Training loss (reference gpt2_train.py:88-99): lm_coef*CE(LM,
    shifted) + mc_coef*CE(MC). Returns (loss, lm_loss, mc_loss), each
    a scalar mean over valid positions / examples."""
    # shift: predict token t+1 from position t
    nll, valid = token_nll(lm_logits[..., :-1, :], lm_labels[..., 1:],
                           ignore_index)
    lm_loss = jnp.sum(nll * valid) / jnp.maximum(jnp.sum(valid), 1)

    mc_nll, _ = token_nll(mc_logits[..., None, :],
                          mc_labels[..., None], ignore_index)
    mc_loss = jnp.mean(mc_nll[..., 0])
    return lm_coef * lm_loss + mc_coef * mc_loss, lm_loss, mc_loss


def convert_gpt2_to_hf(params, cfg: GPT2Config):
    """Inverse of ``convert_torch_gpt2``: emit an HF-`transformers`
    GPT2DoubleHeadsModel state dict (numpy values) + HF config dict
    from this module's params pytree — so a model fine-tuned here can
    be handed back to the torch/HF ecosystem, matching the reference's
    ``save_pretrained`` contract (fed_aggregator.py:209-212,
    gpt2_train.py:146).

    Layout notes: HF GPT2 Conv1D stores (in, out) — identical to flax
    Dense kernels, no transpose; LayerNorm ``weight`` = flax ``scale``;
    the MC head maps to ``multiple_choice_head.summary`` (a torch
    Linear, (out, in) — transposed); ``lm_head.weight`` is the tied
    ``wte`` (HF re-ties on load, included for completeness)."""
    import numpy as np

    def a(x):
        return np.asarray(x)

    t = params["transformer"]
    sd = {
        "transformer.wte.weight": a(t["wte"]),
        "transformer.wpe.weight": a(t["wpe"]),
        "transformer.ln_f.weight": a(t["ln_f"]["scale"]),
        "transformer.ln_f.bias": a(t["ln_f"]["bias"]),
        "lm_head.weight": a(t["wte"]),
    }
    for i in range(cfg.n_layer):
        b = t[f"h_{i}"]
        pre = f"transformer.h.{i}."
        sd[pre + "ln_1.weight"] = a(b["ln_1"]["scale"])
        sd[pre + "ln_1.bias"] = a(b["ln_1"]["bias"])
        sd[pre + "attn.c_attn.weight"] = a(b["attn"]["c_attn"]["kernel"])
        sd[pre + "attn.c_attn.bias"] = a(b["attn"]["c_attn"]["bias"])
        sd[pre + "attn.c_proj.weight"] = a(b["attn"]["c_proj"]["kernel"])
        sd[pre + "attn.c_proj.bias"] = a(b["attn"]["c_proj"]["bias"])
        sd[pre + "ln_2.weight"] = a(b["ln_2"]["scale"])
        sd[pre + "ln_2.bias"] = a(b["ln_2"]["bias"])
        sd[pre + "mlp.c_fc.weight"] = a(b["mlp"]["c_fc"]["kernel"])
        sd[pre + "mlp.c_fc.bias"] = a(b["mlp"]["c_fc"]["bias"])
        sd[pre + "mlp.c_proj.weight"] = a(b["mlp"]["c_proj"]["kernel"])
        sd[pre + "mlp.c_proj.bias"] = a(b["mlp"]["c_proj"]["bias"])
    if "mc_head" in params:
        sd["multiple_choice_head.summary.weight"] = \
            a(params["mc_head"]["kernel"]).T
        sd["multiple_choice_head.summary.bias"] = \
            a(params["mc_head"]["bias"])

    # HF GPT2Config field names coincide with GPT2Config's for every
    # architectural field; the extras below make the dir loadable by
    # transformers.from_pretrained. num_labels=1 gives the DoubleHeads
    # summary head its (1, n_embd) projection.
    hf_config = {
        "model_type": "gpt2",
        "architectures": ["GPT2DoubleHeadsModel"],
        "vocab_size": cfg.vocab_size,
        "n_positions": cfg.n_positions,
        "n_ctx": cfg.n_positions,
        "n_embd": cfg.n_embd,
        "n_layer": cfg.n_layer,
        "n_head": cfg.n_head,
        "layer_norm_epsilon": cfg.layer_norm_epsilon,
        "initializer_range": cfg.initializer_range,
        "activation_function": "gelu_new",
        "summary_type": "cls_index",
        "summary_use_proj": True,
        "summary_proj_to_labels": True,
        "summary_first_dropout": 0.0,
        "num_labels": 1,
    }
    return sd, hf_config


def convert_torch_gpt2(state_dict, cfg: GPT2Config):
    """Convert a (pytorch_)transformers GPT2 state dict into this
    module's params pytree, including the Conv1D (transposed linear)
    layout and resized embeddings for added special tokens
    (gpt2_train.py:101-112). Accepts a dict of numpy arrays."""
    import numpy as np

    def a(name):
        # hub checkpoints for the bare "gpt2" model store keys without
        # the "transformer." base-model prefix; re-saved
        # GPT2LMHeadModel/DoubleHeads dicts include it — accept both
        if name in state_dict:
            return np.asarray(state_dict[name])
        return np.asarray(state_dict[name.removeprefix("transformer.")])

    p = {"transformer": {}}
    t = p["transformer"]
    wte = a("transformer.wte.weight")
    if wte.shape[0] < cfg.vocab_size:
        # new special-token rows: mean-init like HF resize
        extra = np.tile(wte.mean(0, keepdims=True),
                        (cfg.vocab_size - wte.shape[0], 1))
        wte = np.concatenate([wte, extra], 0)
    t["wte"] = wte
    t["wpe"] = a("transformer.wpe.weight")
    for i in range(cfg.n_layer):
        pre = f"transformer.h.{i}."
        # HF GPT2 Conv1D stores (in, out) — same as flax Dense kernels
        t[f"h_{i}"] = {
            "ln_1": {"scale": a(pre + "ln_1.weight"),
                     "bias": a(pre + "ln_1.bias")},
            "attn": {
                "c_attn": {"kernel": a(pre + "attn.c_attn.weight"),
                           "bias": a(pre + "attn.c_attn.bias")},
                "c_proj": {"kernel": a(pre + "attn.c_proj.weight"),
                           "bias": a(pre + "attn.c_proj.bias")},
            },
            "ln_2": {"scale": a(pre + "ln_2.weight"),
                     "bias": a(pre + "ln_2.bias")},
            "mlp": {
                "c_fc": {"kernel": a(pre + "mlp.c_fc.weight"),
                         "bias": a(pre + "mlp.c_fc.bias")},
                "c_proj": {"kernel": a(pre + "mlp.c_proj.weight"),
                           "bias": a(pre + "mlp.c_proj.bias")},
            },
        }
    t["ln_f"] = {"scale": a("transformer.ln_f.weight"),
                 "bias": a("transformer.ln_f.bias")}
    rng = np.random.RandomState(0)
    p["mc_head"] = {
        "kernel": rng.normal(0, cfg.initializer_range,
                             (cfg.n_embd, 1)).astype(np.float32),
        "bias": np.zeros((1,), np.float32),
    }
    return p
