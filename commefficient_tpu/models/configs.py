"""Per-model training configs — a WORKING version of the reference's
models/configs.py (which is bit-rotted: it uses PiecewiseLinear
without importing it and is never wired into the trainers,
SURVEY.md §2.6).

``ModelConfig.set_args(args)`` overlays recommended hyperparameters
onto a parsed Config, but only for fields the user left at their CLI
defaults — explicit flags always win. ``lr_schedule(epoch)`` (when a
config defines one) replaces the default triangular schedule in
cv_train.
"""

from __future__ import annotations

from typing import Optional

from commefficient_tpu.utils import PiecewiseLinear


class ModelConfig:
    #: fields overlaid onto args (name -> value)
    overrides: dict = {}
    #: epoch -> multiplier SHAPE with peak 1.0; the effective LR is
    #: args.lr_scale * shape(epoch), so an explicit --lr_scale always
    #: takes effect. None = keep the triangular default schedule.
    lr_schedule_shape: Optional[PiecewiseLinear] = None

    def set_args(self, args, parser_defaults: dict):
        """Overlay recommended values onto fields still at their
        parser defaults. (The reference unconditionally clobbered user
        flags; note argparse cannot distinguish an omitted flag from
        one explicitly passed at its default value — those are
        overlaid too.)"""
        applied = {}
        for name, val in self.overrides.items():
            if getattr(args, name) == parser_defaults.get(name,
                                                          object()):
                setattr(args, name, val)
                applied[name] = val
        return applied


class FixupResNet50Config(ModelConfig):
    """ImageNet FixupResNet50 step schedule (reference
    configs.py:9-16): peak lr_scale 0.1 decayed 10x at epochs
    30/60/90 (shape below x lr_scale)."""
    overrides = {"lr_scale": 0.1, "weight_decay": 1e-4,
                 "num_epochs": 100.0}
    lr_schedule_shape = PiecewiseLinear(
        [0, 30, 30, 60, 60, 90, 90, 100],
        [1.0, 1.0, 0.1, 0.1, 0.01, 0.01, 0.001, 0.001])


MODEL_CONFIGS = {
    "FixupResNet50": FixupResNet50Config,
}


def get_model_config(model_name: str) -> Optional[ModelConfig]:
    cls = MODEL_CONFIGS.get(model_name)
    return cls() if cls is not None else None
