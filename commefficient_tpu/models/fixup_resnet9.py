"""Fixup-initialized BN-free ResNets: FixupResNet9 (CIFAR) and
FixupResNet50 (ImageNet).

The reference imports Fixup blocks from an external ``fixup`` git
submodule (reference models/fixup_resnet9.py:6, fixup_resnet.py:4;
.gitmodules:1-3); here the blocks are in-tree. Fixup (Zhang et al.,
ICLR'19) removes normalization entirely: residual-branch convs are
rescaled at init (first conv std x L^{-1/(2m-2)}, last conv zero) and
scalar bias/scale parameters are inserted around each conv. BN-free
models are the better fit for federated simulation — no batch
statistics to mix across clients (SURVEY.md §2.6).

TPU notes: NHWC; scalar bias/scale params broadcast for free on VPU;
all-conv + matmul graph maps cleanly onto the MXU.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from commefficient_tpu.models import register_model


def _scalars(module, dtype, *names):
    """Declare scalar fixup params (f32 storage; multiplicative
    scale/mul params init to one, additive biases to zero) and return
    them cast to the compute dtype — adding a raw f32 scalar to a bf16
    tensor would silently promote the activation back to f32."""
    return tuple(
        module.param(n,
                     nn.initializers.ones
                     if n.startswith(("scale", "mul"))
                     else nn.initializers.zeros,
                     (1,)).astype(dtype)
        for n in names)


def _fixup_conv_init(scale: float = 1.0):
    """He-style normal init, std = scale * sqrt(2 / (k*k*c_out)).

    Matches the reference's fan measure ``shape[0] * prod(shape[2:])``
    (out_channels * kernel area; reference fixup_resnet9.py:59-78) on
    flax's (kh, kw, c_in, c_out) kernel layout.
    """
    def init(key, shape, dtype=jnp.float32):
        import jax
        fan = shape[-1] * int(np.prod(shape[:-2]))
        std = scale * np.sqrt(2.0 / fan)
        return (std * jax.random.normal(key, shape)).astype(dtype)
    return init


def _conv3x3(c_out, stride=1, init_scale=1.0, dtype=jnp.float32):
    return nn.Conv(c_out, (3, 3), strides=(stride, stride), padding=1,
                   use_bias=False, dtype=dtype,
                   kernel_init=_fixup_conv_init(init_scale))


def _conv1x1(c_out, stride=1, init_scale=1.0, dtype=jnp.float32):
    return nn.Conv(c_out, (1, 1), strides=(stride, stride), padding=0,
                   use_bias=False, dtype=dtype,
                   kernel_init=_fixup_conv_init(init_scale))


class FixupBasicBlock(nn.Module):
    """Two-conv fixup residual block (the submodule's
    fixup_resnet_cifar.FixupBasicBlock, used at reference
    fixup_resnet9.py:19-22): conv1 std scaled by num_layers^-0.5,
    conv2 zero-init; scalar biases around convs, scale after conv2."""
    c_out: int
    num_layers: int  # total residual blocks in the network (for init)
    stride: int = 1
    downsample: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        # scalar params stay f32 but are applied in the compute dtype,
        # else f32 + bf16 promotion silently undoes --bf16
        sp = _scalars(self, self.dtype,
                      "bias1a", "bias1b", "bias2a", "bias2b", "scale")
        b1a, b1b, b2a, b2b, scale = sp

        out = _conv3x3(self.c_out, self.stride,
                       self.num_layers ** -0.5, self.dtype)(x + b1a)
        out = nn.relu(out + b1b)
        out = _conv3x3(self.c_out, 1, 0.0,
                       self.dtype)(out + b2a)  # zero-init
        out = out * scale + b2b
        if self.downsample:
            identity = nn.avg_pool(x + b1a, (1, 1),
                                   strides=(self.stride, self.stride))
            identity = jnp.concatenate([identity,
                                        jnp.zeros_like(identity)], -1)
        else:
            identity = x
        return nn.relu(out + identity)


class FixupLayer(nn.Module):
    """conv, bias, relu, pool, then num_blocks FixupBasicBlocks
    (reference fixup_resnet9.py:10-31)."""
    c_out: int
    num_blocks: int
    net_num_layers: int
    pool: bool = True
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        b1a, b1b, scale = _scalars(self, self.dtype,
                                   "bias1a", "bias1b", "scale")
        x = _conv3x3(self.c_out, dtype=self.dtype)(x + b1a) \
            * scale + b1b
        x = nn.relu(x)
        if self.pool:
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        for _ in range(self.num_blocks):
            x = FixupBasicBlock(self.c_out,
                                num_layers=self.net_num_layers,
                                dtype=self.dtype)(x)
        return x


@register_model("FixupResNet9")
class FixupResNet9(nn.Module):
    """BN-free ResNet9 (reference fixup_resnet9.py:33-91): prep conv,
    three FixupLayers (1/0/1 residual blocks), 4x4 max-pool, zero-init
    linear head with a scalar pre-bias."""
    num_classes: int = 10
    channels: Optional[Dict[str, int]] = None
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        ch = self.channels or {"prep": 64, "layer1": 128,
                               "layer2": 256, "layer3": 512}
        num_layers = 2  # reference fixup_resnet9.py:36
        b1a, b1b, scale = _scalars(self, self.dtype,
                                   "bias1a", "bias1b", "scale")
        x = x.astype(self.dtype)
        out = _conv3x3(ch["prep"], dtype=self.dtype)(x + b1a) \
            * scale + b1b
        out = nn.relu(out)
        out = FixupLayer(ch["layer1"], 1, num_layers,
                         dtype=self.dtype)(out)
        out = FixupLayer(ch["layer2"], 0, num_layers,
                         dtype=self.dtype)(out)
        out = FixupLayer(ch["layer3"], 1, num_layers,
                         dtype=self.dtype)(out)
        out = nn.max_pool(out, (4, 4), strides=(4, 4))
        out = out.reshape((out.shape[0], -1))
        (b2,) = _scalars(self, self.dtype, "bias2")
        out = nn.Dense(self.num_classes, dtype=self.dtype,
                       kernel_init=nn.initializers.zeros,
                       bias_init=nn.initializers.zeros)(out + b2)
        return out.astype(jnp.float32)

    @staticmethod
    def test_config(num_classes: int = 10) -> Dict[str, Any]:
        return dict(channels={"prep": 1, "layer1": 1,
                              "layer2": 1, "layer3": 1},
                    num_classes=num_classes)


class FixupBottleneck(nn.Module):
    """Three-conv fixup bottleneck (the submodule's
    fixup_resnet_imagenet.FixupBottleneck, used via reference
    fixup_resnet.py:4-10): conv1/conv2 std scaled by
    num_layers^-0.25, conv3 zero-init; projection shortcut is a
    1x1 conv on (x + bias1a)."""
    planes: int
    num_layers: int
    stride: int = 1
    project: bool = False
    expansion: int = 4
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        sp = _scalars(self, self.dtype, "bias1a", "bias1b", "bias2a",
                      "bias2b", "bias3a", "bias3b", "scale")
        b1a, b1b, b2a, b2b, b3a, b3b, scale = sp

        s = self.num_layers ** -0.25
        out = _conv1x1(self.planes, 1, s, self.dtype)(x + b1a)
        out = nn.relu(out + b1b)
        out = _conv3x3(self.planes, self.stride, s,
                       self.dtype)(out + b2a)
        out = nn.relu(out + b2b)
        out = _conv1x1(self.planes * self.expansion, 1, 0.0,
                       self.dtype)(out + b3a)
        out = out * scale + b3b
        if self.project:
            identity = _conv1x1(self.planes * self.expansion,
                                self.stride,
                                dtype=self.dtype)(x + b1a)
        else:
            identity = x
        return nn.relu(out + identity)


@register_model("FixupResNet50")
class FixupResNet50(nn.Module):
    """Fixup ImageNet ResNet-50 (reference fixup_resnet.py:8-10:
    FixupResNet(FixupBottleneck, [3,4,6,3])): 7x7/2 stem with scalar
    bias+scale, 3x3/2 max-pool, four stages, global avg-pool,
    zero-init fc. Used by imagenet.sh (SURVEY.md §6)."""
    num_classes: int = 1000
    stage_sizes: Sequence[int] = (3, 4, 6, 3)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        L = sum(self.stage_sizes)
        b1, b2 = _scalars(self, self.dtype, "bias1", "bias2")
        x = x.astype(self.dtype)
        x = nn.Conv(64, (7, 7), strides=(2, 2), padding=3,
                    use_bias=False, dtype=self.dtype,
                    kernel_init=_fixup_conv_init())(x)
        x = nn.relu(x + b1)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1),
                                                            (1, 1)))
        planes = 64
        in_ch = 64
        for stage, n_blocks in enumerate(self.stage_sizes):
            stride = 1 if stage == 0 else 2
            for b in range(n_blocks):
                x = FixupBottleneck(
                    planes, num_layers=L,
                    stride=stride if b == 0 else 1,
                    project=(b == 0 and
                             (stride != 1 or in_ch != planes * 4)),
                    dtype=self.dtype)(x)
                in_ch = planes * 4
            planes *= 2
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     kernel_init=nn.initializers.zeros,
                     bias_init=nn.initializers.zeros)(x + b2)
        return x.astype(jnp.float32)
