"""Batch-statistics normalization, with optional federated running
statistics.

The reference's torch BatchNorm keeps running averages per worker
process that never federate and diverge per-worker (SURVEY.md §7
"BatchNorm under client-vmap"). Two TPU-native forms live here:

- default (``track_stats=False``): normalize by the current batch
  statistics in train AND eval, with no mutable state — every model
  stays a pure function of (params, x), exactly what vmap-over-clients
  and the flat-param-vector runtime (ops/vec.py) assume.
- ``track_stats=True`` (ResNet9 ``--batchnorm``): additionally record
  the raw batch mean/var in a flax ``batch_stats`` collection each
  train-mode application. The *server* blends participating clients'
  round-averaged statistics into one canonical running-stats state
  (runtime/fed_model.py), which eval reads via
  ``use_running_average=True`` — so eval metrics are independent of
  the eval batch composition, like the reference's
  ``nn.BatchNorm2d`` eval (models/resnet9.py:32-59), but with a
  single well-defined server state instead of per-worker drift.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


class BatchStatNorm(nn.Module):
    """Per-channel normalization over (N, H, W) with learned scale and
    bias. ``use_running_average`` reads the ``batch_stats`` collection
    instead of computing batch statistics; ``track_stats`` records the
    raw batch statistics (no client-side momentum — the server applies
    the running-average blend, see module docstring)."""
    epsilon: float = 1e-5
    scale_init: float = 1.0
    use_running_average: bool = False
    track_stats: bool = False

    @nn.compact
    def __call__(self, x, mask=None):
        """``mask``: optional (N,) row-validity weights. Padded rows
        (static-shape ragged client batches, SURVEY.md §7) must not
        enter the statistics — the reference's BN only ever sees real
        samples because torch batches are dynamically sized."""
        c = x.shape[-1]
        scale = self.param("scale",
                           nn.initializers.constant(self.scale_init),
                           (c,))
        bias = self.param("bias", nn.initializers.zeros, (c,))
        if self.track_stats:
            ra_mean = self.variable("batch_stats", "mean",
                                    lambda: jnp.zeros((c,), jnp.float32))
            ra_var = self.variable("batch_stats", "var",
                                   lambda: jnp.ones((c,), jnp.float32))
        if self.use_running_average:
            assert self.track_stats, \
                "use_running_average needs track_stats"
            mean, var = ra_mean.value, ra_var.value
        elif mask is not None:
            # statistics reduce in float32 regardless of compute
            # dtype (an 8-bit-mantissa sum over N*H*W elements per
            # channel would corrupt them, and they feed the server's
            # running stats)
            xf = x.astype(jnp.float32)
            w = mask.reshape((-1,) + (1,) * (x.ndim - 1)) \
                .astype(jnp.float32)
            denom = jnp.maximum(
                jnp.sum(w) * float(np.prod(x.shape[1:-1])), 1.0)
            axes = tuple(range(x.ndim - 1))
            mean = jnp.sum(xf * w, axis=axes) / denom
            var = jnp.sum(jnp.square(xf - mean) * w,
                          axis=axes) / denom
            if self.track_stats and not self.is_initializing():
                ra_mean.value = mean
                # recorded (not normalizing) variance gets the Bessel
                # n/(n-1) correction: torch BatchNorm2d normalizes with
                # the biased estimate but feeds the UNBIASED one into
                # running_var, and the server's blend must match that
                ra_var.value = var * (denom / jnp.maximum(
                    denom - 1.0, 1.0))
        else:
            axes = tuple(range(x.ndim - 1))
            xf = x.astype(jnp.float32)
            mean = jnp.mean(xf, axis=axes)
            var = jnp.var(xf, axis=axes)
            if self.track_stats and not self.is_initializing():
                n = float(np.prod(x.shape[:-1]))
                ra_mean.value = mean
                ra_var.value = var * (n / max(n - 1.0, 1.0))
        inv = (scale * jax.lax.rsqrt(var + self.epsilon)).astype(x.dtype)
        return x * inv + (bias - mean * inv).astype(x.dtype)
