"""Stateless batch-statistics normalization.

The reference's torch BatchNorm keeps running averages per worker
process that never federate (SURVEY.md §7 "BatchNorm under
client-vmap"); the well-defined TPU-native equivalent normalizes by
the current batch statistics in train AND eval, with no mutable state.
Being stateless keeps every model a pure function of (params, x) —
exactly what vmap-over-clients and the flat-param-vector runtime
(ops/vec.py) assume.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp


class BatchStatNorm(nn.Module):
    """Per-channel normalization by current batch mean/variance over
    (N, H, W), with learned scale and bias. No running averages."""
    epsilon: float = 1e-5
    scale_init: float = 1.0

    @nn.compact
    def __call__(self, x):
        c = x.shape[-1]
        scale = self.param("scale",
                           nn.initializers.constant(self.scale_init),
                           (c,))
        bias = self.param("bias", nn.initializers.zeros, (c,))
        axes = tuple(range(x.ndim - 1))
        mean = jnp.mean(x, axis=axes)
        var = jnp.var(x, axis=axes)
        inv = scale * jax.lax.rsqrt(var + self.epsilon)
        return x * inv + (bias - mean * inv)
