"""Self-contained CIFAR ResNet18s: post-act BN variant and a BN-free
Fixup variant (reference models/fixup_resnet18.py:66-216).

Both share the reference's slightly unusual topology: a 3x3 prep conv
(no norm), four stages with channel plan 64/128/256/256 and strides
1/2/2/2, and a head that concatenates global **avg and max** pooling
(so the classifier input is 2x256 = 512; reference
fixup_resnet18.py:125-133, 206-214).

TPU notes: NHWC; BatchNorm uses batch statistics in train and eval for
the same federated reasons as ResNet9 (models/resnet9.py docstring).
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from typing import Any

from commefficient_tpu.models import register_model
from commefficient_tpu.models.fixup_resnet9 import (_conv1x1, _conv3x3,
                                                    _fixup_conv_init,
                                                    _scalars)
from commefficient_tpu.models.norms import BatchStatNorm

_he = nn.initializers.he_normal()


class PreActBlock(nn.Module):
    """reference fixup_resnet18.py:138-165 — despite the name the
    as-shipped code is post-activation: relu(bn(conv(x))) twice, plus
    an un-normalized 1x1 projection shortcut when shape changes."""
    c_out: int
    stride: int = 1

    @nn.compact
    def __call__(self, x):
        out = nn.Conv(self.c_out, (3, 3), strides=(self.stride,) * 2,
                      padding=1, use_bias=False, kernel_init=_he)(x)
        out = nn.relu(BatchStatNorm()(out))
        out = nn.Conv(self.c_out, (3, 3), padding=1, use_bias=False,
                      kernel_init=_he)(out)
        out = nn.relu(BatchStatNorm()(out))
        if self.stride != 1 or x.shape[-1] != self.c_out:
            x = nn.Conv(self.c_out, (1, 1), strides=(self.stride,) * 2,
                        use_bias=False, kernel_init=_he)(x)
        return out + x


class FixupBlock(nn.Module):
    """reference fixup_resnet18.py:24-63: scalar Adds around each conv,
    scalar Mul after conv2 (conv2 zero-init, conv1 std x L^-0.5), 1x1
    projection shortcut, relu(out + shortcut)."""
    c_out: int
    num_layers: int
    stride: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        a1a, a1b, a2a, a2b, mul = _scalars(
            self, self.dtype, "add1a", "add1b", "add2a", "add2b",
            "mul")
        if self.stride != 1 or x.shape[-1] != self.c_out:
            shortcut = _conv1x1(self.c_out, self.stride,
                                dtype=self.dtype)(x)
        else:
            shortcut = x
        out = _conv3x3(self.c_out, self.stride,
                       self.num_layers ** -0.5, self.dtype)(x + a1a)
        out = nn.relu(out + a1b)
        out = _conv3x3(self.c_out, 1, 0.0, self.dtype)(out + a2a)
        out = out * mul + a2b
        return nn.relu(out + shortcut)


def _avg_max_head(x):
    """Concat of global average and max pooling (reference
    fixup_resnet18.py:125-131)."""
    return jnp.concatenate([jnp.mean(x, axis=(1, 2)),
                            jnp.max(x, axis=(1, 2))], axis=-1)


@register_model("ResNet18")
class ResNet18(nn.Module):
    """reference fixup_resnet18.py:168-216."""
    num_classes: int = 10
    num_blocks: Sequence[int] = (2, 2, 2, 2)

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.relu(nn.Conv(64, (3, 3), padding=1, use_bias=False,
                            kernel_init=_he)(x))
        for c_out, n, stride in zip((64, 128, 256, 256),
                                    self.num_blocks, (1, 2, 2, 2)):
            for b in range(n):
                x = PreActBlock(c_out, stride if b == 0 else 1)(x)
        x = _avg_max_head(x)
        x = nn.Dense(self.num_classes, kernel_init=_he)(x)
        return x


@register_model("FixupResNet18")
class FixupResNet18(nn.Module):
    """reference fixup_resnet18.py:66-135 (zero-init classifier)."""
    num_classes: int = 10
    num_blocks: Sequence[int] = (2, 2, 2, 2)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        L = sum(self.num_blocks)
        x = x.astype(self.dtype)
        x = nn.relu(nn.Conv(64, (3, 3), padding=1, use_bias=False,
                            dtype=self.dtype,
                            kernel_init=_fixup_conv_init())(x))
        for c_out, n, stride in zip((64, 128, 256, 256),
                                    self.num_blocks, (1, 2, 2, 2)):
            for b in range(n):
                x = FixupBlock(c_out, L, stride if b == 0 else 1,
                               dtype=self.dtype)(x)
        x = _avg_max_head(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     kernel_init=nn.initializers.zeros,
                     bias_init=nn.initializers.zeros)(x)
        return x.astype(jnp.float32)
