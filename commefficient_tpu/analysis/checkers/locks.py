"""lock-confinement: declared shared state is touched only under its
lock.

The daemon era (PR 17/18) made the package genuinely multi-threaded —
the live exporter scrapes from HTTP worker threads while the round
loop publishes, the flight recorder dumps from crash hooks while the
driver appends, the scheduler's queues are read by fairness probes.
Lock discipline by convention doesn't survive refactors, so modules
that own threaded state now *declare* it:

    _LOCK_MAP = {"_counters": "_lock", "_PLANE": "_PLANE_LOCK"}

maps attribute (or module-global) names to the lock that confines
them. This checker flags, anywhere in the declaring module:

* **writes** outside a lexical ``with <lock>:`` — attribute/global
  assignment, augmented assignment, subscript stores, ``del``, and
  mutating method calls (``append``/``update``/``pop``/…);
* **iteration reads** outside the lock — ``for x in <attr>``,
  comprehensions over it, and snapshot calls (``list()``, ``dict()``,
  ``sorted()``, ``.items()``/``.values()``/``.keys()`` consumed by a
  loop) — iterating a dict/deque while another thread mutates it
  raises RuntimeError in CPython, which is precisely the crash the
  checker exists to prevent.

Point reads (``self._by_id[k]``, ``len(...)``, membership) stay
unflagged: they are atomic under the GIL and locking them buys
nothing. Plain ``self.<attr> = ...`` stores inside ``__init__`` are
exempt (construction happens-before publication); stores through a
*class* receiver (``JSONLSink._live[...]``) are never exempt.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from commefficient_tpu.analysis.flow import FlowChecker, Program

_MUTATORS = {"append", "appendleft", "extend", "extendleft", "add",
             "update", "setdefault", "pop", "popitem", "popleft",
             "remove", "discard", "clear", "insert", "sort",
             "reverse"}
_SNAPSHOTTERS = {"list", "dict", "set", "tuple", "sorted",
                 "frozenset", "sum", "max", "min", "any", "all"}
_VIEW_METHODS = {"items", "values", "keys"}


def _lock_map_of(mod) -> Dict[str, str]:
    """The module-level ``_LOCK_MAP`` literal, if declared."""
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) \
                and any(isinstance(t, ast.Name)
                        and t.id == "_LOCK_MAP"
                        for t in node.targets) \
                and isinstance(node.value, ast.Dict):
            out = {}
            for k, v in zip(node.value.keys, node.value.values):
                if isinstance(k, ast.Constant) \
                        and isinstance(v, ast.Constant):
                    out[str(k.value)] = str(v.value)
            return out
        if isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name) \
                and node.target.id == "_LOCK_MAP" \
                and isinstance(node.value, ast.Dict):
            return _lock_map_of_dict(node.value)
    return {}


def _lock_map_of_dict(d: ast.Dict) -> Dict[str, str]:
    return {str(k.value): str(v.value)
            for k, v in zip(d.keys, d.values)
            if isinstance(k, ast.Constant)
            and isinstance(v, ast.Constant)}


def _guarded_attr(expr, lock_map) -> Optional[str]:
    """The declared attr an expression refers to (``self._ring`` /
    ``Cls._live`` / module-global ``_PLANE``), else None."""
    if isinstance(expr, ast.Attribute) and expr.attr in lock_map:
        return expr.attr
    if isinstance(expr, ast.Name) and expr.id in lock_map:
        return expr.id
    return None


def _lock_name(expr) -> Optional[str]:
    """The lock a ``with`` item takes: ``self._lock`` → "_lock",
    ``_PLANE_LOCK`` → "_PLANE_LOCK"."""
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def _is_self_store_in_init(target, fn_name) -> bool:
    return (fn_name == "__init__"
            and isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == "self")


def _check_module(rel: str, mod) -> List[Tuple[str, int, str]]:
    lock_map = _lock_map_of(mod)
    if not lock_map:
        return []
    hits: List[Tuple[str, int, str]] = []

    def flag(line, attr, what):
        hits.append((rel, line,
                     f"{what} of '{attr}' outside 'with "
                     f"{lock_map[attr]}:' — _LOCK_MAP confines it"))

    def visit(node, held: Set[str], fn_name: Optional[str],
              at_module_level: bool):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a with-block does not extend into a nested def's body —
            # that body runs later, on whatever thread calls it
            for child in node.body:
                visit(child, set(), node.name, False)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = set(held)
            for item in node.items:
                ln = _lock_name(item.context_expr)
                if ln is not None:
                    inner.add(ln)
            for child in node.body:
                visit(child, inner, fn_name, at_module_level)
            return
        if isinstance(node, ast.ClassDef):
            for child in node.body:
                visit(child, held, fn_name, at_module_level)
            return

        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                base = t.value if isinstance(t, ast.Subscript) else t
                attr = _guarded_attr(base, lock_map)
                if attr is not None \
                        and lock_map[attr] not in held \
                        and not at_module_level \
                        and not (isinstance(t, (ast.Attribute,
                                                ast.Name))
                                 and _is_self_store_in_init(t,
                                                            fn_name)):
                    flag(t.lineno, attr, "write")
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                base = t.value if isinstance(t, ast.Subscript) else t
                attr = _guarded_attr(base, lock_map)
                if attr is not None and lock_map[attr] not in held:
                    flag(t.lineno, attr, "del")
        elif isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in _MUTATORS:
                attr = _guarded_attr(f.value, lock_map)
                if attr is not None and lock_map[attr] not in held:
                    flag(node.lineno, attr, f".{f.attr}() mutation")
            name = f.id if isinstance(f, ast.Name) else None
            if name in _SNAPSHOTTERS:
                for a in node.args:
                    tgt = a
                    if isinstance(a, ast.Call) \
                            and isinstance(a.func, ast.Attribute) \
                            and a.func.attr in _VIEW_METHODS:
                        tgt = a.func.value
                    attr = _guarded_attr(tgt, lock_map)
                    if attr is not None \
                            and lock_map[attr] not in held:
                        flag(node.lineno, attr,
                             f"{name}(...) iteration")
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            it = node.iter
            if isinstance(it, ast.Call) \
                    and isinstance(it.func, ast.Attribute) \
                    and it.func.attr in _VIEW_METHODS:
                it = it.func.value
            attr = _guarded_attr(it, lock_map)
            if attr is not None and lock_map[attr] not in held:
                flag(node.iter.lineno, attr, "loop iteration")
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                               ast.GeneratorExp)):
            for gen in node.generators:
                it = gen.iter
                if isinstance(it, ast.Call) \
                        and isinstance(it.func, ast.Attribute) \
                        and it.func.attr in _VIEW_METHODS:
                    it = it.func.value
                attr = _guarded_attr(it, lock_map)
                if attr is not None and lock_map[attr] not in held:
                    flag(node.lineno, attr, "comprehension iteration")

        for child in ast.iter_child_nodes(node):
            visit(child, held, fn_name, at_module_level)

    for top in mod.tree.body:
        visit(top, set(), None, True)
    return hits


def check(program: Program) -> List[Tuple[str, int, str]]:
    out = []
    for rel in sorted(program.modules):
        mod = program.modules[rel]
        if mod.tree is not None:
            out.extend(_check_module(rel, mod))
    return out


CHECKER = FlowChecker(
    "lock-confinement",
    "declared shared state touched outside its _LOCK_MAP lock",
    check)
