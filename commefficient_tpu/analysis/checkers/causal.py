"""causal-confinement: span machinery unreachable from jit roots.

``--causal_trace`` sells a hard promise: tracing is host-side only
and the compiled program is byte-identical with the flag off (the
HLO-fingerprint tests pin the off mode). The cheapest way to break
that promise silently is a refactor that threads a tracer call into
a traced body — a span open inside a jitted round would freeze the
``clock.tick()`` read into the program (trace-purity would also
object) or, subtler, perturb what gets staged without tripping any
per-call rule. This checker guards the promise structurally: NO
function defined in the causal modules (``telemetry/causal.py``,
``telemetry/critpath.py``) may be reachable from any jit/pallas
root, period — not "is pure enough", but "is not on the traced call
graph at all".
"""

from __future__ import annotations

from typing import List, Tuple

from commefficient_tpu.analysis.flow import FlowChecker, Program

#: modules whose every function must stay off the traced call graph
CONFINED_RELS = ("telemetry/causal.py", "telemetry/critpath.py")


def check(program: Program) -> List[Tuple[str, int, str]]:
    out = []
    seen = set()
    for fq in sorted(program.traced):
        fn = program.functions[fq]
        rel = fn.module.rel.as_posix()
        if rel not in CONFINED_RELS:
            continue
        key = (rel, fn.node.lineno)
        if key in seen:
            continue
        seen.add(key)
        out.append((rel, fn.node.lineno,
                    f"causal-trace function {fn.qual} is reachable "
                    "from a jit root — span machinery is host-side "
                    "only (--causal_trace must stay HLO-identical "
                    "off and on)"))
    return out


CHECKER = FlowChecker(
    "causal-confinement",
    "causal span/critpath code reachable from a jit root",
    check)
