"""trace-purity: host impurities reachable from a jit root.

The legacy ``raw-clock``/``np-on-tracer``/``host-sync`` rules guard by
*module path* — blunt, because a helper in ``telemetry/`` or
``runtime/`` can still be called from a traced body. This checker
guards by *reachability*: walk the call graph from every jit/pallas
root and flag any clock read, print/file I/O, Python/NumPy RNG draw,
or host-sync (``.item()``, ``device_get``, ``block_until_ready``,
``_host``) inside a reachable function. Any of these inside a traced
body either crashes at trace time (ConcretizationTypeError), silently
freezes trace-time state into the compiled program (clocks, RNG — the
round replays round 0's draw forever), or forces a hidden
device→host sync the ledger can't attribute — all three break the
bit-exact probe-mirror and HLO-identity contracts.
"""

from __future__ import annotations

import ast
from typing import List, Tuple

from commefficient_tpu.analysis.flow import FlowChecker, Program

_CLOCK_ATTRS = {"time", "perf_counter", "perf_counter_ns",
                "monotonic", "monotonic_ns"}
_CLOCK_NAMES = {"perf_counter", "perf_counter_ns", "monotonic",
                "monotonic_ns"}
_IO_NAMES = {"print", "open", "input"}
_SYNC_ATTRS = {"device_get", "block_until_ready"}
_SYNC_NAMES = {"device_get", "block_until_ready", "_host"}


def _impure_sites(fn) -> List[Tuple[int, str]]:
    """(line, what) for every host impurity lexically inside ``fn``'s
    own body (nested defs are their own functions — reachability
    decides whether they count, not lexical nesting)."""
    own_nested = {id(g.node) for g in fn.nested}
    hits: List[Tuple[int, str]] = []

    def walk(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)) \
                    and id(child) in own_nested:
                continue
            if isinstance(child, ast.Call):
                f = child.func
                if isinstance(f, ast.Attribute):
                    v = f.value
                    if f.attr in _CLOCK_ATTRS \
                            and isinstance(v, ast.Name) \
                            and v.id == "time":
                        hits.append((child.lineno,
                                     f"raw clock time.{f.attr}()"))
                    elif f.attr in _SYNC_ATTRS:
                        hits.append((child.lineno,
                                     f"host sync .{f.attr}()"))
                    elif f.attr == "item" and not child.args \
                            and not child.keywords:
                        hits.append((child.lineno,
                                     "host sync .item()"))
                    elif isinstance(v, ast.Name) and v.id == "random":
                        hits.append((child.lineno,
                                     f"stdlib random.{f.attr}()"))
                    elif (isinstance(v, ast.Attribute)
                          and v.attr == "random"
                          and isinstance(v.value, ast.Name)
                          and v.value.id in ("np", "numpy")):
                        hits.append((child.lineno,
                                     f"np.random.{f.attr}()"))
                elif isinstance(f, ast.Name):
                    if f.id in _CLOCK_NAMES:
                        hits.append((child.lineno,
                                     f"raw clock {f.id}()"))
                    elif f.id in _IO_NAMES:
                        hits.append((child.lineno,
                                     f"host I/O {f.id}()"))
                    elif f.id in _SYNC_NAMES:
                        hits.append((child.lineno,
                                     f"host sync {f.id}()"))
            walk(child)

    walk(fn.node)
    return hits


def check(program: Program) -> List[Tuple[str, int, str]]:
    out = []
    seen = set()
    for fq in sorted(program.traced):
        fn = program.functions[fq]
        rel = fn.module.rel.as_posix()
        for line, what in _impure_sites(fn):
            key = (rel, line, what)
            if key in seen:
                continue
            seen.add(key)
            out.append((rel, line,
                        f"{what} in jit-reachable {fn.qual} — traced "
                        "bodies must be host-pure (frozen constant / "
                        "hidden sync at best, trace error at worst)"))
    return out


CHECKER = FlowChecker(
    "trace-purity",
    "host I/O, clock, RNG or sync reachable from a jit root",
    check)
