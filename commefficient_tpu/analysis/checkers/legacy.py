"""The legacy (per-file) lint rules, migrated verbatim from the
grep-era ``analysis/lint.py`` onto the flowlint engine's registry.

Each rule here sees one file at a time — ``(rel_path, lines, tree)`` —
exactly as before the migration; the driver moved to
``analysis.flow.run_file_rules`` so the flow tier and this tier share
one parse of the package. Findings, waivers (``# audit: allow(...)``)
and baseline gating are byte-identical to the pre-migration linter
(pinned by tests/test_flowlint.py).

Scoping is by path role relative to the package root:

* ``telemetry/`` owns the raw clocks and the host transfer of ledger
  scalars — exempt from ``raw-clock`` and the span rules.
* ``core/`` and ``ops/`` are *compiled scope*: bodies there run under
  jit tracing, so Python RNG is a frozen-constant bug and
  ``np.asarray`` inside a traced closure is a tracer leak.
* ``runtime/``, ``train/``, ``clientstore/`` are the host hot path:
  device syncs (``.item()``, ``jax.device_get``, ``block_until_ready``,
  ``_host``) must sit inside a telemetry ``span(...)`` block so the
  ledger attributes their cost.
"""

from __future__ import annotations

import ast
import pathlib
from typing import Dict, List, Set, Tuple

from commefficient_tpu.analysis.flow import Rule

COMPILED_SCOPE = ("core", "ops")
HOST_HOT_PATH = ("runtime", "train", "clientstore")


def _top(rel: pathlib.PurePath) -> str:
    return rel.parts[0] if rel.parts else ""


# --- rule: raw-clock ---------------------------------------------------


_CLOCK_ATTRS = {"time", "perf_counter", "perf_counter_ns",
                "monotonic", "monotonic_ns"}


def _check_raw_clock(rel, lines, tree):
    """time.time()/perf_counter() outside telemetry/ — all host timing
    must flow through telemetry.clock so spans, Timer and the ledger
    agree on what a second is."""
    if _top(rel) == "telemetry":
        return []
    hits = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if (isinstance(f, ast.Attribute) and f.attr in _CLOCK_ATTRS
                and isinstance(f.value, ast.Name)
                and f.value.id == "time"):
            hits.append((node.lineno,
                         f"raw clock time.{f.attr}() — use "
                         "telemetry.clock.wall/tick"))
        elif (isinstance(f, ast.Name)
                and f.id in {"perf_counter", "perf_counter_ns",
                             "monotonic", "monotonic_ns"}):
            hits.append((node.lineno,
                         f"raw clock {f.id}() — use "
                         "telemetry.clock.wall/tick"))
    return hits


# --- rule: probe-transfer-span -----------------------------------------


def _check_probe_transfer_span(rel, lines, tree):
    """Probe values may be materialised (_host / jax.device_get) only
    inside a span("metrics_host") block — the sync point IS the
    probes' runtime cost, so it must be ledger-attributed. Line-based
    on purpose: byte-for-byte the semantics of the original grep guard
    it replaced (context naming probes within +-3 lines, span within
    the previous 10)."""
    if _top(rel) == "telemetry":
        return []
    hits = []
    for i, line in enumerate(lines):
        if "_host(" not in line and "device_get(" not in line:
            continue
        stripped = line.lstrip()
        if stripped.startswith("#") or stripped.startswith("def "):
            continue
        ctx = "\n".join(lines[max(0, i - 3):i + 2])
        if "probe" not in ctx.lower() and "sprobes" not in ctx:
            continue
        back = "\n".join(lines[max(0, i - 10):i + 1])
        if 'span("metrics_host")' not in back:
            hits.append((i + 1, "probe value crosses to the host "
                         'outside a span("metrics_host") block'))
    return hits


# --- rule: host-sync ---------------------------------------------------


def _span_guarded_calls(tree) -> Set[int]:
    """Line numbers of Call nodes lexically inside a ``with
    <x>.span(...)`` block (any span name: the requirement is that the
    sync is *attributed*, which span the caller judges)."""
    guarded: Set[int] = set()

    def visit(node, in_span):
        if isinstance(node, ast.With):
            for item in node.items:
                c = item.context_expr
                if (isinstance(c, ast.Call)
                        and isinstance(c.func, ast.Attribute)
                        and c.func.attr == "span"):
                    in_span = True
        if isinstance(node, ast.Call) and in_span:
            guarded.add(node.lineno)
        for child in ast.iter_child_nodes(node):
            visit(child, in_span)

    visit(tree, False)
    return guarded


def _check_host_sync(rel, lines, tree):
    """Device syncs on the host hot path outside any telemetry span:
    each one is a hidden blocking round-trip the ledger cannot see."""
    if _top(rel) not in HOST_HOT_PATH:
        return []
    guarded = _span_guarded_calls(tree)
    hits = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or node.lineno in guarded:
            continue
        f = node.func
        name = None
        if isinstance(f, ast.Attribute):
            if f.attr == "item" and not node.args and not node.keywords:
                name = ".item()"
            elif f.attr in {"device_get", "block_until_ready"}:
                name = f.attr
        elif isinstance(f, ast.Name):
            if f.id in {"device_get", "block_until_ready", "_host"}:
                name = f.id
        if name:
            hits.append((node.lineno,
                         f"host sync {name} outside a telemetry "
                         "span block"))
    return hits


# --- rule: np-on-tracer ------------------------------------------------


def _nested_function_lines(tree) -> Set[int]:
    """Line ranges of functions *defined inside other functions* — in
    compiled-scope modules those closures are what jit traces."""
    spans: List[Tuple[int, int]] = []

    def visit(node, depth):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if depth >= 1:
                spans.append((node.lineno, node.end_lineno or node.lineno))
            depth += 1
        for child in ast.iter_child_nodes(node):
            visit(child, depth)

    visit(tree, 0)
    covered: Set[int] = set()
    for a, b in spans:
        covered.update(range(a, b + 1))
    return covered


def _check_np_on_tracer(rel, lines, tree):
    """np.asarray / np.array inside a traced closure in compiled scope
    forces the tracer to the host (ConcretizationTypeError at best, a
    silent device->host sync via __array__ at worst). Module-level
    numpy (hash-constant setup in ops/sketch.py and friends) is fine —
    only *nested* function bodies are traced."""
    if _top(rel) not in COMPILED_SCOPE:
        return []
    traced = _nested_function_lines(tree)
    hits = []
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call) and node.lineno in traced
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in {"asarray", "array"}
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in {"np", "numpy"}):
            hits.append((node.lineno,
                         f"np.{node.func.attr}() inside a traced "
                         "closure — use jnp, or hoist to setup"))
    return hits


# --- rule: python-rng --------------------------------------------------


def _check_python_rng(rel, lines, tree):
    """Stdlib/NumPy RNG in compiled scope: traced once, the draw
    freezes into the program as a constant — every execution reuses
    round 0's randomness. Use jax.random with threaded keys."""
    if _top(rel) not in COMPILED_SCOPE:
        return []
    hits = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Attribute):
            continue
        # np.random.<fn> / numpy.random.<fn>
        v = node.value
        if (isinstance(v, ast.Attribute) and v.attr == "random"
                and isinstance(v.value, ast.Name)
                and v.value.id in {"np", "numpy"}):
            hits.append((node.lineno,
                         f"np.random.{node.attr} in compiled scope — "
                         "use jax.random"))
        # random.<fn> on the stdlib module
        elif (isinstance(v, ast.Name) and v.id == "random"):
            hits.append((node.lineno,
                         f"random.{node.attr} in compiled scope — "
                         "use jax.random"))
    return hits


# --- rule: noise-confinement -------------------------------------------


_NOISE_FNS = {"PRNGKey", "normal", "truncated_normal", "laplace",
              "gumbel", "cauchy"}


def _check_noise_confinement(rel, lines, tree):
    """Raw ``jax.random.PRNGKey``/``jax.random.normal`` (and friends)
    outside ``privacy/`` are hard audit failures: every noise draw and
    every key-stream genesis must route through privacy/mechanism.py
    (``noise_stream`` / ``gaussian_noise`` / ``add_table_noise``) so
    the DP accountant's claim — "all injected randomness is calibrated
    and charged" — is checkable by construction. A stray
    ``jax.random.normal`` anywhere else is either unaccounted noise
    (a silent privacy hole) or an unseeded stream the replay contract
    cannot reproduce. Exempt: ``privacy/`` (the owner), ``models/``
    (parameter *initialisation* is pre-release randomness, not noise
    injected into a private release), and ``data/chaos.py`` (the
    test/bench-only fault injector, already fenced off by
    chaos-confinement). Key *consumption* — ``fold_in``, ``split``,
    threading keys through round plans — stays legal everywhere; only
    genesis and draws are confined."""
    if _top(rel) in ("privacy", "models") \
            or rel.as_posix() == "data/chaos.py":
        return []
    hits = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not (isinstance(f, ast.Attribute) and f.attr in _NOISE_FNS):
            continue
        v = f.value
        jax_random = (isinstance(v, ast.Attribute)
                      and v.attr == "random"
                      and isinstance(v.value, ast.Name)
                      and v.value.id == "jax")
        bare_random = isinstance(v, ast.Name) and v.id == "random"
        if not (jax_random or bare_random):
            continue
        if f.attr == "PRNGKey":
            hits.append((node.lineno,
                         "raw jax.random.PRNGKey() outside privacy/ — "
                         "mint streams via privacy.noise_stream so "
                         "every injected-randomness source has one "
                         "accountable owner"))
        else:
            hits.append((node.lineno,
                         f"raw jax.random.{f.attr}() noise draw "
                         "outside privacy/ — route through "
                         "privacy.gaussian_noise/add_table_noise so "
                         "the accountant charges it"))
    return hits


# --- rule: raw-devices -------------------------------------------------


def _check_raw_devices(rel, lines, tree):
    """jax.devices()/jax.local_devices() inside telemetry/: the
    observatory must see the fleet through parallel/mesh.py
    (``topology_summary`` / ``first_local_device``) so device
    resolution has ONE owner — raw enumeration here silently disagrees
    with the mesh on subset-mesh and multi-process runs."""
    if _top(rel) != "telemetry":
        return []
    hits = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if (isinstance(f, ast.Attribute)
                and f.attr in {"devices", "local_devices"}
                and isinstance(f.value, ast.Name)
                and f.value.id == "jax"):
            hits.append((node.lineno,
                         f"raw jax.{f.attr}() in telemetry/ — resolve "
                         "devices via parallel.mesh "
                         "(topology_summary/first_local_device)"))
    return hits


# --- rule: chaos-confinement -------------------------------------------


def _is_chaos_module(modname) -> bool:
    return bool(modname) and modname.split(".")[-1] == "chaos"


def _check_chaos_confinement(rel, lines, tree):
    """``data/chaos.py`` (byzantine/fault injection) is strictly a
    test/bench facility: no production module may import it, so the
    adversarial hooks can never ride along into a real run. Tests,
    benches and scripts live outside the scanned package root and wire
    chaos in through the public hooks (``transmit_transform``, loader
    wrapping) instead."""
    if rel.as_posix() == "data/chaos.py":
        return []
    hits = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if _is_chaos_module(a.name):
                    hits.append((node.lineno,
                                 f"import {a.name} outside "
                                 "data/chaos.py — chaos is "
                                 "test/bench-only"))
        elif isinstance(node, ast.ImportFrom):
            if _is_chaos_module(node.module) or any(
                    a.name == "chaos" for a in node.names):
                src = ("." * node.level) + (node.module or "")
                hits.append((node.lineno,
                             f"from {src} import ... pulls in "
                             "data/chaos.py — chaos is "
                             "test/bench-only"))
    return hits


# --- rule: fedservice-confinement --------------------------------------


def _is_fedservice_module(modname) -> bool:
    return bool(modname) and "fedservice" in modname.split(".")


def _check_fedservice_confinement(rel, lines, tree):
    """The multi-tenant daemon (``fedservice/``) sits ON TOP of the
    runtime — it orchestrates FedModels, it is never a dependency of
    one. A runtime module importing the service would invert the
    layering (and let control-plane state leak into the bit-identical
    single-job data plane), so outside ``fedservice/`` itself no
    production module may import it or name its entry points.
    Tests, benches and scripts live outside the scanned package root
    and drive the daemon freely."""
    if _top(rel) == "fedservice":
        return []
    hits = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if _is_fedservice_module(a.name):
                    hits.append((node.lineno,
                                 f"import {a.name} outside "
                                 "fedservice/ — the daemon is a "
                                 "top-layer orchestrator"))
        elif isinstance(node, ast.ImportFrom):
            if _is_fedservice_module(node.module) or any(
                    a.name == "fedservice" for a in node.names):
                src = ("." * node.level) + (node.module or "")
                hits.append((node.lineno,
                             f"from {src} import ... pulls in "
                             "fedservice/ — the daemon is a "
                             "top-layer orchestrator"))
        elif isinstance(node, ast.Name) and \
                node.id in ("FedService", "JobSpec"):
            hits.append((node.lineno,
                         f"{node.id} referenced outside fedservice/ "
                         "— production modules must not depend on "
                         "the daemon"))
    return hits


# --- rule: arrival-confinement -----------------------------------------


def _check_arrival_confinement(rel, lines, tree):
    """Arrival-process injection (asyncfed) is strictly a
    test/bench facility, mirroring chaos-confinement: production
    package modules must never construct an ``ArrivalSchedule`` (it
    lives in data/chaos.py — importing it is already an import
    violation; naming it at all is flagged here as defense in depth)
    nor CALL ``attach_arrival_process`` with a schedule. The
    forwarding hooks themselves (``def attach_arrival_process`` on
    FedModel/AsyncRoundDriver, including the one-line relay in their
    bodies) are the sanctioned injection surface for code living
    outside the package root."""
    if rel.as_posix() == "data/chaos.py":
        return []
    # line ranges of the sanctioned forwarding defs: a call to the
    # inner hook from inside `def attach_arrival_process` is the
    # relay, not an injection
    relay = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == "attach_arrival_process":
            relay.append((node.lineno, node.end_lineno or node.lineno))
    hits = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Name) and \
                node.id == "ArrivalSchedule":
            hits.append((node.lineno,
                         "ArrivalSchedule named in a production "
                         "module — arrival processes are "
                         "test/bench-only (inject via "
                         "attach_arrival_process from outside the "
                         "package)"))
        elif isinstance(node, ast.Attribute) and \
                node.attr == "ArrivalSchedule":
            hits.append((node.lineno,
                         "ArrivalSchedule referenced in a production "
                         "module — arrival processes are "
                         "test/bench-only"))
        elif isinstance(node, ast.Call):
            f = node.func
            name = (f.attr if isinstance(f, ast.Attribute)
                    else f.id if isinstance(f, ast.Name) else None)
            if name != "attach_arrival_process":
                continue
            if any(lo <= node.lineno <= hi for lo, hi in relay):
                continue
            hits.append((node.lineno,
                         "attach_arrival_process() called from a "
                         "production module — arrival injection is "
                         "test/bench-only"))
    return hits


# --- rule: inline-partition-spec ---------------------------------------


_SPEC_NAMES = {"PartitionSpec", "NamedSharding"}


def _check_inline_partition_spec(rel, lines, tree):
    """PartitionSpec/NamedSharding literals outside parallel/: sharding
    layout has ONE owner — parallel/mesh.py's sanctioned constructors
    (client_spec, table_shard_spec, server_state_spec, ...). An inline
    spec in core/ or runtime/ silently forks the layout the program
    auditor and the 1/M memory accounting reason about."""
    if _top(rel) == "parallel":
        return []
    hits = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            if node.module and node.module.startswith("jax.sharding"):
                for a in node.names:
                    if a.name in _SPEC_NAMES:
                        hits.append((
                            node.lineno,
                            f"from jax.sharding import {a.name} "
                            "outside parallel/ — build specs through "
                            "parallel.mesh"))
        elif (isinstance(node, ast.Attribute)
                and node.attr in _SPEC_NAMES):
            hits.append((node.lineno,
                         f"inline .{node.attr} outside parallel/ — "
                         "build specs through parallel.mesh"))
    return hits


# --- rule: checkpoint-mesh-route ---------------------------------------


_MESH_CONSTRUCTORS = {"client_sharding", "server_state_sharding",
                      "replicated", "shard_batch", "make_mesh",
                      "make_mesh2d"}


def _check_checkpoint_mesh_route(rel, lines, tree):
    """Every placement the checkpoint path applies at save/load time —
    a ``device_put`` target or a ``sharding=`` argument — must come
    from a parallel/mesh.py spec constructor (or be the explicit None
    "keep the default layout"). The elastic-restore contract (a CxM
    checkpoint restores bit-exact onto C'xM') holds precisely because
    restore re-derives placement from the CURRENT mesh through the
    same constructors FedModel/FedOptimizer initialised with; an
    ad-hoc sharding built inline here would silently fork the layout
    and break the migration."""
    if rel.as_posix() != "runtime/checkpoint.py":
        return []

    def call_name(e):
        f = e.func
        return (f.attr if isinstance(f, ast.Attribute)
                else f.id if isinstance(f, ast.Name) else None)

    def sanctioned(e, names):
        if isinstance(e, ast.Constant) and e.value is None:
            return True
        if isinstance(e, ast.Call):
            return call_name(e) in _MESH_CONSTRUCTORS
        if isinstance(e, ast.IfExp):
            return (sanctioned(e.body, names)
                    and sanctioned(e.orelse, names))
        if isinstance(e, ast.Name):
            return e.id in names
        return False

    # names whose EVERY assignment is a sanctioned placement (to a
    # fixpoint, so spec = other_spec chains resolve)
    assigns: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    assigns.setdefault(t.id, []).append(node.value)
    names: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for name, vals in assigns.items():
            if name not in names and all(
                    sanctioned(v, names) for v in vals):
                names.add(name)
                changed = True

    hits = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if call_name(node) == "device_put" and len(node.args) >= 2 \
                and not sanctioned(node.args[1], names):
            hits.append((node.lineno,
                         "device_put placement not built by a "
                         "parallel.mesh spec constructor — checkpoint "
                         "save/load shapes must route through "
                         "parallel/mesh.py"))
        for kw in node.keywords:
            if kw.arg in ("sharding", "device") \
                    and not sanctioned(kw.value, names):
                hits.append((node.lineno,
                             f"{kw.arg}= argument not built by a "
                             "parallel.mesh spec constructor — "
                             "checkpoint save/load shapes must route "
                             "through parallel/mesh.py"))
    return hits


# --- rule: byte-literal -------------------------------------------------


_BYTE_WIDTH_LITERALS = {1, 2, 4, 8, 1.0, 2.0, 4.0, 8.0}


def _check_byte_literal(rel, lines, tree):
    """Inline byte-width multiplies (``n * 4``) in accounting code on
    the host path (runtime/, telemetry/): every one of them silently
    hard-codes f32 on the wire, which is exactly the bug class the
    quantized sketch work removed. Byte math must go through
    ``accounting.bytes_of(shape, dtype)`` / ``dtype_bytes`` so a
    --sketch_dtype change reprices every ledger entry at once. Only
    statements whose source mentions "bytes" are in scope — scalar
    math like momentum constants is untouched."""
    if _top(rel) not in ("runtime", "telemetry"):
        return []
    hits = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.BinOp)
                and isinstance(node.op, ast.Mult)):
            continue
        lit = None
        for side in (node.left, node.right):
            if (isinstance(side, ast.Constant)
                    and type(side.value) in (int, float)
                    and side.value in _BYTE_WIDTH_LITERALS):
                lit = side.value
        if lit is None:
            continue
        ctx = " ".join(
            lines[node.lineno - 1:(node.end_lineno or node.lineno)])
        if "bytes" not in ctx.lower():
            continue
        hits.append((node.lineno,
                     f"inline byte-width literal * {lit} in "
                     "accounting code — use accounting.bytes_of/"
                     "dtype_bytes so the wire dtype prices it"))
    return hits


# --- rule: knob-mutation -----------------------------------------------


_KNOB_ATTRS = {"sketch_dtype", "num_rows", "num_cols",
               "approx_recall"}
_CONFIG_RECEIVERS = {"cfg", "args", "config"}


def _check_knob_mutation(rel, lines, tree):
    """The compression knobs (``k``/``num_rows``/``num_cols``/
    ``sketch_dtype``/``approx_recall``) are autopilot state: between
    rounds the controller moves them ONLY through its sanctioned
    re-plan API (``autopilot.apply_knobs`` onto the bucketed re-jit
    cache), which keeps the compiled round variant, the byte
    accounting and the replay record consistent. A direct store
    anywhere else silently diverges the dispatched program from the
    config that priced it — the exact bug class the variant cache
    exists to remove. ``autopilot/`` is exempt (it IS the re-plan
    API); ``config.py`` owns the initial values. Flagged: attribute
    stores of the knob names (``.k`` only on config-shaped receivers
    — cfg/args/config/self.args — so loop counters named ``k`` stay
    legal), and ``replace(...)``/``dataclasses.replace(...)`` calls
    passing knob keywords."""
    if _top(rel) == "autopilot" or rel.as_posix() == "config.py":
        return []

    def recv(v):
        if isinstance(v, ast.Name):
            return v.id
        if isinstance(v, ast.Attribute) \
                and isinstance(v.value, ast.Name) \
                and v.value.id == "self":
            return v.attr
        return None

    hits = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                if not isinstance(t, ast.Attribute):
                    continue
                if t.attr in _KNOB_ATTRS or (
                        t.attr == "k"
                        and recv(t.value) in _CONFIG_RECEIVERS):
                    hits.append((t.lineno,
                                 f"direct write to .{t.attr} outside "
                                 "autopilot/ — knob moves must go "
                                 "through autopilot.apply_knobs so "
                                 "the re-jit cache, accounting and "
                                 "replay record stay consistent"))
        elif isinstance(node, ast.Call):
            f = node.func
            name = (f.attr if isinstance(f, ast.Attribute)
                    else f.id if isinstance(f, ast.Name) else None)
            if name != "replace":
                continue
            knobs = sorted(kw.arg for kw in node.keywords
                           if kw.arg in _KNOB_ATTRS | {"k"})
            if knobs:
                hits.append((node.lineno,
                             f"replace({', '.join(knobs)}=...) "
                             "outside autopilot/ — knob moves must "
                             "go through autopilot.apply_knobs"))
    return hits


# --- rule: mutable-default-arg -----------------------------------------


def _check_mutable_default(rel, lines, tree):
    hits = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for default in list(node.args.defaults) + [
                d for d in node.args.kw_defaults if d is not None]:
            if isinstance(default, (ast.List, ast.Dict, ast.Set)) or (
                    isinstance(default, ast.Call)
                    and isinstance(default.func, ast.Name)
                    and default.func.id in {"list", "dict", "set"}):
                hits.append((default.lineno,
                             f"mutable default argument in "
                             f"{node.name}() — use None + init in body"))
    return hits


# --- rule: live-confinement --------------------------------------------

#: top-level modules that own a socket when imported
_SOCKET_MODULES = {"socket", "socketserver", "http"}
#: the package's only sanctioned socket owner
_LIVE_HOME = "telemetry/live.py"
#: the only module that may construct an SLO engine directly (every
#: other caller routes through build_slo_engine)
_SLO_HOME = "telemetry/slo.py"
_SERVER_CTORS = {"LiveServer", "ThreadingHTTPServer", "HTTPServer"}


def _check_live_confinement(rel, lines, tree):
    """The live operations plane (telemetry/live.py) is the package's
    ONLY sanctioned socket owner and exporter-thread spawner: no
    other production module may import ``socket``/``socketserver``/
    ``http.server`` or construct an HTTP server, and the compiled
    round path (``core/``, ``runtime/``) may not spawn threads at all
    — an exporter accidentally living next to the round loop is
    exactly the state-mutation hazard the read-only-snapshot design
    exists to prevent. SLO engines are constructed only inside
    ``telemetry/slo.py`` (``build_slo_engine`` is the sanctioned
    entry). Scripts and tests live outside the scanned package root
    and may do any of this freely."""
    posix = rel.as_posix()
    hits = []
    for node in ast.walk(tree):
        if posix != _LIVE_HOME:
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name.split(".")[0] in _SOCKET_MODULES:
                        hits.append((node.lineno,
                                     f"import {a.name} outside "
                                     "telemetry/live.py — the live "
                                     "plane is the only sanctioned "
                                     "socket owner"))
            elif isinstance(node, ast.ImportFrom) and node.level == 0 \
                    and node.module \
                    and node.module.split(".")[0] in _SOCKET_MODULES:
                hits.append((node.lineno,
                             f"from {node.module} import ... outside "
                             "telemetry/live.py — the live plane is "
                             "the only sanctioned socket owner"))
        if isinstance(node, ast.Call):
            fn = node.func
            name = (fn.id if isinstance(fn, ast.Name)
                    else fn.attr if isinstance(fn, ast.Attribute)
                    else None)
            if name in _SERVER_CTORS and posix != _LIVE_HOME:
                hits.append((node.lineno,
                             f"{name}(...) constructed outside "
                             "telemetry/live.py — attach via "
                             "attach_live_plane"))
            elif name == "SLOEngine" and posix != _SLO_HOME:
                hits.append((node.lineno,
                             "SLOEngine(...) constructed outside "
                             "telemetry/slo.py — use "
                             "build_slo_engine"))
            elif name == "Thread" and _top(rel) in ("core", "runtime") \
                    and isinstance(fn, ast.Attribute) \
                    and isinstance(fn.value, ast.Name) \
                    and fn.value.id == "threading":
                hits.append((node.lineno,
                             "threading.Thread spawned in the "
                             "compiled round path — host threads "
                             "must not live next to the round loop"))
            elif name == "start_new_thread":
                hits.append((node.lineno,
                             "start_new_thread in a production "
                             "module — spawn threads only through "
                             "sanctioned facilities"))
    return hits


LEGACY_RULES = [
    Rule("raw-clock",
         "time.time()/perf_counter() outside telemetry/",
         _check_raw_clock),
    Rule("probe-transfer-span",
         'probe host transfer outside span("metrics_host")',
         _check_probe_transfer_span),
    Rule("host-sync",
         "device sync on the host hot path outside a telemetry span",
         _check_host_sync),
    Rule("np-on-tracer",
         "np.asarray/np.array inside a traced closure",
         _check_np_on_tracer),
    Rule("python-rng",
         "stdlib/NumPy RNG in compiled scope",
         _check_python_rng),
    Rule("noise-confinement",
         "raw jax.random.PRNGKey/normal noise call outside privacy/",
         _check_noise_confinement),
    Rule("raw-devices",
         "raw jax.devices()/jax.local_devices() inside telemetry/",
         _check_raw_devices),
    Rule("chaos-confinement",
         "data/chaos.py imported by a production module",
         _check_chaos_confinement),
    Rule("arrival-confinement",
         "arrival-process injection outside tests/benches/scripts",
         _check_arrival_confinement),
    Rule("fedservice-confinement",
         "fedservice/ daemon imported by a production module",
         _check_fedservice_confinement),
    Rule("live-confinement",
         "socket/HTTP-server/thread use outside telemetry/live.py",
         _check_live_confinement),
    Rule("inline-partition-spec",
         "PartitionSpec/NamedSharding built outside parallel/",
         _check_inline_partition_spec),
    Rule("checkpoint-mesh-route",
         "checkpoint placement not built by parallel.mesh constructors",
         _check_checkpoint_mesh_route),
    Rule("byte-literal",
         "inline byte-width multiply in runtime/telemetry accounting",
         _check_byte_literal),
    Rule("knob-mutation",
         "compression knob written outside autopilot's re-plan API",
         _check_knob_mutation),
    Rule("mutable-default-arg",
         "mutable default argument",
         _check_mutable_default),
]


LEGACY_RULES_BY_NAME = {r.name: r for r in LEGACY_RULES}
