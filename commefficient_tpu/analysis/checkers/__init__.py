"""The flowlint rule registry — both tiers, declaratively.

* :data:`LEGACY_RULES` — the per-file rules migrated verbatim from
  the grep-era ``analysis/lint.py`` (same names, same findings, same
  waivers; pinned identical by tests/test_flowlint.py).
* :data:`FLOW_CHECKERS` — the whole-program checkers that need the
  call graph / symbol table: trace-purity, prng-keys,
  wire-dtype-crossing, lock-confinement, causal-confinement.

``scripts/audit.py`` runs both tiers and gates them through the same
baseline; ``# audit: allow(<rule>)`` waivers work identically for
either tier.
"""

from commefficient_tpu.analysis.checkers.legacy import (  # noqa: F401
    COMPILED_SCOPE,
    HOST_HOT_PATH,
    LEGACY_RULES,
    LEGACY_RULES_BY_NAME,
)
from commefficient_tpu.analysis.checkers.causal import (
    CHECKER as CAUSAL_CONFINEMENT,
)
from commefficient_tpu.analysis.checkers.locks import (
    CHECKER as LOCK_CONFINEMENT,
)
from commefficient_tpu.analysis.checkers.prng import (
    CHECKER as PRNG_KEYS,
)
from commefficient_tpu.analysis.checkers.purity import (
    CHECKER as TRACE_PURITY,
)
from commefficient_tpu.analysis.checkers.wire import (
    CHECKER as WIRE_DTYPE_CROSSING,
)

FLOW_CHECKERS = [
    TRACE_PURITY,
    PRNG_KEYS,
    WIRE_DTYPE_CROSSING,
    LOCK_CONFINEMENT,
    CAUSAL_CONFINEMENT,
]

FLOW_CHECKERS_BY_NAME = {c.name: c for c in FLOW_CHECKERS}
FLOW_RULE_NAMES = sorted(FLOW_CHECKERS_BY_NAME)
