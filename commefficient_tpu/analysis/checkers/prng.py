"""prng-keys: PRNG-key discipline by intra-function def-use analysis.

DP soundness (PR 16) rests on every key being split/folded into
*disjoint* streams and each stream consumed exactly once — reusing a
parent key after deriving a child re-releases the same randomness the
accountant already charged, and an unconsumed ``split`` result means
some stream the plan budgeted for was silently dropped. This checker
runs a linear (source-order, branch-insensitive) def-use pass over
every function in the key-handling zones — ``privacy/``,
``data/chaos.py``, ``asyncfed/`` — tracking variables that hold keys
(``PRNGKey``/``split``/``fold_in``/``noise_stream``/
``round_noise_key`` results, plus ``rng``/``key``-named parameters)
and flags:

* any use of a key after it was passed to ``split`` (the parent is
  dead once split — JAX's own key contract);
* a *draw* from a key that earlier served as a ``fold_in`` parent
  (deriving a child then drawing from the parent overlaps streams —
  repeated ``fold_in`` of the same parent stays legal: that is the
  disjoint-stream idiom);
* two draws from the same key variable (double consumption);
* a ``split`` result never consumed (``_``-prefixed names opt out).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from commefficient_tpu.analysis.flow import FlowChecker, Program

_SCOPE_TOPS = ("privacy", "asyncfed")
_SCOPE_FILES = ("data/chaos.py",)

#: jax.random draws that consume a key (first positional arg)
_DRAWS = {"normal", "uniform", "bernoulli", "randint",
          "truncated_normal", "laplace", "gumbel", "cauchy",
          "permutation", "choice", "categorical", "bits", "gamma",
          "beta", "exponential", "poisson", "dirichlet"}
#: in-package draw wrappers that consume the key they are handed
_WRAPPER_DRAWS = {"gaussian_noise", "add_table_noise"}
_MAKERS = {"PRNGKey", "key", "noise_stream", "round_noise_key"}
_KEYISH_PARAM = ("rng", "key")


def _in_scope(rel: str) -> bool:
    top = rel.split("/")[0]
    return top in _SCOPE_TOPS or rel in _SCOPE_FILES


def _call_leaf(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _keyish_name(name: str) -> bool:
    low = name.lower()
    return any(low == k or low.endswith(k) or low.startswith(k + "_")
               for k in _KEYISH_PARAM)


def _analyze(fn) -> List[Tuple[int, str]]:
    #: var -> "fresh" | "split" | "folded" | "drawn"
    state: Dict[str, str] = {}
    #: split-result var -> [def line, used?]
    split_results: Dict[str, List] = {}
    hits: List[Tuple[int, str]] = []
    own_nested = {id(g.node) for g in fn.nested}

    args = fn.node.args
    for a in (list(args.posonlyargs) + list(args.args)
              + list(args.kwonlyargs)):
        if _keyish_name(a.arg):
            state[a.arg] = "fresh"

    def mark_use(name: str, line: int, draw: bool):
        if name in split_results:
            split_results[name][1] = True
        s = state.get(name)
        if s is None:
            return
        if s == "split":
            hits.append((line, f"key '{name}' used after split() — "
                         "the parent key is dead once split"))
        elif s == "folded" and draw:
            hits.append((line, f"draw from key '{name}' after it was "
                         "a fold_in parent — parent and child "
                         "streams overlap"))
        elif s == "drawn" and draw:
            hits.append((line, f"key '{name}' consumed by two draws "
                         "— each stream is single-use"))
        if draw:
            state[name] = "drawn"

    def handle_call(call: ast.Call):
        leaf = _call_leaf(call)
        tgt = (call.args[0].id if call.args
               and isinstance(call.args[0], ast.Name) else None)
        if leaf == "split" and tgt is not None:
            mark_use(tgt, call.lineno, draw=False)
            state[tgt] = "split"
        elif leaf == "fold_in" and tgt is not None:
            mark_use(tgt, call.lineno, draw=False)
            if state.get(tgt) in ("fresh", "folded"):
                state[tgt] = "folded"
        elif leaf in _DRAWS | _WRAPPER_DRAWS and tgt is not None:
            mark_use(tgt, call.lineno, draw=True)
        else:
            for a in call.args:
                if isinstance(a, ast.Name) and a.id in state:
                    mark_use(a.id, call.lineno, draw=False)

    def process_expr(expr):
        """Calls inside ``expr`` in walk order, then bare Name
        references to split results (returns/tuples count as
        consumption)."""
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Call):
                handle_call(sub)
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Name) and sub.id in split_results:
                split_results[sub.id][1] = True

    def key_kind(value) -> Optional[str]:
        if isinstance(value, ast.Call):
            leaf = _call_leaf(value)
            if leaf in _MAKERS or leaf == "fold_in":
                return "fresh"
            if leaf == "split":
                return "split_result"
        if isinstance(value, ast.Subscript) \
                and isinstance(value.value, ast.Name) \
                and value.value.id in split_results:
            return "fresh"
        return None

    def handle_assign(node: ast.Assign):
        process_expr(node.value)
        kind = key_kind(node.value)
        if kind is None:
            return
        names: List[str] = []
        for t in node.targets:
            if isinstance(t, ast.Name):
                names.append(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                names.extend(e.id for e in t.elts
                             if isinstance(e, ast.Name))
        for n in names:
            state[n] = "fresh"
            if kind == "split_result" and not n.startswith("_"):
                split_results[n] = [node.lineno, False]

    def walk_stmts(body):
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef,
                                 ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # nested defs analyze separately
            if isinstance(stmt, ast.Assign):
                handle_assign(stmt)
            elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                if stmt.value is not None:
                    process_expr(stmt.value)
            elif isinstance(stmt, (ast.Return, ast.Expr)):
                if stmt.value is not None:
                    process_expr(stmt.value)
                    if isinstance(stmt, ast.Return) \
                            and isinstance(stmt.value, ast.Name):
                        mark_use(stmt.value.id, stmt.lineno,
                                 draw=False)
            elif isinstance(stmt, ast.If):
                process_expr(stmt.test)
                walk_stmts(stmt.body)
                walk_stmts(stmt.orelse)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                process_expr(stmt.iter)
                walk_stmts(stmt.body)
                walk_stmts(stmt.orelse)
            elif isinstance(stmt, ast.While):
                process_expr(stmt.test)
                walk_stmts(stmt.body)
                walk_stmts(stmt.orelse)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    process_expr(item.context_expr)
                walk_stmts(stmt.body)
            elif isinstance(stmt, ast.Try):
                walk_stmts(stmt.body)
                for h in stmt.handlers:
                    walk_stmts(h.body)
                walk_stmts(stmt.orelse)
                walk_stmts(stmt.finalbody)
            else:
                for sub in ast.walk(stmt):
                    if isinstance(sub, ast.Call):
                        handle_call(sub)

    walk_stmts(fn.node.body)

    for name in sorted(split_results):
        dline, used = split_results[name]
        if not used:
            hits.append((dline, f"split result '{name}' never "
                         "consumed — a budgeted stream was silently "
                         "dropped"))
    return hits


def check(program: Program) -> List[Tuple[str, int, str]]:
    out = []
    for fq in sorted(program.functions):
        fn = program.functions[fq]
        rel = fn.module.rel.as_posix()
        if not _in_scope(rel):
            continue
        for line, msg in _analyze(fn):
            out.append((rel, line, msg))
    return out


CHECKER = FlowChecker(
    "prng-keys",
    "PRNG key reused after split/fold or split stream dropped",
    check)
