"""wire-dtype-crossing: wire-format casts and byte tables have owners.

The quantized wire story (int8/fp8 sketches, bf16 canaries) stays
auditable because exactly two modules are allowed to *cross* dtypes
onto the wire format: ``ops/quant.py`` (encode/decode) and
``parallel/wire.py`` (the collective that moves the encoded bytes).
A stray ``.astype(jnp.int8)`` anywhere else is an unaccounted
quantization — it changes recovery error and wire bytes without the
autopilot, the accountant, or the perf gate seeing it. Likewise the
byte-width tables (``{"int8": 1, ...}``) live in ``accounting.py``
and ``config.py`` only; a private copy silently forks the pricing.

Flagged outside the owners:

* ``.astype(<wire dtype>)`` / ``lax.convert_element_type(x, <wire>)``
  where the wire dtypes are int8, the fp8 family, and bfloat16
  (uint8 is exempt: hash-byte packing, not a wire format);
* dict literals mapping ≥2 wire-dtype names to numeric widths.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Tuple

from commefficient_tpu.analysis.flow import FlowChecker, Program

#: modules allowed to cast to wire dtypes
_CAST_OWNERS = {"ops/quant.py", "parallel/wire.py"}
#: modules allowed to hold dtype→bytes tables
_TABLE_OWNERS = _CAST_OWNERS | {"accounting.py", "config.py"}

_WIRE_DTYPES = {"int8", "bfloat16", "float8_e4m3fn", "float8_e5m2",
                "float8_e4m3", "float8_e4m3b11fnuz", "fp8_e4m3",
                "fp8_e5m2"}
_TABLE_KEYS = _WIRE_DTYPES | {"bf16", "fp8", "f32", "float32",
                              "f16", "float16"}


def _dtype_name(expr) -> Optional[str]:
    """The dtype an expression names: ``jnp.int8`` → "int8",
    ``"int8"`` → "int8", bare ``int8`` → "int8"."""
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return expr.value
    return None


def check(program: Program) -> List[Tuple[str, int, str]]:
    out = []
    for rel in sorted(program.modules):
        mod = program.modules[rel]
        if mod.tree is None:
            continue
        cast_owner = rel in _CAST_OWNERS
        table_owner = rel in _TABLE_OWNERS
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and not cast_owner:
                f = node.func
                if isinstance(f, ast.Attribute) \
                        and f.attr == "astype" and node.args:
                    dt = _dtype_name(node.args[0])
                    if dt in _WIRE_DTYPES:
                        out.append((rel, node.lineno,
                                    f".astype({dt}) outside "
                                    "ops/quant.py and "
                                    "parallel/wire.py — wire-format "
                                    "casts must go through the "
                                    "quantizer so bytes and error "
                                    "are accounted"))
                elif isinstance(f, ast.Attribute) \
                        and f.attr == "convert_element_type" \
                        and len(node.args) >= 2:
                    dt = _dtype_name(node.args[1])
                    if dt in _WIRE_DTYPES:
                        out.append((rel, node.lineno,
                                    f"convert_element_type(..., {dt})"
                                    " outside ops/quant.py and "
                                    "parallel/wire.py — wire-format "
                                    "casts must go through the "
                                    "quantizer"))
            elif isinstance(node, ast.Dict) and not table_owner:
                keys = [k.value for k in node.keys
                        if isinstance(k, ast.Constant)
                        and isinstance(k.value, str)]
                if len(keys) >= 2 and len(keys) == len(node.keys) \
                        and all(k in _TABLE_KEYS for k in keys) \
                        and all(isinstance(v, ast.Constant)
                                and type(v.value) in (int, float)
                                for v in node.values):
                    out.append((rel, node.lineno,
                                "private wire-width byte table — "
                                "use accounting.dtype_bytes so one "
                                "table prices the wire"))
    return out


CHECKER = FlowChecker(
    "wire-dtype-crossing",
    "wire-format cast or byte table outside quant/wire owners",
    check)
