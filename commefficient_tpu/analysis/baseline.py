"""Audit report assembly + diff against the committed baseline.

``audit_baseline.json`` (repo root) pins, per program: the trace
fingerprint, the collective inventory, donation counts, and the
transfer count — plus the repo's *waived* lint violations. The tier-1
gate (tests/test_audit.py) and ``scripts/audit.py --baseline`` diff a
fresh report against it, so any new collective, a dropped donation, a
new host transfer, a retrace, or a new waiver is a visible failure
until the change is intentional and the baseline is refreshed with
``python scripts/audit.py --write-baseline``.

Hard invariant failures (``report["failures"]``, unwaived lint hits)
fail regardless of the baseline — they can never be baselined in.
"""

from __future__ import annotations

import json
from typing import Dict, List

BASELINE_SCHEMA = 1

_PINNED_ENTRY_KEYS = ("fingerprint", "collectives", "donation",
                      "dot_dtypes")


def build_report(program_report: Dict, lint_summary: Dict) -> Dict:
    return {"schema": BASELINE_SCHEMA,
            "jax_version": program_report.get("jax_version"),
            "device_count": program_report.get("device_count"),
            "lint": lint_summary,
            "programs": program_report.get("programs", {}),
            "failures": list(program_report.get("failures", []))
            + [f"lint: {v}" for v in lint_summary.get("unwaived", [])]
            + [f"lint: {v}" for v in lint_summary.get("stale_waivers",
                                                      [])]}


def to_baseline(report: Dict) -> Dict:
    """Strip a full report down to the pinned, committable subset."""
    programs = {}
    for name, entry in report["programs"].items():
        pinned = {k: entry[k] for k in _PINNED_ENTRY_KEYS
                  if k in entry}
        pinned["transfers"] = len(entry.get("transfers", []))
        programs[name] = pinned
    return {"schema": BASELINE_SCHEMA,
            "jax_version": report.get("jax_version"),
            "device_count": report.get("device_count"),
            "lint": {"waived": report["lint"].get("waived", [])},
            "programs": programs}


def diff_against_baseline(report: Dict, baseline: Dict) -> List[str]:
    """Regressions of ``report`` vs ``baseline``. Empty = green."""
    problems = list(report.get("failures", []))
    if baseline.get("schema") != BASELINE_SCHEMA:
        problems.append(f"baseline schema {baseline.get('schema')} != "
                        f"{BASELINE_SCHEMA} — refresh the baseline")
        return problems
    if baseline.get("device_count") != report.get("device_count"):
        problems.append(
            f"device count {report.get('device_count')} != baseline "
            f"{baseline.get('device_count')} — the audit mesh must "
            "match the baseline's (8-device CPU mesh)")
    if baseline.get("jax_version") != report.get("jax_version"):
        problems.append(
            f"jax {report.get('jax_version')} != baseline "
            f"{baseline.get('jax_version')}: fingerprints are only "
            "comparable within one jax version — refresh the baseline")

    waived_now = set(report["lint"].get("waived", []))
    waived_then = set(baseline.get("lint", {}).get("waived", []))
    for v in sorted(waived_now - waived_then):
        problems.append(f"new lint waiver (refresh baseline to "
                        f"accept): {v}")
    for v in sorted(waived_then - waived_now):
        problems.append(f"stale baseline waiver (violation gone — "
                        f"refresh baseline): {v}")

    now = report.get("programs", {})
    then = baseline.get("programs", {})
    for name in sorted(set(then) - set(now)):
        problems.append(f"{name}: program missing from audit (in "
                        "baseline)")
    for name in sorted(set(now) - set(then)):
        problems.append(f"{name}: new program not in baseline")
    for name in sorted(set(now) & set(then)):
        fresh, pinned = now[name], then[name]
        if fresh.get("fingerprint") != pinned.get("fingerprint"):
            problems.append(
                f"{name}: trace fingerprint changed "
                f"({pinned.get('fingerprint', '')[:12]} -> "
                f"{fresh.get('fingerprint', '')[:12]}) — program "
                "drift or retrace; refresh the baseline if "
                "intentional")
        if fresh.get("collectives") != pinned.get("collectives"):
            problems.append(
                f"{name}: collective inventory changed: "
                f"{pinned.get('collectives')} -> "
                f"{fresh.get('collectives')}")
        if fresh.get("donation") != pinned.get("donation"):
            problems.append(
                f"{name}: donation coverage changed: "
                f"{pinned.get('donation')} -> {fresh.get('donation')}")
        if len(fresh.get("transfers", [])) != pinned.get("transfers",
                                                         0):
            problems.append(
                f"{name}: host transfer count changed "
                f"({pinned.get('transfers', 0)} -> "
                f"{len(fresh.get('transfers', []))})")
        if fresh.get("dot_dtypes") != pinned.get("dot_dtypes"):
            problems.append(
                f"{name}: dot/conv dtype inventory changed: "
                f"{pinned.get('dot_dtypes')} -> "
                f"{fresh.get('dot_dtypes')}")
    return problems


def load_baseline(path) -> Dict:
    with open(path) as f:
        return json.load(f)


def save_baseline(report: Dict, path) -> None:
    with open(path, "w") as f:
        json.dump(to_baseline(report), f, indent=1, sort_keys=True)
        f.write("\n")
