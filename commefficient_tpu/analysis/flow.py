"""flowlint: call-graph + dataflow static analysis over the package.

The grep-era linter (now the *legacy* per-file rules in
``analysis/checkers/legacy.py``) sees one line at a time; the
invariants the daemon era depends on are *reachability* properties —
"no clock call is reachable from a jit root", "this attribute is only
written under that lock" — that need a symbol table and a call graph.
This module is that engine:

1. **Module table** — every ``.py`` under the package root is parsed
   once into a :class:`ModuleInfo` (source, AST, import aliases).
2. **Function table** — every def (top-level, method, nested) becomes
   a :class:`FunctionInfo` with a stable qualified name
   (``core/rounds.py::build_client_round.<locals>.emit``).
3. **Call graph** — conservative edges: direct calls resolved through
   import aliases and from-imports, ``self.m()`` dispatch through the
   enclosing class and its in-package bases, single-candidate method
   dispatch by attribute name, and *reference* edges for functions
   passed as values (the jax higher-order idiom: ``vmap(f)``,
   ``lax.scan(step, ...)``, ``shard_map(body, ...)``).
4. **Roots** — jit roots (functions passed to ``jax.jit``/``pjit``/
   ``pl.pallas_call``, ``@jit``-decorated defs, and every function
   *defined inside* a builder whose call result is jitted — the
   ``jax.jit(build_client_round(cfg, ...))`` pattern) and thread
   roots (``Thread(target=...)``, ``do_*`` handlers on
   ``BaseHTTPRequestHandler`` subclasses, ``sys.excepthook``
   assignments).
5. **Checkers** — :data:`commefficient_tpu.analysis.checkers
   .FLOW_CHECKERS` run over the program; findings use the same
   :class:`Violation` shape, ``# audit: allow(<rule>)`` waivers and
   baseline gating as the legacy rules, so ``scripts/audit.py`` and
   the tier-1 gate treat both tiers uniformly.

The engine is pure stdlib ``ast`` — no jax import, so
``scripts/audit.py --lint-only`` stays instant — and budgeted: a full
build + all checkers on the whole repo must stay under 10 s
(tests/test_flowlint.py pins it).
"""

from __future__ import annotations

import ast
import pathlib
import re
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Set, Tuple

PKG_ROOT = pathlib.Path(__file__).resolve().parents[1]

#: the package's import name — stripped from absolute imports so
#: ``commefficient_tpu.core.rounds`` and a fixture tree's bare
#: ``core.rounds`` resolve identically
PKG_NAME = PKG_ROOT.name

WAIVER_RE = re.compile(r"#\s*audit:\s*allow\(([a-zA-Z0-9_\-, ]+)\)")


@dataclass
class Violation:
    rule: str
    path: str          # relative to the scanned root
    line: int
    message: str
    waived: bool = False

    def __str__(self):
        w = " [waived]" if self.waived else ""
        return f"{self.path}:{self.line}: {self.rule}: {self.message}{w}"


@dataclass
class Rule:
    """A per-file rule (the legacy tier): no cross-module context.
    ``check(rel_path, source lines, parsed tree) -> [(line, msg)]``."""
    name: str
    description: str
    check: Callable[[pathlib.PurePath, List[str], ast.AST],
                    List[Tuple[int, str]]]


@dataclass
class FlowChecker:
    """A whole-program checker (the flow tier).
    ``check(program) -> [(rel_path_str, line, msg)]``."""
    name: str
    description: str
    check: Callable[["Program"], List[Tuple[str, int, str]]]


def waived_rules_at(lines: List[str], line: int) -> Set[str]:
    """Rules waived at 1-based ``line``: an ``# audit: allow(...)``
    comment on the line itself or the line directly above."""
    out: Set[str] = set()
    for lno in (line, line - 1):
        if 1 <= lno <= len(lines):
            m = WAIVER_RE.search(lines[lno - 1])
            if m:
                out.update(x.strip() for x in m.group(1).split(","))
    return out


# --- module / function tables ------------------------------------------


class ModuleInfo:
    """One parsed source file: AST + import resolution context."""

    def __init__(self, rel: pathlib.PurePath, path: pathlib.Path,
                 text: str):
        self.rel = rel
        self.path = path
        self.text = text
        self.lines = text.splitlines()
        self.tree: Optional[ast.AST] = None
        self.syntax_error: Optional[SyntaxError] = None
        #: local alias -> dotted module path (``import a.b as c``)
        self.imports: Dict[str, str] = {}
        #: local name -> (dotted module, original name) from-imports
        self.import_names: Dict[str, Tuple[str, str]] = {}
        #: top-level function name -> FunctionInfo
        self.functions: Dict[str, "FunctionInfo"] = {}
        #: class name -> ClassInfo
        self.classes: Dict[str, "ClassInfo"] = {}
        try:
            self.tree = ast.parse(text, filename=str(path))
        except SyntaxError as e:
            self.syntax_error = e

    @property
    def dotted(self) -> str:
        parts = list(self.rel.parts)
        if parts[-1].endswith(".py"):
            parts[-1] = parts[-1][:-3]
        if parts[-1] == "__init__":
            parts = parts[:-1]
        return ".".join(parts)

    def _collect_imports(self):
        pkg_parts = list(self.rel.parts[:-1])  # containing package
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    local = a.asname or a.name.split(".")[0]
                    self.imports[local] = _strip_pkg(
                        a.name if a.asname else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    base = pkg_parts[:len(pkg_parts) - (node.level - 1)]
                    mod = ".".join(base + ([node.module]
                                           if node.module else []))
                else:
                    mod = _strip_pkg(node.module or "")
                for a in node.names:
                    local = a.asname or a.name
                    self.import_names[local] = (mod, a.name)


def _strip_pkg(dotted: str) -> str:
    if dotted == PKG_NAME:
        return ""
    if dotted.startswith(PKG_NAME + "."):
        return dotted[len(PKG_NAME) + 1:]
    return dotted


class ClassInfo:
    def __init__(self, module: ModuleInfo, node: ast.ClassDef):
        self.module = module
        self.node = node
        self.name = node.name
        #: method name -> FunctionInfo
        self.methods: Dict[str, "FunctionInfo"] = {}
        #: base-class name expressions, as dotted strings
        self.bases: List[str] = [b for b in
                                 (_dotted_of(e) for e in node.bases)
                                 if b]


class FunctionInfo:
    def __init__(self, module: ModuleInfo, node, qual: str,
                 cls: Optional[ClassInfo], parent: Optional[
                     "FunctionInfo"]):
        self.module = module
        self.node = node
        self.qual = qual                    # dotted within the module
        self.cls = cls
        self.parent = parent
        self.nested: List["FunctionInfo"] = []
        #: resolved outgoing edges (call + reference), filled by
        #: Program._link
        self.edges: Set[str] = set()

    @property
    def fq(self) -> str:
        return f"{self.module.rel.as_posix()}::{self.qual}"

    def all_nested(self) -> List["FunctionInfo"]:
        out = []
        stack = list(self.nested)
        while stack:
            f = stack.pop()
            out.append(f)
            stack.extend(f.nested)
        return out


def _dotted_of(expr) -> Optional[str]:
    """``a.b.c`` expression -> "a.b.c" (None for anything else)."""
    parts = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
        return ".".join(reversed(parts))
    return None


#: jax transforms that wrap a function and pass tracing through —
#: ``jit(value_and_grad(f))`` roots f, ``vmap(g)`` inside a traced
#: body reaches g
_PASSTHROUGH_WRAPPERS = {
    "value_and_grad", "grad", "vmap", "pmap", "checkpoint", "remat",
    "named_call", "custom_vjp", "custom_jvp", "partial", "shard_map",
}

#: higher-order jax calls whose function-valued args execute traced
_HIGHER_ORDER = {
    "scan", "while_loop", "cond", "fori_loop", "switch", "map",
    "associative_scan", "custom_root", "custom_linear_solve",
} | _PASSTHROUGH_WRAPPERS

_JIT_NAMES = {"jit", "pjit"}
_PALLAS_NAMES = {"pallas_call"}
_THREAD_CTORS = {"Thread"}
_HANDLER_BASES = {"BaseHTTPRequestHandler", "SimpleHTTPRequestHandler"}


class Program:
    """The whole-package analysis context handed to flow checkers."""

    def __init__(self, root: pathlib.Path):
        self.root = root
        self.modules: Dict[str, ModuleInfo] = {}          # rel posix
        self.functions: Dict[str, FunctionInfo] = {}      # fq
        self._by_dotted: Dict[str, ModuleInfo] = {}
        self._methods_by_name: Dict[str, List[FunctionInfo]] = {}
        self.jit_roots: Set[str] = set()
        self.thread_roots: Set[str] = set()
        self._traced: Optional[Set[str]] = None
        self._threaded: Optional[Set[str]] = None
        self._ctor_maps: Dict[int, Dict[str, Optional[str]]] = {}
        for path in sorted(root.rglob("*.py")):
            rel = path.relative_to(root)
            mod = ModuleInfo(rel, path, path.read_text())
            self.modules[rel.as_posix()] = mod
            if mod.tree is None:
                continue
            mod._collect_imports()
            self._by_dotted[mod.dotted] = mod
            self._collect_defs(mod)
        for mod in self.modules.values():
            if mod.tree is not None:
                self._link(mod)
                self._find_roots(mod)

    # ----------------------------------------------------- table build

    def _collect_defs(self, mod: ModuleInfo):
        def visit(node, qual, cls, parent):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    q = (f"{qual}.<locals>.{child.name}" if parent
                         else f"{qual}.{child.name}" if qual
                         else child.name)
                    fn = FunctionInfo(mod, child, q, cls, parent)
                    self.functions[fn.fq] = fn
                    if parent is not None:
                        parent.nested.append(fn)
                    elif cls is not None:
                        cls.methods[child.name] = fn
                        self._methods_by_name.setdefault(
                            child.name, []).append(fn)
                    else:
                        mod.functions[child.name] = fn
                    visit(child, q, cls, fn)
                elif isinstance(child, ast.ClassDef):
                    if parent is None and cls is None:
                        ci = ClassInfo(mod, child)
                        mod.classes[child.name] = ci
                        visit(child, child.name, ci, None)
                    else:  # nested class: index methods, no dispatch
                        visit(child, f"{qual}.{child.name}", cls,
                              parent)

        visit(mod.tree, "", None, None)

    # ----------------------------------------------------- resolution

    def module_of(self, dotted: str) -> Optional[ModuleInfo]:
        dotted = _strip_pkg(dotted)
        return self._by_dotted.get(dotted)

    def _class_of(self, mod: ModuleInfo, name: str) \
            -> Optional[ClassInfo]:
        if name in mod.classes:
            return mod.classes[name]
        if name in mod.import_names:
            src, orig = mod.import_names[name]
            target = self.module_of(src)
            if target is not None:
                return target.classes.get(orig)
        return None

    def _method_on(self, ci: ClassInfo, name: str, _depth=0) \
            -> Optional[FunctionInfo]:
        if name in ci.methods:
            return ci.methods[name]
        if _depth > 4:
            return None
        for base in ci.bases:
            bci = self._class_of(ci.module, base.split(".")[-1])
            if bci is not None:
                hit = self._method_on(bci, name, _depth + 1)
                if hit is not None:
                    return hit
        return None

    def _ctor_map(self, owner) -> Dict[str, Optional[str]]:
        """name -> constructor leaf name for every ``name = Ctor(...)``
        assignment in ``owner``'s scope (FunctionInfo or ModuleInfo);
        None marks names assigned ambiguously / from non-calls. One
        walk per scope, memoized — lookups must stay O(1)."""
        key = id(owner)
        cached = self._ctor_maps.get(key)
        if cached is not None:
            return cached
        tree = owner.node if isinstance(owner, FunctionInfo) \
            else owner.tree
        m: Dict[str, Optional[str]] = {}
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign):
                continue
            leaf = None
            if isinstance(node.value, ast.Call):
                f = node.value.func
                leaf = (f.id if isinstance(f, ast.Name)
                        else f.attr if isinstance(f, ast.Attribute)
                        else None)
            for t in node.targets:
                if isinstance(t, ast.Name):
                    if t.id in m and m[t.id] != leaf:
                        m[t.id] = None
                    else:
                        m[t.id] = leaf
        self._ctor_maps[key] = m
        return m

    def _local_ctor_class(self, name: str,
                          fn: Optional[FunctionInfo],
                          mod: ModuleInfo) -> Optional[ClassInfo]:
        """The class a local variable was constructed from, when every
        visible ``name = Ctor(...)`` assignment agrees: one-hop local
        type inference for method dispatch."""
        scope = fn
        while scope is not None:
            m = self._ctor_map(scope)
            if name in m:
                leaf = m[name]
                return None if leaf is None \
                    else self._class_of(mod, leaf)
            scope = scope.parent
        m = self._ctor_map(mod)
        if name in m and m[name] is not None:
            return self._class_of(mod, m[name])
        return None

    def resolve(self, expr, fn: Optional[FunctionInfo],
                mod: ModuleInfo) -> Optional[FunctionInfo]:
        """Resolve a callee/reference expression to a FunctionInfo, or
        None (external / ambiguous — conservatively no edge)."""
        if isinstance(expr, ast.Name):
            name = expr.id
            # nested function visible in the enclosing scope chain
            scope = fn
            while scope is not None:
                for g in scope.nested:
                    if g.node.name == name:
                        return g
                scope = scope.parent
            if name in mod.functions:
                return mod.functions[name]
            if name in mod.classes:
                return mod.classes[name].methods.get("__init__")
            if name in mod.import_names:
                src, orig = mod.import_names[name]
                target = self.module_of(src)
                if target is not None:
                    if orig in target.functions:
                        return target.functions[orig]
                    if orig in target.classes:
                        return target.classes[orig].methods.get(
                            "__init__")
            return None
        if isinstance(expr, ast.Attribute):
            base, attr = expr.value, expr.attr
            # self.m() through the enclosing class (+ bases)
            if isinstance(base, ast.Name) and base.id in ("self",
                                                          "cls") \
                    and fn is not None and fn.cls is not None:
                return self._method_on(fn.cls, attr)
            # module alias: rounds.build_x() / pkg.core.rounds.f()
            dotted = _dotted_of(base)
            if dotted is not None:
                target = None
                head = dotted.split(".")[0]
                if head in mod.imports:
                    target = self.module_of(
                        ".".join([mod.imports[head]]
                                 + dotted.split(".")[1:]))
                    if target is None:
                        # alias of an EXTERNAL module (jnp, np, …):
                        # its attributes are never package functions —
                        # no dispatch (jnp.take must not resolve to
                        # some class's .take method)
                        return None
                if target is None:
                    target = self.module_of(dotted)
                if target is not None:
                    if attr in target.functions:
                        return target.functions[attr]
                    if attr in target.classes:
                        return target.classes[attr].methods.get(
                            "__init__")
                    return None
                # ClassName.method on an in-scope class
                ci = self._class_of(mod, dotted.split(".")[-1])
                if ci is not None:
                    return self._method_on(ci, attr)
            # local constructor-type inference: `x = ClassName(...)`
            # in the enclosing function (or at module level), then
            # `x.m()` dispatches to ClassName.m — no global
            # single-candidate dispatch (an array's `.take()` must
            # not resolve to an unrelated class's method)
            if isinstance(base, ast.Name):
                ci = self._local_ctor_class(base.id, fn, mod)
                if ci is not None:
                    return self._method_on(ci, attr)
        return None

    # ----------------------------------------------------- call graph

    def _link(self, mod: ModuleInfo):
        """Fill ``FunctionInfo.edges`` for every function in ``mod``:
        direct calls plus reference edges for function-valued names
        (passed to vmap/scan/… or stored — address-taken is an edge)."""
        def link_body(fn: FunctionInfo):
            own_nested = {id(g.node) for g in fn.nested}

            def walk(node):
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)) \
                            and id(child) in own_nested:
                        continue  # nested defs link themselves
                    if isinstance(child, ast.Call):
                        callee = self.resolve(child.func, fn, mod)
                        if callee is not None:
                            fn.edges.add(callee.fq)
                    elif isinstance(child, (ast.Name, ast.Attribute)):
                        ref = self.resolve(child, fn, mod)
                        if ref is not None:
                            fn.edges.add(ref.fq)
                    walk(child)

            walk(fn.node)

        for f in self.functions.values():
            if f.module is mod:
                link_body(f)

    # ----------------------------------------------------- roots

    def _jit_arg_roots(self, arg, fn, mod, depth=0) \
            -> List[FunctionInfo]:
        """Functions rooted by ``jit(<arg>)``: the function itself, or
        — for the builder idiom ``jit(build_round(cfg, ...))`` — every
        function defined inside the builder (its returned closure and
        that closure's helpers all live there)."""
        if depth > 4 or arg is None:
            return []
        direct = self.resolve(arg, fn, mod)
        if direct is not None:
            return [direct]
        if isinstance(arg, ast.Call):
            callee_name = (arg.func.attr
                           if isinstance(arg.func, ast.Attribute)
                           else arg.func.id
                           if isinstance(arg.func, ast.Name) else None)
            if callee_name in _PASSTHROUGH_WRAPPERS and arg.args:
                return self._jit_arg_roots(arg.args[0], fn, mod,
                                           depth + 1)
            builder = self.resolve(arg.func, fn, mod)
            if builder is not None:
                roots = builder.all_nested()
                # builders that `return sibling_builder(...)` — the
                # 2D-mesh variants — root the sibling's closures too
                for n in ast.walk(builder.node):
                    if isinstance(n, ast.Return) \
                            and isinstance(n.value, ast.Call):
                        sib = self.resolve(n.value.func, builder,
                                           builder.module)
                        if sib is not None and sib is not builder:
                            roots.extend(sib.all_nested())
                return roots
        if isinstance(arg, ast.Name) and fn is not None:
            # one-hop local: fn body has `f = <expr>` then `jit(f)`
            assigned = None
            for n in ast.walk(fn.node):
                if isinstance(n, ast.Assign) \
                        and any(isinstance(t, ast.Name)
                                and t.id == arg.id
                                for t in n.targets):
                    assigned = n.value
            if assigned is not None:
                return self._jit_arg_roots(assigned, fn, mod,
                                           depth + 1)
        return []

    def _enclosing(self, mod: ModuleInfo) -> Dict[int, FunctionInfo]:
        """id(AST node) -> innermost enclosing FunctionInfo."""
        owner: Dict[int, FunctionInfo] = {}
        for f in self.functions.values():
            if f.module is not mod:
                continue
            own_nested = {id(g.node) for g in f.nested}

            def mark(node, f=f, own_nested=own_nested):
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)) \
                            and id(child) in own_nested:
                        continue
                    owner[id(child)] = f
                    mark(child)

            mark(f.node)
        return owner

    def _find_roots(self, mod: ModuleInfo):
        owner = self._enclosing(mod)
        for node in ast.walk(mod.tree):
            fn = owner.get(id(node))
            if isinstance(node, ast.Call):
                name = (node.func.attr
                        if isinstance(node.func, ast.Attribute)
                        else node.func.id
                        if isinstance(node.func, ast.Name) else None)
                if name in _JIT_NAMES and node.args:
                    for root in self._jit_arg_roots(node.args[0], fn,
                                                    mod):
                        self.jit_roots.add(root.fq)
                elif name in _PALLAS_NAMES and node.args:
                    for root in self._jit_arg_roots(node.args[0], fn,
                                                    mod):
                        self.jit_roots.add(root.fq)
                elif name in _THREAD_CTORS:
                    for kw in node.keywords:
                        if kw.arg == "target":
                            t = self.resolve(kw.value, fn, mod)
                            if t is not None:
                                self.thread_roots.add(t.fq)
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    tgt = dec.func if isinstance(dec, ast.Call) \
                        else dec
                    dn = _dotted_of(tgt) or ""
                    leaf = dn.split(".")[-1]
                    if leaf in _JIT_NAMES:
                        self._root_def(node)
                    elif leaf == "partial" and isinstance(dec,
                                                          ast.Call) \
                            and dec.args:
                        inner = _dotted_of(dec.args[0]) or ""
                        if inner.split(".")[-1] in _JIT_NAMES:
                            self._root_def(node)
            elif isinstance(node, ast.Assign):
                # sys.excepthook = hook  -> thread-ish root (runs on
                # an arbitrary crashing thread)
                for t in node.targets:
                    if isinstance(t, ast.Attribute) \
                            and t.attr == "excepthook":
                        hook = self.resolve(node.value, fn, mod)
                        if hook is not None:
                            self.thread_roots.add(hook.fq)
        # do_* handlers on HTTP handler subclasses run on the server's
        # worker threads
        for ci in mod.classes.values():
            if any(b.split(".")[-1] in _HANDLER_BASES
                   for b in ci.bases):
                for name, m in ci.methods.items():
                    if name.startswith("do_"):
                        self.thread_roots.add(m.fq)

    def _root_def(self, node):
        for f in self.functions.values():
            if f.node is node:
                self.jit_roots.add(f.fq)
                return

    # ----------------------------------------------------- reachability

    def reachable_from(self, roots: Set[str]) -> Set[str]:
        seen = set()
        stack = [r for r in roots if r in self.functions]
        while stack:
            fq = stack.pop()
            if fq in seen:
                continue
            seen.add(fq)
            stack.extend(e for e in self.functions[fq].edges
                         if e not in seen)
        return seen

    @property
    def traced(self) -> Set[str]:
        """Functions reachable from any jit/pallas root (the roots'
        nested defs included — a closure defined inside a traced body
        is traced when referenced)."""
        if self._traced is None:
            self._traced = self.reachable_from(self.jit_roots)
        return self._traced

    @property
    def threaded(self) -> Set[str]:
        if self._threaded is None:
            self._threaded = self.reachable_from(self.thread_roots)
        return self._threaded


# --- engine entry points -----------------------------------------------


def build_program(root: Optional[pathlib.Path] = None) -> Program:
    return Program(PKG_ROOT if root is None else pathlib.Path(root))


def run_flow(root: Optional[pathlib.Path] = None,
             checkers=None,
             program: Optional[Program] = None) -> List[Violation]:
    """Run the flow-tier checkers; returns all violations, waived
    included (callers gate on ``unwaived``-style filtering, same as
    the legacy tier)."""
    from commefficient_tpu.analysis.checkers import FLOW_CHECKERS
    if program is None:
        program = build_program(root)
    checkers = FLOW_CHECKERS if checkers is None else checkers
    out: List[Violation] = []
    for checker in checkers:
        for rel, line, msg in checker.check(program):
            mod = program.modules.get(rel)
            lines = mod.lines if mod is not None else []
            waived = checker.name in waived_rules_at(lines, line)
            out.append(Violation(checker.name, rel, line, msg,
                                 waived=waived))
    out.sort(key=lambda v: (v.path, v.line, v.rule))
    return out


def run_file_rules(root: Optional[pathlib.Path], rules,
                   program: Optional[Program] = None) \
        -> List[Violation]:
    """Drive the per-file (legacy) rules over every module. Shares
    the parsed module table with the flow tier when ``program`` is
    given, so one parse serves both."""
    if program is None:
        program = build_program(root)
    out: List[Violation] = []
    for rel in sorted(program.modules):
        mod = program.modules[rel]
        if mod.tree is None:
            e = mod.syntax_error
            out.append(Violation("syntax", rel, e.lineno or 0,
                                 f"unparseable: {e.msg}"))
            continue
        for rule in rules:
            for line, msg in rule.check(mod.rel, mod.lines, mod.tree):
                waived = rule.name in waived_rules_at(mod.lines, line)
                out.append(Violation(rule.name, rel, line, msg,
                                     waived=waived))
    out.sort(key=lambda v: (v.path, v.line, v.rule))
    return out
