"""Static-analysis subsystem: compiled-program audits + repo linter.

Two passes, both run by ``scripts/audit.py`` and gated in tier-1 by
``tests/test_audit.py`` against the committed ``audit_baseline.json``:

* ``analysis.program`` lowers the jitted round step for every
  (mode, path) pair on the CPU mesh and statically checks donation
  coverage, the collective inventory (cross-checked against the
  telemetry ledger's byte accounting), host-transfer freedom, bf16
  dot/conv dtypes, and trace-cache fingerprints.
* ``analysis.lint`` is an AST rule engine over the package source —
  the grown-up form of the old grep guards — with
  ``# audit: allow(<rule>)`` inline waivers.
"""

from commefficient_tpu.analysis.baseline import diff_against_baseline
from commefficient_tpu.analysis.lint import run_lint
from commefficient_tpu.analysis.program import run_program_audit

__all__ = ["diff_against_baseline", "run_lint", "run_program_audit"]
