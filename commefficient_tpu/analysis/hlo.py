"""Text-level primitives over StableHLO / compiled-HLO dumps.

Everything here is pure string parsing — no jax import — so the same
helpers serve the CPU-mesh audit, the TPU selftest, and unit tests on
canned program text. Two dialects appear:

* *lowered* text (``jit(f).lower(...).as_text()``): StableHLO. Carries
  the donation attribute ``tf.aliasing_output`` on aliased arguments
  and typed ops like ``stablehlo.dot_general ... : (tensor<2x64xbf16>,
  ...)``.
* *compiled* text (``.compile().as_text()``): post-SPMD optimized HLO.
  The only place GSPMD-induced collectives exist, as op-defining lines
  like ``%all-reduce.7 = f32[64]{0} all-reduce(...)`` (async forms
  split into ``-start``/``-done``; we count starts, not dones), plus
  the ``input_output_alias={ {0}: (1, {}, may-alias) }`` header.
"""

from __future__ import annotations

import hashlib
import re
from dataclasses import dataclass
from typing import Dict, List, Tuple

DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    # fp8 family: quantized sketch tables cross the wire as f8e4m3fn
    "f8e4m3fn": 1, "f8e4m3": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1,
    "f8e4m3fnuz": 1, "f8e5m2fnuz": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

_DTYPES = "|".join(sorted(DTYPE_BYTES, key=len, reverse=True))

# one ``dtype[dims]`` shape inside a compiled-HLO result type; dims may
# be empty (scalar) and carry a layout suffix ``{1,0}`` we ignore
_SHAPE_RE = re.compile(rf"\b({_DTYPES})\[([0-9,]*)\]")

COLLECTIVE_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
                    "collective-permute", "all-to-all")

# op-defining occurrence: ``= <result type> <kind>(``; `-start` is the
# async issue (counted), `-done` just retires it (skipped)
_COLLECTIVE_RE = re.compile(
    r"=\s*(?P<result>\(?[^=()]*?\)?)\s*"
    r"(?P<kind>" + "|".join(COLLECTIVE_KINDS) + r")"
    r"(?P<suffix>-start|-done)?\(")

# host-boundary ops in compiled HLO (op-defining position), plus the
# custom-call escape hatches for host callbacks in either dialect
_TRANSFER_RE = re.compile(
    r"=\s*[^=()]*?\b"
    r"(infeed|outfeed|send|send-done|recv|recv-done)\(")
_CALLBACK_MARKERS = ("xla_python_cpu_callback", "xla_ffi_python",
                     "callback_custom_call", "HostExecute",
                     "annotate_device_placement")

# stablehlo.dot_general / stablehlo.convolution with their typed
# signature ``: (tensor<AxBxbf16>, tensor<...>) -> ...``
_DOT_RE = re.compile(
    r"stablehlo\.(dot_general|convolution)\b[^\n]*?:\s*"
    r"\(tensor<([^>]*)>,\s*tensor<([^>]*)>\)")

# dot_general with its full dimension-number + type signature:
# ``contracting_dims = [1] x [0] : (tensor<2x64xf32>,
# tensor<64x32xf32>) -> tensor<2x32xf32>``
_DOT_FLOPS_RE = re.compile(
    r"stablehlo\.dot_general\b[^\n]*?"
    r"contracting_dims\s*=\s*\[([0-9,\s]*)\]\s*x\s*\[[0-9,\s]*\]"
    r"[^\n]*?:\s*\(tensor<([^>]*)>,\s*tensor<([^>]*)>\)\s*"
    r"->\s*tensor<([^>]*)>")

# convolution with its dim_numbers kernel spec (``x[0, 1, i, o]->``)
# and type signature — the ``o`` position locates the output-feature
# dim of the kernel shape
_CONV_FLOPS_RE = re.compile(
    r"stablehlo\.convolution\b[^\n]*?"
    r"x\[([^\]]*)\]\s*->\s*\[[^\]]*\]"
    r"[^\n]*?:\s*\(tensor<([^>]*)>,\s*tensor<([^>]*)>\)\s*"
    r"->\s*tensor<([^>]*)>")


def parse_shape(dtype: str, dims: str) -> Tuple[str, Tuple[int, ...], int]:
    """``("f32", "5,16")`` -> (dtype, (5, 16), byte size)."""
    shape = tuple(int(x) for x in dims.split(",") if x) if dims else ()
    n = 1
    for s in shape:
        n *= s
    return dtype, shape, n * DTYPE_BYTES[dtype]


@dataclass
class CollectiveOp:
    kind: str                      # "all-reduce", ... (async-start folded in)
    shapes: List[Tuple[str, Tuple[int, ...], int]]  # result components
    line_no: int
    line: str

    @property
    def bytes(self) -> int:
        return sum(b for _, _, b in self.shapes)


def collective_inventory(compiled_text: str) -> List[CollectiveOp]:
    """All collective ops in a compiled-HLO dump, with per-component
    result shapes (variadic all-reduces XLA's combiner pass merged
    stay visible as multi-shape entries)."""
    out = []
    for no, line in enumerate(compiled_text.splitlines(), 1):
        m = _COLLECTIVE_RE.search(line)
        if not m or m.group("suffix") == "-done":
            continue
        shapes = [parse_shape(d, dims)
                  for d, dims in _SHAPE_RE.findall(m.group("result"))]
        out.append(CollectiveOp(m.group("kind"), shapes, no,
                                line.strip()))
    return out


def collective_summary(ops: List[CollectiveOp]) -> Dict:
    counts: Dict[str, int] = {}
    byte_totals: Dict[str, int] = {}
    for op in ops:
        counts[op.kind] = counts.get(op.kind, 0) + 1
        byte_totals[op.kind] = byte_totals.get(op.kind, 0) + op.bytes
    return {"counts": counts, "bytes": byte_totals,
            "total_bytes": sum(byte_totals.values())}


def matching_collective_bytes(ops: List[CollectiveOp], kind: str,
                              dtype: str,
                              shape: Tuple[int, ...]) -> int:
    """Total bytes over result *components* of exactly this dtype+shape
    for one collective kind. Summing (instead of taking the first hit)
    makes an accidentally duplicated op show up as 2x the expected
    bytes. The 2D audit keys reduce-scatter output shards through here
    the same way the 1-D audit keys the aggregation all-reduce."""
    total = 0
    for op in ops:
        if op.kind != kind:
            continue
        total += sum(b for d, s, b in op.shapes
                     if d == dtype and s == tuple(shape))
    return total


def matching_reduce_bytes(ops: List[CollectiveOp], dtype: str,
                          shape: Tuple[int, ...]) -> int:
    """All-reduce bytes of exactly this dtype+shape — the 1-D uplink
    cross-check's selector."""
    return matching_collective_bytes(ops, "all-reduce", dtype, shape)


def host_transfer_lines(text: str) -> List[str]:
    """Lines holding host-boundary ops (infeed/outfeed/send/recv) or
    host-callback custom-calls, in either dialect."""
    hits = []
    for no, line in enumerate(text.splitlines(), 1):
        if _TRANSFER_RE.search(line) or any(
                mark in line for mark in _CALLBACK_MARKERS):
            hits.append(f"{no}: {line.strip()}")
    return hits


def donation_marks(stablehlo_text: str) -> Dict[str, int]:
    """Donation evidence in the lowered module, one mark per donated
    argument. Two forms exist in jax 0.4.x:

    * ``tf.aliasing_output = N`` — jax paired the donated input with
      output N at trace time (single-device / replicated programs);
    * ``jax.buffer_donor = true`` — under GSPMD the output sharding
      isn't known at lowering, so jax defers the pairing to XLA.

    A dropped ``donate_argnums`` produces NEITHER mark; whether a
    deferred donor actually aliased is settled by the compiled
    module's ``input_output_alias`` header (``compiled_alias_count``).
    """
    return {"aliased": stablehlo_text.count("tf.aliasing_output"),
            "donors": stablehlo_text.count("jax.buffer_donor")}


def compiled_alias_count(compiled_text: str) -> int:
    """Entries in the compiled module's ``input_output_alias={...}``
    header — the backend's final word on which donations stuck. The
    header nests braces (``{ {3}: (1, {}, may-alias) }``), so scan to
    the balanced close and count output-index tuples."""
    m = re.search(r"input_output_alias=(\{)", compiled_text)
    if not m:
        return 0
    start = m.end(1) - 1
    depth = 0
    for i in range(start, len(compiled_text)):
        ch = compiled_text[i]
        if ch == "{":
            depth += 1
        elif ch == "}":
            depth -= 1
            if depth == 0:
                region = compiled_text[start:i + 1]
                return len(re.findall(r"\}\s*:", region))
    return 0


def dot_dtype_inventory(stablehlo_text: str) -> Dict[str, int]:
    """dot_general/convolution count by lhs element type in lowered
    text. A bf16 model path must show zero f32 entries — an f32 dot
    there means an operand was silently widened before the contraction
    (2x the FLOP cost and memory traffic of the intended bf16 op)."""
    counts: Dict[str, int] = {}
    for _op, lhs, _rhs in _DOT_RE.findall(stablehlo_text):
        elem = lhs.rsplit("x", 1)[-1] if "x" in lhs else lhs
        counts[elem] = counts.get(elem, 0) + 1
    return counts


def _tensor_dims(spec: str) -> Tuple[Tuple[int, ...], str]:
    """``"2x64xf32"`` -> ((2, 64), "f32"); ``"f32"`` -> ((), "f32")."""
    parts = spec.strip().split("x")
    dtype = parts[-1]
    dims = tuple(int(p) for p in parts[:-1])
    return dims, dtype


def _numel(dims: Tuple[int, ...]) -> int:
    n = 1
    for d in dims:
        n *= d
    return n


def flop_inventory(stablehlo_text: str) -> Dict:
    """Multiply-add FLOP estimate for every dot_general/convolution in
    a lowered module (counted as 2 FLOPs per MAC, the roofline
    convention).

    * dot_general: 2 x numel(result) x prod(lhs contracting dims) —
      exact for any batching/contracting layout, since every result
      element is one length-K inner product.
    * convolution: 2 x numel(result) x (numel(kernel) / O) where O is
      the kernel's output-feature dim (from the ``x[...]`` dim-numbers
      spec) — each output element contracts over the kernel's spatial
      x input-feature extent. Exact for dense convs; an upper bound
      under feature-group counts (rare here).

    Returns ``{"dot_flops", "conv_flops", "total_flops", "dot_count",
    "conv_count", "by_dtype": {elem: flops}}``.
    """
    dot_flops = conv_flops = 0
    dot_count = conv_count = 0
    by_dtype: Dict[str, int] = {}
    for m in _DOT_FLOPS_RE.finditer(stablehlo_text):
        lhs_contract, lhs_spec, _rhs_spec, out_spec = m.groups()
        lhs_dims, dtype = _tensor_dims(lhs_spec)
        out_dims, _ = _tensor_dims(out_spec)
        k = 1
        for idx in (int(x) for x in lhs_contract.split(",") if
                    x.strip()):
            k *= lhs_dims[idx]
        f = 2 * _numel(out_dims) * k
        dot_flops += f
        dot_count += 1
        by_dtype[dtype] = by_dtype.get(dtype, 0) + f
    for m in _CONV_FLOPS_RE.finditer(stablehlo_text):
        kern_spec, _lhs_spec, rhs_spec, out_spec = m.groups()
        rhs_dims, dtype = _tensor_dims(rhs_spec)
        out_dims, _ = _tensor_dims(out_spec)
        o_pos = [p.strip() for p in kern_spec.split(",")].index("o")
        o = rhs_dims[o_pos]
        f = 2 * _numel(out_dims) * (_numel(rhs_dims) // max(o, 1))
        conv_flops += f
        conv_count += 1
        by_dtype[dtype] = by_dtype.get(dtype, 0) + f
    return {"dot_flops": dot_flops, "conv_flops": conv_flops,
            "total_flops": dot_flops + conv_flops,
            "dot_count": dot_count, "conv_count": conv_count,
            "by_dtype": by_dtype}


_LOC_LINE = re.compile(r"^#loc")
_TRAILING_LOC = re.compile(r"\s+loc\(.*\)\s*$")


def fingerprint(stablehlo_text: str) -> str:
    """SHA-256 of the lowered module with location metadata stripped —
    the trace-cache identity of a (mode, path, probes) program. Two
    lowerings of the same builder must agree bit-for-bit; a drifting
    fingerprint means the program retraces (or changed under you)."""
    lines = []
    for raw in stablehlo_text.splitlines():
        line = raw.strip()
        if not line or _LOC_LINE.match(line):
            continue
        lines.append(_TRAILING_LOC.sub("", line))
    return hashlib.sha256("\n".join(lines).encode()).hexdigest()
