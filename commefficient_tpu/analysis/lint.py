"""Lint facade over the flowlint engine (analysis/flow.py).

Historically this module *was* the linter: 900+ lines of per-file AST
rules. The rules now live in ``analysis/checkers/legacy.py`` (moved
verbatim — findings are pinned identical by tests/test_flowlint.py)
and are driven by the shared parse in ``analysis.flow``, alongside
the whole-program flow checkers (trace-purity, prng-keys,
wire-dtype-crossing, lock-confinement). This facade keeps the stable
public surface every caller knows:

* ``run_lint(root, rules)`` — the per-file (legacy) tier only, same
  signature and findings as ever;
* ``run_all(root)`` — both tiers off one parse: legacy rules + flow
  checkers (what ``scripts/audit.py`` gates by default);
* ``unwaived`` / ``stale_waivers`` / ``lint_report`` — gating
  helpers, now aware of both tiers' rule names so a waiver naming a
  flow rule is legal and a typo'd one is still a hard failure.

Waivers: ``# audit: allow(<rule>[, <rule>...])`` on the offending
line or the line directly above suppresses the hit. Waived
violations are still reported (``waived=True``) and recorded in the
audit baseline, so a *new* waiver is a visible diff, not a silent
hole.
"""

from __future__ import annotations

import pathlib
from typing import Dict, List, Optional

from commefficient_tpu.analysis.flow import (  # noqa: F401
    PKG_ROOT,
    WAIVER_RE,
    Program,
    Rule,
    Violation,
    build_program,
    run_file_rules,
    run_flow,
    waived_rules_at,
)
from commefficient_tpu.analysis.checkers import (  # noqa: F401
    COMPILED_SCOPE,
    FLOW_CHECKERS,
    FLOW_CHECKERS_BY_NAME,
    FLOW_RULE_NAMES,
    HOST_HOT_PATH,
    LEGACY_RULES,
)

#: the per-file tier, under its historical name — ``RULES_BY_NAME``
#: spans BOTH tiers so waiver validation knows every legal rule name
ALL_RULES = LEGACY_RULES
RULES_BY_NAME = {r.name: r for r in LEGACY_RULES}
RULES_BY_NAME.update(FLOW_CHECKERS_BY_NAME)


def lint_file(path: pathlib.Path, rel: pathlib.PurePath,
              rules=None) -> List[Violation]:
    """Per-file tier on a single file (no cross-module context, so
    flow checkers don't apply here)."""
    rules = LEGACY_RULES if rules is None else rules
    import ast
    text = path.read_text()
    lines = text.splitlines()
    try:
        tree = ast.parse(text, filename=str(path))
    except SyntaxError as e:
        return [Violation("syntax", str(rel), e.lineno or 0,
                          f"unparseable: {e.msg}")]
    out = []
    for rule in rules:
        for line, msg in rule.check(rel, lines, tree):
            waived = rule.name in waived_rules_at(lines, line)
            out.append(Violation(rule.name, str(rel), line, msg,
                                 waived=waived))
    out.sort(key=lambda v: (v.path, v.line, v.rule))
    return out


def run_lint(root: Optional[pathlib.Path] = None,
             rules=None) -> List[Violation]:
    """Run the per-file (legacy) tier over every .py under ``root``
    (default: the installed package). Returns all violations, waived
    ones included — callers gate on ``unwaived(...)``."""
    rules = LEGACY_RULES if rules is None else rules
    return run_file_rules(root, rules)


def run_all(root: Optional[pathlib.Path] = None,
            program: Optional[Program] = None) -> List[Violation]:
    """Both tiers off one parse: legacy per-file rules + flow
    checkers. This is what the audit gates."""
    if program is None:
        program = build_program(root)
    out = run_file_rules(root, LEGACY_RULES, program=program)
    out.extend(run_flow(root, program=program))
    out.sort(key=lambda v: (v.path, v.line, v.rule))
    return out


def unwaived(violations: List[Violation]) -> List[Violation]:
    return [v for v in violations if not v.waived]


def stale_waivers(root: Optional[pathlib.Path] = None,
                  violations: Optional[List[Violation]] = None,
                  rule_names=None) -> List[str]:
    """Waiver comments that no longer suppress anything. An
    ``allow(R)`` waiver comment at line L covers an R violation at L
    or L + 1 (the inverse of ``waived_rules_at``); when the code it
    excused was fixed or moved, the waiver outlives it and silently
    licenses future regressions on that line — so the audit flags it
    for deletion. Also flags waivers naming unknown rules (typo'd
    waivers waive nothing). Rule names from BOTH tiers are legal;
    when ``violations`` is not supplied, both tiers run so a waiver
    matched only by a flow finding isn't misreported as stale.
    ``rule_names`` restricts staleness checking to those rules (pass
    the legacy names when the flow tier was skipped — its waivers
    can't be judged without its findings); unknown-rule waivers are
    always flagged."""
    root = PKG_ROOT if root is None else pathlib.Path(root)
    if violations is None:
        violations = run_all(root)
    checked = set(RULES_BY_NAME) if rule_names is None \
        else set(rule_names)
    waived_by_path: Dict[str, List[Violation]] = {}
    for v in violations:
        if v.waived:
            waived_by_path.setdefault(v.path, []).append(v)
    out: List[str] = []
    for path in sorted(root.rglob("*.py")):
        rel = path.relative_to(root).as_posix()
        vs = waived_by_path.get(rel, [])
        for i, line in enumerate(path.read_text().splitlines(), 1):
            m = WAIVER_RE.search(line)
            if not m:
                continue
            for rule in sorted(x.strip()
                               for x in m.group(1).split(",")):
                if rule not in RULES_BY_NAME:
                    out.append(f"{rel}:{i}: waiver names unknown "
                               f"rule '{rule}'")
                elif rule not in checked:
                    continue  # that tier didn't run this invocation
                elif not any(v.rule == rule and v.line in (i, i + 1)
                             for v in vs):
                    out.append(f"{rel}:{i}: stale waiver "
                               f"allow({rule}) — no {rule} violation "
                               "on this or the next line")
    return out


def lint_report(violations: List[Violation],
                stale: Optional[List[str]] = None) -> Dict:
    """JSON-able summary for scripts/audit.py and the baseline."""
    return {
        "rules": sorted(RULES_BY_NAME),
        "unwaived": [str(v) for v in unwaived(violations)],
        "waived": sorted(str(v) for v in violations if v.waived),
        "stale_waivers": list(stale if stale is not None else ()),
    }
