"""Program audit: lower + compile the jitted round step for every
(mode, path) pair on the mesh and statically check the invariants the
FetchSGD line promises about the compiled program:

* **donation coverage** — every ``donate_argnums`` leaf is actually
  input-output aliased (a dropped donation doubles peak HBM for the
  client-state buffers at scale, silently);
* **collective inventory** — op counts and byte totals per collective
  kind, with the transmit-aggregation all-reduce cross-checked against
  the telemetry ledger's uplink accounting
  (``cfg.upload_wire_bytes_per_client``: the table at the
  ``--sketch_dtype`` wire width + per-row f32 scales where the dtype
  carries them) to exact integer equality for sketch / true_topk /
  uncompressed / fedavg. The quantized programs additionally prove the
  table collective compiled at the wire dtype (s8/f8e4m3fn/bf16) and
  that no f32 table-shaped all-reduce remains. local_topk is the
  documented exception: the mesh reduces the DENSE masked vector over
  the ICI (4·d bytes) while the logical uplink is 4·k — the audit
  asserts the bound instead;
* **no host transfers** — no infeed/outfeed/send/recv/host callbacks
  anywhere in the round program (the only device→host crossing is the
  ``metrics_host`` scalar fetch, which lives OUTSIDE the compiled
  step and is policed by the linter, not here);
* **bf16 dtype discipline** — a bf16 canary model lowers with zero
  f32 dot/conv ops (silent widening = 2x FLOPs + traffic);
* **trace-cache fingerprint** — SHA-256 of the loc-stripped StableHLO
  per (mode, path, probes); double-lowering must agree, and the
  committed ``audit_baseline.json`` pins it so accidental program
  drift / retraces fail visibly.

Geometry is deliberately tiny (d=64, B=2, sketch 2x16): the audit
checks program *shape*, not numerics, and must stay tier-1 fast.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from commefficient_tpu.analysis import hlo
from commefficient_tpu.config import Config
from commefficient_tpu.core.rounds import (ClientStates,
                                           build_client_round,
                                           build_server_round)
from commefficient_tpu.core.server import ServerState
from commefficient_tpu.parallel.mesh import (client_sharding, make_mesh,
                                             make_mesh2d,
                                             model_axis_size, replicated,
                                             server_state_sharding,
                                             shard_batch)

D = 64            # grad_size
B = 2             # padded batch per client
NUM_CLIENTS = 16  # divisible by the 8-device mesh
MESH_W = 8        # round fan-out on the mesh
CHUNK_W = 4       # fan-out for the single-device chunked path
CHUNK = 2
MESH2D = (4, 2)   # clients x model layout for the 2D audit programs

BASE_CFG = dict(local_momentum=0.0, virtual_momentum=0.0,
                weight_decay=0.0, error_type="none", k=3,
                num_rows=2, num_cols=16, num_blocks=1,
                local_batch_size=B, microbatch_size=-1, seed=21)


@dataclasses.dataclass
class ProgramSpec:
    name: str
    mode: str
    path: str               # "fused" | "per_client" | "chunked" | "fused2d"
    cfg_kw: Dict
    probes: bool = False
    probe_recovery: bool = False

    @property
    def use_mesh(self) -> bool:
        return self.path != "chunked"


def build_specs() -> List[ProgramSpec]:
    """The mode x path matrix. Path forcing mirrors how the runtime
    actually lands on each builder branch (core/rounds.py):

    * fused needs no per-client gradient transform — sketch /
      true_topk / uncompressed with zero local momentum/error;
    * per_client is forced by a per-client op: microbatching for the
      fused-eligible modes, local momentum/error for the rest; fedavg
      is inherently per-client (local SGD);
    * chunked engages only single-device with 0 < client_chunk < W.
    """
    fused = [
        ProgramSpec("sketch/fused", "sketch", "fused",
                    dict(error_type="virtual", virtual_momentum=0.9)),
        ProgramSpec("true_topk/fused", "true_topk", "fused",
                    dict(error_type="virtual", virtual_momentum=0.9)),
        ProgramSpec("uncompressed/fused", "uncompressed", "fused",
                    dict(virtual_momentum=0.9)),
        # the --probe_every cadence variant: table + dense ground
        # truth both cross the ICI on probed rounds
        ProgramSpec("sketch/fused+probes", "sketch", "fused",
                    dict(error_type="virtual", virtual_momentum=0.9),
                    probes=True, probe_recovery=True),
        # the pod-scale 2D round: partial tables reduce-scattered over
        # ``model``, the client-axis all-reduce carries only the
        # (r, c/M) column shard
        ProgramSpec("sketch/fused2d", "sketch", "fused2d",
                    dict(error_type="virtual", virtual_momentum=0.9)),
        # quantized wire programs: the table collective must compile
        # at the wire dtype (s8/f8e4m3fn/bf16) with, for the scaled
        # dtypes, exactly one (r, 1) f32 rowmax pmax riding along —
        # the dtype-aware ledger cross-check proves the compiled
        # bytes equal the accounting to the byte
        ProgramSpec("sketch/quant8", "sketch", "fused",
                    dict(error_type="virtual", virtual_momentum=0.9,
                         sketch_dtype="int8")),
        ProgramSpec("sketch/quantfp8", "sketch", "fused",
                    dict(error_type="virtual", virtual_momentum=0.9,
                         sketch_dtype="fp8")),
        ProgramSpec("sketch/quantbf16", "sketch", "fused",
                    dict(error_type="virtual", virtual_momentum=0.9,
                         sketch_dtype="bf16")),
        ProgramSpec("sketch/quant2d", "sketch", "fused2d",
                    dict(error_type="virtual", virtual_momentum=0.9,
                         sketch_dtype="int8")),
        # latency-hiding chunk pipeline (--overlap_depth): the table
        # crosses the wire in min(depth, r) disjoint row chunks, one
        # wire-dtype collective per chunk — the audit proves the
        # per-chunk collective bytes still sum to the ledger's
        # byte-exact total, one chunk-sized f32 scale pmax rides per
        # chunk, and no f32 table (or chunk) ever crosses the ICI
        ProgramSpec("sketch/overlap2", "sketch", "fused",
                    dict(error_type="virtual", virtual_momentum=0.9,
                         sketch_dtype="int8", overlap_depth=2)),
        ProgramSpec("sketch/overlap2d", "sketch", "fused2d",
                    dict(error_type="virtual", virtual_momentum=0.9,
                         sketch_dtype="int8", overlap_depth=2)),
    ]
    per_client_kw = {
        "sketch": dict(error_type="virtual", virtual_momentum=0.9,
                       microbatch_size=1),
        "true_topk": dict(error_type="virtual", virtual_momentum=0.9,
                          local_momentum=0.9),
        "local_topk": dict(error_type="local", local_momentum=0.9,
                           virtual_momentum=0.9),
        "uncompressed": dict(virtual_momentum=0.9, local_momentum=0.9),
        "fedavg": dict(local_batch_size=-1),
    }
    per_client = [ProgramSpec(f"{m}/per_client", m, "per_client", kw)
                  for m, kw in per_client_kw.items()]
    chunked = [ProgramSpec(f"{m}/chunked", m, "chunked",
                           dict(kw, client_chunk=CHUNK))
               for m, kw in per_client_kw.items()]
    return fused + per_client + chunked


SERVER_CFG_KW = {
    # aligned with tests/test_accounting.py MODES so the ledger
    # cross-check and the server audit see the same configs
    "uncompressed": dict(virtual_momentum=0.9),
    "sketch": dict(error_type="virtual", virtual_momentum=0.9),
    "true_topk": dict(error_type="virtual", virtual_momentum=0.9),
    "local_topk": dict(error_type="local", local_momentum=0.9,
                       virtual_momentum=0.9),
    "fedavg": dict(local_batch_size=-1),
}


def make_cfg(mode: str, num_workers: int, **kw) -> Config:
    merged = dict(BASE_CFG)
    merged.update(kw)
    cfg = Config(mode=mode, num_workers=num_workers, **merged)
    cfg.grad_size = D
    return cfg


def _toy_loss(params_flat, batch):
    pred = batch["x"] @ params_flat
    sq = (pred - batch["y"]) ** 2
    n = jnp.maximum(jnp.sum(batch["mask"]), 1.0)
    loss = jnp.sum(sq * batch["mask"]) / n
    return loss, (loss * 0.0 + 1.0,)


def _client_inputs(cfg: Config, mesh):
    W = cfg.num_workers
    rng = np.random.RandomState(0)
    ps = jnp.zeros((D,), jnp.float32)
    sharding = client_sharding(mesh) if mesh is not None else None
    cs = ClientStates.init(cfg, NUM_CLIENTS, ps, sharding=sharding)
    batch = {"x": jnp.asarray(rng.randn(W, B, D).astype(np.float32)),
             "y": jnp.asarray(rng.randn(W, B).astype(np.float32)),
             "mask": jnp.ones((W, B), jnp.float32)}
    ids = jnp.arange(W, dtype=jnp.int32)
    if mesh is not None:
        batch = shard_batch(mesh, batch)
        ps = jax.device_put(ps, replicated(mesh))
        ids = jax.device_put(ids, replicated(mesh))
    # fixed smoke key for fingerprinting, not a noise source
    return ps, cs, batch, ids, jax.random.PRNGKey(0), jnp.float32(0.1)  # audit: allow(noise-confinement)


def _donated_leaves(tree) -> int:
    return len(jax.tree_util.tree_leaves(tree))


def _audit_texts(jitted, args) -> Dict:
    """Lower twice (retrace determinism), compile once; return the
    parsed common report skeleton."""
    lowered = jitted.lower(*args)
    text = lowered.as_text()
    fp = hlo.fingerprint(text)
    fp2 = hlo.fingerprint(jitted.lower(*args).as_text())
    ctext = lowered.compile().as_text()
    ops = hlo.collective_inventory(ctext)
    transfers = (hlo.host_transfer_lines(text)
                 + hlo.host_transfer_lines(ctext))
    marks = hlo.donation_marks(text)
    return {
        "fingerprint": fp,
        "retrace_stable": fp == fp2,
        "collectives": hlo.collective_summary(ops),
        "_ops": ops,
        "transfers": transfers,
        "marked": marks["aliased"] + marks["donors"],
        "compiled_aliases": hlo.compiled_alias_count(ctext),
    }


def audit_client_program(spec: ProgramSpec, mesh=None,
                         donate: bool = True) -> Dict:
    """Audit one client-round program. ``donate=False`` exists for the
    regression test: dropping donation must fail the coverage check."""
    W = MESH_W if spec.use_mesh else CHUNK_W
    cfg = make_cfg(spec.mode, W, **spec.cfg_kw)
    if spec.use_mesh and mesh is None:
        mesh = (make_mesh2d(*MESH2D) if spec.path == "fused2d"
                else make_mesh(jax.devices()))
    fn = build_client_round(cfg, _toy_loss, B,
                            mesh=mesh if spec.use_mesh else None,
                            probes=spec.probes,
                            probe_recovery=spec.probe_recovery)
    jitted = jax.jit(fn, donate_argnums=(1,) if donate else ())
    args = _client_inputs(cfg, mesh if spec.use_mesh else None)
    entry = _audit_texts(jitted, args)
    ops = entry.pop("_ops")

    expected = _donated_leaves(args[1])
    entry["donation"] = {"expected": expected,
                         "marked": entry.pop("marked"),
                         "compiled_aliases":
                             entry.pop("compiled_aliases")}

    # dtype-aware ledger cross-check: the ledger bills the table at
    # the wire dtype plus (for the scaled dtypes) one f32 row scale
    # per row; the compiled program must carry EXACTLY that — the
    # table collective at wire width and the (r, 1) f32 rowmax pmax.
    # One backend caveat: XLA CPU's collective runtime sums s8
    # natively but PROMOTES bf16 all-reduces to f32 and f8 to f16
    # (all-reduce-promotion pass) — on those wires the audit accepts
    # the promoted dtype, normalises its bytes back to wire width for
    # the ledger equality, and records the promotion so the TPU
    # audit (native bf16 collectives) can pin the real width.
    wire = getattr(cfg, "sketch_dtype", "f32")
    wire_hlo = {"f32": "f32", "bf16": "bf16", "int8": "s8",
                "fp8": "f8e4m3fn"}[wire]
    promoted_ok = {"f32": ("f32",), "int8": ("s8",),
                   "bf16": ("bf16", "f32"),
                   "fp8": ("f8e4m3fn", "f16", "f32")}[wire]

    def _wire_bytes(kind, shapes):
        """(bytes normalised to wire width, matched hlo dtype) of the
        first dtype — native first, then promoted — with a matching
        ``kind`` collective at any of ``shapes``."""
        for dt in promoted_ok:
            raw = sum(hlo.matching_collective_bytes(ops, kind, dt, s)
                      for s in dict.fromkeys(tuple(s) for s in shapes))
            if raw:
                factor = (hlo.DTYPE_BYTES[dt]
                          // hlo.DTYPE_BYTES[wire_hlo])
                return raw // factor, dt
        return 0, wire_hlo

    ledger = int(cfg.upload_wire_bytes_per_client)
    # --overlap_depth chunking: the table crosses in min(depth, r)
    # disjoint row chunks, so the wire collectives (and their f32
    # scale pmaxes) compile at chunk-row shapes instead of the whole
    # table's — the byte totals must still sum to the same ledger
    depth = int(getattr(cfg, "overlap_depth", 1))
    chunks = []
    if depth > 1:
        from commefficient_tpu.parallel.wire import row_chunks
        chunks = row_chunks(cfg.num_rows, depth)
    scale_shapes = [(cfg.num_rows, 1), (cfg.num_rows,)]
    for _off, cnt in chunks:
        scale_shapes += [(cnt, 1), (cnt,)]
    scale = (sum(
        hlo.matching_collective_bytes(ops, "all-reduce", "f32", s)
        for s in dict.fromkeys(scale_shapes))
        if wire in ("int8", "fp8") else 0)
    M = model_axis_size(mesh) if spec.use_mesh else 1
    if M > 1:
        # 2D emission: the client-axis all-reduce and the model-axis
        # reduce-scatter both carry the (r, c/M) column shard — XLA
        # sometimes flattens the shard to 1-D, so both layouts key
        shard = (cfg.num_rows, cfg.num_cols // M)
        shard_shapes = [shard, (shard[0] * shard[1],)]
        for _off, cnt in chunks:
            shard_shapes += [(cnt, cfg.num_cols // M),
                             (cnt * (cfg.num_cols // M),)]
        static, static_dt = _wire_bytes("all-reduce", shard_shapes)
        rs, rs_dt = _wire_bytes("reduce-scatter", shard_shapes)
        entry["uplink"] = {
            "ledger_bytes_per_client": ledger,
            "model_shards": M,
            "wire_dtype": wire,
            "compiled_dtype": static_dt,
            "aggregate_allreduce_bytes": static,
            "reduce_scatter_bytes": rs,
            "scale_allreduce_bytes": scale,
            "relation": "sharded",
        }
    else:
        table_shapes = [cfg.transmit_shape,
                        (int(np.prod(cfg.transmit_shape)),)]
        for _off, cnt in chunks:
            table_shapes += [(cnt, cfg.num_cols),
                             (cnt * cfg.num_cols,)]
        static, static_dt = _wire_bytes("all-reduce", table_shapes)
        rs_dt = static_dt
        entry["uplink"] = {
            "ledger_bytes_per_client": ledger,
            "wire_dtype": wire,
            "compiled_dtype": static_dt,
            "aggregate_allreduce_bytes": static,
            "scale_allreduce_bytes": scale,
            # local_topk sends the dense masked vector over the ICI:
            # the 4·k ledger figure is the logical uplink, bounded by
            # the 4·d wire bytes. Everything else must match exactly.
            "relation": ("bound" if spec.mode == "local_topk"
                         else "exact"),
        }

    failures = []
    don = entry["donation"]
    if don["marked"] < don["expected"]:
        failures.append(
            f"donation: {don['marked']}/{don['expected']} donated "
            "state leaves marked in the lowered module — the "
            "donation was dropped")
    elif don["compiled_aliases"] < don["expected"]:
        failures.append(
            f"donation: XLA aliased {don['compiled_aliases']}/"
            f"{don['expected']} donated state leaves — a donated "
            "buffer is being copied instead of reused")
    if entry["transfers"]:
        failures.append(
            f"host transfers in the round program: "
            f"{entry['transfers'][:3]}")
    if not entry["retrace_stable"]:
        failures.append("fingerprint differs across two lowerings of "
                        "the same builder (nondeterministic trace)")
    if spec.path == "chunked":
        if entry["collectives"]["counts"]:
            failures.append(
                "single-device chunked program emits collectives: "
                f"{entry['collectives']['counts']}")
    elif M > 1:
        if rs * M + scale != ledger:
            failures.append(
                f"2D uplink: reduce-scatter shard bytes {rs} x {M} "
                f"model shards + {scale} scale bytes != ledger "
                f"bytes/client {ledger} ({wire} wire) — the "
                "partial-table emission is not reduce-scattering the "
                "quantized (r, c/M) column shard")
        if static * M + scale != ledger:
            failures.append(
                f"2D uplink: client-axis all-reduce bytes {static} x "
                f"{M} + {scale} scale bytes != ledger bytes/client "
                f"{ledger} ({wire} wire) — the aggregation must carry "
                "only the quantized column shard")
        full = hlo.matching_reduce_bytes(ops, wire_hlo,
                                         cfg.transmit_shape)
        if full:
            failures.append(
                f"2D uplink: {full} bytes all-reduced at the FULL "
                f"table shape {cfg.transmit_shape} — the model-axis "
                "sharding is being undone on the wire")
        if wire != "f32" and hlo.matching_reduce_bytes(
                ops, "f32", cfg.transmit_shape):
            failures.append(
                "2D uplink: an f32 table-shaped all-reduce in the "
                f"{wire}-wire program — the table is crossing the ICI "
                "unquantized")
        if wire != "f32" and hlo.matching_collective_bytes(
                ops, "reduce-scatter", "f32", shard) and rs_dt != "f32":
            failures.append(
                "2D uplink: an f32 shard-shaped reduce-scatter beside "
                f"the {wire} wire path — double traffic")
    elif spec.mode == "local_topk":
        if not (static >= ledger):
            failures.append(
                f"uplink: dense wire bytes {static} < logical ledger "
                f"bytes {ledger}")
    else:
        if static + scale != ledger:
            failures.append(
                f"uplink: aggregation all-reduce bytes {static} + "
                f"{scale} scale bytes != ledger bytes/client {ledger} "
                f"({wire} wire, shape {cfg.transmit_shape})")
        if (wire != "f32" and static_dt != "f32"
                and hlo.matching_reduce_bytes(ops, "f32",
                                              cfg.transmit_shape)):
            failures.append(
                f"uplink: an f32 table-shaped all-reduce beside the "
                f"{wire} ({static_dt}) wire path — the table is "
                "crossing the ICI unquantized")
    if chunks:
        # chunk pipeline shape: one wire collective per row chunk,
        # and no chunk ever crosses the ICI at f32 (an extra f32
        # chunk materialisation would silently double the traffic
        # the pipeline exists to hide)
        kind = "reduce-scatter" if M > 1 else "all-reduce"
        chunk_dt = rs_dt if M > 1 else static_dt
        base_c = cfg.num_cols // M if M > 1 else cfg.num_cols
        chunk_set = set()
        for _off, cnt in chunks:
            chunk_set.update({(cnt, base_c), (cnt * base_c,)})
        n_ops = sum(
            1 for op in ops if op.kind == kind
            and any(d == chunk_dt and s in chunk_set
                    for d, s, _b in op.shapes))
        entry["uplink"]["overlap_depth"] = depth
        entry["uplink"]["chunk_collectives"] = n_ops
        if n_ops != len(chunks):
            failures.append(
                f"overlap: {n_ops} chunk-shaped {kind} op(s) for "
                f"{len(chunks)} row chunks — the pipeline is not "
                "issuing one wire collective per chunk")
        if wire != "f32" and chunk_dt != "f32":
            for s in sorted(chunk_set):
                f32b = hlo.matching_reduce_bytes(ops, "f32", s)
                if f32b:
                    failures.append(
                        f"overlap: {f32b} bytes f32-reduced at chunk "
                        f"shape {s} — a chunk is crossing the ICI "
                        "unquantized")
    entry.update(mode=spec.mode, path=spec.path, probes=spec.probes,
                 failures=failures)
    return entry


def audit_server_program(mode: str, donate: bool = True) -> Dict:
    """Audit the server round: ``donate_argnums=(0, 1)`` covers
    ps_weights + both ServerState tables; the server step is
    replicated, so the program must be collective- and transfer-free.

    All three donated leaves (ps_weights, Vvelocity, Verror) alias in
    every mode — non-virtual-error modes thread Verror through
    unchanged and XLA still reuses the buffer — so the check is
    exact."""
    cfg = make_cfg(mode, MESH_W, **SERVER_CFG_KW[mode])
    fn = build_server_round(cfg)
    jitted = jax.jit(fn, donate_argnums=(0, 1) if donate else ())
    args = (jnp.zeros((D,), jnp.float32), ServerState.init(cfg),
            jnp.ones(cfg.transmit_shape, jnp.float32),
            jnp.float32(0.1))
    entry = _audit_texts(jitted, args)
    entry.pop("_ops")
    entry["donation"] = {"expected": 1 + _donated_leaves(args[1]),
                         "marked": entry.pop("marked"),
                         "compiled_aliases":
                             entry.pop("compiled_aliases")}
    failures = []
    don = entry["donation"]
    if min(don["marked"], don["compiled_aliases"]) < don["expected"]:
        failures.append(
            f"donation: {don['marked']} marked / "
            f"{don['compiled_aliases']} compiled-aliased of "
            f"{don['expected']} donated server leaves — ps_weights "
            "and both ServerState tables must reuse their buffers")
    if entry["transfers"]:
        failures.append(f"host transfers: {entry['transfers'][:3]}")
    if entry["collectives"]["counts"]:
        failures.append("replicated server step emits collectives: "
                        f"{entry['collectives']['counts']}")
    if not entry["retrace_stable"]:
        failures.append("nondeterministic server trace")
    entry.update(mode=mode, path="server", probes=False,
                 failures=failures)
    return entry


def audit_server_program_2d(donate: bool = True) -> Dict:
    """Audit the 2D sketch server: momentum/EF column shards update
    locally, the distributed top-k select rebuilds the full table with
    exactly ONE table-sized all-gather (never an all-reduce of a
    table-sized buffer — that would undo the 1/M memory claim on the
    wire), and donation must stick on the sharded state."""
    cfg = make_cfg("sketch", MESH_W, **SERVER_CFG_KW["sketch"])
    mesh = make_mesh2d(*MESH2D)
    fn = build_server_round(cfg, mesh=mesh)
    jitted = jax.jit(fn, donate_argnums=(0, 1) if donate else ())
    state = ServerState.init(
        cfg, sharding=server_state_sharding(mesh, cfg.transmit_shape))
    args = (jnp.zeros((D,), jnp.float32), state,
            jnp.ones(cfg.transmit_shape, jnp.float32),
            jnp.float32(0.1))
    entry = _audit_texts(jitted, args)
    ops = entry.pop("_ops")
    entry["donation"] = {"expected": 1 + _donated_leaves(args[1]),
                         "marked": entry.pop("marked"),
                         "compiled_aliases":
                             entry.pop("compiled_aliases")}
    r, c = cfg.transmit_shape
    table_gathers = sum(
        1 for op in ops if op.kind == "all-gather"
        and any(d == "f32" and s in ((r, c), (r * c,))
                for d, s, _b in op.shapes))
    table_reduce = sum(
        hlo.matching_collective_bytes(ops, "all-reduce", "f32", s)
        for s in ((r, c), (r * c,)))
    entry["table_traffic"] = {"all_gathers": table_gathers,
                              "allreduce_bytes": table_reduce}
    failures = []
    don = entry["donation"]
    if min(don["marked"], don["compiled_aliases"]) < don["expected"]:
        failures.append(
            f"donation: {don['marked']} marked / "
            f"{don['compiled_aliases']} compiled-aliased of "
            f"{don['expected']} donated server leaves — the sharded "
            "momentum/EF tables must reuse their buffers")
    if entry["transfers"]:
        failures.append(f"host transfers: {entry['transfers'][:3]}")
    if table_gathers != 1:
        failures.append(
            f"2D select must rebuild the table with exactly one "
            f"(r, c) all-gather, found {table_gathers}")
    if table_reduce:
        failures.append(
            f"{table_reduce} bytes all-reduced at table size in the "
            "2D server — column shards must stay sharded")
    if not entry["retrace_stable"]:
        failures.append("nondeterministic 2D server trace")
    entry.update(mode="sketch", path="server2d", probes=False,
                 failures=failures)
    return entry


def audit_mesh_1x1_identity() -> Dict:
    """``--mesh 1x1`` must build the SAME program as the 1-D default
    (loc-stripped StableHLO fingerprint): the 2D plumbing may not tax
    the single-device path with even one extra op."""
    cfg = make_cfg("sketch", MESH_W,
                   **dict(error_type="virtual", virtual_momentum=0.9))
    args = _client_inputs(cfg, None)
    texts = {}
    for tag, mesh in (("1d", None), ("1x1", make_mesh2d(1, 1))):
        fn = build_client_round(cfg, _toy_loss, B, mesh=mesh)
        texts[tag] = jax.jit(fn).lower(*args).as_text()
    fp_1d = hlo.fingerprint(texts["1d"])
    fp_11 = hlo.fingerprint(texts["1x1"])
    failures = []
    if fp_1d != fp_11:
        failures.append(
            f"--mesh 1x1 lowers a different program than the 1-D "
            f"default ({fp_1d[:12]} != {fp_11[:12]}) — the 2D branch "
            "leaks into the single-device build")
    return {"mode": "sketch", "path": "mesh1x1", "probes": False,
            "fingerprint": fp_1d, "mesh1x1_fingerprint": fp_11,
            "retrace_stable": True, "failures": failures}


def audit_bf16_canary() -> Dict:
    """bf16 dtype discipline on a conv+dot canary: value_and_grad of a
    small bf16 model must lower with every contraction in bf16 —
    an f32 dot/conv means an operand was silently widened."""

    def model_loss(params, x, y):
        h = jax.lax.conv_general_dilated(
            x, params["conv"], window_strides=(1, 1), padding="SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        h = jnp.maximum(h, 0).reshape(x.shape[0], -1)
        logits = h @ params["dense"]
        return jnp.sum((logits.astype(jnp.float32) - y) ** 2)

    bf16 = jnp.bfloat16
    params = {"conv": jax.ShapeDtypeStruct((3, 3, 2, 4), bf16),
              "dense": jax.ShapeDtypeStruct((8 * 8 * 4, 8), bf16)}
    x = jax.ShapeDtypeStruct((2, 8, 8, 2), bf16)
    y = jax.ShapeDtypeStruct((2, 8), jnp.float32)
    jitted = jax.jit(jax.value_and_grad(model_loss))
    text = jitted.lower(params, x, y).as_text()
    dots = hlo.dot_dtype_inventory(text)
    failures = []
    if dots.get("f32", 0):
        failures.append(
            f"{dots['f32']} f32 dot/conv op(s) in the bf16 model "
            f"path (inventory: {dots}) — silent widening")
    if not dots.get("bf16", 0):
        failures.append(f"no bf16 contractions found at all ({dots})"
                        " — parser or model drift")
    return {"mode": "bf16_canary", "path": "lowered-only",
            "probes": False, "dot_dtypes": dots,
            "fingerprint": hlo.fingerprint(text),
            "retrace_stable": True, "failures": failures}


def run_program_audit(server: bool = True) -> Dict:
    """The full matrix. Returns a JSON-able report:
    ``{"programs": {name: entry}, "failures": [str]}`` — ``failures``
    flattens every entry's failed invariant checks."""
    report: Dict = {"jax_version": jax.__version__,
                    "device_count": jax.device_count(),
                    "programs": {}}
    mesh = make_mesh(jax.devices())
    for spec in build_specs():
        report["programs"][spec.name] = audit_client_program(
            spec, mesh=None if spec.path == "fused2d" else mesh)
    if server:
        for mode in SERVER_CFG_KW:
            report["programs"][f"{mode}/server"] = \
                audit_server_program(mode)
        report["programs"]["sketch/server2d"] = \
            audit_server_program_2d()
    report["programs"]["sketch/mesh1x1"] = audit_mesh_1x1_identity()
    report["programs"]["bf16_canary"] = audit_bf16_canary()
    report["failures"] = [
        f"{name}: {msg}"
        for name, entry in report["programs"].items()
        for msg in entry["failures"]]
    return report
