"""Roofline cost model: expected lower-bound round time.

Closes the loop from the static auditor's program inventories
(hlo.py: FLOPs, collective bytes) to the measured device timelines
(telemetry/trace.py): for a (mode, path, topology) the model computes
the time the round CANNOT beat —

    expected_round_s = max(compute_time, collective_time)

with ``compute_time = FLOPs / (peak_flops x n_devices)`` and
``collective_time = ring all-reduce wire bytes / interconnect BW``.
The ledger then carries ``roofline_utilization = expected / measured
busy`` per profiled round (schema v3): ~1.0 means the round runs at
the roofline, a collapse to 0.1 means 10x is being left on the table
(host gaps, launch overhead, unfused memory-bound tails).

Peak numbers are deliberately coarse catalogue values — the model is
a *lower bound* and a *trend instrument* (did utilization drop vs the
committed perf baseline?), not a simulator. Like hlo.py, nothing here
imports jax; callers pass backend/device strings.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

from commefficient_tpu.analysis.hlo import flop_inventory


@dataclass(frozen=True)
class ChipSpec:
    name: str
    peak_flops: float        # bf16/f32 matmul peak per chip, FLOP/s
    hbm_gbps: float          # memory bandwidth, GB/s
    ici_gbps: float          # per-chip interconnect bandwidth, GB/s


# catalogue values (vendor datasheets, rounded); "cpu" is a deliberate
# small stand-in so CPU smoke runs produce finite utilizations
CHIP_SPECS = {
    "tpu-v4": ChipSpec("tpu-v4", 275e12, 1228.0, 50.0),
    "tpu-v5e": ChipSpec("tpu-v5e", 197e12, 819.0, 50.0),
    "tpu-v5p": ChipSpec("tpu-v5p", 459e12, 2765.0, 100.0),
    "tpu-v6e": ChipSpec("tpu-v6e", 918e12, 1640.0, 100.0),
    "gpu": ChipSpec("gpu", 312e12, 2039.0, 50.0),
    "cpu": ChipSpec("cpu", 2e11, 50.0, 10.0),
}


def chip_spec(backend: str, device_kind: str = "") -> ChipSpec:
    """Best-effort spec lookup from ``jax.default_backend()`` plus the
    device's ``device_kind`` string (e.g. "TPU v5 lite")."""
    kind = (device_kind or "").lower()
    if backend == "tpu":
        if "v5 lite" in kind or "v5e" in kind or "v5litepod" in kind:
            return CHIP_SPECS["tpu-v5e"]
        if "v5p" in kind or "v5" in kind:
            return CHIP_SPECS["tpu-v5p"]
        if "v6" in kind:
            return CHIP_SPECS["tpu-v6e"]
        return CHIP_SPECS["tpu-v4"]
    if backend == "gpu":
        return CHIP_SPECS["gpu"]
    return CHIP_SPECS["cpu"]


def ring_allreduce_wire_bytes(payload_bytes: float,
                              n_devices: int) -> float:
    """Per-chip wire traffic of a ring all-reduce: each chip sends
    (and receives) ``2 (n-1)/n`` of the payload."""
    n = max(int(n_devices), 1)
    if n == 1:
        return 0.0
    return 2.0 * payload_bytes * (n - 1) / n


def expected_round_seconds(total_flops: float,
                           allreduce_payload_bytes: float,
                           spec: ChipSpec,
                           n_devices: int) -> Dict:
    """Roofline lower bound for one round on ``n_devices`` chips.
    ``total_flops`` is the GLOBAL (pre-SPMD) program cost — the
    lowered StableHLO counts every client's pass — so the compute leg
    divides by the device count."""
    n = max(int(n_devices), 1)
    compute_s = float(total_flops) / (spec.peak_flops * n)
    wire = ring_allreduce_wire_bytes(allreduce_payload_bytes, n)
    collective_s = wire / (spec.ici_gbps * 1e9)
    return {"compute_s": compute_s,
            "collective_s": collective_s,
            "expected_round_s": max(compute_s, collective_s),
            "wire_bytes_per_chip": wire}


def build_cost_model(stablehlo_text: str, *, backend: str,
                     device_kind: str = "", n_devices: int = 1,
                     allreduce_payload_bytes: float = 0.0,
                     wire_dtype: str = "f32",
                     label: str = "") -> Dict:
    """One round's roofline expectation from its lowered module text.

    ``allreduce_payload_bytes`` is the round's aggregation payload at
    its WIRE dtype (``Config.upload_wire_bytes_per_client``: sketch
    tables at the --sketch_dtype width + per-row f32 scales, dense
    modes ``4 grad_size``) — passed in rather than re-derived from
    compiled HLO so the profiled run doesn't pay a second full
    compile. ``wire_dtype`` tags the record so a quantized run's
    collective floor is attributable without re-deriving it from the
    byte count. Returns a JSON-able dict the telemetry meta record
    carries."""
    flops = flop_inventory(stablehlo_text)
    spec = chip_spec(backend, device_kind)
    exp = expected_round_seconds(flops["total_flops"],
                                 allreduce_payload_bytes, spec,
                                 n_devices)
    return {
        "label": label,
        "chip": spec.name,
        "backend": backend,
        "n_devices": int(n_devices),
        "total_flops": flops["total_flops"],
        "dot_flops": flops["dot_flops"],
        "conv_flops": flops["conv_flops"],
        "flops_by_dtype": flops["by_dtype"],
        "allreduce_payload_bytes": float(allreduce_payload_bytes),
        "wire_dtype": wire_dtype,
        "wire_bytes_per_chip": exp["wire_bytes_per_chip"],
        "compute_floor_s": exp["compute_s"],
        "collective_floor_s": exp["collective_s"],
        "expected_round_s": exp["expected_round_s"],
    }


def utilization(expected_round_s: Optional[float],
                measured_busy_s: Optional[float]) -> Optional[float]:
    """Roofline utilization fraction (1.0 = running at the bound);
    None when either side is missing/zero."""
    if not expected_round_s or not measured_busy_s:
        return None
    return expected_round_s / measured_busy_s
