"""Wire-dtype collective crossings over the device mesh
(``--sketch_dtype``).

``ops/quant.py`` owns the quantization *algebra* — scales, summation
headroom, rounding; this module owns where that algebra meets the
*mesh*: which axes the row maxima are pmax'd over, which collective
moves the wire-dtype payload, and the dequantize on the far side.
``core/rounds.py`` routes every quantized wire crossing through here,
so the collective-facing surface the static auditor matches against
(`analysis/program.py`: the wire-dtype psum/psum_scatter plus exactly
one (r, 1) f32 rowmax pmax) has a single owner, like the sharding
specs in ``parallel/mesh.py``.
"""

from __future__ import annotations

import jax

from commefficient_tpu.ops import quant


def quantize_for_collective(table: jax.Array, wire: str, axes,
                            n_addends: int):
    """Local f32 table -> ``(wire-dtype table, shared scale)`` ready
    for a wire-dtype psum/psum_scatter over ``axes``: local-quantize
    at full range, pmax the rowmax over the participating mesh axes
    (the (r, 1) f32 side-channel the ledger counts), harmonize onto
    the shared scale with ``n_addends`` summation headroom. bf16 is
    scale-free (scale None)."""
    q, rowmax = quant.quantize_local(table, wire)
    grm = (quant.global_rowmax_over(rowmax, axes)
           if rowmax is not None else None)
    return quant.harmonize(q, rowmax, grm, wire, n_addends)


def wire_allreduce(q: jax.Array, scale, axis_name) -> jax.Array:
    """The table's aggregation all-reduce at wire width: psum the
    quantized table over ``axis_name`` and dequantize on the far side
    — downstream (server momentum/EF) only ever sees f32."""
    return quant.dequantize(jax.lax.psum(q, axis_name), scale)


def wire_reduce_scatter(q: jax.Array, axis_name,
                        scatter_dimension: int = 1) -> jax.Array:
    """The 2D emission's model-axis crossing: sum partial tables and
    leave each peer its column shard — at wire width when ``q`` is
    quantized (r·c·wb/M per link instead of 4·r·c/M)."""
    return jax.lax.psum_scatter(q, axis_name,
                                scatter_dimension=scatter_dimension,
                                tiled=True)
