"""Wire-dtype collective crossings over the device mesh
(``--sketch_dtype``).

``ops/quant.py`` owns the quantization *algebra* — scales, summation
headroom, rounding; this module owns where that algebra meets the
*mesh*: which axes the row maxima are pmax'd over, which collective
moves the wire-dtype payload, and the dequantize on the far side.
``core/rounds.py`` routes every quantized wire crossing through here,
so the collective-facing surface the static auditor matches against
(`analysis/program.py`: the wire-dtype psum/psum_scatter plus exactly
one (r, 1) f32 rowmax pmax) has a single owner, like the sharding
specs in ``parallel/mesh.py``.
"""

from __future__ import annotations

import jax

from commefficient_tpu.ops import quant


def quantize_for_collective(table: jax.Array, wire: str, axes,
                            n_addends: int):
    """Local f32 table -> ``(wire-dtype table, shared scale)`` ready
    for a wire-dtype psum/psum_scatter over ``axes``: local-quantize
    at full range, pmax the rowmax over the participating mesh axes
    (the (r, 1) f32 side-channel the ledger counts), harmonize onto
    the shared scale with ``n_addends`` summation headroom. bf16 is
    scale-free (scale None)."""
    q, rowmax = quant.quantize_local(table, wire)
    grm = (quant.global_rowmax_over(rowmax, axes)
           if rowmax is not None else None)
    return quant.harmonize(q, rowmax, grm, wire, n_addends)


def wire_allreduce(q: jax.Array, scale, axis_name) -> jax.Array:
    """The table's aggregation all-reduce at wire width: psum the
    quantized table over ``axis_name`` and dequantize on the far side
    — downstream (server momentum/EF) only ever sees f32."""
    return quant.dequantize(jax.lax.psum(q, axis_name), scale)


def wire_reduce_scatter(q: jax.Array, axis_name,
                        scatter_dimension: int = 1) -> jax.Array:
    """The 2D emission's model-axis crossing: sum partial tables and
    leave each peer its column shard — at wire width when ``q`` is
    quantized (r·c·wb/M per link instead of 4·r·c/M)."""
    return jax.lax.psum_scatter(q, axis_name,
                                scatter_dimension=scatter_dimension,
                                tiled=True)


def row_chunks(r: int, depth: int):
    """``--overlap_depth`` row chunking: ceil-split ``r`` table rows
    into ``min(depth, r)`` contiguous chunks, returned as
    ``[(offset, count), ...]``. Depth is clamped (never an error) so
    one sweep flag works across geometries; clamped depths still name
    distinct programs (an o4 run of a 3-row table is 3 chunks — a
    different program from o2's 2, so the perf-gate ``o<N>`` keys
    stay honest). Chunks are disjoint row ranges: the collective over
    each composes with per-row quantization scales exactly, so the
    chunked fold is bit-identical to the whole-table crossing."""
    assert r >= 1 and depth >= 1, (r, depth)
    n = min(depth, r)
    size = -(-r // n)
    out = []
    off = 0
    while off < r:
        cnt = min(size, r - off)
        out.append((off, cnt))
        off += cnt
    return out


def chunked_quantize_allreduce(table: jax.Array, wire: str, axes,
                               n_addends: int, axis_name,
                               depth: int) -> jax.Array:
    """Row-chunked quantize + all-reduce: quantize and psum each
    disjoint row chunk separately, interleaved in emission order so
    XLA's latency-hiding scheduler can run chunk i's collective under
    chunk i+1's quantize. Per-row scales make each chunk's algebra
    identical to the row slice of the whole-table crossing (rowmax of
    a chunk == the chunk's rows of the whole-table rowmax), so the
    concatenated result matches ``quantize_for_collective`` +
    ``wire_allreduce`` bit-for-bit — only the collective granularity
    changes. f32 chunks skip quantization (plain per-chunk psum)."""
    import jax.numpy as jnp
    r = table.shape[0]
    parts = []
    for off, cnt in row_chunks(r, depth):
        chunk = jax.lax.slice_in_dim(table, off, off + cnt, axis=0)
        if wire == "f32":
            parts.append(jax.lax.psum(chunk, axis_name))
        else:
            q, scale = quantize_for_collective(chunk, wire, axes,
                                               n_addends)
            parts.append(wire_allreduce(q, scale, axis_name))
    return jnp.concatenate(parts, axis=0)
