"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference has no long-context support at all (SURVEY.md §2.8:
sequences are padded per-batch and processed whole, fed_persona.py:
360-392) — this module is a capability the TPU build adds as
first-class: sequences sharded over a ``seq`` mesh axis so context
length scales with the number of chips.

Two standard formulations, both built on XLA collectives over ICI:

- ``ring_attention``: blockwise causal attention with an online
  (flash-style) softmax; KV blocks rotate around the ring via
  ``jax.lax.ppermute`` while each device keeps its Q shard. Peak
  memory per device is O(T_local · d) and the KV transfer overlaps
  the block matmuls. Exact — not an approximation.
- ``ulysses_attention``: ``jax.lax.all_to_all`` reshards from
  sequence-sharded to head-sharded, runs ordinary fused attention on
  full sequences per head group, and reshards back. Cheaper at modest
  T (two all-to-alls instead of n-1 permutes) but requires
  n_head % axis_size == 0.

Both are called inside ``shard_map`` with q/k/v sharded on the
sequence (T) axis: shapes (B, T_local, H, D). Causal masking uses
global positions derived from ``jax.lax.axis_index``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from commefficient_tpu.compat import axis_size

_NEG_INF = -1e30  # finite mask value: keeps the online softmax NaN-free
                  # for fully-masked (future) KV blocks


def _block_attn(q, k, v, bias_mask, o, m, l, scale):
    """One KV block of online-softmax attention.

    q (B, Tq, H, D); k/v (B, Tk, H, D); bias_mask (Tq, Tk) additive.
    Carries: o (B, Tq, H, D) un-normalised output, m/l (B, Tq, H)
    running max / normaliser.
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    s = s + bias_mask[None, None, :, :]
    m_blk = jnp.max(s, axis=-1)                    # (B, H, Tq)
    m_new = jnp.maximum(m, m_blk.transpose(0, 2, 1))
    # correction of previous accumulators
    corr = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new.transpose(0, 2, 1)[..., None])  # (B,H,Tq,Tk)
    l_new = l * corr + jnp.sum(p, axis=-1).transpose(0, 2, 1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    o_new = o * corr[..., None] + pv
    return o_new, m_new, l_new


def ring_attention(q, k, v, axis_name: str, causal: bool = True):
    """Exact blockwise attention over a sequence-sharded ring.

    Must run inside shard_map; q/k/v are the local shards
    (B, T_local, H, D). Returns the local output shard.
    """
    n = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    B, T, H, D = q.shape
    scale = 1.0 / jnp.sqrt(jnp.float32(D))

    q_pos = idx * T + jnp.arange(T)  # global positions of our queries

    def mask_for(kv_owner):
        """(Tq, Tk) additive causal mask for the block originally
        owned by device ``kv_owner``."""
        if not causal:
            return jnp.zeros((T, T), jnp.float32)
        k_pos = kv_owner * T + jnp.arange(T)
        allowed = q_pos[:, None] >= k_pos[None, :]
        return jnp.where(allowed, 0.0, _NEG_INF)

    # derive the accumulators from q so they carry q's full
    # varying-axes set (the loop carry must type-match after mixing
    # with the rotated KV blocks — and under a multi-axis mesh, e.g.
    # clients x seq, the inputs vary over more axes than just ours)
    zero = (q * 0.0).astype(jnp.float32)
    o = zero
    m = jnp.sum(zero, axis=-1) + _NEG_INF  # (B, T, H)
    l = jnp.sum(zero, axis=-1)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(s, carry):
        o, m, l, kk, vv = carry
        owner = (idx - s) % n  # which device's KV block we hold now
        o, m, l = _block_attn(q, kk, vv, mask_for(owner), o, m, l,
                              scale)
        # rotate KV to the next device (skipped result unused on the
        # last step but keeps the loop body uniform)
        kk = jax.lax.ppermute(kk, axis_name, perm)
        vv = jax.lax.ppermute(vv, axis_name, perm)
        return o, m, l, kk, vv

    o, m, l, _, _ = jax.lax.fori_loop(
        0, n, step, (o, m, l, k.astype(jnp.float32),
                     v.astype(jnp.float32)))
    # fully-masked rows (none under causal with self block) guard
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


def ulysses_attention(q, k, v, axis_name: str, causal: bool = True):
    """All-to-all sequence parallelism (DeepSpeed-Ulysses style):
    reshard seq->heads, dense attention on the full sequence, reshard
    back. Requires H % axis_size == 0. Exact."""
    n = axis_size(axis_name)
    B, T, H, D = q.shape
    assert H % n == 0, f"n_head {H} must divide axis size {n}"

    def seq_to_heads(x):
        # (B, T_local, H, D) -> (B, T_global, H/n, D)
        x = x.reshape(B, T, n, H // n, D)
        x = jax.lax.all_to_all(x, axis_name, split_axis=2,
                               concat_axis=1, tiled=False)
        # all_to_all inserts the gathered axis at concat position
        return x.reshape(B, n * T, H // n, D)

    def heads_to_seq(x):
        x = x.reshape(B, n, T, H // n, D)
        x = jax.lax.all_to_all(x, axis_name, split_axis=0 + 1,
                               concat_axis=2 + 1, tiled=False)
        return x.reshape(B, T, H, D)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    out = jax.nn.dot_product_attention(qh, kh, vh, is_causal=causal)
    return heads_to_seq(out)


def dense_reference(q, k, v, causal: bool = True):
    """Single-device oracle for tests."""
    return jax.nn.dot_product_attention(q, k, v, is_causal=causal)
