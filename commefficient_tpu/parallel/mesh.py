"""Device mesh & sharding layout.

The reference's process topology (1 parameter-server + N worker GPU
processes over NCCL, fed_aggregator.py:131-165) maps to a 1-D JAX mesh
with a single ``clients`` axis:

- participating clients' batches and per-client state rows are sharded
  over ``clients`` (what the reference kept in host shared memory,
  fed_aggregator.py:94-129);
- model weights and server state are replicated (every device runs the
  identical deterministic server step — no PS rank);
- the per-round transmit aggregation is a sum over the sharded axis,
  which XLA lowers to one ICI all-reduce — the moral equivalent of the
  reference's single NCCL ``reduce`` per round (fed_worker.py:139-140).

Multi-host pods need no new code: under the standard JAX
multi-controller runtime, ``jax.devices()`` spans hosts, the same mesh
covers ICI+DCN, and XLA routes the collective hierarchically.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.8 moved shard_map out of experimental
    from jax import shard_map as _sm
    _shard_map = _sm.shard_map if hasattr(_sm, "shard_map") else _sm
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

if hasattr(jax.lax, "pvary"):
    shard_map = _shard_map
else:
    # pre-varying-axes jax: check_rep can't see through the explicit
    # psum that replicates our P() outputs (no pvary/pcast types to
    # track), so the static check must be disabled — the collectives
    # themselves are unchanged
    import functools as _functools
    shard_map = _functools.partial(_shard_map, check_rep=False)

CLIENT_AXIS = "clients"
MODEL_AXIS = "model"


def make_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    devices = list(devices) if devices is not None else jax.devices()
    return Mesh(np.array(devices), (CLIENT_AXIS,))


def make_mesh2d(n_clients: int, n_model: int,
                devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """``clients`` × ``model`` mesh for pod-scale rounds: client
    fwd/bwd stays data-parallel over ``clients`` while server state
    (sketch table columns, momentum, error feedback) shards over
    ``model`` so per-device server memory scales as 1/``model``.
    ``--mesh 1x1`` and ``Mx1`` shapes keep the model axis at size 1,
    which every consumer treats as "replicated exactly like the 1-D
    mesh" — the compiled program is identical."""
    devices = list(devices) if devices is not None else jax.devices()
    need = n_clients * n_model
    if need > len(devices):
        raise ValueError(
            f"mesh {n_clients}x{n_model} needs {need} devices, "
            f"have {len(devices)}")
    arr = np.array(devices[:need]).reshape(n_clients, n_model)
    return Mesh(arr, (CLIENT_AXIS, MODEL_AXIS))


def carve_submeshes(demands, devices=None):
    """Disjoint per-job sub-meshes for the fedservice daemon: carve
    the pod's device list into consecutive blocks, one ``CxM`` mesh
    per ``(n_clients, n_model)`` demand, in demand order. The single
    sanctioned spatial-partitioning constructor — fedservice/ never
    builds a Mesh itself, so sharding layout (and the
    inline-partition-spec lint) keeps one owner. Each carved mesh is
    exactly what ``make_mesh2d(C, M, block)`` builds (``Mx1`` demands
    therefore behave like the 1-D mesh — see make_mesh2d), so a job
    admitted to a carved block compiles the same program it would
    compile on a standalone pod of that shape. Raises ValueError when
    the demands oversubscribe the pod — admission control surfaces
    this as a capacity rejection, never a partial carve."""
    devices = list(devices) if devices is not None else jax.devices()
    need = sum(int(c) * int(m) for c, m in demands)
    if need > len(devices):
        raise ValueError(
            f"sub-mesh demands need {need} devices "
            f"({[f'{c}x{m}' for c, m in demands]}), "
            f"have {len(devices)}")
    out, off = [], 0
    for c, m in demands:
        c, m = int(c), int(m)
        out.append(make_mesh2d(c, m, devices[off:off + c * m]))
        off += c * m
    return out


def client_axis_size(mesh: Mesh) -> int:
    """Devices along ``clients`` — the divisor for batch sharding and
    client-state padding (NOT ``mesh.devices.size``, which overcounts
    on a 2D mesh)."""
    if mesh is None:
        return 1
    return int(dict(mesh.shape).get(CLIENT_AXIS, mesh.devices.size))


def model_axis_size(mesh: Mesh) -> int:
    """Devices along ``model`` (1 for 1-D meshes / None): the server
    state shard count. All 2D-specific code gates on this being > 1."""
    if mesh is None:
        return 1
    return int(dict(mesh.shape).get(MODEL_AXIS, 1))


# ---------------------------------------------------------------------------
# Sanctioned PartitionSpec constructors. Everything outside parallel/
# must build specs through these (the ``inline-partition-spec`` lint
# rule, analysis/lint.py) so sharding layout has one source of truth.

def client_spec() -> P:
    """Leading axis sharded over ``clients`` (batches, client state)."""
    return P(CLIENT_AXIS)


def replicated_spec() -> P:
    return P()


def spec(*axes) -> P:
    """Generic escape hatch for composed layouts (e.g. the
    ``clients`` × ``seq`` specs in core/rounds_sp.py). Prefer the
    named constructors for anything that is server state."""
    return P(*axes)


def table_shard_spec() -> P:
    """Count-sketch table (r, c): rows replicated, columns sharded
    over ``model`` — every model peer owns a c/M column slice of all
    r rows, so shard-local bucket reads stay contiguous."""
    return P(None, MODEL_AXIS)


def server_state_spec(transmit_shape) -> P:
    """Server momentum / error-feedback buffers, shaped like the
    transmit: (r, c) sketch tables shard columns over ``model``;
    (d,) dense vectors shard the coordinate axis over ``model``."""
    if len(transmit_shape) == 2:
        return table_shard_spec()
    return P(MODEL_AXIS)


def server_state_sharding(mesh: Mesh, transmit_shape) -> NamedSharding:
    """NamedSharding for ServerState leaves: model-sharded when the
    mesh has a model axis of size > 1, replicated otherwise (exactly
    the 1-D layout). NamedSharding pads uneven dims internally, so
    (d,) vectors need no divisibility."""
    if model_axis_size(mesh) <= 1:
        return replicated(mesh)
    return NamedSharding(mesh, server_state_spec(transmit_shape))


def mesh_shape_dict(mesh: Optional[Mesh]) -> Optional[dict]:
    """``{axis: size}`` view of a mesh for manifests and checkpoint
    topology segments (None for the 1-D no-mesh path). The single
    serialisable mesh description the elastic-resume lineage is keyed
    by — comparing two of these answers "did the topology change?"."""
    if mesh is None:
        return None
    return {str(k): int(v) for k, v in dict(mesh.shape).items()}


def first_local_device() -> jax.Device:
    """Local device 0 — the canonical probe target for memory stats
    and placement checks. The single sanctioned raw-device escape
    hatch: telemetry code resolves devices through this module (the
    ``raw-devices`` lint rule, analysis/lint.py) so subset meshes and
    multi-host topologies keep one source of truth."""
    return jax.local_devices()[0]


def topology_summary() -> dict:
    """The run's device topology, as recorded by run manifests and
    ledger meta records (and used to key perf-gate baselines):
    ``{device_count, local_device_count, process_index, process_count,
    backend, device_kind}``. Degrades to a 1-device/1-process CPU
    shape if the backend cannot initialise (manifest writing must
    never take a run down)."""
    try:
        devices = jax.devices()
        return {
            "device_count": len(devices),
            "local_device_count": len(jax.local_devices()),
            "process_index": int(jax.process_index()),
            "process_count": int(jax.process_count()),
            "backend": jax.default_backend(),
            "device_kind": devices[0].device_kind if devices else "",
        }
    except Exception:
        return {"device_count": 1, "local_device_count": 1,
                "process_index": 0, "process_count": 1,
                "backend": "unknown", "device_kind": ""}


def initialize_multihost(coordinator_address: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None) -> int:
    """Join the JAX multi-controller runtime for multi-host pods — the
    TPU counterpart of the reference's NCCL process-group init
    (fed_aggregator.py:161-165), except one call replaces the whole
    PS/worker rank topology. After it returns, ``jax.devices()`` spans
    every host, ``make_mesh()`` covers ICI+DCN, and the per-round
    ``psum`` is routed hierarchically by XLA. On Cloud TPU the
    arguments are auto-detected from the environment; pass them
    explicitly elsewhere. Returns this process's index.

    No-op (returns the current process index) when the runtime is
    already initialised or when no cluster is detectable (plain
    single-process dev machine)."""
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id)
    except RuntimeError as e:
        # double-init is fine (the runtime is up); anything else —
        # connection/barrier failures on a real pod — must surface,
        # or each host would silently train alone
        if "only be called once" not in str(e):
            raise
    except ValueError:
        # "coordinator_address should be defined": only tolerable in
        # auto-detect mode on a plain single-process machine
        if (coordinator_address is not None
                or num_processes is not None
                or process_id is not None):
            raise
    return jax.process_index()


def maybe_initialize_multihost_cli(args) -> None:
    """Trainer-CLI wiring, shared by cv_train and gpt2_train: honor
    --device cpu (even where a sitecustomize pre-registers an
    accelerator plugin that outranks JAX_PLATFORMS; a no-op once JAX
    has initialised its backends), then join the multi-controller
    runtime when the pod flags (--coordinator_address/--num_processes/
    --process_id) are present."""
    if getattr(args, "device", None) == "cpu":
        jax.config.update("jax_platforms", "cpu")
    if args.coordinator_address is None and args.num_processes is None \
            and args.process_id is None:
        # --process_id alone still initializes (and surfaces
        # initialize_multihost's error if the rest can't be detected)
        # rather than silently training alone
        return
    pid = initialize_multihost(args.coordinator_address,
                               args.num_processes, args.process_id)
    print(f"multihost: process {pid}/{jax.process_count()}, "
          f"{jax.device_count()} devices")


def client_sharding(mesh: Mesh) -> NamedSharding:
    """Shard leading (client) axis across the mesh."""
    return NamedSharding(mesh, P(CLIENT_AXIS))


def padded_rows(num_clients: int, mesh: Mesh) -> int:
    """Leading-dim size for client-axis-sharded state buffers:
    NamedSharding rejects non-divisible dims, so round up to the mesh
    size (padded rows are never indexed — client ids < num_clients).
    Single source of truth for ClientStates.init and checkpoint
    restore. On a 2D mesh only the ``clients`` axis divides the
    leading dim (rows are replicated over ``model``)."""
    n = client_axis_size(mesh)
    return -(-num_clients // n) * n


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, tree):
    """Place a pytree of (W, ...)-leading arrays with the client axis
    sharded. When W doesn't divide the mesh size (XLA requires
    divisibility) the batch is replicated instead — correct, just not
    load-balanced; pick num_workers divisible by the device count for
    full throughput. The fallback warns once per W so the perf cliff
    is never silent (round-1 review, "mesh-shape perf cliffs")."""
    n = client_axis_size(mesh)

    def put(x):
        if x.shape[0] % n == 0:
            return jax.device_put(x, client_sharding(mesh))
        _warn_unsharded(x.shape[0], n)  # once per (W, n)
        return jax.device_put(x, replicated(mesh))

    return jax.tree_util.tree_map(put, tree)


_WARNED_UNSHARDED = set()


def _warn_unsharded(w: int, n: int):
    if n == 1 or (w, n) in _WARNED_UNSHARDED:
        return
    _WARNED_UNSHARDED.add((w, n))
    import warnings
    warnings.warn(
        f"batch leading dim {w} does not divide the {n}-device mesh: "
        f"replicating instead of sharding the client axis — every "
        f"device computes all {w} clients. Pick --num_workers "
        f"divisible by the device count for full throughput.",
        RuntimeWarning, stacklevel=4)  # shard_batch's caller
    # (stacklevel: warn <- _warn_unsharded <- put <- tree_map frames)
