from commefficient_tpu.parallel.mesh import (  # noqa: F401
    client_sharding,
    make_mesh,
    replicated,
)
from commefficient_tpu.parallel.ring_attention import (  # noqa: F401
    ring_attention,
    ulysses_attention,
)
