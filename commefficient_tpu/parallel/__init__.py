from commefficient_tpu.parallel.mesh import (  # noqa: F401
    client_axis_size,
    client_sharding,
    make_mesh,
    make_mesh2d,
    model_axis_size,
    replicated,
    server_state_sharding,
)
from commefficient_tpu.parallel.ring_attention import (  # noqa: F401
    ring_attention,
    ulysses_attention,
)
