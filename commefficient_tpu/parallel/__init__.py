from commefficient_tpu.parallel.mesh import (  # noqa: F401
    client_sharding,
    make_mesh,
    replicated,
)
