"""The ONE place raw wall/interval clocks live.

Every other module in ``commefficient_tpu`` times through these
aliases (or, better, through ``Telemetry.span``) so that a tier-1
grep test (tests/test_telemetry.py) can keep ad-hoc ``time.time()`` /
``perf_counter()`` timing from creeping back into the codebase — the
pre-telemetry state was three disjoint, schema-free views of the same
run (trainer state dicts, per-script JSON, a barely-used
``--tensorboard`` flag).

``wall``  — epoch seconds, for timestamps humans correlate with logs.
``tick``  — monotonic high-resolution clock, for intervals/spans.
"""

from __future__ import annotations

import time

wall = time.time
tick = time.perf_counter
