"""Declarative probe alarms (``--on_divergence``).

The probe layer (core/rounds.py + core/server.py, schema-v2 records)
gives every round a handful of host-side scalars; this module turns
them into actions so unattended runs fail loudly at the offending
round instead of silently training on garbage. Three rules:

``nan_inf``          — any NaN/Inf in the round's aggregated transmit
                       (``agg_nan`` + ``agg_inf`` > 0).
``residual_growth``  — the error-feedback residual norm grew by more
                       than ``--alarm_residual_ratio`` for
                       ``--alarm_residual_rounds`` CONSECUTIVE probed
                       rounds (one bad round is normal early in
                       training; a sustained geometric climb is the
                       EF-SGD divergence signature).
``recovery_error``   — relative sketch-recovery error above
                       ``--alarm_recovery_error`` (or non-finite);
                       1.0 means the recovered top-k is no better
                       than applying nothing.

Every fired rule is appended to the round record's ``alarms`` list
(when a ledger is attached) regardless of action. The action then
escalates: ``log`` warns, ``ledger-flag`` stays silent outside the
ledger, ``abort`` raises :class:`DivergenceAbort` — the trainers
catch it, flush telemetry (the flagged record becomes the run's final
round record) and stop, exactly like the existing NaN-loss path.
"""

from __future__ import annotations

import logging
import math

logger = logging.getLogger("commefficient_tpu.telemetry.alarms")

ACTIONS = ("log", "ledger-flag", "abort")


class DivergenceAbort(RuntimeError):
    """A probe alarm fired under ``--on_divergence abort``."""

    def __init__(self, round_index: int, alarms):
        self.round_index = int(round_index)
        self.alarms = list(alarms)
        rules = ", ".join(a["rule"] for a in self.alarms)
        super().__init__(
            f"probe alarm(s) [{rules}] at round {round_index}")


def _finite(v):
    return v is not None and math.isfinite(v)


class AlarmEngine:
    """Evaluates the alarm rules against each round's probe dict.

    Stateful only for the consecutive-rounds residual rule; one
    engine observes one run. ``telemetry`` may be a disabled
    Telemetry (alarms still evaluate and can still abort — the
    ledger flag is just unrecorded)."""

    def __init__(self, cfg, telemetry=None):
        assert cfg.on_divergence in ACTIONS, cfg.on_divergence
        self.action = cfg.on_divergence
        self.residual_ratio = float(cfg.alarm_residual_ratio)
        self.residual_rounds = int(cfg.alarm_residual_rounds)
        self.recovery_error = float(cfg.alarm_recovery_error)
        self.telemetry = telemetry
        self._consecutive = 0

    def check(self, round_index: int, probes) -> list:
        """Run every rule on one round's probes. Returns the fired
        alarm dicts (empty for a healthy round); flags them on the
        ledger record, then escalates per the configured action —
        ``abort`` raises :class:`DivergenceAbort` AFTER flagging so
        the record that reaches the sink carries its alarms."""
        if not probes:
            return []
        fired = []

        bad = (probes.get("agg_nan") or 0) + (probes.get("agg_inf")
                                              or 0)
        if bad > 0:
            fired.append({"rule": "nan_inf", "value": float(bad),
                          "threshold": 0.0})

        growth = probes.get("residual_growth")
        if growth is not None:
            if not _finite(growth) or growth > self.residual_ratio:
                self._consecutive += 1
            else:
                self._consecutive = 0
            if self._consecutive >= self.residual_rounds:
                fired.append({"rule": "residual_growth",
                              "value": float(growth),
                              "threshold": self.residual_ratio,
                              "consecutive": self._consecutive})

        rerr = probes.get("recovery_error")
        if rerr is not None and (not _finite(rerr)
                                 or rerr > self.recovery_error):
            fired.append({"rule": "recovery_error",
                          "value": float(rerr),
                          "threshold": self.recovery_error})

        if not fired:
            return []
        for alarm in fired:
            alarm["round"] = int(round_index)
            alarm["action"] = self.action
            if self.telemetry is not None:
                self.telemetry.flag_alarm(round_index, alarm)
        if self.action != "ledger-flag":
            for alarm in fired:
                logger.warning(
                    "probe alarm %s at round %d: value %.6g over "
                    "threshold %.6g", alarm["rule"], round_index,
                    alarm["value"], alarm["threshold"])
        if self.action == "abort":
            raise DivergenceAbort(round_index, fired)
        return fired


def build_alarm_engine(cfg, telemetry=None):
    """An engine when probes are on, else None (no per-round call)."""
    if getattr(cfg, "probe_period", 0):
        return AlarmEngine(cfg, telemetry)
    return None
