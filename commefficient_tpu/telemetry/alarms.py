"""Declarative probe alarms (``--on_divergence``).

The probe layer (core/rounds.py + core/server.py, schema-v2 records)
gives every round a handful of host-side scalars; this module turns
them into actions so unattended runs fail loudly at the offending
round instead of silently training on garbage. Three rules:

``nan_inf``          — any NaN/Inf in the round's aggregated transmit
                       (``agg_nan`` + ``agg_inf`` > 0).
``residual_growth``  — the error-feedback residual norm grew by more
                       than ``--alarm_residual_ratio`` for
                       ``--alarm_residual_rounds`` CONSECUTIVE probed
                       rounds (one bad round is normal early in
                       training; a sustained geometric climb is the
                       EF-SGD divergence signature).
``recovery_error``   — relative sketch-recovery error above
                       ``--alarm_recovery_error`` (or non-finite);
                       1.0 means the recovered top-k is no better
                       than applying nothing.
``step_time_regression`` — the round's wall step time drifted more
                       than ``--alarm_step_time_ratio`` x above the
                       run's rolling median (window
                       ``--alarm_step_time_window``, after a short
                       warmup that skips compile rounds). A
                       *performance* alarm, not an algorithmic one:
                       it catches the slow bleed (fragmentation, a
                       background compile storm, thermal throttle)
                       that end-of-run means average away. Evaluated
                       on synchronous rounds only — pipelined
                       dispatch times measure the host, not the
                       round.
``byzantine_suspect`` — a per-client transmit-norm outlier:
                       ``client_norm_max`` above
                       ``--alarm_byzantine_ratio`` x
                       ``client_norm_mean``. Sign-flip hides inside
                       the norm distribution; scaling/noise attacks
                       stick out here even when a robust fold has
                       already neutralised them — the operator wants
                       the *name* of the problem, not just survival.
``fold_rejection_rate`` — the robust fold (``--robust_agg``)
                       deviated from the plain mean by more than
                       ``--alarm_fold_rejection`` (relative). High
                       rejection means the fold is actively fighting
                       someone; sustained high rejection on honest
                       data means the trim/clip is set too tight.
``async_staleness``  — buffered-arrival health (``--async_buffer_size``
                       runs): the round folded an update staler than
                       ``--alarm_async_staleness`` rounds. A growing
                       max staleness means the arrival process is
                       outrunning the fold cadence (the buffer drains
                       older and older mass) — the serving analogue
                       of the residual-growth rule.
``privacy_budget_exhausted`` — DP runs (``--dp sketch``) with a hard
                       budget (``--dp_epsilon`` > 0): the accountant's
                       cumulative ε(δ) reached the budget. The runtime
                       routes the post-round ε through ``check`` as
                       the ``dp_epsilon`` probe (stamped on the v5
                       record either way), so under ``--on_divergence
                       abort`` the run stops AT the first round whose
                       release exhausted the budget — the noised
                       table was already released, so the abort is
                       "spend no further", not "unrelease". The alarm
                       dict carries ``rounds_left`` (the accountant's
                       pre-charge projection, 0 when already over) so
                       the ledger names the predicted exhaustion
                       round.
``job_starvation``   — fedservice daemon health (fedservice/): a
                       runnable job waited more than
                       ``--alarm_job_starvation`` scheduler ticks
                       since it last ran. Fired by the daemon's OWN
                       alarm engine against its fairness probes (the
                       per-job engines never see other jobs), so a
                       greedy scheduling policy that starves a tenant
                       fails loudly instead of silently serving one
                       job's traffic.
``admission_rejected`` — a JobSpec was refused at admission (capacity,
                       duplicate id/seed — the ``admission_rejected``
                       probe counts this tick's refusals). Always
                       armed on the daemon's engine, like ``nan_inf``:
                       a rejected manifest is an operator-visible
                       event whatever the thresholds say.
``slo_burn``         — declarative SLO health (telemetry/slo.py): the
                       run's worst multi-window error-budget burn
                       rate (``slo_burn_max`` probe) reached
                       ``--alarm_slo_burn``. Burn 1.0 means the run
                       is consuming its error budget exactly as fast
                       as the budget allows; the conventional paging
                       threshold is well above 1 (e.g. 2: the budget
                       dies in half its window). Evaluated via
                       ``check_slo`` on runs with their own SLO
                       engine, or through ``check`` when the SLO
                       probes arrive merged (the fedservice daemon's
                       fairness tick). Fires once per burning round —
                       the flight recorder's one-bundle-per-rule
                       policy keeps the postmortem volume bounded.
``collective_skew``  — trace-derived (schema-v4 ``device_time``): a
                       profiled round's straggler wait dominates its
                       collective bucket — max cross-device
                       enter-delta above ``--alarm_collective_skew``
                       x the round's collective seconds. The fleet
                       version of the step-time rule: one slow
                       participant taxes every device in the mesh,
                       and the skew decomposition names it. Only
                       rounds inside a trace window are evaluated.

Every fired rule is appended to the round record's ``alarms`` list
(when a ledger is attached) regardless of action. The action then
escalates: ``log`` warns, ``ledger-flag`` stays silent outside the
ledger, ``abort`` raises :class:`DivergenceAbort` — the trainers
catch it, flush telemetry (the flagged record becomes the run's final
round record) and stop, exactly like the existing NaN-loss path.
"""

from __future__ import annotations

import logging
import math
from collections import deque
from statistics import median

logger = logging.getLogger("commefficient_tpu.telemetry.alarms")

ACTIONS = ("log", "ledger-flag", "abort")


class DivergenceAbort(RuntimeError):
    """A probe alarm fired under ``--on_divergence abort``."""

    def __init__(self, round_index: int, alarms):
        self.round_index = int(round_index)
        self.alarms = list(alarms)
        rules = ", ".join(a["rule"] for a in self.alarms)
        super().__init__(
            f"probe alarm(s) [{rules}] at round {round_index}")


def _finite(v):
    return v is not None and math.isfinite(v)


class AlarmEngine:
    """Evaluates the alarm rules against each round's probe dict.

    Stateful only for the consecutive-rounds residual rule; one
    engine observes one run. ``telemetry`` may be a disabled
    Telemetry (alarms still evaluate and can still abort — the
    ledger flag is just unrecorded)."""

    #: step-time samples required before the regression rule arms —
    #: the first rounds carry compile/warmup time and are not signal
    STEP_TIME_WARMUP = 5

    def __init__(self, cfg, telemetry=None):
        assert cfg.on_divergence in ACTIONS, cfg.on_divergence
        self.action = cfg.on_divergence
        self.residual_ratio = float(cfg.alarm_residual_ratio)
        self.residual_rounds = int(cfg.alarm_residual_rounds)
        self.recovery_error = float(cfg.alarm_recovery_error)
        self.step_time_ratio = float(
            getattr(cfg, "alarm_step_time_ratio", 0.0) or 0.0)
        self.step_time_window = int(
            getattr(cfg, "alarm_step_time_window", 16) or 16)
        self.collective_skew = float(
            getattr(cfg, "alarm_collective_skew", 0.0) or 0.0)
        self.byzantine_ratio = float(
            getattr(cfg, "alarm_byzantine_ratio", 0.0) or 0.0)
        self.fold_rejection = float(
            getattr(cfg, "alarm_fold_rejection", 0.0) or 0.0)
        self.async_staleness = float(
            getattr(cfg, "alarm_async_staleness", 0.0) or 0.0)
        self.job_starvation = float(
            getattr(cfg, "alarm_job_starvation", 0.0) or 0.0)
        self.slo_burn = float(
            getattr(cfg, "alarm_slo_burn", 0.0) or 0.0)
        self.privacy_budget = (
            float(getattr(cfg, "dp_epsilon", 0.0) or 0.0)
            if str(getattr(cfg, "dp", "off")) != "off" else 0.0)
        self.telemetry = telemetry
        self._consecutive = 0
        self._step_times = deque(maxlen=self.step_time_window)

    def check(self, round_index: int, probes) -> list:
        """Run every rule on one round's probes. Returns the fired
        alarm dicts (empty for a healthy round); flags them on the
        ledger record, then escalates per the configured action —
        ``abort`` raises :class:`DivergenceAbort` AFTER flagging so
        the record that reaches the sink carries its alarms."""
        if not probes:
            return []
        fired = []

        bad = (probes.get("agg_nan") or 0) + (probes.get("agg_inf")
                                              or 0)
        if bad > 0:
            fired.append({"rule": "nan_inf", "value": float(bad),
                          "threshold": 0.0})

        growth = probes.get("residual_growth")
        if growth is not None:
            if not _finite(growth) or growth > self.residual_ratio:
                self._consecutive += 1
            else:
                self._consecutive = 0
            if self._consecutive >= self.residual_rounds:
                fired.append({"rule": "residual_growth",
                              "value": float(growth),
                              "threshold": self.residual_ratio,
                              "consecutive": self._consecutive})

        rerr = probes.get("recovery_error")
        if rerr is not None and (not _finite(rerr)
                                 or rerr > self.recovery_error):
            fired.append({"rule": "recovery_error",
                          "value": float(rerr),
                          "threshold": self.recovery_error})

        if self.byzantine_ratio > 0:
            cmax = probes.get("client_norm_max")
            cmean = probes.get("client_norm_mean")
            if cmax is not None and cmean is not None:
                ratio = (float(cmax) / float(cmean)
                         if float(cmean) > 0 else
                         (math.inf if float(cmax) > 0 else 0.0))
                if not _finite(ratio) \
                        or ratio > self.byzantine_ratio:
                    fired.append({"rule": "byzantine_suspect",
                                  "value": float(ratio),
                                  "threshold": self.byzantine_ratio,
                                  "client_norm_max": float(cmax),
                                  "client_norm_mean": float(cmean)})

        if self.fold_rejection > 0:
            frr = probes.get("fold_rejection_rate")
            if frr is not None and (not _finite(frr)
                                    or frr > self.fold_rejection):
                fired.append({"rule": "fold_rejection_rate",
                              "value": float(frr),
                              "threshold": self.fold_rejection})

        if self.async_staleness > 0:
            smax = probes.get("async_staleness_max")
            if smax is not None and (not _finite(smax)
                                     or smax > self.async_staleness):
                fired.append({
                    "rule": "async_staleness",
                    "value": float(smax),
                    "threshold": self.async_staleness,
                    "buffer_occupancy": probes.get(
                        "async_buffer_occupancy"),
                    "backlog": probes.get("async_backlog")})

        if self.job_starvation > 0:
            waited = probes.get("job_starved_rounds")
            if waited is not None and (not _finite(waited)
                                       or waited > self.job_starvation):
                fired.append({
                    "rule": "job_starvation",
                    "value": float(waited),
                    "threshold": self.job_starvation,
                    "job": probes.get("job_starved_index"),
                    "occupancy": probes.get("job_occupancy_min")})

        fired.extend(self._slo_rule(probes))

        rejected = probes.get("admission_rejected")
        if rejected is not None and float(rejected) > 0:
            fired.append({"rule": "admission_rejected",
                          "value": float(rejected),
                          "threshold": 0.0})

        if self.privacy_budget > 0:
            eps = probes.get("dp_epsilon")
            if eps is not None and (not _finite(eps)
                                    or eps >= self.privacy_budget):
                fired.append({
                    "rule": "privacy_budget_exhausted",
                    "value": float(eps),
                    "threshold": self.privacy_budget,
                    "dp_delta": probes.get("dp_delta"),
                    "dp_sigma": probes.get("dp_sigma"),
                    "rounds_left": probes.get("dp_rounds_left")})

        return self._escalate(round_index, fired)

    def _slo_rule(self, probes) -> list:
        """The ``slo_burn`` rule body (no escalation — callers own
        that): fires when the worst per-objective burn rate reaches
        ``--alarm_slo_burn``. The alarm dict carries every
        ``slo_burn_*`` probe so the ledger names WHICH objective is
        burning, not just that one is."""
        if self.slo_burn <= 0:
            return []
        burn = probes.get("slo_burn_max")
        if burn is None:
            return []
        if _finite(burn) and burn < self.slo_burn:
            return []
        alarm = {"rule": "slo_burn", "value": float(burn),
                 "threshold": self.slo_burn}
        for key, v in sorted(probes.items()):
            if key.startswith("slo_burn_") and key != "slo_burn_max":
                alarm[key] = None if v is None else float(v)
        return [alarm]

    def check_slo(self, round_index: int, slo_probes) -> list:
        """Evaluate ONLY the ``slo_burn`` rule on one round's SLO
        probes. The runtime routes the SLO engine's output here
        (rather than through ``check``) because ``check`` is stateful
        — calling it twice per round would double-advance the
        consecutive-residual counter. Same flag/log/abort escalation
        as every other rule."""
        if not slo_probes:
            return []
        return self._escalate(round_index,
                              self._slo_rule(slo_probes))

    def check_step_time(self, round_index: int, step_s: float) -> list:
        """``step_time_regression``: fires when this round's wall
        step time exceeds ``step_time_ratio`` x the rolling median of
        the last ``step_time_window`` rounds (after warmup). The
        offending sample is NOT folded into the window — a sustained
        regression keeps firing instead of re-normalising itself.
        Same flag/log/abort escalation as the probe rules."""
        if self.step_time_ratio <= 0:
            return []
        step_s = float(step_s)
        if len(self._step_times) < self.STEP_TIME_WARMUP:
            self._step_times.append(step_s)
            return []
        med = median(self._step_times)
        threshold = self.step_time_ratio * med
        if med <= 0 or step_s <= threshold:
            self._step_times.append(step_s)
            return []
        fired = [{"rule": "step_time_regression",
                  "value": step_s, "threshold": threshold,
                  "rolling_median": med}]
        return self._escalate(round_index, fired)

    def check_device_time(self, round_index: int, buckets) -> list:
        """``collective_skew``: fires when a traced round's max
        cross-device enter-delta (telemetry/trace.py skew stats)
        exceeds ``collective_skew`` x the round's collective bucket.
        Wired as ``Telemetry.on_device_time`` so it runs when trace
        buckets merge — after the round closed, before emission (the
        flagged record still reaches the sink with its alarms)."""
        if self.collective_skew <= 0 or not buckets:
            return []
        skew = buckets.get("skew") or {}
        delta = skew.get("max_enter_delta_s")
        coll = float(buckets.get("collective_s") or 0.0)
        if delta is None or coll <= 0:
            return []
        threshold = self.collective_skew * coll
        if float(delta) <= threshold:
            return []
        fired = [{"rule": "collective_skew",
                  "value": float(delta), "threshold": threshold,
                  "collective_s": coll,
                  "straggler_device": skew.get("straggler_device")}]
        return self._escalate(round_index, fired)

    def _escalate(self, round_index: int, fired: list) -> list:
        """Shared escalation tail: flag the ledger record, then act —
        ``abort`` raises AFTER flagging so the record that reaches the
        sink carries its alarms."""
        if not fired:
            return []
        for alarm in fired:
            alarm["round"] = int(round_index)
            alarm["action"] = self.action
            if self.telemetry is not None:
                self.telemetry.flag_alarm(round_index, alarm)
        if self.action != "ledger-flag":
            for alarm in fired:
                logger.warning(
                    "probe alarm %s at round %d: value %.6g over "
                    "threshold %.6g", alarm["rule"], round_index,
                    alarm["value"], alarm["threshold"])
        if self.action == "abort":
            raise DivergenceAbort(round_index, fired)
        return fired


def build_alarm_engine(cfg, telemetry=None):
    """An engine when probes are on or the step-time / collective-skew
    rules are armed, else None (no per-round call)."""
    if (getattr(cfg, "probe_period", 0)
            or float(getattr(cfg, "alarm_step_time_ratio", 0.0)
                     or 0.0) > 0
            or float(getattr(cfg, "alarm_collective_skew", 0.0)
                     or 0.0) > 0
            or float(getattr(cfg, "alarm_byzantine_ratio", 0.0)
                     or 0.0) > 0
            or float(getattr(cfg, "alarm_fold_rejection", 0.0)
                     or 0.0) > 0
            or float(getattr(cfg, "alarm_async_staleness", 0.0)
                     or 0.0) > 0
            or float(getattr(cfg, "alarm_job_starvation", 0.0)
                     or 0.0) > 0
            or float(getattr(cfg, "alarm_slo_burn", 0.0)
                     or 0.0) > 0
            or (str(getattr(cfg, "dp", "off")) != "off"
                and float(getattr(cfg, "dp_epsilon", 0.0) or 0.0)
                > 0)):
        return AlarmEngine(cfg, telemetry)
    return None
