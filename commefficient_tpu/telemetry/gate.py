"""Perf-gate math: noise-aware comparison of run metrics vs a
committed baseline.

The regression gate is the wall-clock sibling of
``audit_baseline.json``: ``perf_baseline.json`` pins, per metric, the
median and MAD (median absolute deviation) of the samples a reference
run produced, and ``compare()`` fails a fresh run only when it lands
outside BOTH a relative tolerance and a ``k x MAD`` noise band:

    lower-is-better:  fail when median_now > median_base
                                + max(rel_tol x median_base,
                                      mad_k x MAD_base)
    higher-is-better: symmetric, below the baseline

Median-of-N + MAD instead of mean + stddev because bench samples are
dispatch-latency contaminated (the relay adds rare 2-3x outliers):
one bad draw must move neither the baseline nor the verdict.

Metrics extracted from a ledger (``metrics_from_records``):

* ``span:<name>:ms`` — per-round host span samples (p50/p95 reported,
  the gate runs on the full sample set);
* ``device:<bucket>_s`` — schema-v3 per-round device-time buckets
  (compute/collective/transfer/host_gap/busy);
* ``bench:<metric>`` — bench-record headline values
  (clients/s — higher is better); a bench record's ``round_times_s``
  list also yields ``bench:<metric>:round_s`` samples.

Pure stdlib, no jax — importable by tier-1 unit tests and by
``scripts/perf_gate.py``.
"""

from __future__ import annotations

import json
from statistics import median
from typing import Dict, List

from commefficient_tpu.telemetry import clock

BASELINE_SCHEMA = 1

#: default gate knobs (CLI-overridable): generous enough for CI-class
#: noise, tight enough that a 2x regression can never pass
REL_TOL = 0.25
MAD_K = 5.0
#: a metric whose baseline median is under this (seconds-type metrics)
#: is below timer resolution/scheduler noise — never gated hard
MIN_GATED_SECONDS = 1e-4


def mad(samples: List[float]) -> float:
    """Median absolute deviation — the robust sigma."""
    if not samples:
        return 0.0
    m = median(samples)
    return median([abs(x - m) for x in samples])


def _pct(sorted_vals, q):
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def summarize_samples(samples: List[float], better: str) -> Dict:
    sv = sorted(samples)
    return {"median": median(sv), "mad": mad(sv), "n": len(sv),
            "p50": _pct(sv, 50), "p95": _pct(sv, 95),
            "better": better}


def metrics_from_records(records) -> Dict[str, Dict]:
    """Gateable metrics from one ledger's records (see module doc).
    Every metric value is a summarized sample set."""
    spans: Dict[str, List[float]] = {}
    device: Dict[str, List[float]] = {}
    bench: Dict[str, Dict] = {}
    for rec in records:
        kind = rec.get("kind")
        if kind == "round":
            for name, secs in (rec.get("spans") or {}).items():
                spans.setdefault(name, []).append(1e3 * float(secs))
            for bname, val in (rec.get("device_time") or {}).items():
                if isinstance(val, (int, float)):
                    device.setdefault(bname, []).append(float(val))
        elif kind == "bench":
            metric = rec.get("metric")
            if metric is None:
                continue
            val = rec.get("value")
            if isinstance(val, (int, float)):
                bench.setdefault(f"bench:{metric}", {
                    "samples": [], "better": "higher"})[
                        "samples"].append(float(val))
            times = rec.get("round_times_s")
            if isinstance(times, list) and times:
                bench.setdefault(f"bench:{metric}:round_s", {
                    "samples": [], "better": "lower"})[
                        "samples"].extend(float(t) for t in times)
    out: Dict[str, Dict] = {}
    for name, vals in sorted(spans.items()):
        out[f"span:{name}:ms"] = summarize_samples(vals, "lower")
    for name, vals in sorted(device.items()):
        better = "higher" if name == "roofline_utilization" else "lower"
        out[f"device:{name}"] = summarize_samples(vals, better)
    for name, entry in sorted(bench.items()):
        out[name] = summarize_samples(entry["samples"],
                                      entry["better"])
    return out


def make_baseline(metrics: Dict[str, Dict], *, source: str = "",
                  extra: Dict = None) -> Dict:
    base = {"schema": BASELINE_SCHEMA, "ts": clock.wall(),
            "source": source, "metrics": metrics}
    if extra:
        base.update(extra)
    return base


def _threshold(base_entry: Dict, rel_tol: float, mad_k: float):
    m = base_entry["median"]
    return max(rel_tol * abs(m), mad_k * base_entry.get("mad", 0.0))


def compare(baseline: Dict, metrics: Dict[str, Dict],
            rel_tol: float = REL_TOL,
            mad_k: float = MAD_K) -> Dict:
    """Gate ``metrics`` against ``baseline``. Returns::

        {"regressions": [...], "improvements": [...],
         "skipped": [...], "checked": N}

    Only metrics present on BOTH sides are gated (a new span or a
    trace-less run is a skip, not a failure). Sub-resolution timing
    metrics are never hard failures (MIN_GATED_SECONDS-equivalent:
    0.1 ms for ms-metrics, 100 µs for s-metrics)."""
    if baseline.get("schema") != BASELINE_SCHEMA:
        raise ValueError(
            f"baseline schema {baseline.get('schema')!r} != "
            f"{BASELINE_SCHEMA} — re-capture the baseline")
    base_metrics = baseline.get("metrics", {})
    regressions, improvements, skipped = [], [], []
    checked = 0
    for name in sorted(set(base_metrics) | set(metrics)):
        b, c = base_metrics.get(name), metrics.get(name)
        if b is None or c is None:
            skipped.append({"metric": name,
                            "reason": ("not in baseline" if b is None
                                       else "not in current run")})
            continue
        floor = (MIN_GATED_SECONDS * 1e3 if name.endswith(":ms")
                 else MIN_GATED_SECONDS)
        if name.startswith(("span:", "device:", "bench:")) and \
                name != "device:roofline_utilization" and \
                b["better"] == "lower" and abs(b["median"]) < floor:
            skipped.append({"metric": name,
                            "reason": "below timing resolution"})
            continue
        checked += 1
        tol = _threshold(b, rel_tol, mad_k)
        delta = c["median"] - b["median"]
        entry = {"metric": name, "baseline": b["median"],
                 "current": c["median"],
                 "delta": delta, "tolerance": tol,
                 "better": b["better"]}
        if b["better"] == "lower":
            if delta > tol:
                regressions.append(entry)
            elif delta < -tol:
                improvements.append(entry)
        else:
            if delta < -tol:
                regressions.append(entry)
            elif delta > tol:
                improvements.append(entry)
    return {"regressions": regressions,
            "improvements": improvements,
            "skipped": skipped, "checked": checked}


def render_verdict(verdict: Dict) -> str:
    lines = [f"perf gate: {verdict['checked']} metric(s) checked, "
             f"{len(verdict['regressions'])} regression(s), "
             f"{len(verdict['improvements'])} improvement(s), "
             f"{len(verdict['skipped'])} skipped"]
    for r in verdict["regressions"]:
        lines.append(
            f"  REGRESSION {r['metric']}: {r['baseline']:.6g} -> "
            f"{r['current']:.6g} ({'+' if r['delta'] >= 0 else ''}"
            f"{r['delta']:.6g}, tolerance {r['tolerance']:.6g}, "
            f"{r['better']} is better)")
    for r in verdict["improvements"]:
        lines.append(
            f"  improvement {r['metric']}: {r['baseline']:.6g} -> "
            f"{r['current']:.6g}")
    return "\n".join(lines)


def load_baseline(path: str) -> Dict:
    with open(path) as f:
        return json.load(f)


def save_baseline(baseline: Dict, path: str):
    with open(path, "w") as f:
        json.dump(baseline, f, indent=1, sort_keys=True)
        f.write("\n")
