"""Perf-gate math: noise-aware comparison of run metrics vs a
committed baseline.

The regression gate is the wall-clock sibling of
``audit_baseline.json``: ``perf_baseline.json`` pins, per metric, the
median and MAD (median absolute deviation) of the samples a reference
run produced, and ``compare()`` fails a fresh run only when it lands
outside BOTH a relative tolerance and a ``k x MAD`` noise band:

    lower-is-better:  fail when median_now > median_base
                                + max(rel_tol x median_base,
                                      mad_k x MAD_base)
    higher-is-better: symmetric, below the baseline

Median-of-N + MAD instead of mean + stddev because bench samples are
dispatch-latency contaminated (the relay adds rare 2-3x outliers):
one bad draw must move neither the baseline nor the verdict.

Metrics extracted from a ledger (``metrics_from_records``):

* ``span:<name>:ms`` — per-round host span samples (p50/p95 reported,
  the gate runs on the full sample set);
* ``device:<bucket>_s`` — schema-v3 per-round device-time buckets
  (compute/collective/transfer/host_gap/busy);
* ``bench:<metric>`` — bench-record headline values
  (clients/s — higher is better); a bench record's ``round_times_s``
  list also yields ``bench:<metric>:round_s`` samples;
* ``device:skew_*`` — schema-v4 collective-skew stats (max/p95
  cross-device enter-delta — lower is better).

Baselines are **topology-keyed** (schema 2): one committed
``perf_baseline.json`` holds an independent metrics entry per
``(device_count, process_count)`` point — suffixed ``m<C>x<M>`` for
2D-mesh runs and ``q<dtype>`` for quantized-wire runs — so the
8-device headline is guarded by an 8-device reference and can never
be "regressed" by comparison against a single-chip run, and an int8
wire is never compared against an f32 one. Schema-1 baselines (one flat,
topology-blind metrics dict) remain readable: they resolve for any
topology, exactly as they always did, until re-captured.

Pure stdlib, no jax — importable by tier-1 unit tests and by
``scripts/perf_gate.py``.
"""

from __future__ import annotations

import json
from statistics import median
from typing import Dict, List

from commefficient_tpu.telemetry import clock

BASELINE_SCHEMA = 2
READABLE_BASELINE_SCHEMAS = (1, 2)

#: topology key for runs whose device/process counts are unknown
#: (pre-fleet ledgers with no meta record; direct metrics-dict tests)
ANY_TOPOLOGY = "any"

#: default gate knobs (CLI-overridable): generous enough for CI-class
#: noise, tight enough that a 2x regression can never pass
REL_TOL = 0.25
MAD_K = 5.0
#: a metric whose baseline median is under this (seconds-type metrics)
#: is below timer resolution/scheduler noise — never gated hard
MIN_GATED_SECONDS = 1e-4


def mad(samples: List[float]) -> float:
    """Median absolute deviation — the robust sigma."""
    if not samples:
        return 0.0
    m = median(samples)
    return median([abs(x - m) for x in samples])


def _pct(sorted_vals, q):
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def summarize_samples(samples: List[float], better: str) -> Dict:
    sv = sorted(samples)
    return {"median": median(sv), "mad": mad(sv), "n": len(sv),
            "p50": _pct(sv, 50), "p95": _pct(sv, 95),
            "better": better}


def metrics_from_records(records) -> Dict[str, Dict]:
    """Gateable metrics from one ledger's records (see module doc).
    Every metric value is a summarized sample set."""
    spans: Dict[str, List[float]] = {}
    device: Dict[str, List[float]] = {}
    bench: Dict[str, Dict] = {}
    for rec in records:
        kind = rec.get("kind")
        if kind == "round":
            for name, secs in (rec.get("spans") or {}).items():
                spans.setdefault(name, []).append(1e3 * float(secs))
            dt = rec.get("device_time") or {}
            for bname, val in dt.items():
                if isinstance(val, (int, float)):
                    device.setdefault(bname, []).append(float(val))
            skew = dt.get("skew")
            if isinstance(skew, dict):
                for sname in ("max_enter_delta_s", "p95_enter_delta_s"):
                    val = skew.get(sname)
                    if isinstance(val, (int, float)):
                        device.setdefault(f"skew_{sname}",
                                          []).append(float(val))
        elif kind == "bench":
            metric = rec.get("metric")
            if metric is None:
                continue
            val = rec.get("value")
            if isinstance(val, (int, float)):
                bench.setdefault(f"bench:{metric}", {
                    "samples": [], "better": "higher"})[
                        "samples"].append(float(val))
            times = rec.get("round_times_s")
            if isinstance(times, list) and times:
                bench.setdefault(f"bench:{metric}:round_s", {
                    "samples": [], "better": "lower"})[
                        "samples"].extend(float(t) for t in times)
    out: Dict[str, Dict] = {}
    for name, vals in sorted(spans.items()):
        out[f"span:{name}:ms"] = summarize_samples(vals, "lower")
    for name, vals in sorted(device.items()):
        # more hidden collective time is better, like utilization;
        # every other device bucket is time spent (lower wins)
        better = ("higher" if name in ("roofline_utilization",
                                       "overlapped_s") else "lower")
        out[f"device:{name}"] = summarize_samples(vals, better)
    for name, entry in sorted(bench.items()):
        out[name] = summarize_samples(entry["samples"],
                                      entry["better"])
    return out


def mesh_suffix(mesh_shape) -> str:
    """Canonical key fragment for a run's mesh layout: ``m<C>x<M>``
    for a genuinely 2D (clients x model) mesh, ``""`` for the 1-D
    layouts every pre-mesh run used — so existing ``d<D>p<P>`` pins
    keep matching 1-D runs unchanged, and only mesh-sharded runs get
    (and require) their own entry. Accepts the ledger/manifest dict
    form ({"clients": C, "model": M}) or a (C, M) pair."""
    if not mesh_shape:
        return ""
    if isinstance(mesh_shape, dict):
        c = int(mesh_shape.get("clients", 0) or 0)
        m = int(mesh_shape.get("model", 0) or 0)
    else:
        c, m = (int(x) for x in tuple(mesh_shape)[:2])
    if m <= 1:
        return ""
    return f"m{c}x{m}"


def wire_suffix(wire_dtype) -> str:
    """Canonical key fragment for a run's uplink wire dtype:
    ``q<dtype>`` for quantized sketches (``qint8``, ``qbf16``,
    ``qfp8``), ``""`` for f32/unknown — so every pre-quantization pin
    keeps matching f32 runs unchanged, and a quantized run gets (and
    REQUIRES) its own entry. An int8 round moves ~4x fewer collective
    bytes than the f32 reference; letting it resolve an f32 pin would
    make the gate read the dtype change as a giant perf swing in both
    directions."""
    if not wire_dtype or str(wire_dtype) == "f32":
        return ""
    return f"q{wire_dtype}"


def async_suffix(async_k) -> str:
    """Canonical key fragment for a buffered-arrival run:
    ``a<K>`` when ``--async_buffer_size K`` was on, ``""`` for the
    synchronous barrier every pre-async pin measured. A buffered
    round overlaps the next cohort's arrivals with the fold, so its
    wall profile is a different experiment from the synchronous run
    of the same config — an async ledger must never resolve (or
    overwrite) a synchronous pin."""
    k = int(async_k or 0)
    return f"a{k}" if k > 0 else ""


def overlap_suffix(overlap_depth) -> str:
    """Canonical key fragment for a chunked-emission run: ``o<N>``
    when ``--overlap_depth N`` > 1 was on, ``""`` for the serial
    round every pre-overlap pin measured (depth 1 is HLO-identical to
    the pre-overlap program, so it keeps the bare key). A pipelined
    round's collective profile is a different experiment from the
    serial one — an o4 ledger must never resolve (or overwrite) an
    o1/bare pin, and there is NO cross-depth fallback (like the wire
    and async fragments, unlike the mesh fragment)."""
    n = int(overlap_depth or 0)
    return f"o{n}" if n > 1 else ""


def band_suffix(band) -> str:
    """Canonical key fragment for an autopilot-controlled run:
    ``b<lo-hi>`` (``b0.2-0.6``) when ``--autopilot on`` held the
    recovery error inside ``--autopilot_band LO:HI``, ``""`` for
    static-knob runs. An autopilot run's wall profile mixes every
    lattice point the controller visited (plus the re-jit cache's
    compile stalls), so it is a different experiment from any one
    static program — and two different bands walk different ladders.
    Like the wire/async/overlap fragments there is NO fallback: a
    banded ledger must never resolve (or overwrite) a static pin, nor
    another band's. Accepts "LO:HI", "LO-HI", or a (lo, hi) pair."""
    if not band:
        return ""
    if isinstance(band, str):
        s = band.replace(":", "-")
    else:
        lo, hi = (float(x) for x in tuple(band)[:2])
        s = f"{lo:g}-{hi:g}"
    return f"b{s}"


def privacy_suffix(dp_epsilon) -> str:
    """Canonical key fragment for a differentially-private run:
    ``p<eps>`` (``p3.5``; ``p0`` is DP with an unlimited budget) when
    ``--dp sketch`` clipped the clients and noised the aggregated
    table, ``""`` for the noiseless runs every pre-privacy pin
    measured. The calibrated Gaussian changes both what the ledger's
    recovery probes see and the round's wall profile (per-client
    clip, the noise draw, the forced-f32 wire), so a DP round is a
    different experiment from the same config without it — and two
    different budgets drive different autopilot walks. Like the
    wire/async/overlap/band fragments there is NO fallback in either
    direction: a DP ledger must never resolve (or overwrite) a
    noiseless pin, nor another budget's. ``dp_epsilon`` must be None
    for non-DP runs — 0.0 is a real value (unlimited budget), not an
    absence."""
    if dp_epsilon is None:
        return ""
    return f"p{float(dp_epsilon):g}"


def service_suffix(service_jobs) -> str:
    """Canonical key fragment for a multi-tenant fedservice run:
    ``j<J>`` when the daemon multiplexed J >= 2 jobs over the pod,
    ``""`` for solo runs — a single job through the daemon is
    bit-identical to driving the model directly (the fedservice
    parity contract), so it honestly keeps the bare key. A J-job
    run's wall profile interleaves J independent round programs (plus
    the scheduler's switching cost), which no single-job pin
    measured — and a 2-job and a 3-job pod are different experiments
    too. Like the wire/async/overlap/band/privacy fragments there is
    NO fallback in either direction: a j3 ledger must never resolve
    (or overwrite) a solo pin, nor a j2 one."""
    j = int(service_jobs or 0)
    return f"j{j}" if j > 1 else ""


def topology_key(device_count=None, process_count=None,
                 mesh_shape=None, wire_dtype=None,
                 async_k=None, overlap_depth=None, band=None,
                 dp_epsilon=None, service_jobs=None) -> str:
    """Baseline entry key for one topology point. ``d<D>p<P>`` when
    both counts are known — suffixed ``m<C>x<M>`` for 2D-mesh runs
    (a 4x2 and an 8x1 run on the same 8 chips are different programs,
    not one noise band), ``q<dtype>`` for quantized-wire runs
    (int8 vs f32 collectives are different experiments), ``a<K>``
    for buffered-arrival runs (an async fold overlaps work a barrier
    round waits for), ``o<N>`` for chunked-emission runs (a
    pipelined collective profile is a different experiment from the
    serial one), ``b<lo-hi>`` for autopilot-controlled runs (the
    knob walk mixes lattice points no static program mixes) and
    ``p<eps>`` for differentially-private runs (the clip + table
    noise is a different experiment from the noiseless program) —
    :data:`ANY_TOPOLOGY` otherwise: unknown
    topologies form their own bucket rather than silently matching a
    counted one. Quantized/async/overlapped/banded/private/
    multi-tenant runs with unknown counts still split off
    (``any-q<dtype>``, ``any-a<K>``, ``any-o<N>``, ``any-b<lo-hi>``,
    ``any-p<eps>``, ``any-j<J>``)."""
    if device_count is None or process_count is None:
        w = (wire_suffix(wire_dtype) + async_suffix(async_k)
             + overlap_suffix(overlap_depth) + band_suffix(band)
             + privacy_suffix(dp_epsilon)
             + service_suffix(service_jobs))
        return f"{ANY_TOPOLOGY}-{w}" if w else ANY_TOPOLOGY
    return (f"d{int(device_count)}p{int(process_count)}"
            f"{mesh_suffix(mesh_shape)}{wire_suffix(wire_dtype)}"
            f"{async_suffix(async_k)}{overlap_suffix(overlap_depth)}"
            f"{band_suffix(band)}{privacy_suffix(dp_epsilon)}"
            f"{service_suffix(service_jobs)}")


def make_topology_entry(metrics: Dict[str, Dict], *, source: str = "",
                        device_count=None, process_count=None,
                        config_hash: str = "", mesh_shape=None,
                        wire_dtype=None, async_k=None,
                        overlap_depth=None, band=None,
                        dp_epsilon=None, service_jobs=None) -> Dict:
    entry = {"ts": clock.wall(), "source": source, "metrics": metrics}
    if device_count is not None:
        entry["device_count"] = int(device_count)
    if process_count is not None:
        entry["process_count"] = int(process_count)
    if config_hash:
        entry["config_hash"] = config_hash
    if mesh_suffix(mesh_shape):
        entry["mesh_shape"] = (dict(mesh_shape)
                               if isinstance(mesh_shape, dict)
                               else list(mesh_shape))
    if wire_suffix(wire_dtype):
        entry["wire_dtype"] = str(wire_dtype)
    if async_suffix(async_k):
        entry["async_buffer_size"] = int(async_k)
    if overlap_suffix(overlap_depth):
        entry["overlap_depth"] = int(overlap_depth)
    if band_suffix(band):
        entry["autopilot_band"] = (str(band) if isinstance(band, str)
                                   else list(band))
    if privacy_suffix(dp_epsilon):
        entry["dp_epsilon"] = float(dp_epsilon)
    if service_suffix(service_jobs):
        entry["service_jobs"] = int(service_jobs)
    return entry


def make_baseline(metrics: Dict[str, Dict], *, source: str = "",
                  extra: Dict = None, device_count=None,
                  process_count=None, config_hash: str = "",
                  mesh_shape=None, wire_dtype=None,
                  async_k=None, overlap_depth=None,
                  band=None, dp_epsilon=None,
                  service_jobs=None) -> Dict:
    """A fresh schema-2 baseline holding one topology entry."""
    key = topology_key(device_count, process_count, mesh_shape,
                       wire_dtype, async_k, overlap_depth, band,
                       dp_epsilon, service_jobs)
    base = {"schema": BASELINE_SCHEMA, "ts": clock.wall(),
            "topologies": {key: make_topology_entry(
                metrics, source=source, device_count=device_count,
                process_count=process_count, config_hash=config_hash,
                mesh_shape=mesh_shape, wire_dtype=wire_dtype,
                async_k=async_k, overlap_depth=overlap_depth,
                band=band, dp_epsilon=dp_epsilon,
                service_jobs=service_jobs)}}
    if extra:
        base.update(extra)
    return base


def migrate_baseline(baseline: Dict) -> Dict:
    """Schema-1 -> schema-2: the flat metrics dict becomes the
    :data:`ANY_TOPOLOGY` entry (it was captured topology-blind, so
    that is the honest key). Schema-2 passes through unchanged."""
    if baseline.get("schema") == BASELINE_SCHEMA:
        return baseline
    return {"schema": BASELINE_SCHEMA,
            "ts": baseline.get("ts", clock.wall()),
            "topologies": {ANY_TOPOLOGY: {
                "ts": baseline.get("ts", clock.wall()),
                "source": baseline.get("source", ""),
                "metrics": baseline.get("metrics", {})}}}


def update_baseline(baseline: Dict, metrics: Dict[str, Dict], *,
                    source: str = "", device_count=None,
                    process_count=None, config_hash: str = "",
                    mesh_shape=None, wire_dtype=None,
                    async_k=None, overlap_depth=None,
                    band=None, dp_epsilon=None,
                    service_jobs=None) -> Dict:
    """Insert/replace ONE topology's entry, leaving every other
    topology point untouched — how the gate CLI re-captures the
    8-device headline without disturbing the single-chip one.
    Schema-1 input is migrated first. Returns the (new) baseline."""
    base = migrate_baseline(dict(baseline)) if baseline else \
        {"schema": BASELINE_SCHEMA, "ts": clock.wall(),
         "topologies": {}}
    base["topologies"] = dict(base.get("topologies", {}))
    key = topology_key(device_count, process_count, mesh_shape,
                       wire_dtype, async_k, overlap_depth, band,
                       dp_epsilon, service_jobs)
    base["topologies"][key] = make_topology_entry(
        metrics, source=source, device_count=device_count,
        process_count=process_count, config_hash=config_hash,
        mesh_shape=mesh_shape, wire_dtype=wire_dtype,
        async_k=async_k, overlap_depth=overlap_depth, band=band,
        dp_epsilon=dp_epsilon, service_jobs=service_jobs)
    base["ts"] = clock.wall()
    return base


def baseline_entry(baseline: Dict, device_count=None,
                   process_count=None, mesh_shape=None,
                   wire_dtype=None, async_k=None,
                   overlap_depth=None, band=None, dp_epsilon=None,
                   service_jobs=None):
    """The topology entry ``compare`` gates against, or None when the
    baseline has no entry for this topology. A 2D-mesh run resolves
    its exact ``d<D>p<P>m<C>x<M>`` entry first and falls back to the
    mesh-blind ``d<D>p<P>`` pin (pins captured before mesh keying
    existed keep gating until re-captured — migration, not a hole).
    Quantized-wire and buffered-arrival runs get NO such fallback: an
    int8 run must never resolve an f32 pin (the dtype changes the
    collective bytes ~4x) and an async run must never resolve a
    synchronous pin (the buffered fold overlaps waits the barrier
    round eats) — cross-mode comparison is a category error, not
    noise. An ungated quantized/async topology stays None (compare
    raises loudly). Schema-1 baselines resolve for ANY topology
    (their historical, topology-blind behaviour — re-capture to get
    keyed guarding)."""
    schema = baseline.get("schema")
    if schema not in READABLE_BASELINE_SCHEMAS:
        raise ValueError(
            f"baseline schema {schema!r} not in "
            f"{READABLE_BASELINE_SCHEMAS} — re-capture the baseline")
    if schema == 1:
        return {"source": baseline.get("source", ""),
                "metrics": baseline.get("metrics", {})}
    topologies = baseline.get("topologies", {})
    entry = topologies.get(
        topology_key(device_count, process_count, mesh_shape,
                     wire_dtype, async_k, overlap_depth, band,
                     dp_epsilon, service_jobs))
    if entry is None and mesh_suffix(mesh_shape):
        # drop only the mesh fragment; the wire, async, overlap, band,
        # privacy AND service fragments stay — there is no
        # cross-dtype, cross-mode, cross-depth, cross-band,
        # cross-budget or cross-J fallback (an o2 pipelined round has
        # a different collective schedule than the serial o1 program;
        # a b0.2-0.6 autopilot walk mixes programs no static pin
        # measured; a p3.5 run's probes carry calibrated noise no
        # noiseless pin ever saw; a j3 pod interleaves three round
        # programs no solo pin ever dispatched)
        entry = topologies.get(
            topology_key(device_count, process_count,
                         wire_dtype=wire_dtype, async_k=async_k,
                         overlap_depth=overlap_depth, band=band,
                         dp_epsilon=dp_epsilon,
                         service_jobs=service_jobs))
    return entry


def _threshold(base_entry: Dict, rel_tol: float, mad_k: float):
    m = base_entry["median"]
    return max(rel_tol * abs(m), mad_k * base_entry.get("mad", 0.0))


def compare(baseline: Dict, metrics: Dict[str, Dict],
            rel_tol: float = REL_TOL,
            mad_k: float = MAD_K, device_count=None,
            process_count=None, mesh_shape=None,
            wire_dtype=None, async_k=None,
            overlap_depth=None, band=None, dp_epsilon=None,
            service_jobs=None) -> Dict:
    """Gate ``metrics`` against ``baseline``'s entry for this
    topology. Returns::

        {"regressions": [...], "improvements": [...],
         "skipped": [...], "checked": N, "topology": key}

    Only metrics present on BOTH sides are gated (a new span or a
    trace-less run is a skip, not a failure). Sub-resolution timing
    metrics are never hard failures (MIN_GATED_SECONDS-equivalent:
    0.1 ms for ms-metrics, 100 µs for s-metrics). Raises ValueError
    when the baseline has no entry for this topology — an ungated
    topology point must fail loudly, not pass silently."""
    key = topology_key(device_count, process_count, mesh_shape,
                       wire_dtype, async_k, overlap_depth, band,
                       dp_epsilon, service_jobs)
    entry = baseline_entry(baseline, device_count, process_count,
                           mesh_shape, wire_dtype, async_k,
                           overlap_depth, band, dp_epsilon,
                           service_jobs)
    if entry is None:
        have = ", ".join(sorted(baseline.get("topologies", {}))) \
            or "none"
        raise ValueError(
            f"no baseline entry for topology {key} (have: {have}) — "
            f"capture one with --write-baseline")
    base_metrics = entry.get("metrics", {})
    regressions, improvements, skipped = [], [], []
    checked = 0
    for name in sorted(set(base_metrics) | set(metrics)):
        b, c = base_metrics.get(name), metrics.get(name)
        if b is None or c is None:
            skipped.append({"metric": name,
                            "reason": ("not in baseline" if b is None
                                       else "not in current run")})
            continue
        floor = (MIN_GATED_SECONDS * 1e3 if name.endswith(":ms")
                 else MIN_GATED_SECONDS)
        if name.startswith(("span:", "device:", "bench:")) and \
                name != "device:roofline_utilization" and \
                b["better"] == "lower" and abs(b["median"]) < floor:
            skipped.append({"metric": name,
                            "reason": "below timing resolution"})
            continue
        checked += 1
        tol = _threshold(b, rel_tol, mad_k)
        delta = c["median"] - b["median"]
        entry = {"metric": name, "baseline": b["median"],
                 "current": c["median"],
                 "delta": delta, "tolerance": tol,
                 "better": b["better"]}
        if b["better"] == "lower":
            if delta > tol:
                regressions.append(entry)
            elif delta < -tol:
                improvements.append(entry)
        else:
            if delta < -tol:
                regressions.append(entry)
            elif delta > tol:
                improvements.append(entry)
    return {"regressions": regressions,
            "improvements": improvements,
            "skipped": skipped, "checked": checked,
            "topology": key}


def render_verdict(verdict: Dict) -> str:
    topo = verdict.get("topology")
    lines = [f"perf gate"
             f"{f' [{topo}]' if topo else ''}: "
             f"{verdict['checked']} metric(s) checked, "
             f"{len(verdict['regressions'])} regression(s), "
             f"{len(verdict['improvements'])} improvement(s), "
             f"{len(verdict['skipped'])} skipped"]
    for r in verdict["regressions"]:
        lines.append(
            f"  REGRESSION {r['metric']}: {r['baseline']:.6g} -> "
            f"{r['current']:.6g} ({'+' if r['delta'] >= 0 else ''}"
            f"{r['delta']:.6g}, tolerance {r['tolerance']:.6g}, "
            f"{r['better']} is better)")
    for r in verdict["improvements"]:
        lines.append(
            f"  improvement {r['metric']}: {r['baseline']:.6g} -> "
            f"{r['current']:.6g}")
    return "\n".join(lines)


def load_baseline(path: str) -> Dict:
    with open(path) as f:
        return json.load(f)


def save_baseline(baseline: Dict, path: str):
    with open(path, "w") as f:
        json.dump(baseline, f, indent=1, sort_keys=True)
        f.write("\n")
