"""Flight recorder: bounded round ring + atomic postmortem bundles.

A crashed or alarming run's most valuable evidence is the last few
rounds of full-fidelity telemetry — exactly the records the ledger
may not have flushed (or the operator may not have enabled). The
recorder is an ordinary telemetry sink keeping an in-memory ring of
the last N round records (plus the run's meta record and a short
queue of recent compile/alarm events); on any alarm fire,
``GracefulShutdown``, or unhandled crash it dumps a **postmortem
bundle** — one self-describing JSON file under
``--postmortem_dir`` (default ``runs/postmortems/``) written with
the registry's tmp + fsync + rename discipline, so a bundle either
exists completely or not at all (a SIGKILL mid-dump leaves only the
inert ``.tmp``). When a ``runs_dir`` is known the bundle is also
stamped into the run registry (``postmortem`` lineage keys) so
``telemetry_report.py --postmortem`` and the runs-dir report can
find it.

Dump policy: one bundle per distinct firing rule per run (a rule
that keeps firing re-describes the same incident), plus one each for
``graceful_shutdown`` and ``crash``. Dumps are observability — every
failure degrades to a warning, never to failing the run it observes.
"""

from __future__ import annotations

import json
import os
import sys
import threading
from collections import deque

from commefficient_tpu.telemetry import clock
from commefficient_tpu.telemetry.record import validate_record
from commefficient_tpu.telemetry.sinks import _json_default

POSTMORTEM_SCHEMA = 1
POSTMORTEM_PREFIX = "postmortem_"

#: lock-confinement declarations (flowlint ``lock-confinement``).
#: The recorder is written by the round loop but dumped from OTHER
#: threads — the crash excepthook fires on whichever thread raised,
#: and a daemon's alarm path can dump while another job's sink is
#: mid-``write``. Iterating ``_ring``/``_events`` (deques) while a
#: writer appends past maxlen raises ``RuntimeError: deque mutated
#: during iteration``, so every touch goes through ``_lock``.
_LOCK_MAP = {
    "_ring": "_lock",
    "_events": "_lock",
    "_meta": "_lock",
    "_dumped": "_lock",
    "last_bundle": "_lock",
}

#: recent compile/alarm events retained alongside the round ring
EVENT_QUEUE = 64

#: bundle keys every reader may rely on
BUNDLE_REQUIRED_KEYS = (
    "schema", "kind", "ts", "reason", "rule", "labels", "config",
    "config_hash", "ring_rounds", "rounds", "events", "meta",
    "environment",
)


class FlightRecorder:
    """Sink-shaped ring of the last ``ring_rounds`` emitted records.

    ``labels`` (job/process/run) stamp the bundle; ``runs_dir``
    (optional) arms the registry lineage stamp. ``out_dir`` overrides
    ``cfg.postmortem_dir`` (tests)."""

    def __init__(self, cfg, ring_rounds: int, labels=None,
                 runs_dir: str = "", out_dir: str = ""):
        assert int(ring_rounds) > 0, ring_rounds
        from commefficient_tpu.telemetry import registry
        self._cfg = cfg
        self.ring_rounds = int(ring_rounds)
        self._lock = threading.Lock()
        self._ring = deque(maxlen=self.ring_rounds)
        self._events = deque(maxlen=EVENT_QUEUE)
        self._meta = None
        self.labels = {k: str(v) for k, v in (labels or {}).items()}
        self.runs_dir = runs_dir
        self.out_dir = (out_dir
                        or str(getattr(cfg, "postmortem_dir", "")
                               or "runs/postmortems"))
        self._config = registry.config_dict(cfg)
        self._config_hash = registry.config_hash(cfg)
        self._dumped = set()
        #: path of the most recent bundle (None before any dump)
        self.last_bundle = None

    # ------------------------------------------------------------- sink

    def write(self, rec):
        kind = rec.get("kind")
        if kind == "meta":
            with self._lock:
                self._meta = dict(rec)
            return
        if kind != "round":
            return
        counters = rec.get("counters") or {}
        alarms = rec.get("alarms") or []
        with self._lock:
            self._ring.append(rec)
            if counters.get("compile_events"):
                self._events.append({
                    "kind": "compile", "round": rec.get("round"),
                    "events": counters["compile_events"],
                    "secs": counters.get("compile_secs")})
            for alarm in alarms:
                self._events.append(dict(alarm, kind="alarm"))
        if alarms:
            # the firing record is already IN the ring (appended
            # above), so the bundle always contains its own trigger;
            # dump() takes the lock itself, so call it outside ours
            context = {"alarms": alarms, "round": rec.get("round")}
            diff = self._critpath_diff(rec,
                                       {str(a.get("rule"))
                                        for a in alarms})
            if diff is not None:
                context["critpath_diff"] = diff
            self.dump("alarm", rule=str(alarms[0].get("rule")),
                      context=context)

    #: latency-shaped rules whose postmortems benefit from a causal
    #: "why": the bundle gets the firing round's critical path diffed
    #: against the ring's rolling-median round
    CRITPATH_RULES = ("step_time_regression", "slo_burn")

    def _critpath_diff(self, rec, rules):
        """Critical-path diff of the firing round vs the per-bucket
        median of the prior ring (--causal_trace runs only; any
        failure degrades to None — this is bundle garnish, never a
        reason to lose the bundle)."""
        if not rules.intersection(self.CRITPATH_RULES) \
                or not isinstance(rec.get("causal"), dict):
            return None
        try:
            from commefficient_tpu.telemetry.critpath import (
                critical_path, critpath_diff, median_buckets)
            with self._lock:
                prior = [r for r in self._ring if r is not rec
                         and isinstance(r.get("causal"), dict)]
            cur = critical_path(rec["causal"], rec.get("device_time"))
            base = median_buckets(
                [critical_path(r["causal"], r.get("device_time"))
                 for r in prior])
            if base is None:
                return None
            return critpath_diff(cur, base)
        except Exception:  # noqa: BLE001 — observability only
            return None

    def close(self):
        pass  # the ring is only evidence; nothing to flush

    # ------------------------------------------------------------- dump

    def dump(self, reason: str, rule=None, context=None):
        """Write one atomic postmortem bundle; returns its path (or
        the prior path when this (reason, rule) already dumped, or
        None when the write failed — warned, never raised)."""
        key = (str(reason), None if rule is None else str(rule))
        with self._lock:
            if key in self._dumped:
                return self.last_bundle
            # claim the key BEFORE the file I/O so a concurrent dump
            # of the same incident (crash hook racing the alarm path)
            # can't write twice; rolled back below if the write fails.
            # Snapshot the ring under the same lock — a writer
            # appending past maxlen while we iterate would raise
            # "deque mutated during iteration" and lose the bundle.
            self._dumped.add(key)
            rounds = list(self._ring)
            events = list(self._events)
            meta = self._meta
        bundle = {
            "schema": POSTMORTEM_SCHEMA,
            "kind": "postmortem",
            "ts": clock.wall(),
            "reason": str(reason),
            "rule": None if rule is None else str(rule),
            "context": context or {},
            "labels": dict(self.labels),
            "config": self._config,
            "config_hash": self._config_hash,
            "ring_rounds": self.ring_rounds,
            "rounds": rounds,
            "events": events,
            "meta": meta,
        }
        try:
            from commefficient_tpu.telemetry import registry
            bundle["environment"] = registry._environment()
            os.makedirs(self.out_dir, exist_ok=True)
            tag = f"{reason}" + (f"_{rule}" if rule else "")
            name = f"{POSTMORTEM_PREFIX}{int(bundle['ts'])}_{tag}"
            path = os.path.join(self.out_dir, name + ".json")
            n = 1
            while os.path.exists(path):
                path = os.path.join(self.out_dir,
                                    f"{name}.{n}.json")
                n += 1
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(bundle, f, indent=1, sort_keys=True,
                          default=_json_default)
                f.write("\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except Exception as e:  # noqa: BLE001 — observability only
            print(f"WARNING: postmortem bundle not written "
                  f"({type(e).__name__}: {e})", file=sys.stderr)
            with self._lock:
                self._dumped.discard(key)
            return None
        with self._lock:
            self.last_bundle = path
        if self.runs_dir:
            try:
                from commefficient_tpu.telemetry import registry
                manifest = registry.write_manifest(
                    self.runs_dir, args=self._cfg,
                    ledger=str(getattr(self._cfg, "ledger", "")
                               or ""),
                    extra={"postmortem": os.path.abspath(path),
                           "postmortem_reason": str(reason),
                           "postmortem_rule": bundle["rule"],
                           "job_id": self.labels.get("job")})
                # back-pointer: the bundle's registry lineage entry
                bundle["manifest"] = os.path.abspath(manifest)
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(bundle, f, indent=1, sort_keys=True,
                              default=_json_default)
                    f.write("\n")
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)
            except Exception as e:  # noqa: BLE001
                print(f"WARNING: postmortem registry stamp failed "
                      f"({type(e).__name__}: {e})", file=sys.stderr)
        return path


def install_crash_hook(recorder: FlightRecorder):
    """Chain ``sys.excepthook`` so an unhandled crash dumps a bundle
    before the traceback prints. Returns the installed hook (tests
    restore the prior one themselves)."""
    prev = sys.excepthook

    def _hook(tp, val, tb):
        try:
            recorder.dump(
                "crash",
                context={"exception": f"{tp.__name__}: {val}"})
        except Exception:  # noqa: BLE001 — never mask the crash
            pass
        prev(tp, val, tb)

    sys.excepthook = _hook
    return _hook


def load_postmortem(path: str):
    """Read + validate a bundle: ``(bundle, problems)``. Problems are
    strings (missing keys, invalid ring records); an unreadable file
    raises like any other open/parse error — the caller asked for
    THIS file."""
    with open(path) as f:
        bundle = json.load(f)
    problems = []
    if bundle.get("kind") != "postmortem":
        problems.append(f"kind {bundle.get('kind')!r} is not "
                        "'postmortem'")
    if bundle.get("schema") != POSTMORTEM_SCHEMA:
        problems.append(f"schema {bundle.get('schema')!r} != "
                        f"{POSTMORTEM_SCHEMA}")
    for key in BUNDLE_REQUIRED_KEYS:
        if key not in bundle:
            problems.append(f"bundle missing {key!r}")
    rounds = bundle.get("rounds")
    if not isinstance(rounds, list):
        problems.append("rounds is not a list")
    else:
        if len(rounds) > int(bundle.get("ring_rounds") or 0):
            problems.append("rounds overflow the declared ring size")
        for rec in rounds:
            for p in validate_record(rec):
                problems.append(f"round {rec.get('round')}: {p}")
    return bundle, problems
