"""Ledger record schema (version 2).

A run ledger is a JSONL file: one self-describing record per line.
Every record carries ``schema`` (this module's version) and ``kind``:

``meta``   — one per run, first line: the static description of the
             round program (mode, grad_size, geometry, the
             ``core.rounds.round_plan`` dict) so a ledger is
             interpretable without the launching command line.
``round``  — one per TRAINING round: wall-time spans (seconds) for
             sampler / gather / h2d / round dispatch / metrics
             materialisation / server step / write-back, counters
             (clientstore prefetch hit-vs-miss, compile events),
             uplink/downlink bytes (identical to FedModel's
             accounting counters), and host-RSS / HBM peak
             watermarks.
``epoch``  — the trainer's per-epoch TableLogger row.
``bench``  — a benchmark headline metric (bench.py, scripts/*_bench):
             the same schema whether it lands in BENCH_*.json's
             harness line or a run ledger.
``summary``— end-of-run aggregate (ConsoleSink's closing record).

Span attribution note: the ``sampler`` span measures fetching the
NEXT round's batch and is attributed to the round that is open while
the fetch happens (the first fetch of a run precedes any round and is
not recorded).

Schema v2 (backward-readable — readers accept both versions) adds two
keys to round records:

``probes`` — None when probing is off, else the round's algorithm
             diagnostics dict (core/rounds.py + core/server.py probe
             outputs: update/residual/momentum norms, NaN/Inf counts,
             mass coverage, sketch-recovery error, host-derived
             residual growth ratio). Keys vary by mode and cadence.
``alarms`` — list of alarm dicts appended by telemetry/alarms.py
             ({"rule", "value", "threshold", "action"}); empty when
             nothing fired. A round that triggered ``--on_divergence
             abort`` is the flagged final record of the run.

Schema v3 adds one key to round records:

``device_time`` — None unless the round ran inside a profiler trace
             window (``--profile``), else the parsed device-timeline
             buckets (telemetry/trace.py attribute_rounds): window_s /
             busy_s / compute_s / collective_s / transfer_s /
             host_gap_s, plus ``roofline_utilization`` (expected
             lower-bound round time over measured busy time,
             analysis/cost.py) when a cost model was registered.
             compute + collective + transfer + host_gap == window by
             construction.

Schema v4 is a fleet extension — no new required keys, two content
changes:

``device_time.per_device`` / ``device_time.skew`` — the aggregate
             buckets gain nested per-device buckets ({busy, compute,
             collective, transfer, wait, wire} per device lane; wait
             + wire == collective exactly) and round-level collective
             skew stats (max/p95 enter-delta seconds, straggler
             device id, matched-collective count). These are the only
             dict-valued entries allowed inside ``device_time``.
             Additive numeric buckets stay schema-4: ``overlapped_s``
             (collective wall time hidden behind some lane's compute,
             telemetry/trace.py — ``collective_s - overlapped_s`` is
             the serial collective share) appears on traces parsed
             after --overlap_depth landed; readers treat any extra
             numeric bucket generically.
``process`` — optional on every record: the jax process index that
             observed it. Stamped by the per-process ledger shards
             (``<ledger>.p<k>.jsonl``, telemetry/core.py) so merged
             multi-host ledgers (scripts/ledger_merge.py) stay
             attributable.

Schema v5 adds three keys to round records (the DP ledger trail,
privacy/):

``dp_epsilon`` — None outside ``--dp sketch`` runs, else the
             accountant's cumulative ε(δ) AFTER this round was
             charged — the record stream is the spend trajectory, and
             the ``privacy_budget_exhausted`` alarm reads the same
             value.
``dp_delta``   — the δ the ε above is stated at (``--dp_delta``);
             None outside DP runs.
``dp_sigma``   — the effective noise multiplier this round was
             charged at (the dispatched variant's ``dp_noise_mult``
             over the round's staleness weight scale); None outside
             DP runs.

Schema v6 adds one key to round records (the live operations plane,
telemetry/slo.py + telemetry/flightrec.py) and two summary-record
conventions:

``slo``    — None unless an SLO engine evaluated the round, else the
             per-objective {target, seen, fast_rate, slow_rate,
             burn} snapshot (``SLOEngine.stamp``) taken after the
             round's observation — the ledger twin of the
             ``slo_burn_*`` probe keys the same engine merges into
             ``probes``.
``alarm_fired`` — summary records (and only summary records) may
             carry the run's alarm totals by rule
             ({rule: count}); ``Telemetry.close`` emits one when any
             alarm fired, so ``telemetry_report.py`` shows alarm
             totals without scanning every round record.
``postmortem`` — not a ledger record: flight-recorder bundles
             (kind ``postmortem``, telemetry/flightrec.py) are
             standalone JSON files under ``runs/postmortems/`` whose
             ``rounds`` list holds schema-validated round records;
             the run registry's ``postmortem``/``postmortem_reason``
             manifest keys are the lineage stamp.

Schema v7 adds NO required keys — one optional round-record key
(causal round tracing, telemetry/causal.py):

``causal`` — absent unless the run set ``--causal_trace`` (absent,
             not None: the off path must add zero ledger fields),
             else {"trace", "job", "round", "wall", "spans"} where
             ``spans`` is the round's span DAG — dicts with
             deterministic ``id``, ``parent`` (None for the round
             root), ``name``, critical-path ``bucket``, monotonic
             ``b``/``e`` seconds, and an optional ``trace`` override
             for spans a process records into ANOTHER trace (the
             fedservice daemon's ``sched_grant`` riding its own tick
             record but belonging to the tenant's round trace).
             ``scripts/ledger_merge.py`` reassembles per-trace DAGs
             by id across ``.p<k>``/``.job<j>`` shards;
             telemetry/critpath.py folds each DAG into per-bucket
             critical-path seconds.
"""

from __future__ import annotations

from commefficient_tpu.telemetry import clock

LEDGER_SCHEMA_VERSION = 7

# versions validate_record accepts: v1 (pre-probe), v2 (pre-trace),
# v3 (pre-fleet), v4 (pre-DP), v5 (pre-SLO) and v6 (pre-causal)
# ledgers stay readable by the report tooling
READABLE_SCHEMA_VERSIONS = (1, 2, 3, 4, 5, 6, 7)

# device_time keys whose values are nested dicts (v4); every other
# bucket value must be numeric
DEVICE_TIME_DICT_KEYS = ("per_device", "skew")

KINDS = ("meta", "round", "epoch", "bench", "summary")

# keys every round record must carry (values may be None where noted)
ROUND_REQUIRED_KEYS = (
    "schema", "kind", "ts", "round", "spans", "counters",
    "uplink_bytes", "downlink_bytes",      # None until accounted
    "host_rss_peak_bytes",                 # None off-Linux
    "hbm_peak_bytes",                      # None off-accelerator
)

# v2 additions (not required of v1 records)
ROUND_V2_KEYS = (
    "probes",                              # None with probing off
    "alarms",                              # [] when nothing fired
)

# v3 additions (not required of v1/v2 records)
ROUND_V3_KEYS = (
    "device_time",                         # None outside --profile
)

# v5 additions (not required of v1-v4 records)
ROUND_V5_KEYS = (
    "dp_epsilon",                          # None outside --dp runs
    "dp_delta",                            # None outside --dp runs
    "dp_sigma",                            # None outside --dp runs
)

# v6 additions (not required of v1-v5 records)
ROUND_V6_KEYS = (
    "slo",                                 # None without an SLO engine
)

# v7 adds no required keys: ``causal`` is optional (present only
# under --causal_trace) so the off path adds zero ledger fields
ROUND_V7_KEYS = ()

# keys every span dict inside a causal stamp must carry
CAUSAL_SPAN_KEYS = ("id", "parent", "name", "bucket", "b", "e")


def _base(kind: str) -> dict:
    return {"schema": LEDGER_SCHEMA_VERSION, "kind": kind,
            "ts": clock.wall()}


def make_meta_record(**fields) -> dict:
    rec = _base("meta")
    rec.update(fields)
    return rec


def make_round_record(round_index: int) -> dict:
    rec = _base("round")
    rec.update({
        "round": int(round_index),
        "spans": {},
        "counters": {},
        "uplink_bytes": None,
        "downlink_bytes": None,
        "host_rss_peak_bytes": None,
        "hbm_peak_bytes": None,
        "probes": None,
        "alarms": [],
        "device_time": None,
        "dp_epsilon": None,
        "dp_delta": None,
        "dp_sigma": None,
        "slo": None,
    })
    return rec


def make_epoch_record(row: dict, epoch: int) -> dict:
    rec = _base("epoch")
    rec["epoch"] = int(epoch)
    rec["row"] = {k: v for k, v in row.items()}
    return rec


def make_bench_record(metric: str, value, unit: str, **extra) -> dict:
    rec = _base("bench")
    rec.update({"metric": str(metric), "value": value,
                "unit": str(unit)})
    rec.update(extra)
    return rec


def make_summary_record(**fields) -> dict:
    rec = _base("summary")
    rec.update(fields)
    return rec


def _validate_causal(causal) -> list:
    """Problems with an optional v7 ``causal`` stamp (the key is
    validated only when present — absence is the off-mode contract)."""
    if not isinstance(causal, dict):
        return ["causal is not a dict"]
    problems = []
    if not isinstance(causal.get("trace"), str):
        problems.append("causal.trace is not a string")
    if not isinstance(causal.get("round"), int):
        problems.append("causal.round is not an int")
    if not isinstance(causal.get("wall"), (int, float)):
        problems.append("causal.wall is non-numeric")
    spans = causal.get("spans")
    if not isinstance(spans, list):
        return problems + ["causal.spans is not a list"]
    for span in spans:
        if not isinstance(span, dict):
            problems.append("causal span is not a dict")
            continue
        for key in CAUSAL_SPAN_KEYS:
            if key not in span:
                problems.append(f"causal span missing {key!r}")
        for key in ("id", "name", "bucket"):
            if key in span and not isinstance(span[key], str):
                problems.append(f"causal span {key} is not a string")
        if span.get("parent") is not None \
                and not isinstance(span.get("parent"), str):
            problems.append("causal span parent is not str-or-None")
        for key in ("b", "e"):
            if key in span and not isinstance(span[key], (int, float)):
                problems.append(f"causal span {key} is non-numeric")
    return problems


def validate_record(rec) -> list:
    """Schema check: a list of problem strings, empty when valid."""
    problems = []
    if not isinstance(rec, dict):
        return [f"record is {type(rec).__name__}, not dict"]
    schema = rec.get("schema")
    if schema not in READABLE_SCHEMA_VERSIONS:
        problems.append(f"schema {schema!r} not in "
                        f"{READABLE_SCHEMA_VERSIONS}")
    kind = rec.get("kind")
    if kind not in KINDS:
        problems.append(f"unknown kind {kind!r}")
    if not isinstance(rec.get("ts"), (int, float)):
        problems.append("ts missing or non-numeric")
    if kind == "round":
        required = ROUND_REQUIRED_KEYS
        if isinstance(schema, int) and schema >= 2:
            required = required + ROUND_V2_KEYS
        if isinstance(schema, int) and schema >= 3:
            required = required + ROUND_V3_KEYS
        if isinstance(schema, int) and schema >= 5:
            required = required + ROUND_V5_KEYS
        if isinstance(schema, int) and schema >= 6:
            required = required + ROUND_V6_KEYS
        for key in required:
            if key not in rec:
                problems.append(f"round record missing {key!r}")
        if not isinstance(rec.get("spans"), dict):
            problems.append("spans is not a dict")
        elif any(not isinstance(v, (int, float))
                 for v in rec["spans"].values()):
            problems.append("non-numeric span value")
        if not isinstance(rec.get("counters"), dict):
            problems.append("counters is not a dict")
        for key in ("uplink_bytes", "downlink_bytes") + ROUND_V5_KEYS:
            v = rec.get(key)
            if v is not None and not isinstance(v, (int, float)):
                problems.append(f"{key} is non-numeric")
        slo = rec.get("slo")
        if slo is not None and not isinstance(slo, dict):
            problems.append("slo is not a dict")
        if "causal" in rec:                # optional (v7): validate
            problems.extend(_validate_causal(rec["causal"]))
        dt = rec.get("device_time")
        if dt is not None:
            if not isinstance(dt, dict):
                problems.append("device_time is not a dict")
            else:
                for k, v in dt.items():
                    if k in DEVICE_TIME_DICT_KEYS:
                        if not isinstance(v, dict):
                            problems.append(
                                f"device_time.{k} is not a dict")
                    elif not isinstance(v, (int, float)):
                        problems.append("non-numeric device_time bucket")
    proc = rec.get("process")
    if proc is not None and not isinstance(proc, int):
        problems.append("process is non-integer")
    if kind == "bench":
        for key in ("metric", "value", "unit"):
            if key not in rec:
                problems.append(f"bench record missing {key!r}")
    if kind == "epoch" and not isinstance(rec.get("row"), dict):
        problems.append("epoch record missing row dict")
    if kind == "summary":
        fired = rec.get("alarm_fired")
        if fired is not None and (
                not isinstance(fired, dict)
                or any(not isinstance(v, (int, float))
                       for v in fired.values())):
            problems.append("alarm_fired is not a {rule: count} dict")
    return problems
