"""Live operations plane: metrics registry + Prometheus exporter.

Everything the post-hoc ledger records is derived per round anyway;
this module keeps a live, in-process view of it and serves the view
in Prometheus text exposition format so an operator (or ``scripts/
fedwatch.py``) can watch a running daemon instead of waiting for the
run to end.

Three parts:

``LiveRegistry``   — thread-safe counters / gauges / rolling-window
                     summaries, labeled; renders the text exposition
                     under its lock (the exporter thread only ever
                     READS a snapshot — it can never mutate run
                     state).
``LiveMetricsSink``— an ordinary telemetry sink (``write``/``close``)
                     that derives registry updates from the records
                     flowing through the fan-out: round seconds,
                     clients/s, wire bytes, staleness, backlog, ε
                     spend, fairness probes, alarm fire counts, SLO
                     burn.
``LiveServer``     — a localhost-only stdlib ``http.server`` thread
                     with ``/metrics`` and ``/healthz``. Off by
                     default; armed by ``--live_port``.

This module is the package's ONLY sanctioned socket owner (the
``live-confinement`` lint rule pins that), timing routes through
``telemetry.clock``, and with ``--live_port`` unset nothing here is
ever constructed — the telemetry no-op fast path is untouched.
"""

from __future__ import annotations

import threading
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

#: metric namespace prefix on every exported series
PREFIX = "commeff_"

#: lock-confinement declarations (enforced by the flowlint
#: ``lock-confinement`` checker): every write to / iteration over
#: these attrs must sit inside ``with <lock>:`` lexically. The
#: registry maps are mutated by round-loop threads and iterated by
#: the exporter thread; ``_PLANE`` is the process-wide singleton the
#: daemon and its jobs race to initialise.
_LOCK_MAP = {
    "_counters": "_lock",
    "_gauges": "_lock",
    "_summaries": "_lock",
    "_labels": "_lock",
    "_PLANE": "_PLANE_LOCK",
}

#: rolling samples kept per summary series (quantiles are over this
#: window; _sum/_count are whole-run)
SUMMARY_WINDOW = 256

#: quantiles exported per summary series
QUANTILES = (0.5, 0.95, 1.0)


def _escape(value: str) -> str:
    return (str(value).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def _label_str(labels) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _labels_key(labels) -> tuple:
    return tuple(sorted((str(k), str(v))
                        for k, v in (labels or {}).items()))


def _quantile(sorted_vals, q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1,
            max(0, int(round(q * (len(sorted_vals) - 1)))))
    return float(sorted_vals[i])


class LiveRegistry:
    """Thread-safe metric store. Writers are the round loop (via
    ``LiveMetricsSink``); the only other toucher is the exporter
    thread, which takes the same lock and renders — strictly
    read-only by construction."""

    def __init__(self):
        self._lock = threading.Lock()
        # name -> {labels_key: value}; labels_key -> labels dict
        self._counters = {}
        self._gauges = {}
        # name -> {labels_key: (deque window, sum, count)}
        self._summaries = {}
        self._labels = {}

    def counter_add(self, name: str, value, labels=None):
        key = _labels_key(labels)
        with self._lock:
            self._labels[key] = dict(labels or {})
            series = self._counters.setdefault(name, {})
            series[key] = series.get(key, 0.0) + float(value)

    def gauge_set(self, name: str, value, labels=None):
        key = _labels_key(labels)
        with self._lock:
            self._labels[key] = dict(labels or {})
            self._gauges.setdefault(name, {})[key] = float(value)

    def observe(self, name: str, value, labels=None):
        """One sample into a rolling-window summary series."""
        key = _labels_key(labels)
        with self._lock:
            self._labels[key] = dict(labels or {})
            series = self._summaries.setdefault(name, {})
            window, total, count = series.get(
                key, (deque(maxlen=SUMMARY_WINDOW), 0.0, 0))
            window.append(float(value))
            series[key] = (window, total + float(value), count + 1)

    def snapshot(self) -> dict:
        """Deep-copied view for renderers/tests — mutating it cannot
        touch live state."""
        with self._lock:
            return {
                "counters": {n: {k: v for k, v in s.items()}
                             for n, s in self._counters.items()},
                "gauges": {n: {k: v for k, v in s.items()}
                           for n, s in self._gauges.items()},
                "summaries": {
                    n: {k: (list(w), t, c)
                        for k, (w, t, c) in s.items()}
                    for n, s in self._summaries.items()},
                "labels": dict(self._labels),
            }

    def render(self) -> str:
        """Prometheus text exposition (version 0.0.4) of the whole
        registry."""
        snap = self.snapshot()
        labels_of = snap["labels"]
        out = []
        for name in sorted(snap["counters"]):
            out.append(f"# TYPE {name} counter")
            for key in sorted(snap["counters"][name]):
                out.append(f"{name}{_label_str(labels_of[key])} "
                           f"{snap['counters'][name][key]:g}")
        for name in sorted(snap["gauges"]):
            out.append(f"# TYPE {name} gauge")
            for key in sorted(snap["gauges"][name]):
                out.append(f"{name}{_label_str(labels_of[key])} "
                           f"{snap['gauges'][name][key]:g}")
        for name in sorted(snap["summaries"]):
            out.append(f"# TYPE {name} summary")
            for key in sorted(snap["summaries"][name]):
                window, total, count = snap["summaries"][name][key]
                svals = sorted(window)
                base = dict(labels_of[key])
                for q in QUANTILES:
                    ql = dict(base, quantile=f"{q:g}")
                    out.append(f"{name}{_label_str(ql)} "
                               f"{_quantile(svals, q):g}")
                out.append(f"{name}_sum{_label_str(base)} {total:g}")
                out.append(f"{name}_count{_label_str(base)} {count}")
        return "\n".join(out) + "\n"


#: keys copied from a round's probe dict straight to labeled gauges
_PROBE_GAUGES = (
    "async_staleness_mean", "async_staleness_max", "async_backlog",
    "async_buffer_occupancy", "job_active", "job_ran",
    "job_backlog_total", "job_backlog_max", "job_starved_rounds",
    "job_occupancy_min",
)


class LiveMetricsSink:
    """Telemetry sink deriving live metrics from the record stream.

    ``labels`` ride on every series this sink writes (``job``,
    ``process``, ``run`` — the run key fragment); one registry serves
    many sinks, so a daemon's J job sinks interleave into one labeled
    scrape."""

    def __init__(self, registry: LiveRegistry, labels=None):
        self.registry = registry
        self.labels = {k: str(v) for k, v in (labels or {}).items()}
        self._workers = None

    def write(self, rec):
        kind = rec.get("kind")
        if kind == "meta":
            plan = rec.get("plan") or {}
            w = plan.get("num_workers")
            if w:
                self._workers = int(w)
            return
        if kind == "summary":
            fired = rec.get("alarm_fired") or {}
            for rule, n in fired.items():
                # totals already streamed per round; summary is the
                # authoritative end-of-run count, so gauge it
                self.registry.gauge_set(
                    PREFIX + "alarms_run_total", float(n),
                    dict(self.labels, rule=str(rule)))
            return
        if kind != "round":
            return
        reg, labels = self.registry, self.labels
        reg.counter_add(PREFIX + "rounds_total", 1, labels)
        spans = rec.get("spans") or {}
        round_s = float(sum(spans.values())) if spans else 0.0
        if round_s > 0:
            reg.observe(PREFIX + "round_seconds", round_s, labels)
            if self._workers:
                reg.gauge_set(PREFIX + "clients_per_s",
                              self._workers / round_s, labels)
        for key, metric in (("uplink_bytes", "uplink_bytes_total"),
                            ("downlink_bytes",
                             "downlink_bytes_total")):
            v = rec.get(key)
            if v:
                reg.counter_add(PREFIX + metric, float(v), labels)
        probes = rec.get("probes") or {}
        for key in _PROBE_GAUGES:
            v = probes.get(key)
            if v is not None:
                reg.gauge_set(PREFIX + key, float(v), labels)
        for key, v in probes.items():
            if key.startswith("slo_burn_") and v is not None:
                reg.gauge_set(PREFIX + "slo_burn", float(v),
                              dict(labels,
                                   objective=key[len("slo_burn_"):]))
        eps = rec.get("dp_epsilon")
        if eps is not None:
            reg.gauge_set(PREFIX + "dp_epsilon", float(eps), labels)
        causal = rec.get("causal")
        if isinstance(causal, dict):
            # --causal_trace runs export the round's critical-path
            # bucket attribution (seconds per bucket); fedwatch
            # derives its "crit" dominant-bucket column from these
            from commefficient_tpu.telemetry.critpath import \
                critical_path
            crit = critical_path(causal, rec.get("device_time"))
            if crit is not None:
                for b, s in crit["buckets"].items():
                    if s > 0:
                        reg.gauge_set(PREFIX + "critpath_seconds",
                                      float(s),
                                      dict(labels, bucket=str(b)))
        for alarm in rec.get("alarms") or []:
            reg.counter_add(
                PREFIX + "alarms_total", 1,
                dict(labels, rule=str(alarm.get("rule"))))

    def close(self):
        pass  # the registry (and server) outlive any one run


class _Handler(BaseHTTPRequestHandler):
    registry = None  # bound per-server via subclassing

    def do_GET(self):  # noqa: N802 — http.server API
        if self.path.split("?")[0] == "/metrics":
            body = self.registry.render().encode()
            ctype = "text/plain; version=0.0.4; charset=utf-8"
        elif self.path.split("?")[0] == "/healthz":
            body, ctype = b"ok\n", "text/plain; charset=utf-8"
        else:
            self.send_error(404)
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # silence per-request stderr spam
        pass


class LiveServer:
    """Localhost-only exporter thread. ``port=0`` binds an ephemeral
    port (tests); the bound port is ``self.port``."""

    def __init__(self, registry: LiveRegistry, port: int,
                 host: str = "127.0.0.1"):
        handler = type("_BoundHandler", (_Handler,),
                       {"registry": registry})
        self._httpd = ThreadingHTTPServer((host, int(port)), handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="live-metrics-exporter")
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._thread.join(timeout=5)
            self._httpd = None


# --- process-wide plane ------------------------------------------------
# One registry + at most one server per process: a fedservice daemon
# attaches J job sinks (distinct labels) to the same scrape endpoint.

_PLANE = {"registry": None, "server": None}
_PLANE_LOCK = threading.Lock()


def live_registry() -> LiveRegistry:
    with _PLANE_LOCK:
        if _PLANE["registry"] is None:
            _PLANE["registry"] = LiveRegistry()
        return _PLANE["registry"]


def ensure_server(port: int) -> LiveServer:
    """The process's exporter, started on first call. A later call
    with a different port keeps the first server (one scrape endpoint
    per process; the daemon and its jobs share it)."""
    reg = live_registry()
    with _PLANE_LOCK:
        if _PLANE["server"] is None:
            _PLANE["server"] = LiveServer(reg, port)
        return _PLANE["server"]


def shutdown_plane():
    """Stop the exporter and drop the registry (tests; a fresh plane
    per test keeps scrapes deterministic)."""
    with _PLANE_LOCK:
        server = _PLANE["server"]
        _PLANE["server"] = None
        _PLANE["registry"] = None
    if server is not None:
        server.close()


def attach_live_plane(telemetry, cfg, labels=None, runs_dir=""):
    """Arm the live plane on one run's telemetry per its Config.

    ``--live_port`` > 0 starts (or joins) the process exporter and
    attaches a :class:`LiveMetricsSink`; ``--flightrec_rounds`` > 0
    attaches a flight recorder. Returns ``(sink, recorder)`` — both
    None (and the telemetry fan-out untouched, preserving the
    disabled fast path) when neither knob is armed."""
    port = int(getattr(cfg, "live_port", 0) or 0)
    ring = int(getattr(cfg, "flightrec_rounds", 0) or 0)
    sink = None
    if port > 0:
        ensure_server(port)
        sink = LiveMetricsSink(live_registry(), labels)
        telemetry.add_sink(sink)
    recorder = None
    if ring > 0:
        from commefficient_tpu.telemetry.flightrec import FlightRecorder
        recorder = FlightRecorder(cfg, ring, labels=labels,
                                  runs_dir=runs_dir)
        telemetry.add_sink(recorder)
    return sink, recorder
