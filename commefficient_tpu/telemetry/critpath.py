"""Per-round critical-path extraction over causal span DAGs.

Input is a round's ``causal`` stamp (telemetry/causal.py): a root
span covering the round's wall interval plus nested child spans. The
round loop is sequential on the host thread — device overlap hides
*inside* spans, not between them — so the longest dependency chain
IS the root interval, and the explanatory work is attributing every
second of it to the bucket that bounded progress then.

``critical_path`` walks the span tree recursively: a parent's
interval is partitioned among its children (clipped, sorted by begin
time); gaps between children belong to the parent's own bucket;
whatever the root itself can't hand to a child lands in
``host_other``. The invariant — checked by the golden-DAG tests and
the ``causal_smoke`` selftest leg — is exact by construction:

    sum(buckets.values()) == root.e - root.b == causal["wall"]

Overlap awareness: host spans can't see how much collective time the
overlap engine actually hid behind compute, but the round record's
``device_time`` stamp can. When provided, ``critical_path`` moves
the *exposed* collective seconds — ``max(0, collective - overlapped)``
clipped to the compute bucket — from ``compute`` to
``collective_exposed``, so a chunked-overlap run attributes only the
un-hidden tail to the wire.

Cross-process spans (a daemon's ``sched_grant`` stitched into a
tenant trace) are timestamped on a different monotonic clock; they
clip to the root interval and so contribute structure (parent edges
for orphan checks) but never skew the attribution.
"""

from __future__ import annotations

from commefficient_tpu.telemetry.causal import BUCKETS

#: two clocks reading "the same" boundary (clock.tick() before vs
#: after a record stamp) disagree by far less than this; golden-DAG
#: tests assert exactness, real runs assert within tolerance.
CLOCK_TOLERANCE = 5e-3


def _attribute(span, children_of, buckets):
    """Recursively attribute ``span``'s interval: child intervals to
    the children (clipped, begin-sorted), gaps to ``span``'s own
    bucket."""
    b, e = float(span["b"]), float(span["e"])
    cursor = b
    own = span.get("bucket", "host_other")
    if own not in buckets:
        own = "host_other"
    for child in sorted(children_of.get(span["id"], ()),
                        key=lambda s: float(s["b"])):
        cb = min(max(float(child["b"]), cursor), e)
        ce = min(max(float(child["e"]), cb), e)
        if cb > cursor:
            buckets[own] += cb - cursor
        _attribute({**child, "b": cb, "e": ce}, children_of, buckets)
        cursor = max(cursor, ce)
    if e > cursor:
        buckets[own] += e - cursor


def critical_path(causal, device_time=None):
    """Fold one round's ``causal`` stamp into per-bucket seconds.

    Returns ``{"round", "wall", "buckets": {bucket: seconds}}`` with
    ``sum(buckets) == wall`` exactly, or None when ``causal`` is not
    a usable stamp. ``device_time`` (the round record's v3 stamp, if
    any) reapportions overlap-hidden collective time as described in
    the module docstring.
    """
    if not isinstance(causal, dict):
        return None
    spans = [s for s in causal.get("spans") or ()
             if isinstance(s, dict)]
    root = next((s for s in spans if s.get("parent") is None
                 and "trace" not in s), None)
    if root is None:
        return None
    children_of = {}
    for s in spans:
        if s is not root and s.get("parent") is not None:
            children_of.setdefault(s["parent"], []).append(s)
    buckets = {b: 0.0 for b in BUCKETS}
    _attribute(root, children_of, buckets)

    if isinstance(device_time, dict):
        per = device_time.get("per_device")
        lanes = per[0] if isinstance(per, (list, tuple)) and per \
            else per if isinstance(per, dict) else None
        if isinstance(lanes, dict):
            coll = float(lanes.get("collective_s") or 0.0)
            hidden = float(lanes.get("overlapped_s") or 0.0)
            exposed = min(max(0.0, coll - hidden), buckets["compute"])
            buckets["compute"] -= exposed
            buckets["collective_exposed"] += exposed

    wall = float(root["e"]) - float(root["b"])
    return {"round": causal.get("round"), "wall": wall,
            "buckets": buckets}


def dominant_bucket(crit):
    """``("h2d", 0.62)``-style headline for console columns; None
    when the round had no measurable wall time."""
    if not crit or crit["wall"] <= 0:
        return None
    b, s = max(crit["buckets"].items(), key=lambda kv: kv[1])
    return b, s / crit["wall"]


def median_buckets(crits):
    """Per-bucket median across rounds — the 'typical round' a
    regression diff compares against. None on empty input."""
    crits = [c for c in crits if c]
    if not crits:
        return None
    out = {}
    for b in BUCKETS:
        vals = sorted(c["buckets"].get(b, 0.0) for c in crits)
        n = len(vals)
        out[b] = (vals[n // 2] if n % 2
                  else 0.5 * (vals[n // 2 - 1] + vals[n // 2]))
    return out


def critpath_diff(cur, base):
    """Explain ``cur`` (a ``critical_path`` result) against ``base``
    (a ``median_buckets`` map): absolute and multiplicative growth
    per bucket, sorted by absolute growth. This is what an alarm
    firing attaches to its flight-recorder bundle."""
    if not cur or not isinstance(base, dict):
        return None
    rows = []
    for b in BUCKETS:
        c = cur["buckets"].get(b, 0.0)
        m = base.get(b, 0.0)
        rows.append({"bucket": b, "cur_s": c, "median_s": m,
                     "delta_s": c - m,
                     "ratio": (c / m) if m > 0 else None})
    rows.sort(key=lambda r: r["delta_s"], reverse=True)
    return {"round": cur.get("round"), "wall": cur["wall"],
            "base_wall": sum(base.values()), "rows": rows}
