"""Run registry: self-describing manifests under ``runs/``.

Every train/bench run that writes a ledger also drops one small JSON
manifest — git sha, config hash, jax version, backend/topology, the
ledger path, and any headline bench metrics — so a directory of runs
is navigable without the launching shell history:

    runs/manifests/run_<utc-seconds>_<confighash8>.json

``scripts/telemetry_report.py --runs_dir`` discovers ledgers through
these, and ``scripts/perf_gate.py`` uses them to pick "latest vs
baseline" without hand-typed paths. Manifests are written by process 0
only and never on bare smoke invocations (no ``--ledger``) — ``runs/``
stays free of junk from every pytest run.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import subprocess
import sys

from commefficient_tpu.telemetry import clock

MANIFEST_SCHEMA = 1
MANIFEST_DIR = "manifests"
MANIFEST_PREFIX = "run_"

#: Config fields that never change what the program computes — they
#: must not perturb the config hash (two reruns of one experiment
#: with different ledger paths are the SAME configuration)
_HASH_EXCLUDE = ("ledger", "telemetry_console", "use_tensorboard",
                 "do_profile", "clientstore_dir", "live_port",
                 "flightrec_rounds", "postmortem_dir", "causal_trace")


def config_dict(args) -> dict:
    """JSON-able view of a Config (or argparse namespace): scalar
    fields only, hash-excluded knobs dropped."""
    if dataclasses.is_dataclass(args):
        src = dataclasses.asdict(args)
    else:
        src = dict(getattr(args, "__dict__", {}) or {})
    return {k: v for k, v in sorted(src.items())
            if k not in _HASH_EXCLUDE
            and isinstance(v, (int, float, str, bool, type(None)))}


def config_hash(args) -> str:
    """SHA-256 of the sorted scalar config — the identity under which
    runs are comparable."""
    blob = json.dumps(config_dict(args), sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()


def git_sha(cwd=None) -> str:
    try:
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=cwd,
            capture_output=True, text=True, timeout=10)
        if out.returncode == 0:
            return out.stdout.strip()
    except Exception:
        pass
    return ""


def _environment() -> dict:
    env = {"python": sys.version.split()[0]}
    try:
        import jax
        from commefficient_tpu.parallel import mesh
        topo = mesh.topology_summary()
        env["jax_version"] = jax.__version__
        env["backend"] = topo["backend"]
        env["device_count"] = topo["device_count"]
        env["process_count"] = topo["process_count"]
        env["device_kind"] = topo["device_kind"]
    except Exception:
        pass
    return env


def run_topology(manifest: dict) -> tuple:
    """(device_count, process_count) of a run — the topology half of
    the comparability key. Pre-fleet manifests that never recorded
    the counts key as (None, None): they only ever compare against
    each other, never silently against a counted run."""
    dc = manifest.get("device_count")
    pc = manifest.get("process_count")
    return (int(dc) if dc is not None else None,
            int(pc) if pc is not None else None)


def run_mesh_shape(manifest: dict):
    """The run's recorded mesh layout ({axis: size} dict) or None —
    pre-mesh manifests and 1-D runs record nothing here."""
    shape = manifest.get("mesh_shape")
    return dict(shape) if isinstance(shape, dict) else None


def run_wire_dtype(manifest: dict):
    """The run's uplink wire dtype (``--sketch_dtype``) from its
    recorded config, or None for non-sketch / pre-quantization
    manifests — they only ever carried f32 on the wire. An autopilot
    run reports the dtype of the point the controller CONVERGED on
    (the recorded trajectory's ``final`` key): that is the wire the
    steady-state rounds — the ones a perf pin should describe —
    actually moved, so a walk that lands on int8 pins as
    ``...qint8b<lo-hi>``."""
    cfg = manifest.get("config") or {}
    if cfg.get("mode") != "sketch":
        return None
    ap = run_autopilot(manifest)
    final = (ap or {}).get("final") or ""
    if final:
        # variant keys are "<dtype>-k..-r..-c..-re.." (autopilot/
        # lattice.py key_str); the leading segment is the wire dtype
        return final.split("-", 1)[0] or None
    return cfg.get("sketch_dtype") or None


def run_async_k(manifest: dict):
    """The run's buffered-arrival buffer size
    (``--async_buffer_size``) from its recorded config, or None for
    synchronous / pre-async manifests — they all ran the barrier
    round."""
    cfg = manifest.get("config") or {}
    k = int(cfg.get("async_buffer_size") or 0)
    return k if k > 0 else None


def run_overlap_depth(manifest: dict):
    """The run's round-pipeline chunk depth (``--overlap_depth``)
    from its recorded config, or None for serial / pre-overlap
    manifests — depth 1 IS the serial round, so only depth > 1 keys a
    distinct experiment."""
    cfg = manifest.get("config") or {}
    if cfg.get("mode") != "sketch":
        return None
    n = int(cfg.get("overlap_depth") or 0)
    return n if n > 1 else None


def run_autopilot(manifest: dict):
    """The run's recorded autopilot trajectory block (band, ladder,
    per-round observations — the bit-exact replay input of
    ``python -m commefficient_tpu.autopilot.replay``), or None for
    static-knob / pre-autopilot manifests."""
    rec = manifest.get("autopilot")
    return rec if isinstance(rec, dict) else None


def run_band(manifest: dict):
    """The run's ``--autopilot_band LO:HI`` string, or None for
    static-knob manifests — the band half of the ``b<lo-hi>``
    topology fragment (telemetry/gate.py band_suffix)."""
    cfg = manifest.get("config") or {}
    if str(cfg.get("autopilot") or "off") != "on":
        return None
    return cfg.get("autopilot_band") or None


def run_dp_epsilon(manifest: dict):
    """The run's privacy budget (``--dp_epsilon``) from its recorded
    config when the run was differentially private (``--dp`` != off),
    or None for noiseless / pre-privacy manifests — the budget half
    of the ``p<eps>`` topology fragment (telemetry/gate.py
    privacy_suffix). 0.0 is a REAL return (DP on, unlimited budget):
    such a run keys ``p0``, never the bare noiseless key."""
    cfg = manifest.get("config") or {}
    if str(cfg.get("dp") or "off") == "off":
        return None
    return float(cfg.get("dp_epsilon") or 0.0)


def run_service_jobs(manifest: dict):
    """The number of jobs a fedservice daemon multiplexed for this
    run (``service_jobs``, stamped by the service/bench manifest
    writer), or None for solo / pre-service manifests — and for
    single-job daemon runs, which are bit-identical to the direct
    path and honestly share its key (telemetry/gate.py
    service_suffix)."""
    j = int(manifest.get("service_jobs") or 0)
    return j if j > 1 else None


def run_job_id(manifest: dict):
    """The job this manifest describes inside a fedservice daemon
    (``job_id``, stamped at admission), or None for non-service
    manifests. The job lineage key: ``latest_ledgers(job=...)``
    filters on it, so each tenant's run chain is navigable without
    grepping the shared runs/ directory."""
    job = manifest.get("job_id")
    return str(job) if job is not None else None


def run_segments(manifest: dict) -> list:
    """The run's per-topology segments (``topology_segments``, stamped
    by the trainers from checkpoint lineage for resumed runs). Empty
    for unresumed / pre-elastic manifests."""
    segs = manifest.get("topology_segments")
    return [s for s in segs if isinstance(s, dict)] \
        if isinstance(segs, list) else []


def run_topology_changed(manifest: dict) -> bool:
    """True when a resumed run crossed a topology boundary mid-run:
    its segments span more than one distinct (device_count,
    process_count, mesh_shape). Such a run's ledger mixes rounds
    measured under different topologies, so the perf gate must NEVER
    resolve it to a single baseline pin — gate each segment's own
    ledger instead (scripts/perf_gate.py refuses)."""
    keys = set()
    for s in run_segments(manifest):
        ms = s.get("mesh_shape")
        keys.add((s.get("device_count"), s.get("process_count"),
                  json.dumps(ms, sort_keys=True)
                  if isinstance(ms, dict) else None))
    return len(keys) > 1


def run_key(manifest: dict) -> tuple:
    """(config_hash, device_count, process_count): two runs are
    comparable — diffable by the report, gateable against one
    baseline entry — only when ALL three match. Config hash alone is
    not an identity: the same config on 1 vs 8 devices is a scaling
    experiment, not a regression. 2D-mesh runs append their
    ``m<C>x<M>`` fragment, quantized-wire runs their ``q<dtype>``
    fragment, buffered-arrival runs their ``a<K>`` fragment and
    chunk-pipelined runs their ``o<N>`` fragment and
    autopilot-controlled runs their ``b<lo-hi>`` fragment and
    differentially-private runs their ``p<eps>`` fragment (a 4x2 and
    an 8x1 program on the same chips — or an int8 and an f32 wire, or
    a buffered and a barrier round, or a depth-2 pipelined and a
    serial round, or a knob walk and a static program, or a noised
    table and a noiseless one — are different experiments) and
    multi-tenant fedservice runs their ``j<J>`` fragment (a pod
    interleaving J round programs is a different experiment from
    any solo run); 1-D f32
    synchronous serial static noiseless solo runs keep the historical
    3-tuple, so old manifests stay comparable to each other."""
    from commefficient_tpu.telemetry.gate import (async_suffix,
                                                  band_suffix,
                                                  mesh_suffix,
                                                  overlap_suffix,
                                                  privacy_suffix,
                                                  service_suffix,
                                                  wire_suffix)
    key = (manifest.get("config_hash") or "",) + run_topology(manifest)
    suffix = (mesh_suffix(run_mesh_shape(manifest))
              + wire_suffix(run_wire_dtype(manifest))
              + async_suffix(run_async_k(manifest))
              + overlap_suffix(run_overlap_depth(manifest))
              + band_suffix(run_band(manifest))
              + privacy_suffix(run_dp_epsilon(manifest))
              + service_suffix(run_service_jobs(manifest)))
    return key + (suffix,) if suffix else key


def write_manifest(runs_dir: str = "runs", *, args=None,
                   ledger: str = "", bench: dict = None,
                   mesh_shape=None, extra: dict = None) -> str:
    """Write one run manifest; returns its path. ``bench`` is a dict
    of headline metrics ({metric: {"value", "unit", ...}} or any
    JSON-able shape); ``extra`` merges into the top level last."""
    chash = config_hash(args) if args is not None else ""
    rec = {
        "schema": MANIFEST_SCHEMA,
        "kind": "run_manifest",
        "ts": clock.wall(),
        "git_sha": git_sha(),
        "config_hash": chash,
        "config": config_dict(args) if args is not None else {},
        "argv": list(sys.argv),
        "ledger": os.path.abspath(ledger) if ledger else "",
        "bench": bench or {},
        "mesh_shape": (dict(mesh_shape)
                       if isinstance(mesh_shape, dict) else mesh_shape),
    }
    rec.update(_environment())
    if rec.get("ledger") and (rec.get("process_count") or 1) > 1:
        from commefficient_tpu.telemetry.sinks import shard_ledger_path
        rec["ledger_shards"] = [
            shard_ledger_path(rec["ledger"], k)
            for k in range(1, rec["process_count"])]
    if extra:
        rec.update(extra)
    out_dir = os.path.join(runs_dir, MANIFEST_DIR)
    os.makedirs(out_dir, exist_ok=True)
    name = f"{MANIFEST_PREFIX}{int(rec['ts'])}_{chash[:8] or 'nocfg'}"
    path = os.path.join(out_dir, name + ".json")
    # same-second rerun of the same config: keep both manifests
    n = 1
    while os.path.exists(path):
        path = os.path.join(out_dir, f"{name}.{n}.json")
        n += 1
    # tmp + rename: a writer killed mid-dump must never leave a
    # half-written manifest at the canonical name (list_manifests
    # skips unparseable files, but a torn manifest would silently
    # drop the run from the registry; the orphaned .tmp is inert)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(rec, f, indent=1, sort_keys=True)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def maybe_write_manifest(args, **kw):
    """Trainer/bench entry point: a manifest when (and only when) the
    run wrote a ledger, from process 0, never under ``--test`` smoke.
    Failures degrade to a warning — observability must not fail the
    run it observes."""
    ledger = str(getattr(args, "ledger", "") or "")
    if not ledger or getattr(args, "do_test", False):
        return None
    try:
        import jax
        if jax.process_index() != 0:
            return None
    except Exception:
        pass
    try:
        return write_manifest(args=args, ledger=ledger, **kw)
    except Exception as e:  # noqa: BLE001 — observability only
        print(f"WARNING: run manifest not written "
              f"({type(e).__name__}: {e})")
        return None


def list_manifests(runs_dir: str = "runs") -> list:
    """All readable manifests under ``runs_dir``, oldest first.
    Returns [(path, manifest_dict), ...]; unparseable files are
    skipped."""
    out_dir = os.path.join(runs_dir, MANIFEST_DIR)
    if not os.path.isdir(out_dir):
        return []
    out = []
    for name in sorted(os.listdir(out_dir)):
        if not (name.startswith(MANIFEST_PREFIX)
                and name.endswith(".json")):
            continue
        path = os.path.join(out_dir, name)
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        if rec.get("kind") == "run_manifest":
            out.append((path, rec))
    out.sort(key=lambda pr: pr[1].get("ts", 0.0))
    return out


def latest_ledgers(runs_dir: str = "runs", n: int = 2,
                   key: tuple = None, job: str = None) -> list:
    """The newest ``n`` manifests whose ledger file still exists,
    newest FIRST: [(manifest_path, manifest, ledger_path), ...].

    ``key`` (a ``run_key`` tuple) restricts hits to comparable runs —
    the report/gate pass the newest run's key so "latest vs previous"
    never pairs different configs or topologies. ``job`` restricts
    hits to one fedservice tenant's lineage (manifests whose
    ``job_id`` matches), so a shared runs/ directory answers "this
    job's latest ledger" without pairing two tenants' runs."""
    hits = []
    for path, rec in reversed(list_manifests(runs_dir)):
        ledger = rec.get("ledger") or ""
        if not (ledger and os.path.exists(ledger)):
            continue
        if key is not None and run_key(rec) != tuple(key):
            continue
        if job is not None and run_job_id(rec) != str(job):
            continue
        hits.append((path, rec, ledger))
        if len(hits) >= n:
            break
    return hits
