"""Device-time attribution: profiler round markers + trace parsing.

The round ledger (core.py) measures *host* phases; this module closes
the gap to the device timeline. Two halves:

**Markers** — while a ``trace_window`` is open, ``FedModel`` brackets
each round in a ``jax.profiler.StepTraceAnnotation`` (name
``fed_round``, ``step_num`` = the ledger round index) and the
device-relevant phases (h2d / round_dispatch / server) in
``TraceAnnotation``s. The round annotation is opened at
``begin_round`` and closed at the NEXT round's begin — mirroring the
ledger record lifecycle, so the server step (dispatched after
``_call_train`` returns) lands inside its own round's window. State is
module-level (one live FedModel per process, like
``fed_model._CURRENT_MODEL``); every call is a single flag check when
no trace is active, so the round hot loop pays nothing.

**Parser** — ``jax.profiler.stop_trace`` writes a Chrome trace-event
dump (``plugins/profile/<ts>/<host>.trace.json.gz``): ``ph:"X"``
complete events with µs ``ts``/``dur`` and ``ph:"M"`` metadata naming
each pid/tid lane. Device lanes are the ``/device:*`` processes (TPU,
GPU) or the ``tf_XLA*`` client threads (CPU backend).
``attribute_rounds`` buckets every device event into its round's
window: {compute, collective, h2d/d2h transfer, host-gap}, by interval
union so nested/overlapping op events never double-count. Buckets sum
to the round window by construction — the acceptance bar for the
schema-v3 ``device_time`` ledger field.

Schema v4 keeps each device lane's interval set instead of collapsing
to one union: every round additionally carries
``per_device[<device_id>]`` buckets ({busy, compute, collective,
transfer} for that device alone) and a *skew decomposition* of the
collective bucket. Matching collective events are aligned across
device lanes (k-th in-window occurrence of each collective op name);
a device's collective time then splits into **wait** (straggler skew:
from this device entering the collective until the LAST device
enters) and **wire** (the post-alignment transfer, ``collective -
wait`` — exact by construction). Round-level skew stats (max/p95
enter-delta, the straggler device id) land in ``device_time.skew``
and feed the ``collective_skew`` alarm rule (telemetry/alarms.py).
The cross-device aggregate buckets are computed from the pooled
interval set exactly as in v3 — bit-for-bit unchanged.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re

ROUND_MARKER = "fed_round"
PHASE_PREFIX = "fed_phase"

#: substrings (lowercase) classifying a device-lane event
COLLECTIVE_TOKENS = (
    "all-reduce", "allreduce", "all-gather", "allgather",
    "reduce-scatter", "reducescatter", "all-to-all", "alltoall",
    "collective-permute", "collectivepermute", "collective-broadcast",
)
TRANSFER_TOKENS = (
    "infeed", "outfeed", "copy", "memcpy", "transfer",
    "h2d", "d2h", "send", "recv",
)

# one live FedModel per process (fed_model._CURRENT_MODEL) -> one
# module-level marker state; "ann" is the currently-open round
# StepTraceAnnotation, closed at the next begin or at window exit
_STATE = {"tracing": False, "ann": None, "round": None}


def tracing() -> bool:
    return _STATE["tracing"]


def set_tracing(on: bool):
    """Flipped by ``profiler.trace_window`` enter/exit. Turning
    tracing off force-closes any open round marker first, so its end
    timestamp lands inside the trace."""
    if not on:
        end_round_marker()
    _STATE["tracing"] = bool(on)


def begin_round_marker(round_index: int):
    """Open round ``round_index``'s StepTraceAnnotation (closing the
    previous round's). No-op unless a trace window is active."""
    if not _STATE["tracing"]:
        return
    end_round_marker()
    import jax
    ann = jax.profiler.StepTraceAnnotation(ROUND_MARKER,
                                           step_num=int(round_index))
    ann.__enter__()
    _STATE["ann"] = ann
    _STATE["round"] = int(round_index)


def end_round_marker():
    ann, _STATE["ann"] = _STATE["ann"], None
    _STATE["round"] = None
    if ann is not None:
        ann.__exit__(None, None, None)


class _NullPhase:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_PHASE = _NullPhase()


def phase(name: str):
    """Context manager: a ``TraceAnnotation`` named
    ``fed_phase::<name>`` when tracing, the shared no-op otherwise.
    Used alongside (not instead of) the telemetry host spans."""
    if not _STATE["tracing"]:
        return _NULL_PHASE
    import jax
    return jax.profiler.TraceAnnotation(f"{PHASE_PREFIX}::{name}")


# --- trace file discovery + loading ------------------------------------


def find_trace_file(logdir: str):
    """Newest ``*.trace.json.gz`` under ``logdir`` (searched at any
    depth: jax writes ``plugins/profile/<timestamp>/<host>.trace.
    json.gz``). None when the profiler wrote nothing."""
    pats = (os.path.join(logdir, "**", "*.trace.json.gz"),
            os.path.join(logdir, "**", "*.trace.json"))
    hits = []
    for pat in pats:
        hits.extend(glob.glob(pat, recursive=True))
    if not hits:
        return None
    return max(hits, key=os.path.getmtime)


def load_trace_events(path_or_logdir: str):
    """Chrome trace-event list from a ``.trace.json(.gz)`` file, or
    from the newest one under a directory."""
    path = path_or_logdir
    if os.path.isdir(path):
        path = find_trace_file(path)
        if path is None:
            raise FileNotFoundError(
                f"no .trace.json(.gz) under {path_or_logdir}")
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        doc = json.load(f)
    events = doc.get("traceEvents", doc) if isinstance(doc, dict) \
        else doc
    return [e for e in events if isinstance(e, dict)]


# --- lane classification -----------------------------------------------


def _lane_names(events):
    """(pid -> process_name, (pid, tid) -> thread_name) from the
    ``ph:"M"`` metadata events."""
    procs, threads = {}, {}
    for e in events:
        if e.get("ph") != "M":
            continue
        name = (e.get("args") or {}).get("name", "")
        if e.get("name") == "process_name":
            procs[e.get("pid")] = name
        elif e.get("name") == "thread_name":
            threads[(e.get("pid"), e.get("tid"))] = name
    return procs, threads


def lane_devices(events):
    """(pid, tid) -> device id for every device-side execution lane.

    TPU/GPU xplanes expose one ``/device:<KIND>:<N>`` process per
    device — every thread under it belongs to that device, so the id
    is the process-name suffix (``TPU:0``). The CPU backend runs each
    virtual device on a ``tf_XLA*`` runtime thread; each such thread
    is its own lane, labelled ``cpu:<n>`` by the trailing integer of
    the thread name (stable across a run, unlike raw tids)."""
    procs, threads = _lane_names(events)
    out = {}
    for e in events:
        if e.get("ph") != "X":
            continue
        key = (e.get("pid"), e.get("tid"))
        if key in out:
            continue
        pname = procs.get(key[0], "")
        tname = threads.get(key, "")
        if pname.startswith("/device:"):
            out[key] = pname[len("/device:"):]
        elif tname.startswith("tf_XLA"):
            m = re.search(r"(\d+)$", tname)
            out[key] = "cpu:%s" % (m.group(1) if m else key[1])
    return out


def device_lanes(events):
    """(pid, tid) pairs whose events are device-side execution:
    ``/device:*`` processes (TPU/GPU xplanes) or ``tf_XLA*`` runtime
    threads (the CPU backend's per-device execution threads)."""
    return set(lane_devices(events))


# --- interval math -----------------------------------------------------


def _union(intervals):
    """Merged, sorted interval list — nested/overlapping device events
    (module > fusion > op) collapse to their covering span."""
    out = []
    for a, b in sorted(intervals):
        if out and a <= out[-1][1]:
            out[-1][1] = max(out[-1][1], b)
        else:
            out.append([a, b])
    return out


def _measure(merged):
    return sum(b - a for a, b in merged)


def _clip(intervals, lo, hi):
    out = []
    for a, b in intervals:
        a, b = max(a, lo), min(b, hi)
        if b > a:
            out.append((a, b))
    return out


def _subtract(a, b):
    """``a \\ b`` for merged, sorted interval lists — a lane's compute
    slice is its busy union minus its collective/transfer cover."""
    out = []
    j = 0
    for lo, hi in a:
        cur = lo
        while j < len(b) and b[j][1] <= cur:
            j += 1
        k = j
        while k < len(b) and b[k][0] < hi:
            s, e = b[k]
            if s > cur:
                out.append((cur, s))
            cur = max(cur, e)
            k += 1
        if cur < hi:
            out.append((cur, hi))
    return out


def _intersect(a, b):
    """``a ∩ b`` for merged, sorted interval lists — the overlapped
    bucket is collective ∩ (some lane's compute)."""
    out = []
    i = j = 0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if hi > lo:
            out.append((lo, hi))
        if a[i][1] < b[j][1]:
            i += 1
        else:
            j += 1
    return out


# --- per-round attribution ---------------------------------------------


def round_windows(events):
    """[(round_index, ts_us, end_us), ...] from the ``fed_round``
    StepTraceAnnotations, in timeline order. Each window is the
    annotation's own extent (begin_round -> next begin_round /
    trace-window exit)."""
    wins = []
    for e in events:
        if e.get("ph") != "X" or e.get("name") != ROUND_MARKER:
            continue
        args = e.get("args") or {}
        step = args.get("step_num", args.get("round"))
        if step is None:
            continue
        ts, dur = float(e.get("ts", 0.0)), float(e.get("dur", 0.0))
        wins.append((int(step), ts, ts + dur))
    wins.sort(key=lambda w: w[1])
    return wins


def _classify(name: str) -> str:
    low = name.lower()
    if any(t in low for t in COLLECTIVE_TOKENS):
        return "collective"
    if any(t in low for t in TRANSFER_TOKENS):
        return "transfer"
    return "compute"


def _collective_groups(coll_by_dev, lo, hi):
    """Align matching collective events across devices inside one
    round window.

    ``coll_by_dev``: device -> [(op_name, ts, end), ...]. Each
    device's in-window occurrences of an op name are sorted by start;
    the k-th occurrence on every device forms one *group* (the same
    HLO collective executes once per participant, so equal names +
    occurrence rank is the alignment key). Returns
    ``[{device: (enter, exit)}, ...]`` with enters/exits clipped to
    the window."""
    per = {}
    for dev, insts in coll_by_dev.items():
        for name, ts, end in insts:
            a, b = max(ts, lo), min(end, hi)
            if b > a:
                per.setdefault(name, {}).setdefault(dev, []).append((a, b))
    groups = []
    for name in sorted(per):
        by_dev = per[name]
        for occ in by_dev.values():
            occ.sort()
        depth = max(len(occ) for occ in by_dev.values())
        for k in range(depth):
            groups.append({d: occ[k]
                           for d, occ in sorted(by_dev.items())
                           if k < len(occ)})
    return groups


def _p95(values):
    if not values:
        return 0.0
    vals = sorted(values)
    # nearest-rank: matches the ledger's other percentile fields
    idx = max(0, int(round(0.95 * len(vals) + 0.5)) - 1)
    return vals[min(idx, len(vals) - 1)]


def _skew_stats(groups):
    """Per-device wait intervals + round skew stats from the aligned
    collective groups of one window.

    For a group entered last at ``last_enter``, a device's *wait* is
    ``[enter, min(last_enter, exit)]`` — the straggler-skew slice of
    its collective time; the remainder is *wire*. Single-participant
    groups contribute no wait (all wire). The straggler device is the
    one that caused the most waiting: argmax over devices of the
    summed enter-delta of the groups it entered last."""
    wait_iv = {}
    deltas, caused = [], {}
    for g in groups:
        if len(g) < 2:
            continue
        enters = {d: iv[0] for d, iv in g.items()}
        last_enter = max(enters.values())
        delta = last_enter - min(enters.values())
        deltas.append(delta)
        # deterministic straggler on ties: largest enter, then id
        straggler = max(sorted(g), key=lambda d: (enters[d], d))
        caused[straggler] = caused.get(straggler, 0.0) + delta
        for d, (a, b) in g.items():
            w = min(last_enter, b)
            if w > a:
                wait_iv.setdefault(d, []).append((a, w))
    stats = {
        "n_collectives": len(deltas),
        "max_enter_delta_s": round(max(deltas) / 1e6, 9) if deltas else 0.0,
        "p95_enter_delta_s": round(_p95(deltas) / 1e6, 9),
        "straggler_device": (max(sorted(caused), key=lambda d: caused[d])
                             if caused else None),
    }
    return wait_iv, stats


def attribute_rounds(events) -> dict:
    """Per-round device-time buckets from one trace's events:

        {round_index: {"window_s", "busy_s", "compute_s",
                       "collective_s", "transfer_s", "host_gap_s",
                       "overlapped_s",
                       "per_device": {device_id: {...}},
                       "skew": {...}}}

    ``busy`` is the union of all device-lane events clipped to the
    round window (parallel lanes don't double-count wall time);
    collective/transfer are the unions of the matching-named events;
    ``compute = busy - collective - transfer`` and ``host_gap =
    window - busy``, so the four buckets sum to the window exactly.
    The aggregate buckets pool every lane's intervals — identical to
    the schema-v3 computation bit-for-bit.

    ``overlapped_s`` is the slice of ``collective_s`` that ran
    concurrently with some lane's compute (pooled collective union ∩
    union of per-lane compute) — an overlay on the partition, not a
    fifth bucket: the four buckets above still sum to the window
    exactly, and ``collective_s - overlapped_s`` is the serial
    collective share the --overlap_depth pipeline is built to
    collapse.

    ``per_device[<id>]`` repeats the bucket math on that device's own
    interval set and splits its collective bucket into ``wait_s``
    (straggler skew, from the cross-device alignment of matching
    collectives) and ``wire_s = collective_s - wait_s`` — an exact
    partition by construction. ``skew`` carries the round-level stats
    (max/p95 enter-delta, straggler device id, matched-group count).
    """
    wins = round_windows(events)
    if not wins:
        return {}
    lanes = lane_devices(events)
    dev, coll, xfer = [], [], []
    by_dev = {}          # device -> {"dev": [...], "coll": [...], "xfer": [...]}
    coll_insts = {}      # device -> [(op_name, ts, end), ...]
    for e in events:
        key = (e.get("pid"), e.get("tid"))
        if e.get("ph") != "X" or key not in lanes:
            continue
        name = e.get("name", "")
        if name == ROUND_MARKER or name.startswith(PHASE_PREFIX):
            continue
        ts, dur = float(e.get("ts", 0.0)), float(e.get("dur", 0.0))
        iv = (ts, ts + dur)
        dev.append(iv)
        d = lanes[key]
        slot = by_dev.setdefault(d, {"dev": [], "coll": [], "xfer": []})
        slot["dev"].append(iv)
        kind = _classify(name)
        if kind == "collective":
            coll.append(iv)
            slot["coll"].append(iv)
            coll_insts.setdefault(d, []).append((name, iv[0], iv[1]))
        elif kind == "transfer":
            xfer.append(iv)
            slot["xfer"].append(iv)
    dev, coll, xfer = _union(dev), _union(coll), _union(xfer)
    for slot in by_dev.values():
        for k in slot:
            slot[k] = _union(slot[k])

    out = {}
    for ridx, lo, hi in wins:
        busy = _union(_clip(dev, lo, hi))
        c = _union(_clip(coll, lo, hi))
        t = _union(_clip(xfer, lo, hi))
        busy_us = _measure(busy)
        coll_us = _measure(c)
        # transfer time that isn't already counted as collective
        # (disjoint buckets: the four sum to the window)
        xfer_us = _measure(_union(t + c)) - coll_us
        win_us = hi - lo
        # overlapped: wall time where the pooled collective union runs
        # concurrently with some lane's COMPUTE (its busy minus its
        # own collective/transfer cover) — the slice of collective_s
        # the --overlap_depth pipeline hid behind compute. An overlay
        # on the partition, not a fifth bucket: compute + collective +
        # transfer + host_gap still sum to the window exactly, and
        # 0 <= overlapped_s <= collective_s; collective_s -
        # overlapped_s is the SERIAL collective share.
        comp_iv = []
        for slot in by_dev.values():
            d_busy = _union(_clip(slot["dev"], lo, hi))
            d_other = _union(_clip(slot["coll"], lo, hi)
                             + _clip(slot["xfer"], lo, hi))
            comp_iv.extend(_subtract(d_busy, d_other))
        ovl_us = _measure(_intersect(c, _union(comp_iv)))
        buckets = {
            "window_s": round(win_us / 1e6, 6),
            "busy_s": round(busy_us / 1e6, 6),
            "compute_s": round((busy_us - coll_us - xfer_us) / 1e6, 6),
            "collective_s": round(coll_us / 1e6, 6),
            "transfer_s": round(xfer_us / 1e6, 6),
            "host_gap_s": round((win_us - busy_us) / 1e6, 6),
            "overlapped_s": round(min(ovl_us, coll_us) / 1e6, 6),
        }
        groups = _collective_groups(coll_insts, lo, hi)
        wait_iv, skew = _skew_stats(groups)
        per_device = {}
        for d in sorted(by_dev):
            slot = by_dev[d]
            d_busy_us = _measure(_union(_clip(slot["dev"], lo, hi)))
            d_c = _union(_clip(slot["coll"], lo, hi))
            d_t = _union(_clip(slot["xfer"], lo, hi))
            d_coll_us = _measure(d_c)
            d_xfer_us = _measure(_union(list(d_t) + list(d_c))) - d_coll_us
            d_wait_us = _measure(_union(_clip(wait_iv.get(d, ()), lo, hi)))
            coll_s = round(d_coll_us / 1e6, 6)
            wait_s = round(min(d_wait_us, d_coll_us) / 1e6, 6)
            per_device[d] = {
                "busy_s": round(d_busy_us / 1e6, 6),
                "compute_s": round(
                    (d_busy_us - d_coll_us - d_xfer_us) / 1e6, 6),
                "collective_s": coll_s,
                "transfer_s": round(d_xfer_us / 1e6, 6),
                "wait_s": wait_s,
                # difference of two 6-dp values: wait + wire ==
                # collective holds exactly, not just to tolerance
                "wire_s": round(coll_s - wait_s, 6),
            }
        buckets["per_device"] = per_device
        buckets["skew"] = skew
        out[ridx] = buckets
    return out


def attribute_logdir(logdir: str) -> dict:
    """``attribute_rounds`` over the newest trace under ``logdir``;
    empty dict when no trace file exists."""
    path = find_trace_file(logdir)
    if path is None:
        return {}
    return attribute_rounds(load_trace_events(path))
