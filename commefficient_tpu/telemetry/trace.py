"""Device-time attribution: profiler round markers + trace parsing.

The round ledger (core.py) measures *host* phases; this module closes
the gap to the device timeline. Two halves:

**Markers** — while a ``trace_window`` is open, ``FedModel`` brackets
each round in a ``jax.profiler.StepTraceAnnotation`` (name
``fed_round``, ``step_num`` = the ledger round index) and the
device-relevant phases (h2d / round_dispatch / server) in
``TraceAnnotation``s. The round annotation is opened at
``begin_round`` and closed at the NEXT round's begin — mirroring the
ledger record lifecycle, so the server step (dispatched after
``_call_train`` returns) lands inside its own round's window. State is
module-level (one live FedModel per process, like
``fed_model._CURRENT_MODEL``); every call is a single flag check when
no trace is active, so the round hot loop pays nothing.

**Parser** — ``jax.profiler.stop_trace`` writes a Chrome trace-event
dump (``plugins/profile/<ts>/<host>.trace.json.gz``): ``ph:"X"``
complete events with µs ``ts``/``dur`` and ``ph:"M"`` metadata naming
each pid/tid lane. Device lanes are the ``/device:*`` processes (TPU,
GPU) or the ``tf_XLA*`` client threads (CPU backend).
``attribute_rounds`` buckets every device event into its round's
window: {compute, collective, h2d/d2h transfer, host-gap}, by interval
union so nested/overlapping op events never double-count. Buckets sum
to the round window by construction — the acceptance bar for the
schema-v3 ``device_time`` ledger field.
"""

from __future__ import annotations

import glob
import gzip
import json
import os

ROUND_MARKER = "fed_round"
PHASE_PREFIX = "fed_phase"

#: substrings (lowercase) classifying a device-lane event
COLLECTIVE_TOKENS = (
    "all-reduce", "allreduce", "all-gather", "allgather",
    "reduce-scatter", "reducescatter", "all-to-all", "alltoall",
    "collective-permute", "collectivepermute", "collective-broadcast",
)
TRANSFER_TOKENS = (
    "infeed", "outfeed", "copy", "memcpy", "transfer",
    "h2d", "d2h", "send", "recv",
)

# one live FedModel per process (fed_model._CURRENT_MODEL) -> one
# module-level marker state; "ann" is the currently-open round
# StepTraceAnnotation, closed at the next begin or at window exit
_STATE = {"tracing": False, "ann": None, "round": None}


def tracing() -> bool:
    return _STATE["tracing"]


def set_tracing(on: bool):
    """Flipped by ``profiler.trace_window`` enter/exit. Turning
    tracing off force-closes any open round marker first, so its end
    timestamp lands inside the trace."""
    if not on:
        end_round_marker()
    _STATE["tracing"] = bool(on)


def begin_round_marker(round_index: int):
    """Open round ``round_index``'s StepTraceAnnotation (closing the
    previous round's). No-op unless a trace window is active."""
    if not _STATE["tracing"]:
        return
    end_round_marker()
    import jax
    ann = jax.profiler.StepTraceAnnotation(ROUND_MARKER,
                                           step_num=int(round_index))
    ann.__enter__()
    _STATE["ann"] = ann
    _STATE["round"] = int(round_index)


def end_round_marker():
    ann, _STATE["ann"] = _STATE["ann"], None
    _STATE["round"] = None
    if ann is not None:
        ann.__exit__(None, None, None)


class _NullPhase:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_PHASE = _NullPhase()


def phase(name: str):
    """Context manager: a ``TraceAnnotation`` named
    ``fed_phase::<name>`` when tracing, the shared no-op otherwise.
    Used alongside (not instead of) the telemetry host spans."""
    if not _STATE["tracing"]:
        return _NULL_PHASE
    import jax
    return jax.profiler.TraceAnnotation(f"{PHASE_PREFIX}::{name}")


# --- trace file discovery + loading ------------------------------------


def find_trace_file(logdir: str):
    """Newest ``*.trace.json.gz`` under ``logdir`` (searched at any
    depth: jax writes ``plugins/profile/<timestamp>/<host>.trace.
    json.gz``). None when the profiler wrote nothing."""
    pats = (os.path.join(logdir, "**", "*.trace.json.gz"),
            os.path.join(logdir, "**", "*.trace.json"))
    hits = []
    for pat in pats:
        hits.extend(glob.glob(pat, recursive=True))
    if not hits:
        return None
    return max(hits, key=os.path.getmtime)


def load_trace_events(path_or_logdir: str):
    """Chrome trace-event list from a ``.trace.json(.gz)`` file, or
    from the newest one under a directory."""
    path = path_or_logdir
    if os.path.isdir(path):
        path = find_trace_file(path)
        if path is None:
            raise FileNotFoundError(
                f"no .trace.json(.gz) under {path_or_logdir}")
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        doc = json.load(f)
    events = doc.get("traceEvents", doc) if isinstance(doc, dict) \
        else doc
    return [e for e in events if isinstance(e, dict)]


# --- lane classification -----------------------------------------------


def _lane_names(events):
    """(pid -> process_name, (pid, tid) -> thread_name) from the
    ``ph:"M"`` metadata events."""
    procs, threads = {}, {}
    for e in events:
        if e.get("ph") != "M":
            continue
        name = (e.get("args") or {}).get("name", "")
        if e.get("name") == "process_name":
            procs[e.get("pid")] = name
        elif e.get("name") == "thread_name":
            threads[(e.get("pid"), e.get("tid"))] = name
    return procs, threads


def device_lanes(events):
    """(pid, tid) pairs whose events are device-side execution:
    ``/device:*`` processes (TPU/GPU xplanes) or ``tf_XLA*`` runtime
    threads (the CPU backend's per-device execution threads)."""
    procs, threads = _lane_names(events)
    lanes = set()
    for e in events:
        if e.get("ph") != "X":
            continue
        key = (e.get("pid"), e.get("tid"))
        pname = procs.get(key[0], "")
        tname = threads.get(key, "")
        if pname.startswith("/device:") or tname.startswith("tf_XLA"):
            lanes.add(key)
    return lanes


# --- interval math -----------------------------------------------------


def _union(intervals):
    """Merged, sorted interval list — nested/overlapping device events
    (module > fusion > op) collapse to their covering span."""
    out = []
    for a, b in sorted(intervals):
        if out and a <= out[-1][1]:
            out[-1][1] = max(out[-1][1], b)
        else:
            out.append([a, b])
    return out


def _measure(merged):
    return sum(b - a for a, b in merged)


def _clip(intervals, lo, hi):
    out = []
    for a, b in intervals:
        a, b = max(a, lo), min(b, hi)
        if b > a:
            out.append((a, b))
    return out


# --- per-round attribution ---------------------------------------------


def round_windows(events):
    """[(round_index, ts_us, end_us), ...] from the ``fed_round``
    StepTraceAnnotations, in timeline order. Each window is the
    annotation's own extent (begin_round -> next begin_round /
    trace-window exit)."""
    wins = []
    for e in events:
        if e.get("ph") != "X" or e.get("name") != ROUND_MARKER:
            continue
        args = e.get("args") or {}
        step = args.get("step_num", args.get("round"))
        if step is None:
            continue
        ts, dur = float(e.get("ts", 0.0)), float(e.get("dur", 0.0))
        wins.append((int(step), ts, ts + dur))
    wins.sort(key=lambda w: w[1])
    return wins


def _classify(name: str) -> str:
    low = name.lower()
    if any(t in low for t in COLLECTIVE_TOKENS):
        return "collective"
    if any(t in low for t in TRANSFER_TOKENS):
        return "transfer"
    return "compute"


def attribute_rounds(events) -> dict:
    """Per-round device-time buckets from one trace's events:

        {round_index: {"window_s", "busy_s", "compute_s",
                       "collective_s", "transfer_s", "host_gap_s"}}

    ``busy`` is the union of all device-lane events clipped to the
    round window (parallel lanes don't double-count wall time);
    collective/transfer are the unions of the matching-named events;
    ``compute = busy - collective - transfer`` and ``host_gap =
    window - busy``, so the four buckets sum to the window exactly.
    """
    wins = round_windows(events)
    if not wins:
        return {}
    lanes = device_lanes(events)
    dev, coll, xfer = [], [], []
    for e in events:
        if e.get("ph") != "X" or (e.get("pid"), e.get("tid")) not in lanes:
            continue
        name = e.get("name", "")
        if name == ROUND_MARKER or name.startswith(PHASE_PREFIX):
            continue
        ts, dur = float(e.get("ts", 0.0)), float(e.get("dur", 0.0))
        iv = (ts, ts + dur)
        dev.append(iv)
        kind = _classify(name)
        if kind == "collective":
            coll.append(iv)
        elif kind == "transfer":
            xfer.append(iv)
    dev, coll, xfer = _union(dev), _union(coll), _union(xfer)

    out = {}
    for ridx, lo, hi in wins:
        busy = _union(_clip(dev, lo, hi))
        c = _union(_clip(coll, lo, hi))
        t = _union(_clip(xfer, lo, hi))
        busy_us = _measure(busy)
        coll_us = _measure(c)
        # transfer time that isn't already counted as collective
        # (disjoint buckets: the four sum to the window)
        xfer_us = _measure(_union(t + c)) - coll_us
        win_us = hi - lo
        out[ridx] = {
            "window_s": round(win_us / 1e6, 6),
            "busy_s": round(busy_us / 1e6, 6),
            "compute_s": round((busy_us - coll_us - xfer_us) / 1e6, 6),
            "collective_s": round(coll_us / 1e6, 6),
            "transfer_s": round(xfer_us / 1e6, 6),
            "host_gap_s": round((win_us - busy_us) / 1e6, 6),
        }
    return out


def attribute_logdir(logdir: str) -> dict:
    """``attribute_rounds`` over the newest trace under ``logdir``;
    empty dict when no trace file exists."""
    path = find_trace_file(logdir)
    if path is None:
        return {}
    return attribute_rounds(load_trace_events(path))
