"""Pluggable ledger sinks.

Every sink exposes ``write(record)`` + ``close()``; records are the
schema-v1 dicts of ``telemetry.record``.  A sink consumes the kinds
it cares about and ignores the rest, so one Telemetry fans out to any
combination.
"""

from __future__ import annotations

import glob
import json
import os
import re
import threading

import numpy as np

from commefficient_tpu.telemetry.record import (make_bench_record,
                                                make_summary_record)

#: lock-confinement declaration (flowlint ``lock-confinement``): the
#: JSONLSink two-writer guard is a process-wide class dict — a daemon
#: opening per-job shards from worker threads races the check-then-
#: claim, so claim and eviction must hold ``_live_lock``.
_LOCK_MAP = {"_live": "_live_lock"}


def shard_ledger_path(path: str, process_index: int) -> str:
    """Per-process ledger path: process 0 owns the canonical ``path``;
    process k writes the ``<path>.p<k>.jsonl`` shard that
    ``scripts/ledger_merge.py`` joins back on round id. Namespacing by
    process index means two processes pointed at the same ``--ledger``
    can never interleave writes into one file."""
    k = int(process_index)
    return path if k == 0 else f"{path}.p{k}.jsonl"


def job_ledger_path(path: str, job_index: int) -> str:
    """Per-job ledger path under a fedservice daemon: job ``j``'s
    records go to the ``<path>.job<j>.jsonl`` shard that
    ``scripts/ledger_merge.py`` joins next to the ``.p<k>`` process
    shards. Namespacing by job index (like ``shard_ledger_path`` does
    by process index) keeps J concurrent jobs pointed at one
    ``--ledger`` from ever interleaving writes into one file — the
    shard file IS the job identity, so the records themselves stay
    byte-identical to a solo run's."""
    return f"{path}.job{int(job_index)}.jsonl"


def job_index_of_ledger(path: str):
    """The job index a ledger shard path encodes (``<base>.job<j>
    .jsonl`` → ``j``), or None for a canonical/process-shard path —
    the live plane derives its ``job`` metric label from this, since
    the shard file IS the job identity and records carry no job
    stamp."""
    m = re.search(r"\.job(\d+)\.jsonl(?:\.p\d+\.jsonl)?$",
                  str(path or ""))
    return int(m.group(1)) if m else None


def recover_ledger_shards(path: str) -> dict:
    """Sweep a canonical ledger path AND every sibling shard — the
    ``.p<k>`` process shards, the ``.job<j>`` job shards, and the job
    shards' own process shards — through :func:`recover_torn_tail`.

    Returns ``{shard_path: bytes_dropped}`` for shards that lost a
    torn tail (empty when everything was clean). ``JSONLSink``
    recovers its own file at open, but a fedservice daemon restarted
    after a SIGKILL may never re-admit the tenant that owned a torn
    shard — this sweep runs at daemon start so no orphaned torn tail
    survives to poison ``scripts/ledger_merge.py``."""
    if not path:
        return {}
    candidates = [path]
    candidates += sorted(
        set(glob.glob(glob.escape(path) + ".job*.jsonl")
            + glob.glob(glob.escape(path) + ".p*.jsonl")))
    dropped = {}
    for p in candidates:
        if not os.path.isfile(p):
            continue
        n = recover_torn_tail(p)
        if n:
            dropped[p] = n
    return dropped


def recover_torn_tail(path: str) -> int:
    """Truncate a JSONL file's torn last line in place, if any.

    A writer killed mid-write (SIGKILL, power loss) can leave a
    partial final line. Every complete line ends with ``\\n`` and
    parses as JSON; anything after the last newline — or a final
    newline-terminated line that does not parse — is the torn tail.
    Returns the number of bytes dropped (0 for a clean file)."""
    try:
        size = os.path.getsize(path)
    except OSError:
        return 0
    if size == 0:
        return 0
    with open(path, "rb+") as f:
        # scan back from EOF for the last complete line boundary
        f.seek(0, os.SEEK_END)
        end = f.tell()
        f.seek(max(0, end - 1))
        keep = end
        if f.read(1) != b"\n":
            # no trailing newline: drop everything past the previous
            # one (the whole file, if it is a single torn line)
            chunk = min(end, 1 << 16)
            f.seek(end - chunk)
            tail = f.read(chunk)
            nl = tail.rfind(b"\n")
            keep = end - chunk + nl + 1 if nl >= 0 else 0
        if keep != end:
            f.truncate(keep)
    return size - keep


def last_round_index(path: str):
    """Max round id among a ledger's round records (None when the
    file is missing/empty/has no round records). Unparseable lines
    are skipped — read-side torn tolerance."""
    last = None
    try:
        f = open(path)
    except OSError:
        return None
    with f:
        for line in f:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rec.get("kind") == "round":
                r = rec.get("round")
                if r is not None and (last is None or r > last):
                    last = int(r)
    return last


class JSONLSink:
    """One JSON object per line, appended to ``path``; each record is
    serialised to its full line FIRST, then written with a single
    ``write`` + flush — a crash between records leaves a clean file,
    and a crash mid-write leaves at most one torn tail, which the
    append-open truncates away (``recover_torn_tail``). When
    ``process`` is given, every record is stamped with that jax
    process index (multi-host shards stay attributable post-merge).

    ``resume_after``: round records with ``round`` <= this id are
    silently dropped — the resume path replays from the last
    checkpoint, and bit-exact replay would otherwise duplicate the
    rounds the previous run already recorded (pass
    ``last_round_index(path)`` to keep ledger round ids monotone and
    deduplicated across a crash/resume cycle)."""

    #: absolute path -> the sink currently holding it in this process —
    #: a second writer on the same file would interleave its records
    #: between the first writer's write() calls, producing a ledger
    #: no reader can attribute (and, under two flush cadences, torn
    #: half-lines). Refusing at open time turns the silent corruption
    #: into an immediate error; close() releases the claim. A
    #: registered sink whose underlying file handle is already closed
    #: is a *dead* writer (crash/resume path) — it can never write
    #: again, so its claim is evicted rather than honoured.
    _live = {}
    _live_lock = threading.Lock()

    def __init__(self, path: str, process=None, resume_after=None):
        self.path = path
        self.process = None if process is None else int(process)
        self.resume_after = (None if resume_after is None
                             else int(resume_after))
        abspath = os.path.abspath(path)
        self._f = None
        self._abspath = abspath
        # claim under the lock BEFORE opening: two threads racing the
        # unlocked check-then-claim would both pass the prior check
        # and both open the file — the exact interleaving the guard
        # exists to refuse
        with JSONLSink._live_lock:
            prior = JSONLSink._live.get(abspath)
            # a claimed prior with _f None is mid-__init__ (close()
            # and a failed open both drop the claim) — still live
            if prior is not None and (prior._f is None
                                      or not prior._f.closed):
                raise RuntimeError(
                    f"ledger {path} already has a live JSONLSink in "
                    "this process — two writers on one path would "
                    "interleave torn records. Close the first sink, "
                    "or shard the path (shard_ledger_path / "
                    "job_ledger_path)")
            JSONLSink._live[abspath] = self
        try:
            parent = os.path.dirname(abspath)
            os.makedirs(parent, exist_ok=True)
            recover_torn_tail(path)
            self._f = open(path, "a")
        except BaseException:
            with JSONLSink._live_lock:
                if JSONLSink._live.get(abspath) is self:
                    del JSONLSink._live[abspath]
            raise

    def write(self, rec):
        if self.resume_after is not None \
                and rec.get("kind") == "round" \
                and rec.get("round") is not None \
                and int(rec["round"]) <= self.resume_after:
            return
        if self.process is not None:
            rec = dict(rec, process=self.process)
        line = json.dumps(rec, separators=(",", ":"),
                          default=_json_default) + "\n"
        self._f.write(line)
        self._f.flush()

    def close(self):
        if self._f is not None:
            self._f.close()
            self._f = None
            with JSONLSink._live_lock:
                if JSONLSink._live.get(self._abspath) is self:
                    del JSONLSink._live[self._abspath]


def _json_default(obj):
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    return str(obj)


def append_bench_record(path: str, metric: str, result, **extra):
    """One-call ``--ledger`` helper for the bench scripts: append
    their headline result dict as a schema-v1 bench record (stdout
    output stays the harness contract, untouched)."""
    sink = JSONLSink(path)
    try:
        sink.write(make_bench_record(metric, result, "json", **extra))
    finally:
        sink.close()


class TensorBoardSink:
    """TensorBoard writer (the single home of what used to be
    duplicated ``make_summary_writer``/``write_epoch_scalars`` setup
    in cv_train/gpt2_train): epoch rows become per-epoch scalars,
    round records become per-round span/byte scalars. Uses torch's
    bundled SummaryWriter; degrades to a no-op with a warning when
    unavailable."""

    def __init__(self, logdir: str):
        self._writer = None
        try:
            from torch.utils.tensorboard import SummaryWriter
        except ImportError:
            import warnings
            warnings.warn("tensorboard writer unavailable; "
                          "--tensorboard ignored")
            return
        self._writer = SummaryWriter(log_dir=logdir)

    def write(self, rec):
        if self._writer is None:
            return
        kind = rec.get("kind")
        if kind == "epoch":
            for key, val in rec["row"].items():
                if isinstance(val, (int, float, np.floating,
                                    np.integer)):
                    self._writer.add_scalar(key.replace(" ", "_"),
                                            float(val), rec["epoch"])
            self._writer.flush()
        elif kind == "round":
            step = rec["round"]
            for name, secs in rec["spans"].items():
                self._writer.add_scalar(f"round/{name}_ms",
                                        1e3 * float(secs), step)
            for key in ("uplink_bytes", "downlink_bytes"):
                if rec.get(key) is not None:
                    self._writer.add_scalar(f"round/{key}",
                                            float(rec[key]), step)

    def close(self):
        if self._writer is not None:
            self._writer.close()
            self._writer = None


class ConsoleSink:
    """End-of-run summary on stdout: per-span totals/means, byte
    totals, prefetch hit rate, compile events — the quick look that
    previously required reassembling three log formats."""

    def __init__(self, out=None):
        self._out = out
        self.rounds = 0
        self.spans = {}
        self.counters = {}
        self.uplink = 0.0
        self.downlink = 0.0
        self.alarms = {}

    def write(self, rec):
        if rec.get("kind") != "round":
            return
        self.rounds += 1
        for name, secs in rec["spans"].items():
            self.spans[name] = self.spans.get(name, 0.0) + secs
        for name, n in rec["counters"].items():
            self.counters[name] = self.counters.get(name, 0) + n
        self.uplink += rec.get("uplink_bytes") or 0.0
        self.downlink += rec.get("downlink_bytes") or 0.0
        for alarm in rec.get("alarms") or []:
            rule = str(alarm.get("rule"))
            self.alarms[rule] = self.alarms.get(rule, 0) + 1

    def summary(self) -> dict:
        n = max(self.rounds, 1)
        rec = make_summary_record(
            rounds=self.rounds,
            uplink_mib=round(self.uplink / 2**20, 3),
            downlink_mib=round(self.downlink / 2**20, 3),
            span_total_s={k: round(v, 4)
                          for k, v in sorted(self.spans.items())},
            span_mean_ms={k: round(1e3 * v / n, 3)
                          for k, v in sorted(self.spans.items())},
            counters=dict(sorted(self.counters.items())),
        )
        if self.alarms:
            rec["alarm_fired"] = dict(sorted(self.alarms.items()))
        return rec

    def close(self):
        if not self.rounds:
            return
        import sys
        out = self._out or sys.stdout
        s = self.summary()
        print("== telemetry summary "
              f"({s['rounds']} rounds) ==", file=out)
        print(f"  comm: up {s['uplink_mib']} MiB, "
              f"down {s['downlink_mib']} MiB", file=out)
        for name in s["span_total_s"]:
            print(f"  span {name}: total {s['span_total_s'][name]} s, "
                  f"mean {s['span_mean_ms'][name]} ms/round", file=out)
        if s["counters"]:
            print(f"  counters: {s['counters']}", file=out)
        if s.get("alarm_fired"):
            print(f"  alarms fired: {s['alarm_fired']}", file=out)
