"""Structured run observability: round ledgers, spans, sinks.

The repo's single instrumented source of truth — per-round wall-time
spans, uplink/downlink bytes unified with FedModel's accounting,
memory watermarks, compile events — with pluggable sinks (JSONL
ledger, TensorBoard, console summary) and near-zero overhead when
disabled.  See record.py for the ledger schema, core.py for the span
lifecycle, scripts/telemetry_report.py for rendering/diffing ledgers.

``telemetry.profiler`` (jax.profiler trace windows) is imported
lazily by its users, not here: it reaches back into ``utils`` for
logdir naming and must not cycle through this package import.
"""

from commefficient_tpu.telemetry import clock, trace
from commefficient_tpu.telemetry.causal import (CausalTracer,
                                                assemble_traces,
                                                build_causal_tracer)
from commefficient_tpu.telemetry.core import (NULL_TELEMETRY, Telemetry,
                                              build_telemetry,
                                              hbm_peak_bytes,
                                              host_rss_peak_bytes)
from commefficient_tpu.telemetry.critpath import (critical_path,
                                                  critpath_diff,
                                                  median_buckets)
from commefficient_tpu.telemetry.record import (LEDGER_SCHEMA_VERSION,
                                                make_bench_record,
                                                make_meta_record,
                                                make_round_record,
                                                validate_record)
from commefficient_tpu.telemetry.flightrec import (FlightRecorder,
                                                   install_crash_hook,
                                                   load_postmortem)
from commefficient_tpu.telemetry.live import (LiveMetricsSink,
                                              LiveRegistry,
                                              attach_live_plane,
                                              live_registry,
                                              shutdown_plane)
from commefficient_tpu.telemetry.sinks import (ConsoleSink, JSONLSink,
                                               TensorBoardSink,
                                               append_bench_record,
                                               job_index_of_ledger,
                                               job_ledger_path,
                                               recover_ledger_shards)
from commefficient_tpu.telemetry.slo import (SLOEngine, SLOSpec,
                                             build_slo_engine)

__all__ = [
    "clock",
    "trace",
    "NULL_TELEMETRY",
    "Telemetry",
    "build_telemetry",
    "host_rss_peak_bytes",
    "hbm_peak_bytes",
    "LEDGER_SCHEMA_VERSION",
    "make_bench_record",
    "make_meta_record",
    "make_round_record",
    "validate_record",
    "ConsoleSink",
    "JSONLSink",
    "TensorBoardSink",
    "append_bench_record",
    "job_ledger_path",
    "job_index_of_ledger",
    "recover_ledger_shards",
    "FlightRecorder",
    "install_crash_hook",
    "load_postmortem",
    "LiveMetricsSink",
    "LiveRegistry",
    "attach_live_plane",
    "live_registry",
    "shutdown_plane",
    "SLOEngine",
    "SLOSpec",
    "build_slo_engine",
    "CausalTracer",
    "assemble_traces",
    "build_causal_tracer",
    "critical_path",
    "critpath_diff",
    "median_buckets",
]
