"""Round-ledger telemetry: spans, counters, and record lifecycle.

One ``Telemetry`` instance observes one run.  The hot-path contract:

- **disabled** (no sinks): ``begin_round`` is a single truthiness
  check, ``span()`` returns one shared no-op context manager, and
  ``count()`` returns immediately — no per-round allocation, nothing
  retained.  ``bench.py`` with telemetry off must stay within 1% of
  the recorded baseline, and the whole disabled path is a handful of
  attribute loads per round.
- **enabled**: ``begin_round`` opens a round record; ``span(name)``
  accumulates wall-time into it; ``count(name)`` bumps a counter.
  Records are emitted to every sink in round order once they are (a)
  no longer the current round and (b) carry their uplink/downlink
  bytes (``set_round_bytes`` — deferred under ``--pipeline_depth``
  until the trainer drains).  ``close()`` flushes whatever remains.

Round lifecycle (mirrors runtime/fed_model.py):

    begin_round(r)        # top of FedModel._call_train
      span("h2d") ...     # client pass spans
      set_round_bytes(r)  # sync path: end of _call_train;
                          # pipelined: FedModel.flush replay
      span("server") ...  # FedOptimizer.step (record still current)
    begin_round(r+1)      # closes r -> watermark snapshot -> emit

Compile events come from ``jax.monitoring``'s duration listener
(registered once, process-wide); each record carries the delta of
compile count/seconds observed while it was current.
"""

from __future__ import annotations

from collections import OrderedDict

from commefficient_tpu.telemetry import clock
from commefficient_tpu.telemetry.record import (make_meta_record,
                                                make_round_record)


class _NullSpan:
    """Shared, allocation-free no-op context manager."""
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_spans", "_name", "_t0", "_causal")

    def __init__(self, spans, name, causal=None):
        self._spans = spans
        self._name = name
        self._causal = causal

    def __enter__(self):
        self._t0 = clock.tick()
        if self._causal is not None:
            # open AFTER t0 so the causal frame nests inside the
            # accumulated span second-for-second; nesting (driver
            # spans inside async_fold) comes from the tracer's stack
            self._causal.open(self._name)
        return self

    def __exit__(self, *exc):
        if self._causal is not None:
            self._causal.close_span()
        dt = clock.tick() - self._t0
        self._spans[self._name] = self._spans.get(self._name, 0.0) + dt
        return False


# --- process-wide compile-event accounting -----------------------------
# jax.monitoring listeners cannot be unregistered, so one module-level
# listener accumulates and each Telemetry snapshots deltas.
_COMPILE = {"events": 0, "secs": 0.0}
_LISTENER_STATE = {"done": False}


def compile_mark():
    """Snapshot of the process-wide compile accumulator; pair with
    ``compile_delta`` to attribute the compiles between two points to a
    specific cause (fed_model stamps first-dispatch compiles of a round
    variant onto the round record as ``vcompile_*:<key>`` counters)."""
    return (_COMPILE["events"], _COMPILE["secs"])


def compile_delta(mark):
    """(events, secs) accumulated since ``mark``."""
    ev0, s0 = mark
    return (_COMPILE["events"] - ev0, _COMPILE["secs"] - s0)


def _ensure_compile_listener():
    if _LISTENER_STATE["done"]:
        return
    _LISTENER_STATE["done"] = True
    try:
        from jax import monitoring

        def _on_duration(event, secs, **kw):
            if "compile" in event:
                _COMPILE["events"] += 1
                _COMPILE["secs"] += float(secs)

        monitoring.register_event_duration_secs_listener(_on_duration)
    except Exception:  # jax too old/new: compile fields stay zero
        pass


def host_rss_peak_bytes():
    """Peak resident set size of this process (bytes), or None."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmHWM:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    try:
        import resource
        return int(resource.getrusage(
            resource.RUSAGE_SELF).ru_maxrss) * 1024  # Linux: KiB
    except Exception:
        return None
    return None


def hbm_peak_bytes():
    """Peak accelerator bytes-in-use on local device 0, or None (CPU
    backends don't report; any failure degrades to None)."""
    try:
        from commefficient_tpu.parallel import mesh
        stats = mesh.first_local_device().memory_stats()
        if stats:
            return int(stats.get("peak_bytes_in_use", 0)) or None
    except Exception:
        pass
    return None


class Telemetry:
    """Span/counter recorder + sink fan-out for one run."""

    def __init__(self, sinks=None):
        self._sinks = list(sinks or ())
        self._records = OrderedDict()   # round index -> record
        self._closed_rounds = set()     # indices no longer current
        self._alarm_counts = {}         # rule -> fires this run
        self._current = None            # the open round record
        self._compile_mark = (0, 0.0)
        self._shut = False
        # emission hold: a profiler trace window buffers closed
        # records until its trace is parsed, so per-round device-time
        # buckets (schema v3) can merge before the record reaches the
        # sinks. Round ORDER is unchanged — the hold only delays the
        # drain.
        self._hold = False
        # expected lower-bound round seconds (analysis/cost.py),
        # registered by FedModel under --profile; merged device-time
        # buckets derive roofline_utilization from it
        self.expected_round_s = None
        # optional callback(round_index, buckets) invoked when trace
        # buckets merge — FedModel points it at the alarm engine's
        # collective-skew check so trace-derived skew can escalate
        # like any other alarm rule
        self.on_device_time = None
        # optional CausalTracer (--causal_trace): every _Span also
        # opens/closes a causal frame, and closing a round stamps its
        # span DAG onto the record as the optional v7 ``causal`` key.
        # None (the default) keeps the hot path byte-identical.
        self.causal = None
        if self._sinks:
            _ensure_compile_listener()

    # --- configuration --------------------------------------------------

    @property
    def enabled(self) -> bool:
        return bool(self._sinks)

    def add_sink(self, sink):
        """Attach a sink mid-run (trainers attach the TensorBoard sink
        once the run's logdir exists)."""
        self._sinks.append(sink)
        _ensure_compile_listener()

    def set_causal_tracer(self, tracer):
        """Attach a CausalTracer (or None to detach). Only meaningful
        on an enabled Telemetry — causal stamps ride round records."""
        self.causal = tracer if self._sinks else None

    def emit(self, rec):
        for sink in self._sinks:
            sink.write(rec)

    def emit_meta(self, **fields):
        if self._sinks:
            self.emit(make_meta_record(**fields))

    # --- round lifecycle ------------------------------------------------

    def begin_round(self, index: int):
        """Open round ``index``; closes (and may emit) the previous
        round. No-op when disabled."""
        if not self._sinks:
            return None
        self._close_current()
        rec = make_round_record(index)
        self._records[index] = rec
        self._current = rec
        self._compile_mark = (_COMPILE["events"], _COMPILE["secs"])
        if self.causal is not None:
            self.causal.begin_round(index)
        return rec

    def _close_current(self):
        rec, self._current = self._current, None
        if rec is None:
            return
        rec["host_rss_peak_bytes"] = host_rss_peak_bytes()
        rec["hbm_peak_bytes"] = hbm_peak_bytes()
        ev0, s0 = self._compile_mark
        rec["counters"]["compile_events"] = _COMPILE["events"] - ev0
        rec["counters"]["compile_secs"] = round(
            _COMPILE["secs"] - s0, 6)
        if self.causal is not None:
            stamp = self.causal.end_round()
            if stamp is not None:
                rec["causal"] = stamp
        self._closed_rounds.add(rec["round"])
        self._drain()

    def span(self, name: str):
        """Context manager accumulating wall-time into the current
        round record; the shared no-op outside a round / disabled."""
        if self._current is None:
            return NULL_SPAN
        return _Span(self._current["spans"], name, self.causal)

    def count(self, name: str, n: int = 1):
        if self._current is not None:
            c = self._current["counters"]
            c[name] = c.get(name, 0) + n

    def set_round_bytes(self, index: int, downlink, uplink):
        """Attach the round's FedModel accounting totals. Arrives at
        the end of the client pass (synchronous) or at flush replay
        (``--pipeline_depth`` > 1)."""
        rec = self._records.get(index)
        if rec is None:
            return
        rec["downlink_bytes"] = float(downlink)
        rec["uplink_bytes"] = float(uplink)
        self._drain()

    def set_round_privacy(self, index: int, epsilon, delta, sigma):
        """Stamp the round's DP ledger trail (schema v5): cumulative
        ε(δ) after the round was charged, the δ it is stated at, and
        the effective noise multiplier charged. Arrives right after
        the accountant steps (runtime/fed_model.py) — always before
        emission, which waits on ``set_round_bytes``."""
        rec = self._records.get(index)
        if rec is None:
            return
        rec["dp_epsilon"] = float(epsilon)
        rec["dp_delta"] = float(delta)
        rec["dp_sigma"] = float(sigma)

    def set_round_slo(self, index: int, stamp: dict):
        """Attach the SLO engine's per-objective snapshot (schema v6
        ``slo`` key) to round ``index``'s record. Arrives from the
        round-finish hook (runtime/fed_model.py or the fedservice
        tick), always before emission."""
        rec = self._records.get(index)
        if rec is None or not stamp:
            return
        rec["slo"] = dict(stamp)

    def merge_round_probes(self, index: int, probes: dict):
        """Merge algorithm-probe values onto round ``index``'s record
        (schema v2). Client-pass probes land inside ``metrics_host``;
        server-pass probes merge during ``FedOptimizer.step`` while
        the record is still current; pipelined rounds merge at flush
        replay — all strictly before the record can emit (emission
        waits on ``set_round_bytes``, which arrives last)."""
        rec = self._records.get(index)
        if rec is None or not probes:
            return
        if rec.get("probes") is None:
            rec["probes"] = {}
        rec["probes"].update(probes)

    def hold_emission(self, on: bool):
        """Buffer record emission while a profiler trace window is
        open (``on=True``); releasing the hold drains whatever became
        eligible meanwhile. ``close()`` overrides any hold."""
        self._hold = bool(on)
        if not self._hold:
            self._drain()

    def merge_round_device_time(self, index: int, buckets: dict):
        """Attach trace-derived device-time buckets (schema v3) to
        round ``index``'s record — called by the trace window at exit,
        while ``hold_emission`` keeps the records buffered. Derives
        ``roofline_utilization`` when a cost model registered
        ``expected_round_s``."""
        rec = self._records.get(index)
        if rec is None or not buckets:
            return
        buckets = dict(buckets)
        exp = self.expected_round_s
        busy = buckets.get("busy_s")
        if exp and busy:
            # 6 dp: CPU-scale utilizations sit at 1e-6..1e-3 and must
            # not round to zero
            buckets["roofline_utilization"] = round(exp / busy, 6)
        rec["device_time"] = buckets
        cb = self.on_device_time
        if cb is not None:
            cb(index, buckets)

    def flag_alarm(self, index: int, alarm: dict):
        """Append an alarm dict to round ``index``'s record (schema
        v2 ``alarms`` list) and bump the run's per-rule fire count
        (the ``alarm_fired`` totals ``close()`` emits on the summary
        record). Safe any time before emission."""
        rule = str(alarm.get("rule"))
        self._alarm_counts[rule] = self._alarm_counts.get(rule, 0) + 1
        rec = self._records.get(index)
        if rec is None:
            return
        rec.setdefault("alarms", []).append(alarm)

    def _drain(self, force: bool = False):
        """Emit front records that are closed and byte-complete (or
        everything closed, when forced) — ledger order == round
        order. A trace-window hold defers everything (except forced
        close) until the trace is parsed and merged."""
        if self._hold and not force:
            return
        while self._records:
            idx, rec = next(iter(self._records.items()))
            if idx not in self._closed_rounds:
                break
            if rec["uplink_bytes"] is None and not force:
                break
            self._records.pop(idx)
            self._closed_rounds.discard(idx)
            self.emit(rec)

    # --- non-round records ----------------------------------------------

    def epoch(self, row: dict, epoch: int):
        """Emit the trainer's per-epoch row (TableLogger shape)."""
        if not self._sinks:
            return
        from commefficient_tpu.telemetry.record import make_epoch_record
        self.emit(make_epoch_record(row, epoch))

    # --- shutdown ---------------------------------------------------------

    def close(self):
        """Flush every pending record and close sinks. Idempotent.
        A run in which any alarm fired additionally emits one summary
        record carrying the per-rule ``alarm_fired`` totals, so
        report tooling can show alarm counts without scanning every
        round record; clean runs' ledgers are unchanged."""
        if self._shut:
            return
        self._shut = True
        self._close_current()
        self._drain(force=True)
        if self._alarm_counts and self._sinks:
            from commefficient_tpu.telemetry.record import \
                make_summary_record
            self.emit(make_summary_record(
                alarm_fired=dict(sorted(self._alarm_counts.items()))))
        for sink in self._sinks:
            try:
                sink.close()
            except Exception:
                pass
        self._sinks = []


#: module-level disabled instance — importers needing "a telemetry"
#: without plumbing can use this; everything on it no-ops.
NULL_TELEMETRY = Telemetry()


def build_telemetry(args, extra_sinks=(), process_index=None,
                    process_count=None) -> Telemetry:
    """Resolve a run's Telemetry from its Config.

    ``--ledger PATH`` attaches a JSONL sink on EVERY process: process
    0 writes the canonical ledger at ``PATH`` (round records carry the
    replicated accounting arrays, so one canonical writer suffices);
    process k > 0 writes the ``PATH.p<k>.jsonl`` shard — its own
    host-phase spans, RSS watermarks, and locally-observed bytes —
    announced once per run so multi-host data is never silently
    dropped. ``scripts/ledger_merge.py`` joins the shards back on
    round id. Records are process-stamped whenever the mesh is
    multi-process. ``--telemetry_console`` attaches the end-of-run
    console summary (process 0 only). The TensorBoard sink is attached
    later by the trainer, which owns the run logdir.

    ``process_index``/``process_count`` default to the live jax
    runtime; tests inject them to exercise the shard layout without a
    multi-process mesh.

    ``--resume`` runs append to the SAME ledger: the sink truncates
    any torn tail the interrupted writer left, then drops replayed
    round records at or below the file's last recorded round id, so
    the resumed ledger's round ids stay monotone and deduplicated
    (replay is bit-exact from the checkpoint, so dropping the
    duplicates loses nothing).
    """
    sinks = list(extra_sinks)
    path = getattr(args, "ledger", "") or ""
    console = bool(getattr(args, "telemetry_console", False))
    if path or console:
        if process_index is None or process_count is None:
            try:
                import jax
                process_index = jax.process_index()
                process_count = jax.process_count()
            except Exception:
                process_index, process_count = 0, 1
        pidx, pcount = int(process_index), int(process_count)
        from commefficient_tpu.telemetry.sinks import (ConsoleSink,
                                                       JSONLSink,
                                                       last_round_index,
                                                       shard_ledger_path)
        if path:
            spath = shard_ledger_path(path, pidx)
            stamp = pidx if pcount > 1 else None
            resume_after = (last_round_index(spath)
                            if getattr(args, "do_resume", False)
                            else None)
            sinks.append(JSONLSink(spath, process=stamp,
                                   resume_after=resume_after))
            if pidx != 0:
                print(f"telemetry: process {pidx}/{pcount} writing "
                      f"ledger shard {spath} (process 0 owns the "
                      f"canonical ledger; merge with "
                      f"scripts/ledger_merge.py)")
        if console and pidx == 0:
            sinks.append(ConsoleSink())
    return Telemetry(sinks)
