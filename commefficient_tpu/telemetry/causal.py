"""Causal round tracing: distributed spans with deterministic ids.

The observability stack measures *how much* (per-phase seconds,
device buckets, SLO burn) but not *why a given round took as long as
it did*. This module adds the causal link: a span model threaded
through the round lifecycle — JobSpec admission → scheduler grant →
cohort issue → arrival dequeue → prefetch/gather → h2d → round
dispatch → server fold → checkpoint/ledger flush — whose per-round
DAG ``telemetry/critpath.py`` folds into a critical-path explanation
("this round was slow because arrival_wait grew 6×").

Design rules:

* **Deterministic ids.** A trace id is a pure function of
  ``(job, round)`` and a span id of ``(job, round, seq)`` — no
  wall-clock or RNG component. Two processes that never talk (the
  fedservice daemon granting a slot, the tenant running the round)
  mint the SAME ids for the same causal event, so
  ``scripts/ledger_merge.py`` stitches cross-process traces by id
  with no coordination protocol. Well-known ``SEQ_*`` slots anchor
  the lifecycle events both sides must agree on.
* **Spans ride the record stream.** The closing round record carries
  the trace as its schema-v7 ``causal`` stamp; ``.p<k>``/``.job<j>``
  shards carry their own spans and the merge reassembles the DAG.
* **Host-side only, off by default.** A tracer is constructed ONLY
  under ``--causal_trace``; with the flag unset nothing here is ever
  imported on the round path and the compiled program is
  byte-identical (HLO-identity pinned in tests/test_probes.py, and
  the flowlint ``causal-confinement`` rule keeps this module out of
  jitted reachability).

Span times are monotonic ``clock.tick()`` seconds — only the *ids*
are deterministic; cross-process spans therefore stitch structurally
(by id) rather than on a shared clock, and the critical-path
invariant (buckets sum == wall) is stated per trace, on one clock.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager

from commefficient_tpu.telemetry import clock

#: critical-path attribution buckets (telemetry/critpath.py). Every
#: second of a round's wall time lands in exactly one of these;
#: ``host_other`` is the honest residual — wall time between
#: instrumented spans (record bookkeeping, accounting glue) that no
#: named phase claims.
BUCKETS = ("sched_wait", "arrival_wait", "host_gather", "h2d",
           "compute", "collective_exposed", "writeback", "flush",
           "host_other")

#: span name -> bucket. Unknown names fall to ``host_other`` so a
#: new span can never silently inflate a named bucket.
BUCKET_OF = {
    "admission": "sched_wait",
    "sched_grant": "sched_wait",
    "sched_wait": "sched_wait",
    "async_fold": "arrival_wait",
    "cohort_issue": "arrival_wait",
    "arrival_dequeue": "arrival_wait",
    "sampler": "host_gather",
    "gather": "host_gather",
    "prefetch": "host_gather",
    "h2d": "h2d",
    "h2d_state": "h2d",
    "round_dispatch": "compute",
    "metrics_host": "compute",
    "server": "compute",
    "autopilot_warm": "compute",
    "collective": "collective_exposed",
    "writeback": "writeback",
    "flush": "flush",
    "checkpoint": "flush",
    "ledger_flush": "flush",
}

#: well-known seq slots: ids both sides of a process boundary must
#: agree on without talking. Dynamically numbered spans start at
#: ``SEQ_DYNAMIC`` so they can never collide with an anchor.
SEQ_ROOT = 0       # the round's root span (tenant round loop)
SEQ_ADMIT = 1      # JobSpec admission (fedservice daemon)
SEQ_GRANT = 2      # scheduler grant (fedservice daemon)
SEQ_DYNAMIC = 8


def trace_id(job, round_index: int) -> str:
    """Deterministic trace id for round ``round_index`` of ``job``
    (an int job index, a string like ``"service"``, or None for a
    solo run)."""
    j = "solo" if job is None else str(job)
    return f"j{j}.r{int(round_index)}"


def span_id(job, round_index: int, seq: int) -> str:
    return f"{trace_id(job, round_index)}.s{int(seq)}"


def bucket_of(name: str) -> str:
    return BUCKET_OF.get(str(name), "host_other")


class CausalTracer:
    """Per-run span recorder. One tracer serves one record stream
    (solo FedModel, fedservice tenant, or the daemon itself); the
    round lifecycle mirrors ``telemetry.core``: ``begin_round`` opens
    the root span, ``span()``/``open``/``close_span`` nest child
    spans under it, ``end_round`` closes the root and returns the
    schema-v7 ``causal`` stamp.

    Spans recorded from threads other than the round-loop owner
    (prefetch workers) attach flat under the root — the owner's open
    stack is single-threaded state and is never touched cross-thread.
    """

    def __init__(self, job=None):
        self.job = job
        self._round = None
        self._root_b = None
        self._seq = SEQ_DYNAMIC
        self._spans = []
        self._stack = []            # open frames: [id, name, b]
        self._owner = None          # round-loop thread ident
        self._foreign = []          # spans for OTHER traces (grants)

    # ------------------------------------------------------ lifecycle

    def begin_round(self, index: int):
        """Open round ``index``'s root span; an unclosed previous
        round is discarded (interrupted round — its record never
        emits either)."""
        self._round = int(index)
        self._root_b = clock.tick()
        self._seq = SEQ_DYNAMIC
        self._spans = []
        self._stack = []
        self._owner = threading.get_ident()

    def end_round(self):
        """Close the root span; returns the round's ``causal`` stamp
        (None when no round is open)."""
        if self._round is None:
            return None
        r, job = self._round, self.job
        e = clock.tick()
        root = {
            "id": span_id(job, r, SEQ_ROOT),
            "parent": None,
            "name": "round",
            "bucket": "host_other",
            "b": self._root_b,
            "e": e,
        }
        spans = [root] + self._spans
        foreign, self._foreign = self._foreign, []
        spans += foreign
        payload = {
            "trace": trace_id(job, r),
            "job": None if job is None else job,
            "round": r,
            "wall": e - self._root_b,
            "spans": spans,
        }
        self._round = None
        self._spans = []
        self._stack = []
        return payload

    # ------------------------------------------------------ recording

    def open(self, name: str):
        """Push an open span frame (paired with ``close_span``).
        No-op outside a round or from a non-owner thread."""
        if self._round is None \
                or threading.get_ident() != self._owner:
            return
        sid = span_id(self.job, self._round, self._seq)
        self._seq += 1
        self._stack.append([sid, str(name), clock.tick()])

    def close_span(self):
        """Pop the innermost open frame into a finished span whose
        parent is the enclosing frame (the root when none)."""
        if self._round is None \
                or threading.get_ident() != self._owner \
                or not self._stack:
            return
        sid, name, b = self._stack.pop()
        parent = (self._stack[-1][0] if self._stack
                  else span_id(self.job, self._round, SEQ_ROOT))
        self._spans.append({
            "id": sid, "parent": parent, "name": name,
            "bucket": bucket_of(name), "b": b, "e": clock.tick(),
        })

    @contextmanager
    def span(self, name: str):
        """Context-manager form of ``open``/``close_span`` for
        callers without a Telemetry (the asyncfed driver)."""
        self.open(name)
        try:
            yield
        finally:
            self.close_span()

    def add_event(self, name: str, b: float, e: float, *,
                  trace: str, sid: str, parent=None):
        """Record a span for ANOTHER trace — the fedservice daemon
        stamping a ``sched_grant`` into a tenant's round trace. The
        span buffers until this tracer's next ``end_round`` and rides
        that record with an explicit ``trace`` override; ids are
        deterministic, so the tenant-side parent needs no handshake.
        """
        self._foreign.append({
            "id": str(sid), "parent": parent, "name": str(name),
            "bucket": bucket_of(name), "b": float(b), "e": float(e),
            "trace": str(trace),
        })


def build_causal_tracer(cfg, job=None):
    """The run's tracer per its Config: None unless ``--causal_trace``
    is set — the disabled path constructs nothing and the round loop
    stays untouched."""
    if not getattr(cfg, "causal_trace", False):
        return None
    return CausalTracer(job=job)


def assemble_traces(records) -> dict:
    """Stitch the causal spans riding a record stream back into
    per-trace DAGs — the cross-process reassembly ``scripts/
    ledger_merge.py`` and the report tooling run after joining
    ``.p<k>``/``.job<j>`` shards.

    Returns ``{trace_id: {"spans": {id: span}, "round": r,
    "orphans": [ids whose parent resolves to no span in the trace]}}``.
    A span whose ``parent`` is None is a root, never an orphan; the
    deterministic id scheme means a daemon's grant span and the
    tenant's round root land in the same trace without any shared
    state."""
    traces = {}
    for rec in records:
        causal = rec.get("causal") if isinstance(rec, dict) else None
        if not isinstance(causal, dict):
            continue
        default = causal.get("trace")
        for span in causal.get("spans") or ():
            tid = span.get("trace", default)
            t = traces.setdefault(tid, {"spans": {}, "round": None,
                                        "orphans": []})
            t["spans"][span["id"]] = span
            if causal.get("trace") == tid:
                t["round"] = causal.get("round")
    for t in traces.values():
        t["orphans"] = sorted(
            sid for sid, span in t["spans"].items()
            if span.get("parent") is not None
            and span["parent"] not in t["spans"])
    return traces
