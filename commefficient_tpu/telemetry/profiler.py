"""Opt-in ``jax.profiler`` trace windows (``--profile``).

The structured replacement for the reference's cProfile scaffolding
(fed_aggregator.py:46-52, SURVEY §5): an xplane trace of a bounded
window, written where the rest of the run's observability lands.
``profile_epoch`` keeps its historical shape (trace the first trained
epoch); ``trace_window`` is the generic round-window form for
benches/scripts.

When a ``telemetry`` object rides along, the window becomes the
device-time attribution pipeline (telemetry/trace.py): round markers
activate for the window's duration, record emission is held, and at
exit the written trace is parsed into per-round buckets that merge
onto the buffered records as schema-v3 ``device_time`` fields before
the hold releases. A parse failure degrades to a warning — the run's
ledger still emits, just without device-time fields.
"""

from __future__ import annotations

import os


class trace_window:
    """Context manager: capture a JAX profiler (xplane) trace of the
    enclosed region into ``logdir`` when ``active``. Pass the run's
    ``telemetry`` to attribute the trace back onto the round ledger."""

    def __init__(self, logdir: str, active: bool = True,
                 telemetry=None):
        self.active = bool(active)
        self.logdir = logdir
        self.telemetry = telemetry
        self.round_buckets = {}

    def __enter__(self):
        if self.active:
            import jax

            from commefficient_tpu.telemetry import trace
            os.makedirs(self.logdir, exist_ok=True)
            jax.profiler.start_trace(self.logdir)
            trace.set_tracing(True)
            if self.telemetry is not None and self.telemetry.enabled:
                self.telemetry.hold_emission(True)
        return self

    def __exit__(self, *exc):
        if not self.active:
            return False
        import jax

        from commefficient_tpu.telemetry import trace
        # close any open round marker BEFORE stopping the trace, so
        # its end timestamp lands inside the dump
        trace.set_tracing(False)
        jax.profiler.stop_trace()
        print(f"profiler trace written to {self.logdir}")
        tel = self.telemetry
        if tel is not None and tel.enabled:
            try:
                self.round_buckets = trace.attribute_logdir(self.logdir)
                for ridx, buckets in sorted(self.round_buckets.items()):
                    tel.merge_round_device_time(ridx, buckets)
                if self.round_buckets:
                    n = len(self.round_buckets)
                    busy = sum(b["busy_s"]
                               for b in self.round_buckets.values())
                    win = sum(b["window_s"]
                              for b in self.round_buckets.values())
                    tel.emit_meta(
                        trace_logdir=self.logdir,
                        trace_rounds=n,
                        trace_busy_s=round(busy, 6),
                        trace_window_s=round(win, 6),
                        expected_round_s=tel.expected_round_s)
            except Exception as e:  # noqa: BLE001 — observability only
                from commefficient_tpu.telemetry.alarms import \
                    DivergenceAbort
                if isinstance(e, DivergenceAbort):
                    # a collective_skew alarm escalated to abort while
                    # the buckets merged — that's the run policy
                    # acting, not an attribution failure; let it stop
                    # the trainer like any other abort
                    raise
                print("WARNING: trace attribution failed "
                      f"({type(e).__name__}: {e}); ledger emits "
                      "without device_time")
            finally:
                tel.hold_emission(False)
        return False


class profile_epoch(trace_window):
    """Trace ONE epoch (the first trained one) into
    ``<logdir>/profile`` when ``--profile``."""

    def __init__(self, args, epoch, start_epoch=0, logdir=None,
                 telemetry=None):
        if logdir is None:
            from commefficient_tpu.utils import make_logdir
            logdir = make_logdir(args)
        super().__init__(
            os.path.join(logdir, "profile"),
            active=(getattr(args, "do_profile", False)
                    and epoch == start_epoch),
            telemetry=telemetry)
