"""Opt-in ``jax.profiler`` trace windows (``--profile``).

The structured replacement for the reference's cProfile scaffolding
(fed_aggregator.py:46-52, SURVEY §5): an xplane trace of a bounded
window, written where the rest of the run's observability lands.
``profile_epoch`` keeps its historical shape (trace the first trained
epoch); ``trace_window`` is the generic round-window form for
benches/scripts.
"""

from __future__ import annotations

import os


class trace_window:
    """Context manager: capture a JAX profiler (xplane) trace of the
    enclosed region into ``logdir`` when ``active``."""

    def __init__(self, logdir: str, active: bool = True):
        self.active = bool(active)
        self.logdir = logdir

    def __enter__(self):
        if self.active:
            import jax
            os.makedirs(self.logdir, exist_ok=True)
            jax.profiler.start_trace(self.logdir)
        return self

    def __exit__(self, *exc):
        if self.active:
            import jax
            jax.profiler.stop_trace()
            print(f"profiler trace written to {self.logdir}")
        return False


class profile_epoch(trace_window):
    """Trace ONE epoch (the first trained one) into
    ``<logdir>/profile`` when ``--profile``."""

    def __init__(self, args, epoch, start_epoch=0, logdir=None):
        if logdir is None:
            from commefficient_tpu.utils import make_logdir
            logdir = make_logdir(args)
        super().__init__(
            os.path.join(logdir, "profile"),
            active=(getattr(args, "do_profile", False)
                    and epoch == start_epoch))
