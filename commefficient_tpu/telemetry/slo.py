"""Declarative per-job SLOs with multi-window error-budget burn rates.

An SLO here is a statement about the round stream — "p95 round
latency under T seconds", "staleness never above S rounds", "ε spend
no faster than linear to the planned horizon", "no job starved more
than K ticks" — plus an **error budget**: the fraction of rounds
allowed to violate it (``--slo_error_budget``, default 5%, which is
exactly what a p95 target means). The engine does no alerting on a
single bad round. Instead it tracks the violation rate over TWO
rolling windows (``--slo_fast_window`` / ``--slo_window``) and
reports each objective's **burn rate**: violation rate over budget.
A burn of 1.0 means the job is spending its error budget exactly as
fast as the SLO allows; 2.0 means twice as fast.

The alarm condition is the classic multi-window rule: fire only when
BOTH windows burn hot — the fast window proves the problem is
happening *now*, the slow window proves it is *sustained* (one slow
round after a compile never pages anyone). The reported burn per
objective is therefore ``min(fast_burn, slow_burn)``, compared by
``telemetry/alarms.py``'s ``slo_burn`` rule against
``--alarm_slo_burn`` under the shared ``--on_divergence`` action.

Everything here is plain host-side Python over floats the round
already produced — no clocks (callers measure with
``telemetry.clock``), no sockets, no threads; the ``live-confinement``
lint rule pins SLO evaluation to this module.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass

#: objective names, in the order the engine evaluates them
OBJECTIVES = ("round_latency", "staleness", "privacy_burn",
              "starvation")


@dataclass(frozen=True)
class SLOSpec:
    """One job's declarative SLO targets. A target of 0 disarms that
    objective; a spec with every target 0 builds no engine."""

    #: p95 round-latency target (seconds); a round counts against the
    #: budget when its wall seconds exceed this
    round_p95_s: float = 0.0
    #: staleness ceiling (rounds): the round's max folded staleness
    #: (``async_staleness_max`` probe) must stay at or under it
    staleness_max: float = 0.0
    #: planned privacy horizon (rounds): with a DP budget ε*, round n
    #: violates when cumulative ε exceeds the linear schedule
    #: ε* · (n+1)/horizon — spending faster than the run can afford
    eps_horizon: int = 0
    #: the ε* the linear schedule above is drawn to (``--dp_epsilon``)
    eps_budget: float = 0.0
    #: starvation bound (scheduler ticks): the fedservice fairness
    #: probe ``job_starved_rounds`` must stay at or under it
    starvation_ticks: float = 0.0
    #: allowed violation fraction per window (the error budget)
    error_budget: float = 0.05
    #: slow window (rounds) — the "sustained" half of the rule
    window: int = 32
    #: fast window (rounds) — the "happening now" half; also the
    #: warmup: no burn is reported before this many observations
    fast_window: int = 8

    @property
    def armed(self) -> bool:
        return (self.round_p95_s > 0 or self.staleness_max > 0
                or (self.eps_horizon > 0 and self.eps_budget > 0)
                or self.starvation_ticks > 0)

    @staticmethod
    def from_config(cfg) -> "SLOSpec":
        eps = (float(getattr(cfg, "dp_epsilon", 0.0) or 0.0)
               if str(getattr(cfg, "dp", "off")) != "off" else 0.0)
        return SLOSpec(
            round_p95_s=float(getattr(cfg, "slo_round_p95", 0.0)
                              or 0.0),
            staleness_max=float(getattr(cfg, "slo_staleness_max", 0.0)
                                or 0.0),
            eps_horizon=int(getattr(cfg, "slo_eps_rounds", 0) or 0),
            eps_budget=eps,
            starvation_ticks=float(getattr(cfg, "slo_starvation", 0.0)
                                   or 0.0),
            error_budget=float(getattr(cfg, "slo_error_budget", 0.05)
                               or 0.05),
            window=int(getattr(cfg, "slo_window", 32) or 32),
            fast_window=int(getattr(cfg, "slo_fast_window", 8) or 8),
        )


class _Objective:
    """One objective's rolling violation windows."""

    __slots__ = ("name", "target", "fast", "slow", "seen")

    def __init__(self, name, target, spec: SLOSpec):
        self.name = name
        self.target = float(target)
        self.fast = deque(maxlen=spec.fast_window)
        self.slow = deque(maxlen=spec.window)
        self.seen = 0

    def push(self, violated: bool):
        v = 1.0 if violated else 0.0
        self.fast.append(v)
        self.slow.append(v)
        self.seen += 1

    def burn(self, error_budget: float, warmup: int) -> float:
        """min(fast, slow) window burn; 0.0 until ``warmup``
        observations so a cold engine never alarms on its first
        sample."""
        if self.seen < warmup:
            return 0.0
        fast = sum(self.fast) / len(self.fast)
        slow = sum(self.slow) / len(self.slow)
        return min(fast, slow) / error_budget


class SLOEngine:
    """Evaluates one job's :class:`SLOSpec` over the round stream.

    ``observe`` is called once per finished round (dispatch order)
    with whatever signals the caller has; objectives whose signal is
    absent that round simply do not advance. Returns the round's SLO
    probe dict — ``slo_burn_<objective>`` per armed objective that
    advanced at least once, plus ``slo_burn_max`` — which the caller
    merges onto the ledger record and routes to the alarm engine
    (``AlarmEngine.check_slo`` or via ``check``'s probe dict)."""

    def __init__(self, spec: SLOSpec):
        assert spec.armed, "SLOEngine built from a disarmed spec"
        assert 0.0 < spec.error_budget <= 1.0, spec.error_budget
        assert 1 <= spec.fast_window <= spec.window, \
            (spec.fast_window, spec.window)
        self.spec = spec
        self._objectives = {}
        if spec.round_p95_s > 0:
            self._objectives["round_latency"] = _Objective(
                "round_latency", spec.round_p95_s, spec)
        if spec.staleness_max > 0:
            self._objectives["staleness"] = _Objective(
                "staleness", spec.staleness_max, spec)
        if spec.eps_horizon > 0 and spec.eps_budget > 0:
            self._objectives["privacy_burn"] = _Objective(
                "privacy_burn", spec.eps_budget, spec)
        if spec.starvation_ticks > 0:
            self._objectives["starvation"] = _Objective(
                "starvation", spec.starvation_ticks, spec)
        #: the most recent ``slo_burn_max`` (0.0 before any observe)
        self.last_burn = 0.0

    def observe(self, round_index: int, *, round_s=None,
                staleness_max=None, dp_epsilon=None,
                starved_ticks=None) -> dict:
        """Advance every armed objective that has a signal this round
        and return the SLO probe dict (empty when nothing armed
        advanced yet)."""
        spec = self.spec
        obj = self._objectives
        if round_s is not None and "round_latency" in obj:
            obj["round_latency"].push(
                float(round_s) > spec.round_p95_s)
        if staleness_max is not None and "staleness" in obj:
            obj["staleness"].push(
                float(staleness_max) > spec.staleness_max)
        if dp_epsilon is not None and "privacy_burn" in obj:
            # linear spend schedule: after n+1 charged rounds the run
            # may have spent ε* (n+1)/horizon of its budget
            allowed = spec.eps_budget * min(
                1.0, (obj["privacy_burn"].seen + 1)
                / spec.eps_horizon)
            obj["privacy_burn"].push(float(dp_epsilon) > allowed)
        if starved_ticks is not None and "starvation" in obj:
            obj["starvation"].push(
                float(starved_ticks) > spec.starvation_ticks)
        probes = {}
        for name, o in obj.items():
            if o.seen == 0:
                continue
            probes[f"slo_burn_{name}"] = o.burn(
                spec.error_budget, spec.fast_window)
        if probes:
            probes["slo_burn_max"] = max(probes.values())
            self.last_burn = probes["slo_burn_max"]
        return probes

    def stamp(self) -> dict:
        """The schema-v6 ``slo`` record stamp: per-objective target /
        violation-rate / burn snapshot after the latest observe."""
        spec = self.spec
        out = {}
        for name, o in self._objectives.items():
            if o.seen == 0:
                continue
            out[name] = {
                "target": o.target,
                "seen": o.seen,
                "fast_rate": round(sum(o.fast) / max(1, len(o.fast)),
                                   6),
                "slow_rate": round(sum(o.slow) / max(1, len(o.slow)),
                                   6),
                "burn": round(o.burn(spec.error_budget,
                                     spec.fast_window), 6),
            }
        return out

    @property
    def burning(self) -> bool:
        """True when the latest observed burn is at or above 1.0 —
        the job is spending error budget faster than its SLO allows.
        fedservice admission reads this to flag hot tenants before
        admitting new ones."""
        return self.last_burn >= 1.0


def build_slo_engine(cfg):
    """An :class:`SLOEngine` when any ``--slo_*`` target is armed,
    else None (no per-round call, no state)."""
    spec = SLOSpec.from_config(cfg)
    return SLOEngine(spec) if spec.armed else None
