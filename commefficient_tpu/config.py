"""Config / flag system.

Mirrors the reference's single argparse surface (utils.py:102-230 in
/root/reference/CommEfficient) flag-for-flag so experiment commands
port 1:1, but materialises the result in a typed ``Config`` dataclass
that the jitted runtime treats as static. TPU-specific knobs (mesh
shape, dtype policy) are additive.
"""

from __future__ import annotations

import argparse
import dataclasses
from typing import Optional, Sequence

MODES = ("sketch", "true_topk", "local_topk", "fedavg", "uncompressed")
ERROR_TYPES = ("none", "local", "virtual")
DP_MODES = ("worker", "server")
ROBUST_AGGS = ("none", "median", "trimmed", "clip")
SKETCH_DTYPES = ("f32", "bf16", "int8", "fp8")
DOWNLINK_ENCODINGS = ("dense", "delta")

# dataset -> num classes (reference utils.py:37-44)
FED_DATASETS = {
    "CIFAR10": 10,
    "CIFAR100": 100,
    "EMNIST": 62,
    "ImageNet": 1000,
    "PERSONA": -1,
    "Synthetic": 10,
}

# natural client counts when --num_clients is omitted
# (reference fed_aggregator.py:66-73)
NATURAL_NUM_CLIENTS = {
    "EMNIST": 3500,
    "CIFAR10": None,  # non-iid CIFAR10 unsupported without --num_clients
    "PERSONA": 17568,
}


def num_classes_of_dataset(dataset_name: str) -> int:
    return FED_DATASETS[dataset_name]


@dataclasses.dataclass
class Config:
    """Typed mirror of the reference's parsed args (utils.py:102-230)."""

    # meta
    do_test: bool = False
    mode: str = "sketch"
    use_tensorboard: bool = False
    do_profile: bool = False  # JAX profiler trace of the first epoch
    # bfloat16 activations/matmuls (params + grads stay float32): full
    # MXU rate on TPU. The TPU analogue of cifar10_fast's fp16
    # training; no reference equivalent (it trains f32)
    do_bf16: bool = False
    # GPT-2 sequence parallelism: shard each client's sequences over
    # this many chips (ring or ulysses attention). 1 = off.
    seq_devices: int = 1
    seq_impl: str = "ring"
    # fault injection: each sampled client independently drops out of
    # the round with this probability (its contribution is excluded
    # and the round renormalises over the survivors). The reference
    # has no dropout simulation (SURVEY §5 failure detection).
    dropout_prob: float = 0.0
    # mixup augmentation for CV training. The reference's imagenet.sh
    # passes --mixup/--mixup_alpha but its parse_args never defines
    # them and its compute_loss_mixup is dead code (SURVEY §2.7);
    # here they work (host-side per-client mixing, lam ~ Beta(a, a)).
    do_mixup: bool = False
    mixup_alpha: float = 1.0
    seed: int = 21

    # model/data
    model: str = "ResNet9"
    do_finetune: bool = False
    do_checkpoint: bool = False
    # full-state resume (beyond the reference's save-only checkpoints)
    do_resume: bool = False
    checkpoint_every: int = 0  # epochs; 0 = end of training only
    checkpoint_path: str = "./checkpoint"
    finetune_path: str = "./finetune"
    finetuned_from: Optional[str] = None
    num_results_train: int = 2
    num_results_val: int = 2
    dataset_name: str = ""
    dataset_dir: str = "./dataset"
    do_batchnorm: bool = False
    nan_threshold: float = 999.0

    # compression
    k: int = 50000
    num_cols: int = 500000
    num_rows: int = 5
    num_blocks: int = 20
    do_topk_down: bool = False

    # optimization
    local_momentum: float = 0.9
    virtual_momentum: float = 0.0
    weight_decay: float = 5e-4
    num_epochs: float = 24.0
    # LR-schedule horizon; defaults to num_epochs. Set it when a run
    # will stop early and be resumed (--resume) so every invocation
    # decays over the same total, keeping resumed training identical
    # to an uninterrupted run.
    schedule_epochs: Optional[float] = None
    num_fedavg_epochs: int = 1
    fedavg_batch_size: int = -1
    fedavg_lr_decay: float = 1.0
    error_type: str = "none"
    lr_scale: Optional[float] = None
    pivot_epoch: float = 5.0

    # parallelization
    port: int = 5315  # kept for CLI parity; unused (no sockets in SPMD runtime)
    num_clients: Optional[int] = None
    num_workers: int = 1  # participating clients per round
    device: str = "tpu"
    # number of TPU devices for the mesh; <= 0 = all available (the
    # reference's flag counted GPUs and defaulted to 1 — here a single
    # jitted program spans the mesh, so "all" is the natural default)
    num_devices: int = -1
    share_ps_gpu: bool = False  # parity no-op: there is no PS rank
    do_iid: bool = False
    train_dataloader_workers: int = 0
    val_dataloader_workers: int = 0

    # GPT-2 / text
    model_checkpoint: str = "gpt2"
    num_candidates: int = 2
    # candidates evaluated at validation. The reference restricts
    # candidates only when training (fed_persona.py:251-254) — val MC
    # accuracy is over the item's full ~20 candidates. 0 = auto-detect
    # (the maximum candidate count across the val set).
    val_candidates: int = 0
    max_history: int = 2
    local_batch_size: int = 8
    valid_batch_size: int = 8
    microbatch_size: int = -1
    lm_coef: float = 1.0
    mc_coef: float = 1.0
    max_grad_norm: Optional[float] = None
    personality_permutations: int = 1
    eval_before_start: bool = False

    # differential privacy (legacy reference-parity worker/server
    # mechanism — kept bit-for-bit; see --dp below for the
    # accountant-backed sketch mechanism)
    do_dp: bool = False
    dp_mode: str = "worker"
    l2_norm_clip: float = 1.0
    noise_multiplier: float = 0.0
    # DP sketching (privacy/): "sketch" L2-clips each client's dense
    # gradient to --dp_clip and adds calibrated Gaussian noise to the
    # aggregated sketch table BEFORE wire quantization, with an RDP
    # accountant riding the ledger. "off" traces nothing — the round
    # program is HLO-identical to a build without the feature.
    dp: str = "off"
    dp_clip: float = 1.0
    dp_noise_mult: float = 0.0
    # accountant target δ and total ε budget (0 = unlimited). A
    # finite budget arms the privacy_budget_exhausted alarm
    # (--on_divergence semantics) and hard-constrains the autopilot
    # knob ladder (no lattice point that exhausts ε before
    # --num_rounds is ever visited).
    dp_delta: float = 1e-5
    dp_epsilon: float = 0.0

    # --- TPU-native additions (no reference equivalent) ---
    # 2D pod mesh "CxM": C devices data-parallel over ``clients`` ×
    # M devices sharding server state (sketch table columns, momentum,
    # error feedback) over ``model`` — per-device server memory scales
    # as 1/M. "" = the 1-D clients mesh over --num_devices. M > 1 is
    # supported for the server-state modes (sketch, uncompressed);
    # "1x1" compiles to exactly the single-device 1-D program.
    mesh: str = ""
    param_dtype: str = "float32"
    compute_dtype: str = "float32"  # set bfloat16 for MXU throughput
    # lax.approx_max_k (recall approx_recall) for the index-producing
    # top-k selections: unsketch recovery and the true_topk server
    # select (exact top_k at k=50k over millions of coords lowers to
    # a full sort on TPU). Missed coordinates stay in the error
    # accumulators and resurface next round. The DENSE selections
    # (local_topk client masking, topk_down) at large d always use
    # the exact threshold-select path, which is faster than the
    # approximate sort (ops/topk.py) — this flag no longer affects
    # them there.
    approx_topk: bool = False
    approx_recall: float = 0.95  # recall target for --approx_topk
    # rounds the host may run ahead of the device before materialising
    # metrics/accounting (1 = synchronous, reference-faithful timing)
    pipeline_depth: int = 1
    # multi-host pod launch (jax.distributed): when set, the trainers
    # call initialize_multihost(coordinator_address, num_processes,
    # process_id) before building the mesh — one process per host,
    # same command everywhere (the reference's NCCL init_process_group
    # topology, fed_aggregator.py:161-165). On Cloud TPU pods leave
    # all three unset: auto-detected from the environment.
    coordinator_address: Optional[str] = None
    num_processes: Optional[int] = None
    process_id: Optional[int] = None
    # write the final GPT-2 model as pytorch_model.bin + HF config
    # (loadable by transformers.from_pretrained) in addition to the
    # flax msgpack — the reference's save_pretrained contract
    # (fed_aggregator.py:209-212)
    do_hf_export: bool = False
    # Synthetic-dataset heterogeneity dial: classes held by each
    # natural client (1 = the pathological one-class split; >1 =
    # milder non-iid). Ignored by the on-disk datasets, whose splits
    # come from the archives.
    classes_per_client: int = 1
    # Synthetic-dataset size dial: train items per class. 5000 with
    # --num_clients 10000 reproduces the FetchSGD paper's CIFAR10
    # federation shape (10 000 clients x 5 one-class images).
    synthetic_per_class: int = 64
    # Synthetic-dataset class-overlap dial: scales class means against
    # the fixed noise std. 1.0 = trivially separable; 0.025 gives a
    # Bayes ceiling near 0.86, making long-horizon convergence anchors
    # accuracy-discriminating (FedSynthetic.bayes_accuracy reports the
    # exact ceiling for the generated split).
    synthetic_separation: float = 1.0
    # Synthetic val-set size: 128 (default) is fine for smoke runs;
    # discriminating anchors need ~2000 for sub-percent granularity
    synthetic_num_val: int = 128
    # GPT-2: rematerialise transformer blocks in backward (activation
    # memory ~ 1/n_layer, ~1/3 extra FLOPs) — the long-context lever
    do_remat: bool = False
    # GPT-2 attention lowering: "xla" (jax.nn.dot_product_attention)
    # or "flash" (Pallas TPU flash-attention kernel) — see
    # models/gpt2.py GPT2Config.attn_impl
    attn_impl: str = "xla"
    # sketch rotation granularity (ops/sketch.py CountSketch.rot_lanes):
    # -1 = auto (default): 1024 on a TPU backend when the geometry is
    # large-d Pallas-eligible (the round-5 24-epoch anchors measured
    # tail-accuracy parity with full-granularity rotations at both
    # seeds, so the −44% kernel-pair / −8% flagship-round win is on by
    # default — core/rounds.py args2sketch); 0 everywhere else, since
    # quantized rotations pay their heavier collision tail for nothing
    # without the Pallas sublane roll. 0 = force full granularity;
    # >0 quantizes rotations to multiples of that lane width.
    # Sketch tables/error state are not comparable across different
    # resolved values (different rotation streams) — a checkpoint
    # resumed under a different backend re-resolves -1, so pin an
    # explicit value when moving sketch-mode checkpoints across
    # platforms.
    sketch_rot_lanes: int = -1
    # wire dtype of the uplinked sketch table (ops/quant.py): "f32"
    # (default; the program compiles bit-identical to a build without
    # the flag), "bf16" (plain cast, summed in bf16 on the wire),
    # "int8"/"fp8" (per-row scales: each shard quantizes against its
    # local row maxabs, then harmonizes onto the pmax'd global row
    # scale with summation headroom so the wire-dtype psum cannot
    # overflow). Emission accumulates in f32; the server dequantizes
    # before momentum/error feedback so optimizer state stays f32.
    # Count-sketch is mean-zero and tolerant of coarse quantization
    # (FedSKETCH; arXiv:1903.04488) — int8 cuts uplink ~4x at a
    # recovery-error cost well inside the probe alarm band on the
    # reference config (README compression-modes table).
    sketch_dtype: str = "f32"
    # downlink encoding of the broadcast update: "dense" ships the
    # changed coordinates as f32 (reference-shaped); "delta" ships
    # (idx:int32, val:wire_dtype) pairs plus a round-delta bitmap
    # naming the indices repeated from the previous round's support,
    # so a client that saw round t-1 pays 1 bit instead of 4 bytes
    # per repeated index. Accounting-level encoding: the compiled
    # round program is unchanged (runtime/fed_model.py).
    downlink_encoding: str = "dense"
    # scan the round's client fan-out in chunks of this many clients
    # (0 = all at once): caps live per-client intermediates at
    # chunk x d — the memory lever for large-W rounds of the local-
    # state modes on one chip (the reference's serial per-worker client
    # loop bounds memory the same way, fed_worker.py:59-133). Ignored
    # on a multi-device mesh (the client axis is already divided).
    client_chunk: int = 0
    # latency-hiding round pipeline (sketch mode): chunk sketch
    # emission over table rows and issue each chunk's wire collective
    # while the next chunk quantizes — XLA's latency-hiding scheduler
    # overlaps collective i with chunk i+1's compute. 1 = today's
    # serial program (bit-identical HLO); N > 1 splits the (r, c)
    # table into min(N, r) row chunks. The folded result is unchanged:
    # the sketch is linear over disjoint row chunks and quantization
    # scales are per-row, so row-chunked quantize + harmonize +
    # collective composes exactly with the whole-table path.
    overlap_depth: int = 1
    # GPT-2: tokens per logits chunk in the chunked tied-head
    # cross-entropy (models/gpt2.py lm_nll_sums_chunked) — the
    # vocab-head temp memory scales with this chunk, not the sequence.
    # 0 = auto: 256 on the sequence-parallel path (the measured memory
    # knee, BENCHMARKS.md SP table), 1024 on the single-device path
    # (throughput-flat across 512-4096 at the 8x geometry).
    tokens_per_chunk: int = 0
    # GPT-2: fused-linear-CE vocab head (ops/flce_pallas.py) — the
    # per-chunk logits round-trips of the chunked path go away
    # entirely. "auto" = Pallas kernels on a TPU default backend at
    # lane-aligned widths, chunked elsewhere; "on"/"off" force.
    # Default off pending the on-chip A/B (scripts/gpt2_bench.py
    # --fused_ce).
    fused_ce: str = "off"
    # Per-client state placement (commefficient_tpu/clientstore):
    # "device" keeps the dense (num_clients, *transmit_shape) arrays in
    # HBM (reference-shaped); "host" keeps them in a budgeted host
    # arena with an mmap spill tier and materializes only the round's
    # participants on device — million-client populations on a fixed
    # HBM budget; "auto" resolves at build time: host when the dense
    # population would exceed --clientstore_bytes, device otherwise.
    clientstore: str = "device"
    # arena budget for --clientstore host/auto (bytes); rows beyond it
    # are evicted LRU-first to the mmap spill tier
    clientstore_bytes: int = 1 << 30
    # spill-tier directory ("" = private temp dir, removed on exit)
    clientstore_dir: str = ""
    # telemetry (commefficient_tpu/telemetry): path of the JSONL round
    # ledger ("" = disabled — the no-op fast path costs nothing on the
    # round hot loop). One schema-v1 record per training round: spans,
    # comm bytes (identical to the accounting counters), prefetch
    # hit/miss, compile events, memory watermarks. Render/diff with
    # scripts/telemetry_report.py.
    ledger: str = ""
    # end-of-run console summary of the round ledger (per-span
    # totals/means, byte totals) — works with or without --ledger
    telemetry_console: bool = False
    # algorithm probes (telemetry schema v2): 0 = off (the round step
    # compiles to exactly the pre-probe HLO — no extra outputs). N > 0
    # compiles the cheap O(d) probes (update/residual/momentum norms,
    # NaN/Inf counts, mass coverage) into every round and additionally
    # runs the expensive true sketch-recovery-error probe
    # ‖unsketch(S(g)) − g‖/‖g‖ on rounds where round % N == 0 (it
    # needs the dense aggregate the sketch path otherwise never
    # materialises).
    probe_every: int = 0
    # shorthand for --probe_every 1: every probe, every round
    probe_full: bool = False
    # alarm engine (telemetry/alarms.py) action when a probe rule
    # fires: "log" (warn + ledger flag), "ledger-flag" (ledger flag
    # only), "abort" (flag, then raise DivergenceAbort so the trainer
    # stops at the offending round)
    on_divergence: str = "log"
    # residual-growth rule: Verror-norm growth ratio > this for
    # --alarm_residual_rounds consecutive probed rounds
    alarm_residual_ratio: float = 2.0
    alarm_residual_rounds: int = 3
    # recovery-error rule: ‖unsketch(S(g)) − g‖/‖g‖ above this (1.0 =
    # the recovered update is no better than sending nothing)
    alarm_recovery_error: float = 1.0
    # step-time regression rule (telemetry/alarms.py): fire when a
    # round's wall step time exceeds this ratio x the rolling median
    # of the last --alarm_step_time_window rounds. 0 = off. Works
    # without probes; shares the --on_divergence action.
    alarm_step_time_ratio: float = 0.0
    alarm_step_time_window: int = 16
    # collective-skew rule (telemetry/alarms.py): fire when a traced
    # round's max cross-device collective enter-delta exceeds this
    # ratio x the round's collective seconds (schema-v4 device_time
    # skew stats). 0 = off. Needs --profile to produce trace buckets;
    # shares the --on_divergence action.
    alarm_collective_skew: float = 0.0
    # robust aggregation (core/robust.py): how the round folds the
    # per-client transmits. "none" = the plain datapoint-weighted mean
    # (bit-identical program to a build without the flag); "median" =
    # coordinate-wise median over per-client (or grouped) per-datapoint
    # mean transmits; "trimmed" = coordinate-wise trimmed mean dropping
    # --robust_trim_frac of each tail; "clip" = per-client norm clip to
    # --robust_clip_norm before the plain weighted fold. Robust folds
    # need materialised per-client transmits, so they disable the
    # fused-gradient and sketch-after-local-sum fast paths (sketch mode
    # sketches per client — the median-of-sketches estimator of the
    # sketched-SGD line). The server only ever sees the robust
    # aggregate: rejected client mass is never fed into the virtual
    # momentum/error state.
    robust_agg: str = "none"
    # fraction of clients trimmed from EACH tail per coordinate under
    # --robust_agg trimmed (t = floor(frac * alive))
    robust_trim_frac: float = 0.1
    # per-client transmit-norm clip threshold (per-datapoint-mean
    # scale) under --robust_agg clip; 0 = auto (the median of the
    # round's alive per-client norms)
    robust_clip_norm: float = 0.0
    # --robust_agg median: fold clients into this many groups (mean
    # within a group, median across groups — 1903.04488's
    # median-of-means over sketches); 0 = every client its own group.
    # num_workers must divide evenly.
    robust_median_groups: int = 0
    # byzantine_suspect rule (telemetry/alarms.py): fire when the
    # round's max per-client transmit norm exceeds this ratio x the
    # alive-client mean norm (needs probes for client_norm_* to
    # exist). 0 = off; shares the --on_divergence action.
    alarm_byzantine_ratio: float = 0.0
    # fold_rejection_rate rule: fire when the robust fold's relative
    # deviation from the plain mean exceeds this (the mass the fold
    # rejected; needs --robust_agg != none and probes). 0 = off.
    alarm_fold_rejection: float = 0.0
    # periodic round-cadence autosave (runtime/checkpoint.py): save a
    # full resumable checkpoint every N completed training rounds
    # (0 = off; epoch-cadence --checkpoint_every is independent).
    # Mid-epoch saves capture the sampler's in-progress epoch state,
    # so a crash resumes at the autosaved round, bit-exact.
    checkpoint_every_rounds: int = 0
    # retention for round-cadence autosaves: keep this many numbered
    # history snapshots (ckpt_<tag>_r<round>.npz hardlinks) besides
    # the latest; 0 = latest only
    checkpoint_keep: int = 0
    # buffered asynchronous rounds (asyncfed/): fold the arrival
    # buffer every K arrived clients instead of barriering on the
    # full cohort. 0 = synchronous barrier (the compiled round is
    # bit-identical to async-off builds); K must be in
    # [1, num_workers] — the compiled cohort width stays num_workers
    # and a fold with fewer arrivals pads dead slots (mask 0).
    async_buffer_size: int = 0
    # staleness exponent alpha: an update folded s rounds after it
    # was issued is weighted 1/(1+s)^alpha (transmit AND its
    # datapoint count, so the fold stays a weighted per-datapoint
    # mean and stale mass never corrupts virtual momentum/EF).
    # alpha = 0 keeps weights exactly 1 and the buffered fold
    # reduces bit-exactly to the synchronous round at K = cohort.
    async_staleness_weight: float = 0.0
    # async_staleness rule (telemetry/alarms.py): fire when the
    # round's max folded staleness (rounds) exceeds this. 0 = off;
    # shares the --on_divergence action.
    alarm_async_staleness: float = 0.0
    # job_starvation rule (telemetry/alarms.py), evaluated by the
    # fedservice daemon's own engine: fire when a runnable job has
    # waited more than this many scheduler ticks since it last ran.
    # 0 = off; shares the --on_divergence action.
    alarm_job_starvation: float = 0.0
    # live operations plane (telemetry/live.py): serve the process's
    # in-memory metric registry in Prometheus text exposition format
    # from a localhost-only exporter thread at this port (/metrics +
    # /healthz). 0 = off: nothing is constructed and the build stays
    # bit-identical. Entirely host-side; excluded from the registry
    # run key like the other observability taps.
    live_port: int = 0
    # flight recorder (telemetry/flightrec.py): keep the last N round
    # records in an in-memory ring and dump an atomic postmortem
    # bundle on any alarm fire / graceful shutdown / crash. 0 = off.
    flightrec_rounds: int = 0
    # where postmortem bundles land (stamped into the run registry
    # when --runs_dir is known)
    postmortem_dir: str = "runs/postmortems"
    # causal round tracing (telemetry/causal.py): record the round's
    # span DAG with deterministic ids and stamp it on the round
    # record (optional schema-v7 "causal" key) for the critical-path
    # explainer (telemetry/critpath.py). Off (default): no tracer is
    # constructed, no ledger field appears, and the compiled program
    # is bit-identical. Entirely host-side; hash-excluded like the
    # other observability taps.
    causal_trace: bool = False
    # per-job SLO targets (telemetry/slo.py) — each 0 leaves that
    # objective un-armed; any nonzero target arms the SLO engine,
    # which merges slo_burn_* probes into the round record and stamps
    # the v6 "slo" key:
    # round-latency objective: a round slower than this p95 target
    # (seconds) is an SLO violation
    slo_round_p95: float = 0.0
    # staleness objective: a round whose max folded staleness exceeds
    # this ceiling (rounds) is a violation
    slo_staleness_max: float = 0.0
    # privacy-burn objective: ε must stay under the linear spend
    # schedule dp_epsilon * (round+1) / slo_eps_rounds over this
    # horizon (rounds); needs --dp sketch with a hard --dp_epsilon
    slo_eps_rounds: int = 0
    # starvation objective (fedservice daemon): a tick whose max
    # job wait exceeds this many ticks is a violation
    slo_starvation: float = 0.0
    # fraction of windowed rounds allowed to violate before the burn
    # rate reads 1.0 (the error budget)
    slo_error_budget: float = 0.05
    # slow / fast rolling windows (rounds) for the multi-window burn
    # rate: burn = min(fast_rate, slow_rate) / error_budget — the
    # fast window gives detection latency, the slow window keeps a
    # transient spike from paging
    slo_window: int = 32
    slo_fast_window: int = 8
    # slo_burn rule (telemetry/alarms.py): fire when slo_burn_max
    # reaches this burn rate. 0 = off; shares the --on_divergence
    # action.
    alarm_slo_burn: float = 0.0
    # adaptive compression autopilot (commefficient_tpu/autopilot):
    # "on" runs the seeded between-rounds controller that walks the
    # discrete knob lattice (sketch_dtype x k x rows x cols x recall)
    # toward the cheapest round program whose recovery error stays
    # inside --autopilot_band, dispatching through a bounded LRU of
    # jitted round variants (re-jit cache). "off" (default): no
    # controller, and the compiled program is bit-identical to a
    # build without the flag (the base variant is built from THIS
    # config object unchanged).
    autopilot: str = "off"
    # target recovery-error band "LO:HI" (required with --autopilot
    # on): the controller cheapens below LO after the cooldown, backs
    # off above HI immediately and never re-enters the offending
    # point. The LO..HI gap is the hysteresis that prevents
    # oscillation.
    autopilot_band: str = ""
    # in-band probed rounds to wait between cheapening moves (back-off
    # ignores it — safety beats cooldown)
    autopilot_cooldown: int = 2
    # bound of the round-variant LRU (jitted programs kept alive);
    # evicted variants recompile on re-visit, stamped in the ledger
    autopilot_cache_size: int = 4
    # pre-compile a decided move's round variant under the current
    # round's host phase (AOT lower+compile), so the switch round
    # never stalls on XLA; only DECIDED points are ever warmed —
    # unvisited lattice points never compile eagerly
    autopilot_warm_ahead: bool = True
    # hold the controller at one lattice point (variant-key spelling,
    # e.g. "int8-k50000-r5-c500000-re9500"): the full autopilot
    # machinery engages (cache, trajectory, manifest record) but no
    # move is ever made — bit-identical to the equivalent static
    # config
    autopilot_pin: str = ""
    # let the ladder extend past the dtype axis into column-halving
    # geometry steps; a geometry move changes the sketch table shape
    # and RESETS server momentum/error feedback (runtime/fed_model.py)
    autopilot_geometry: bool = False

    # populated at runtime (reference sets args.grad_size the same way,
    # fed_aggregator.py:88)
    grad_size: int = 0

    def __post_init__(self):
        self.validate()

    def validate(self) -> "Config":
        """Parse-time cross-flag validation — same checks, same timing
        as the reference's parse_args (utils.py:225-228): only the
        fedavg combination is rejected up front."""
        assert self.mode in MODES, self.mode
        assert self.error_type in ERROR_TYPES, self.error_type
        assert self.dp_mode in DP_MODES, self.dp_mode
        assert self.dp in ("off", "sketch"), \
            "--dp must be off|sketch"
        assert self.dp_clip > 0, "--dp_clip must be > 0"
        assert self.dp_noise_mult >= 0, \
            "--dp_noise_mult must be >= 0"
        assert 0.0 < self.dp_delta < 1.0, \
            "--dp_delta must be in (0, 1)"
        assert self.dp_epsilon >= 0, \
            "--dp_epsilon must be >= 0 (0 = unlimited budget)"
        if self.dp_epsilon > 0:
            assert self.dp != "off", \
                "--dp_epsilon budget needs --dp sketch (nothing " \
                "spends the budget otherwise)"
            assert self.dp_noise_mult > 0, \
                "--dp_epsilon budget needs --dp_noise_mult > 0 " \
                "(a noiseless release exhausts any finite ε " \
                "immediately)"
        assert 0.0 < self.approx_recall <= 1.0, \
            "--approx_recall must be in (0, 1]"
        assert self.pipeline_depth >= 1, \
            "--pipeline_depth must be >= 1"
        assert self.tokens_per_chunk >= 0, \
            "--tokens_per_chunk must be >= 0 (0 = auto)"
        assert self.fused_ce in ("auto", "on", "off"), \
            "--fused_ce must be auto|on|off"
        assert self.clientstore in ("device", "host", "auto"), \
            "--clientstore must be device|host|auto"
        assert self.clientstore_bytes >= 0, \
            "--clientstore_bytes must be >= 0"
        assert self.probe_every >= 0, \
            "--probe_every must be >= 0 (0 = probes off)"
        assert self.on_divergence in ("log", "ledger-flag", "abort"), \
            "--on_divergence must be log|ledger-flag|abort"
        assert self.alarm_residual_rounds >= 1, \
            "--alarm_residual_rounds must be >= 1"
        assert self.alarm_step_time_ratio >= 0, \
            "--alarm_step_time_ratio must be >= 0 (0 = rule off)"
        assert self.alarm_step_time_window >= 2, \
            "--alarm_step_time_window must be >= 2"
        assert self.alarm_collective_skew >= 0, \
            "--alarm_collective_skew must be >= 0 (0 = rule off)"
        assert self.robust_agg in ROBUST_AGGS, \
            "--robust_agg must be none|median|trimmed|clip"
        assert 0.0 <= self.robust_trim_frac < 0.5, \
            "--robust_trim_frac must be in [0, 0.5)"
        assert self.robust_clip_norm >= 0, \
            "--robust_clip_norm must be >= 0 (0 = auto)"
        assert self.robust_median_groups >= 0, \
            "--robust_median_groups must be >= 0 (0 = per-client)"
        assert self.alarm_byzantine_ratio >= 0, \
            "--alarm_byzantine_ratio must be >= 0 (0 = rule off)"
        assert self.alarm_fold_rejection >= 0, \
            "--alarm_fold_rejection must be >= 0 (0 = rule off)"
        assert self.checkpoint_every_rounds >= 0, \
            "--checkpoint_every_rounds must be >= 0 (0 = off)"
        assert self.checkpoint_keep >= 0, \
            "--checkpoint_keep must be >= 0"
        assert self.async_buffer_size >= 0, \
            "--async_buffer_size must be >= 0 (0 = synchronous)"
        assert self.async_staleness_weight >= 0, \
            "--async_staleness_weight must be >= 0"
        assert self.alarm_async_staleness >= 0, \
            "--alarm_async_staleness must be >= 0 (0 = rule off)"
        assert self.alarm_job_starvation >= 0, \
            "--alarm_job_starvation must be >= 0 (0 = rule off)"
        assert 0 <= self.live_port <= 65535, \
            "--live_port must be in [0, 65535] (0 = off)"
        assert self.flightrec_rounds >= 0, \
            "--flightrec_rounds must be >= 0 (0 = off)"
        assert self.slo_round_p95 >= 0, \
            "--slo_round_p95 must be >= 0 (0 = objective off)"
        assert self.slo_staleness_max >= 0, \
            "--slo_staleness_max must be >= 0 (0 = objective off)"
        assert self.slo_eps_rounds >= 0, \
            "--slo_eps_rounds must be >= 0 (0 = objective off)"
        if self.slo_eps_rounds > 0:
            assert self.dp != "off" and self.dp_epsilon > 0, \
                "--slo_eps_rounds needs --dp sketch with a hard " \
                "--dp_epsilon budget (nothing spends ε otherwise)"
        assert self.slo_starvation >= 0, \
            "--slo_starvation must be >= 0 (0 = objective off)"
        assert 0.0 < self.slo_error_budget <= 1.0, \
            "--slo_error_budget must be in (0, 1]"
        assert self.slo_window >= 1, \
            "--slo_window must be >= 1"
        assert 1 <= self.slo_fast_window <= self.slo_window, \
            "--slo_fast_window must be in [1, --slo_window]"
        assert self.alarm_slo_burn >= 0, \
            "--alarm_slo_burn must be >= 0 (0 = rule off)"
        assert self.autopilot in ("off", "on"), \
            "--autopilot must be off|on"
        assert self.autopilot_cooldown >= 0, \
            "--autopilot_cooldown must be >= 0"
        assert self.autopilot_cache_size >= 1, \
            "--autopilot_cache_size must be >= 1"
        if self.autopilot == "on":
            assert self.mode == "sketch", \
                "--autopilot on requires --mode sketch (the knob " \
                "lattice is sketch geometry + wire dtype)"
            assert self.autopilot_band, \
                "--autopilot on requires --autopilot_band LO:HI"
            try:
                lo, hi = (float(p)
                          for p in self.autopilot_band.split(":"))
            except ValueError:
                raise AssertionError(
                    "--autopilot_band must be LO:HI, e.g. 0.2:0.6 "
                    f"(got {self.autopilot_band!r})") from None
            assert 0.0 <= lo < hi, \
                "--autopilot_band needs 0 <= LO < HI"
            assert self.probe_period > 0, \
                "--autopilot on needs probes (--probe_every N > 0): " \
                "the controller steers on the recovery-error probe"
        if self.async_buffer_size > 0:
            assert self.async_buffer_size <= self.num_workers, \
                "--async_buffer_size must be <= --num_workers " \
                "(the compiled cohort width is num_workers)"
        assert self.sketch_dtype in SKETCH_DTYPES, \
            "--sketch_dtype must be f32|bf16|int8|fp8"
        assert self.overlap_depth >= 1, \
            "--overlap_depth must be >= 1 (1 = serial round)"
        assert self.downlink_encoding in DOWNLINK_ENCODINGS, \
            "--downlink_encoding must be dense|delta"
        if self.mesh:
            import re
            assert re.fullmatch(r"[0-9]+x[0-9]+", self.mesh.lower()), \
                "--mesh must be CxM (e.g. 4x2)"
            c, m = self.mesh2d
            assert c >= 1 and m >= 1, "--mesh axes must be >= 1"
        if self.mode == "fedavg":
            assert self.local_batch_size == -1, \
                "fedavg requires --local_batch_size -1"
            assert self.local_momentum == 0, \
                "fedavg requires --local_momentum 0"
            assert self.error_type == "none", \
                "fedavg requires --error_type none"
        return self

    def validate_runtime(self) -> "Config":
        """Mode-lattice invariants, checked when the federated runtime
        is built (the reference enforces these in the worker/server hot
        path: fed_worker.py:206-230, fed_aggregator.py:514, 575-578).

        NB the reference's *defaults* (mode=sketch + local_momentum
        0.9) violate these and crash on the first training round;
        failing here at setup is the friendlier equivalent.
        """
        self.validate()
        if self.do_test:
            # the reference's --test short-circuits the worker before
            # any of these asserts run (fed_worker.py:118-123), so its
            # smoke mode works at default flags; normalize the default
            # combo here so ours does too
            if self.mode == "sketch" and self.local_momentum:
                self.virtual_momentum = max(self.virtual_momentum,
                                            self.local_momentum)
                self.local_momentum = 0.0
            if self.mode in ("sketch", "uncompressed") \
                    and self.error_type == "local":
                self.error_type = "virtual"
        if self.sketch_dtype != "f32":
            # the wire dtype quantizes the sketch table; the other
            # modes transmit dense/top-k floats whose accounting and
            # server fold never route through the table quantizer
            assert self.mode == "sketch", \
                "--sketch_dtype != f32 requires --mode sketch " \
                "(only the sketch table has a quantized wire path)"
        if self.overlap_depth > 1:
            # only the sketch table emits in disjoint row chunks;
            # dense transmits have no chunkable collective payload
            assert self.mode == "sketch", \
                "--overlap_depth > 1 requires --mode sketch " \
                "(only the sketch table emits in row chunks)"
        if self.dp != "off":
            assert self.mode == "sketch", \
                "--dp sketch requires --mode sketch (the mechanism " \
                "noises the aggregated sketch table)"
            assert not self.do_dp, \
                "--dp sketch replaces the legacy --do_dp worker/" \
                "server mechanism; enable only one"
            assert self.client_chunk == 0, \
                "--dp sketch noises the round's aggregated table " \
                "once; incompatible with --client_chunk (the " \
                "chunked scan never materialises it pre-wire)"
            # the accountant charges a per-client sqrt(r)·C/W bound;
            # median/trimmed releases don't have it (one client can
            # move a coordinate median by far more than its mean
            # share), and a cohort-derived clip cap (median of alive
            # norms) makes every client's scale depend on everyone's
            # data — also outside the bound
            assert self.robust_agg in ("none", "clip"), \
                "--dp sketch composes only with --robust_agg " \
                "{none,clip}: median/trimmed folds do not have the " \
                "sqrt(r)*clip/W sensitivity the accountant charges"
            assert self.robust_agg != "clip" \
                or self.robust_clip_norm > 0, \
                "--dp sketch with the clip fold needs a fixed " \
                "--robust_clip_norm > 0 (the auto median-of-norms " \
                "cap couples every client's scale to the whole " \
                "cohort, voiding the per-client sensitivity bound)"
        if self.mode == "sketch":
            # sketched SGD with local error/momentum is undefined: we
            # can't know which part of a sketch is "error"
            # (fed_worker.py:221-230)
            assert self.error_type != "local", \
                "sketch mode cannot use local error accumulation"
            assert self.local_momentum == 0, \
                "sketch mode cannot use local momentum " \
                "(momentum factor masking is impossible in sketch space)"
        if self.mode == "true_topk":
            # virtual error is required server-side (fed_aggregator.py:514)
            assert self.error_type == "virtual", \
                "true_topk requires --error_type virtual"
        if self.mode == "local_topk":
            assert self.error_type in ("local", "none"), \
                "local_topk cannot use virtual error (fed_aggregator.py:547)"
        if self.mode == "uncompressed":
            assert self.error_type != "local", \
                "local error accumulation is pointless uncompressed " \
                "(fed_worker.py:223-224)"
        if self.model_axis > 1:
            # the model axis shards *server* state; only the modes
            # whose server state is dense transmit-shaped buffers
            # (sketch tables / uncompressed vectors) have anything to
            # shard — the local-state modes keep their per-client rows
            # on the clients axis
            assert self.mode in ("sketch", "uncompressed"), \
                "--mesh with model axis > 1 supports sketch and " \
                "uncompressed modes only"
            if self.mode == "sketch":
                assert self.num_cols % self.model_axis == 0, \
                    "--mesh model axis must divide --num_cols " \
                    "(the sketch table shards by columns)"
            assert self.client_chunk == 0, \
                "--mesh with model axis > 1 is incompatible with " \
                "--client_chunk (the chunked scan is single-device)"
        if self.robust_agg != "none":
            # robust folds need the round's per-client transmits
            # materialised at once; the chunked scan only ever holds
            # a running sum
            assert self.client_chunk == 0, \
                "--robust_agg needs the full per-client transmit " \
                "stack; incompatible with --client_chunk"
            if self.robust_agg == "median" \
                    and self.robust_median_groups > 1:
                assert self.num_workers % self.robust_median_groups \
                    == 0, "--robust_median_groups must divide " \
                    "--num_workers"
        if self.async_buffer_size > 0:
            # the buffered fold weights the round's per-client
            # transmits by staleness; the chunked scan only ever
            # holds a running sum, and the async driver *is* the
            # round-overlap mechanism, so the pipelined dispatch
            # queue stays at depth 1
            assert self.client_chunk == 0, \
                "--async_buffer_size needs the full per-client " \
                "transmit stack; incompatible with --client_chunk"
            assert self.pipeline_depth == 1, \
                "--async_buffer_size overlaps rounds via the " \
                "arrival buffer; incompatible with --pipeline_depth"
        return self

    @property
    def probe_period(self) -> int:
        """Resolved probe cadence: 0 = probes off entirely;
        --probe_full forces every-round probing regardless of
        --probe_every."""
        return 1 if self.probe_full else self.probe_every

    @property
    def resolved_num_clients(self) -> Optional[int]:
        if self.num_clients is not None:
            return self.num_clients
        return NATURAL_NUM_CLIENTS.get(self.dataset_name)

    @property
    def mesh2d(self):
        """Parsed --mesh "CxM" as (clients, model), or None for the
        1-D default."""
        if not self.mesh:
            return None
        c, m = (int(p) for p in self.mesh.lower().split("x"))
        return (c, m)

    @property
    def model_axis(self) -> int:
        """Model-axis size of the requested mesh (1 when unset or
        1-D — the replicated-server-state layout)."""
        shape = self.mesh2d
        return shape[1] if shape else 1

    @property
    def transmit_shape(self):
        """Shape of what one client transmits (and of server V/error
        state): the sketch table in sketch mode, else the flat grad
        (reference fed_worker.py:45-50, fed_aggregator.py:403-407)."""
        if self.mode == "sketch":
            return (self.num_rows, self.num_cols)
        return (self.grad_size,)

    @property
    def upload_floats_per_client(self) -> int:
        """Floats uploaded per participating client per round
        (reference fed_aggregator.py:292-300)."""
        return {
            "uncompressed": self.grad_size,
            "true_topk": self.grad_size,
            "local_topk": self.k,
            "sketch": self.num_rows * self.num_cols,
            "fedavg": self.grad_size,
        }[self.mode]

    @property
    def upload_wire_bytes_per_client(self) -> float:
        """Bytes uploaded per participating client per round, at the
        wire dtype: the quantized sketch table plus (int8/fp8) its
        per-row f32 scales; every other mode ships f32."""
        from commefficient_tpu import accounting
        if self.mode == "sketch":
            return accounting.sketch_wire_bytes(
                self.num_rows, self.num_cols, self.sketch_dtype)
        return accounting.bytes_of(self.upload_floats_per_client, "f32")

    @property
    def downlink_value_bytes(self) -> int:
        """Bytes per broadcast value on the downlink: wire width
        under --downlink_encoding delta (values ship quantized), f32
        under dense."""
        from commefficient_tpu import accounting
        if self.downlink_encoding == "delta":
            return accounting.dtype_bytes(self.sketch_dtype)
        return accounting.dtype_bytes("f32")

    def replace(self, **kw) -> "Config":
        return dataclasses.replace(self, **kw)


def build_parser(default_lr: Optional[float] = None,
                 model_names: Optional[Sequence[str]] = None
                 ) -> argparse.ArgumentParser:
    """Argparse surface — same flags as reference utils.py:102-214."""
    parser = argparse.ArgumentParser()

    # meta-args
    parser.add_argument("--test", action="store_true", dest="do_test")
    parser.add_argument("--mode", choices=MODES, default="sketch")
    parser.add_argument("--profile", action="store_true",
                        dest="do_profile")
    parser.add_argument("--bf16", action="store_true", dest="do_bf16")
    parser.add_argument("--seq_devices", type=int, default=1)
    parser.add_argument("--seq_impl", choices=["ring", "ulysses"],
                        default="ring")
    parser.add_argument("--dropout_prob", type=float, default=0.0)
    parser.add_argument("--mixup", action="store_true", dest="do_mixup")
    parser.add_argument("--mixup_alpha", type=float, default=1.0)
    parser.add_argument("--tensorboard", dest="use_tensorboard",
                        action="store_true")
    parser.add_argument("--seed", type=int, default=21)

    # data/model args
    if model_names is None:
        from commefficient_tpu import models
        model_names = models.model_names()
    parser.add_argument("--model", default="ResNet9",
                        choices=model_names or None)
    parser.add_argument("--finetune", action="store_true", dest="do_finetune")
    parser.add_argument("--checkpoint", action="store_true",
                        dest="do_checkpoint")
    parser.add_argument("--resume", action="store_true",
                        dest="do_resume")
    parser.add_argument("--checkpoint_every", type=int, default=0)
    parser.add_argument("--checkpoint_path", type=str, default="./checkpoint")
    parser.add_argument("--finetune_path", type=str, default="./finetune")
    parser.add_argument("--finetuned_from", type=str,
                        choices=list(FED_DATASETS.keys()))
    parser.add_argument("--num_results_train", type=int, default=2)
    parser.add_argument("--num_results_val", type=int, default=2)
    parser.add_argument("--dataset_name", type=str, default="",
                        choices=list(FED_DATASETS.keys()))
    parser.add_argument("--dataset_dir", type=str, default="./dataset")
    parser.add_argument("--batchnorm", action="store_true",
                        dest="do_batchnorm")
    parser.add_argument("--nan_threshold", type=float, default=999)

    # compression args
    parser.add_argument("--k", type=int, default=50000)
    parser.add_argument("--num_cols", type=int, default=500000)
    parser.add_argument("--num_rows", type=int, default=5)
    parser.add_argument("--num_blocks", type=int, default=20)
    parser.add_argument("--topk_down", action="store_true",
                        dest="do_topk_down")

    # optimization args
    parser.add_argument("--local_momentum", type=float, default=0.9)
    parser.add_argument("--virtual_momentum", type=float, default=0)
    parser.add_argument("--weight_decay", type=float, default=5e-4)
    parser.add_argument("--num_epochs", type=float, default=24)
    parser.add_argument("--schedule_epochs", type=float, default=None)
    parser.add_argument("--num_fedavg_epochs", type=int, default=1)
    parser.add_argument("--fedavg_batch_size", type=int, default=-1)
    parser.add_argument("--fedavg_lr_decay", type=float, default=1)
    parser.add_argument("--error_type", choices=ERROR_TYPES, default="none")
    parser.add_argument("--lr_scale", type=float, default=default_lr)
    parser.add_argument("--pivot_epoch", type=float, default=5)

    # parallelization args
    parser.add_argument("--port", type=int, default=5315)
    parser.add_argument("--num_clients", type=int)
    parser.add_argument("--num_workers", type=int, default=1)
    parser.add_argument("--device", type=str,
                        choices=["cpu", "tpu", "cuda"], default="tpu")
    parser.add_argument("--num_devices", type=int, default=-1)
    parser.add_argument("--share_ps_gpu", action="store_true")
    parser.add_argument("--iid", action="store_true", dest="do_iid")
    parser.add_argument("--train_dataloader_workers", type=int, default=0)
    parser.add_argument("--val_dataloader_workers", type=int, default=0)

    # GPT2 args
    parser.add_argument("--model_checkpoint", type=str, default="gpt2")
    parser.add_argument("--num_candidates", type=int, default=2)
    parser.add_argument("--val_candidates", type=int, default=0)
    parser.add_argument("--max_history", type=int, default=2)
    parser.add_argument("--local_batch_size", type=int, default=8)
    parser.add_argument("--valid_batch_size", type=int, default=8)
    parser.add_argument("--microbatch_size", type=int, default=-1)
    parser.add_argument("--lm_coef", type=float, default=1.0)
    parser.add_argument("--mc_coef", type=float, default=1.0)
    parser.add_argument("--max_grad_norm", type=float)
    parser.add_argument("--personality_permutations", type=int, default=1)
    parser.add_argument("--eval_before_start", action="store_true")

    # differential privacy args
    parser.add_argument("--dp", choices=["off", "sketch"],
                        default="off",
                        help="DP sketching (privacy/): clip each "
                        "client's dense gradient to --dp_clip and "
                        "add calibrated Gaussian noise to the "
                        "aggregated sketch table before wire "
                        "quantization; an RDP accountant rides the "
                        "ledger")
    parser.add_argument("--dp_clip", type=float, default=1.0,
                        help="per-client L2 clip cap for --dp sketch")
    parser.add_argument("--dp_noise_mult", type=float, default=0.0,
                        help="noise multiplier σ for --dp sketch "
                        "(noise std = σ × per-client table "
                        "sensitivity)")
    parser.add_argument("--dp_delta", type=float, default=1e-5,
                        help="accountant δ for the ε(δ) conversion")
    parser.add_argument("--dp_epsilon", type=float, default=0.0,
                        help="total ε budget (0 = unlimited): arms "
                        "the privacy_budget_exhausted alarm and "
                        "hard-constrains the autopilot ladder")
    # legacy reference-parity worker/server DP (was spelled --dp
    # before the sketch mechanism took that flag)
    parser.add_argument("--do_dp", action="store_true", dest="do_dp")
    parser.add_argument("--dp_mode", choices=DP_MODES, default="worker")
    parser.add_argument("--l2_norm_clip", type=float, default=1.0)
    parser.add_argument("--noise_multiplier", type=float, default=0.0)

    # TPU-native additions
    parser.add_argument("--mesh", type=str, default="",
                        help="2D pod mesh 'CxM': C devices "
                        "data-parallel over clients x M devices "
                        "sharding server state over model (sketch/"
                        "uncompressed modes; per-device server memory "
                        "~1/M). Default: 1-D clients mesh")
    parser.add_argument("--param_dtype", type=str, default="float32")
    parser.add_argument("--compute_dtype", type=str, default="float32")
    parser.add_argument("--approx_topk", action="store_true")
    parser.add_argument("--approx_recall", type=float, default=0.95)
    parser.add_argument("--pipeline_depth", type=int, default=1)
    parser.add_argument("--classes_per_client", type=int, default=1)
    parser.add_argument("--synthetic_per_class", type=int, default=64)
    parser.add_argument("--synthetic_separation", type=float,
                        default=1.0)
    parser.add_argument("--synthetic_num_val", type=int, default=128)
    parser.add_argument("--hf_export", action="store_true",
                        dest="do_hf_export")
    parser.add_argument("--coordinator_address", type=str,
                        default=None)
    parser.add_argument("--num_processes", type=int, default=None)
    parser.add_argument("--process_id", type=int, default=None)
    parser.add_argument("--remat", action="store_true",
                        dest="do_remat")
    parser.add_argument("--tokens_per_chunk", type=int, default=0,
                        help="tokens per logits chunk in the chunked "
                        "vocab cross-entropy (0 = auto)")
    parser.add_argument("--fused_ce", type=str, default="off",
                        choices=["auto", "on", "off"],
                        help="fused-linear-CE vocab head (Pallas; "
                        "ops/flce_pallas.py): auto = on at TPU "
                        "default backend, chunked elsewhere")
    parser.add_argument("--attn_impl", type=str, default="xla",
                        choices=["xla", "flash"],
                        help="GPT-2 attention lowering: XLA fusion or "
                        "the Pallas TPU flash-attention kernel")
    parser.add_argument("--sketch_rot_lanes", type=int, default=-1,
                        help="quantize sketch rotations to multiples "
                        "of this lane width (-1 = auto: 1024 on TPU "
                        "at large-d Pallas-eligible geometries, else "
                        "0; 0 = force full granularity); speeds the "
                        "Pallas kernels' rolls, see BENCHMARKS.md")
    parser.add_argument("--sketch_dtype", type=str, default="f32",
                        choices=list(SKETCH_DTYPES),
                        help="wire dtype of the uplinked sketch "
                        "table (sketch mode): f32 (bit-identical "
                        "program to a build without the flag), bf16, "
                        "or int8/fp8 with per-row scales — emission "
                        "stays f32, the table quantizes before the "
                        "all-reduce/reduce-scatter, the server "
                        "dequantizes before momentum/error feedback")
    parser.add_argument("--downlink_encoding", type=str,
                        default="dense",
                        choices=list(DOWNLINK_ENCODINGS),
                        help="downlink byte encoding: dense f32 "
                        "coordinates, or delta — (idx:int32, "
                        "val:wire_dtype) pairs plus a bitmap over "
                        "the previous round's support for repeated "
                        "indices (accounting-level; the compiled "
                        "program is unchanged)")
    parser.add_argument("--overlap_depth", type=int, default=1,
                        help="latency-hiding round pipeline (sketch "
                        "mode): emit the table in min(N, rows) row "
                        "chunks and overlap each chunk's wire "
                        "collective with the next chunk's "
                        "emit+quantize (1 = serial round, "
                        "bit-identical program)")
    parser.add_argument("--client_chunk", type=int, default=0,
                        help="scan the round's client fan-out in "
                        "chunks of this many clients (0 = all at "
                        "once) — memory lever for large rounds of "
                        "the local-state modes on one chip")
    parser.add_argument("--clientstore", type=str, default="device",
                        choices=["device", "host", "auto"],
                        help="per-client state placement: dense HBM "
                        "arrays (device), budgeted host arena + mmap "
                        "spill with per-round participant gather "
                        "(host), or resolve by footprint vs "
                        "--clientstore_bytes (auto)")
    parser.add_argument("--clientstore_bytes", type=int,
                        default=1 << 30,
                        help="host client-store arena budget in bytes "
                        "(rows beyond it spill to mmap)")
    parser.add_argument("--clientstore_dir", type=str, default="",
                        help="client-store spill directory "
                        "(default: private temp dir)")
    parser.add_argument("--ledger", type=str, default="",
                        help="write one JSONL telemetry record per "
                        "training round to this path (spans, comm "
                        "bytes, memory watermarks; see "
                        "scripts/telemetry_report.py)")
    parser.add_argument("--telemetry_console", action="store_true",
                        help="print an end-of-run summary of the "
                        "round telemetry (span totals/means, bytes)")
    parser.add_argument("--probe_every", type=int, default=0,
                        help="algorithm probes (ledger schema v2): "
                        "cheap norm/NaN probes every round, the "
                        "sketch-recovery-error probe every N rounds "
                        "(0 = probes off, no compiled overhead)")
    parser.add_argument("--probe_full", action="store_true",
                        help="shorthand for --probe_every 1")
    parser.add_argument("--on_divergence", type=str, default="log",
                        choices=["log", "ledger-flag", "abort"],
                        help="alarm action when a probe rule fires "
                        "(NaN/Inf, residual growth, recovery error): "
                        "warn, flag the ledger record, or abort the "
                        "run at the offending round")
    parser.add_argument("--alarm_residual_ratio", type=float,
                        default=2.0,
                        help="fire when the error-feedback residual "
                        "norm grows by more than this ratio for "
                        "--alarm_residual_rounds consecutive rounds")
    parser.add_argument("--alarm_residual_rounds", type=int, default=3)
    parser.add_argument("--alarm_recovery_error", type=float,
                        default=1.0,
                        help="fire when relative sketch-recovery "
                        "error exceeds this")
    parser.add_argument("--alarm_step_time_ratio", type=float,
                        default=0.0,
                        help="step_time_regression rule: fire when a "
                        "round's wall step time exceeds this ratio x "
                        "the rolling median (0 = off; action from "
                        "--on_divergence)")
    parser.add_argument("--alarm_step_time_window", type=int,
                        default=16,
                        help="rolling-median window (rounds) for "
                        "--alarm_step_time_ratio")
    parser.add_argument("--alarm_collective_skew", type=float,
                        default=0.0,
                        help="collective_skew rule: fire when a traced "
                        "round's max cross-device collective "
                        "enter-delta exceeds this ratio x its "
                        "collective seconds (0 = off; needs --profile; "
                        "action from --on_divergence)")
    parser.add_argument("--robust_agg", type=str, default="none",
                        choices=list(ROBUST_AGGS),
                        help="robust fold over per-client transmits: "
                        "median (coordinate-wise median of sketch "
                        "groups), trimmed (trimmed mean), clip "
                        "(norm-clipped fold). Rejected mass never "
                        "enters the error-feedback residuals.")
    parser.add_argument("--robust_trim_frac", type=float, default=0.1,
                        help="fraction trimmed from each tail per "
                        "coordinate under --robust_agg trimmed")
    parser.add_argument("--robust_clip_norm", type=float, default=0.0,
                        help="per-client transmit-norm clip threshold "
                        "under --robust_agg clip (0 = auto: median of "
                        "alive per-client norms)")
    parser.add_argument("--robust_median_groups", type=int, default=0,
                        help="number of client groups for "
                        "median-of-sketch-groups (0 = every client "
                        "its own group; must divide --num_workers)")
    parser.add_argument("--alarm_byzantine_ratio", type=float,
                        default=0.0,
                        help="byzantine_suspect rule: fire when "
                        "max/mean per-client transmit norm exceeds "
                        "this ratio (0 = off; needs probes; action "
                        "from --on_divergence)")
    parser.add_argument("--alarm_fold_rejection", type=float,
                        default=0.0,
                        help="fold_rejection_rate rule: fire when the "
                        "robust fold deviates from the plain mean by "
                        "more than this relative rate (0 = off; needs "
                        "probes; action from --on_divergence)")
    parser.add_argument("--checkpoint_every_rounds", type=int,
                        default=0,
                        help="autosave the checkpoint every N rounds "
                        "(0 = off; independent of the epoch-cadence "
                        "--checkpoint_every)")
    parser.add_argument("--checkpoint_keep", type=int, default=0,
                        help="history snapshots retained by the round "
                        "autosaver (0 = latest only)")
    parser.add_argument("--async_buffer_size", type=int, default=0,
                        help="fold the arrival buffer every K arrived "
                        "clients instead of barriering on the cohort "
                        "(0 = synchronous; K <= --num_workers)")
    parser.add_argument("--async_staleness_weight", type=float,
                        default=0.0,
                        help="staleness exponent alpha: an update "
                        "folded s rounds late is weighted "
                        "1/(1+s)^alpha (0 = unweighted; at K = cohort "
                        "it reduces bit-exactly to the sync round)")
    parser.add_argument("--alarm_async_staleness", type=float,
                        default=0.0,
                        help="async_staleness rule: fire when the "
                        "round's max folded staleness exceeds this "
                        "many rounds (0 = off; action from "
                        "--on_divergence)")
    parser.add_argument("--alarm_job_starvation", type=float,
                        default=0.0,
                        help="job_starvation rule (fedservice "
                        "daemon): fire when a runnable job waited "
                        "more than this many scheduler ticks since "
                        "it last ran (0 = off; action from "
                        "--on_divergence)")
    parser.add_argument("--live_port", type=int, default=0,
                        help="serve live metrics (Prometheus text "
                        "exposition) from a localhost-only exporter "
                        "thread at this port: /metrics + /healthz "
                        "(0 = off, nothing constructed)")
    parser.add_argument("--flightrec_rounds", type=int, default=0,
                        help="flight recorder: keep the last N round "
                        "records in memory and dump an atomic "
                        "postmortem bundle on alarm fire / graceful "
                        "shutdown / crash (0 = off)")
    parser.add_argument("--postmortem_dir", type=str,
                        default="runs/postmortems",
                        help="directory postmortem bundles land in")
    parser.add_argument("--causal_trace", action="store_true",
                        dest="causal_trace",
                        help="causal round tracing: record the "
                        "round's span DAG (deterministic ids) onto "
                        "round records for the critical-path "
                        "explainer (telemetry_report.py --critpath); "
                        "host-side only, off keeps the build "
                        "bit-identical")
    parser.add_argument("--slo_round_p95", type=float, default=0.0,
                        help="SLO round-latency objective: a round "
                        "slower than this many seconds is a "
                        "violation (0 = objective off)")
    parser.add_argument("--slo_staleness_max", type=float,
                        default=0.0,
                        help="SLO staleness objective: a round whose "
                        "max folded staleness exceeds this many "
                        "rounds is a violation (0 = off)")
    parser.add_argument("--slo_eps_rounds", type=int, default=0,
                        help="SLO privacy-burn objective: ε must "
                        "stay under the linear spend schedule "
                        "--dp_epsilon * (round+1) / horizon over "
                        "this many rounds (0 = off; needs --dp "
                        "sketch with a hard --dp_epsilon)")
    parser.add_argument("--slo_starvation", type=float, default=0.0,
                        help="SLO starvation objective (fedservice "
                        "daemon): a tick whose max job wait exceeds "
                        "this many ticks is a violation (0 = off)")
    parser.add_argument("--slo_error_budget", type=float,
                        default=0.05,
                        help="fraction of windowed rounds allowed to "
                        "violate an SLO before its burn rate reads "
                        "1.0")
    parser.add_argument("--slo_window", type=int, default=32,
                        help="slow rolling window (rounds) for the "
                        "multi-window burn rate")
    parser.add_argument("--slo_fast_window", type=int, default=8,
                        help="fast rolling window (rounds); burn = "
                        "min(fast, slow rate) / error budget")
    parser.add_argument("--alarm_slo_burn", type=float, default=0.0,
                        help="slo_burn rule: fire when the worst "
                        "per-objective burn rate (slo_burn_max) "
                        "reaches this (0 = off; action from "
                        "--on_divergence)")
    parser.add_argument("--autopilot", type=str, default="off",
                        choices=["off", "on"],
                        help="adaptive compression autopilot "
                        "(commefficient_tpu/autopilot): walk the "
                        "discrete knob lattice (sketch_dtype x k x "
                        "rows x cols x recall) toward the cheapest "
                        "round program whose recovery error stays "
                        "inside --autopilot_band, re-jitting round "
                        "variants through a bounded LRU cache. off "
                        "(default) compiles bit-identical to a build "
                        "without the flag")
    parser.add_argument("--autopilot_band", type=str, default="",
                        help="target recovery-error band LO:HI "
                        "(required with --autopilot on); cheapen "
                        "below LO after the cooldown, back off above "
                        "HI immediately and never re-enter the "
                        "offending point")
    parser.add_argument("--autopilot_cooldown", type=int, default=2,
                        help="in-band probed rounds between "
                        "cheapening moves (back-off ignores it)")
    parser.add_argument("--autopilot_cache_size", type=int, default=4,
                        help="round-variant LRU bound; evicted "
                        "variants recompile on re-visit (ledger-"
                        "stamped)")
    parser.add_argument("--autopilot_warm_ahead", type=int, default=1,
                        help="1 = AOT-compile a decided move's round "
                        "variant under the current round's host "
                        "phase; 0 = lazy compile at the switch "
                        "round's dispatch")
    parser.add_argument("--autopilot_pin", type=str, default="",
                        help="hold the controller at one lattice "
                        "point (variant-key spelling, e.g. "
                        "int8-k50000-r5-c500000-re9500) — full "
                        "autopilot machinery, zero moves, "
                        "bit-identical to the equivalent static "
                        "config")
    parser.add_argument("--autopilot_geometry", action="store_true",
                        help="extend the knob ladder past the dtype "
                        "axis into column-halving geometry steps "
                        "(a geometry move resets server momentum/"
                        "error feedback)")

    return parser


def parse_args(default_lr: Optional[float] = None, argv=None) -> Config:
    parser = build_parser(default_lr)
    ns = parser.parse_args(argv)
    field_names = {f.name for f in dataclasses.fields(Config)}
    kw = {k: v for k, v in vars(ns).items() if k in field_names}
    return Config(**kw)
