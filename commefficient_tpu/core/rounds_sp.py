"""Federated round with sequence parallelism inside each client — the
2-D mesh composition ("clients" x "seq").

The 1-D engine (core/rounds.py) shards *clients* over the mesh; each
client's forward fits one device. For long-sequence federated LM
training (GPT-2/PersonaChat at context lengths the reference could
never reach — it has no sequence parallelism at all, SURVEY.md §2.8),
this module composes both axes:

- the client batch is sharded over ``clients`` AND its token arrays
  over ``seq``;
- inside one ``shard_map`` block, each device holds its client slice's
  sequence shard; the GPT-2 forward runs ring (or Ulysses) attention
  over ``seq`` (models/gpt2.py seq_axis) with global-position
  embeddings;
- the loss is a masked token-CE over local positions (labels are
  pre-shifted host-side so the shard boundary needs no halo exchange)
  plus the MC-head CE, normalised by ``psum`` counts over ``seq``;
- parameter gradients are ``psum``-ed over ``seq`` (params are
  replicated on that axis), then the per-client transmits sum over
  ``clients`` — exactly the 1-D engine's aggregation semantics, so the
  aggregated gradient equals the dense single-device oracle
  (tested in tests/test_rounds_sp.py) and any linear compressor
  (count-sketch) composes on top unchanged.

Client state: the SP round is *stateless* per client (uncompressed /
sketch modes only — no local momentum, no local error feedback), so
the host-resident client store (clientstore/) never applies here;
``--clientstore host`` composes with the 1-D engine's stateful modes
(local_topk, fedavg) and FedModel raises if combined with
``pipeline_depth > 1`` rather than silently degrading. If stateful
modes are ever added to this path, the dense_rows participant-row
contract in core/rounds.py build_client_round is the template.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from commefficient_tpu.compat import axis_size
from commefficient_tpu.models.gpt2 import (GPT2Config, GPT2DoubleHeads,
                                           lm_nll_sums_chunked,
                                           token_nll)
from commefficient_tpu.parallel.mesh import (CLIENT_AXIS, client_spec,
                                             replicated_spec, shard_map,
                                             spec)

SEQ_AXIS = "seq"


def make_sp_mesh(n_clients_axis: int, n_seq_axis: int,
                 devices=None) -> Mesh:
    import numpy as np
    devices = list(devices) if devices is not None else jax.devices()
    n = n_clients_axis * n_seq_axis
    assert len(devices) >= n, (len(devices), n)
    return Mesh(np.array(devices[:n]).reshape(n_clients_axis,
                                              n_seq_axis),
                (CLIENT_AXIS, SEQ_AXIS))


def shift_lm_labels(lm_labels, ignore_index: int = -1):
    """Host-side global shift: position t is labelled with token t+1
    (the loss shift of gpt2_double_heads_loss), so sequence shards
    never need their right neighbour's first token. Default
    ignore_index -1 matches the persona loaders' label padding
    (data/loader.py PersonaFedLoader)."""
    shifted = jnp.roll(lm_labels, -1, axis=-1)
    return shifted.at[..., -1].set(ignore_index)


def build_sp_gpt2_round(cfg: GPT2Config, mesh: Mesh,
                        unravel: Callable, lm_coef: float = 1.0,
                        mc_coef: float = 1.0,
                        ignore_index: int = -1,
                        tokens_per_chunk: int = 0):
    """Returns jit-able ``round(flat_params, batch) -> (agg_grad,
    per_client_losses)`` — losses are per participating client (W,),
    zero for clients with no real examples, so the trainer reports
    per-client metrics exactly like the 1-D engine.

    ``batch`` (host layout, W = participating clients):
      input_ids / token_type_ids (W, B, N, T) int32,
      shifted_labels (W, B, N, T) int32 (see shift_lm_labels),
      mc_token_ids (W, B, N) int32 — GLOBAL positions,
      mc_labels (W, B) int32, mask (W, B) float32 per-EXAMPLE mask
      (ragged client batches: padded rows are excluded from both loss
      terms; a client with no real rows contributes nothing).
    """
    sp_cfg = dataclasses.replace(cfg, seq_axis=SEQ_AXIS)
    model = GPT2DoubleHeads(sp_cfg)
    ignore = ignore_index
    # 0 = auto: 256 tokens/chunk — the measured knee of the SP
    # temp-memory table (BENCHMARKS.md / scripts/sp_mem_bench.py:
    # 0.89 GB vs 1.20 GB at the old 1024 default and 1.91 GB for the
    # dense-equivalent full-shard chunk at T_local=1024; within noise
    # of 128) and throughput-flat. --tokens_per_chunk overrides.
    tokens_per_chunk = tokens_per_chunk or 256

    def client_loss(flat, ids, tt, labels, mc_ids, mc_labels,
                    ex_mask):
        """Local-shard loss contributions for ONE client:
        (lm_nll_sum_local, lm_valid_count_local, mc_nll_mean) —
        the seq-psum happens outside so grad sees pure locals.
        ``ex_mask`` (B,) zeroes padded examples out of both terms.

        The LM term uses the chunked tied-head cross-entropy
        (models/gpt2.py lm_nll_sums_chunked) on the LOCAL sequence
        shard: the (B·N, T_local, V) logits tensor is never
        materialised, so peak vocab-head memory is one token chunk —
        SP keeps the long-context headroom it exists to provide
        instead of re-capping it at real vocab sizes. Labels arrive
        globally pre-shifted (shift_lm_labels), so local sums need no
        halo and seq-psum to the exact global numerator/denominator."""
        params = unravel(flat)
        B, N, Tl = ids.shape
        h, wte, mc_logits = model.apply(
            {"params": params}, ids, mc_ids, tt, return_hidden=True)
        sn, sv = lm_nll_sums_chunked(
            h, wte, labels.reshape(B * N, Tl), sp_cfg.dtype,
            ignore_index=ignore, tokens_per_chunk=tokens_per_chunk)
        e_mask = jnp.broadcast_to(ex_mask[:, None],
                                  (B, N)).reshape(B * N)
        lm_sum = jnp.sum(sn * e_mask)
        lm_cnt = jnp.sum(sv * e_mask)
        mc_nll, _ = token_nll(mc_logits[..., None, :],
                              mc_labels[..., None], ignore)
        mc = (jnp.sum(mc_nll[..., 0] * ex_mask)
              / jnp.maximum(jnp.sum(ex_mask), 1.0))
        return lm_sum, lm_cnt, mc

    def block(flat, ids, tt, labels, mc_ids, mc_labels, mask):
        # local shapes: (Wl, B, N, Tl) tokens, (Wl, B, N) mc, (Wl, B).
        # Gradients of the replicated ``flat`` are automatically
        # psum-med over BOTH mesh axes by shard_map's autodiff, so the
        # per-device objective must be the exact local share of the
        # global weighted objective: the lm term contributes its LOCAL
        # numerator over the GLOBAL count (seq shards sum to the full
        # mean) and the mc term — identical on every seq shard after
        # the gather-psum — is divided by the seq axis size.
        assert mask.ndim == 2, f"mask must be (W, B), got {mask.shape}"
        ex_mask = mask  # (Wl, B) per-example
        w = (jnp.sum(ex_mask, axis=1) > 0).astype(jnp.float32)  # (Wl,)
        seq_n = axis_size(SEQ_AXIS)

        def local_objective(f):
            def per_client(ids_c, tt_c, labels_c, mc_c, mcl_c, ex_c):
                lm_sum, lm_cnt, mc = client_loss(
                    f, ids_c, tt_c, labels_c, mc_c, mcl_c, ex_c)
                global_cnt = jnp.maximum(
                    jax.lax.psum(lm_cnt, SEQ_AXIS), 1.0)
                share = (lm_coef * lm_sum / global_cnt
                         + mc_coef * mc / seq_n)
                report = (lm_coef
                          * jax.lax.psum(lm_sum, SEQ_AXIS) / global_cnt
                          + mc_coef * mc)
                return share, report

            shares, reports = jax.vmap(per_client)(
                ids, tt, labels, mc_ids, mc_labels, ex_mask)
            return jnp.sum(shares * w), reports

        (_, losses), g = jax.value_and_grad(
            local_objective, has_aux=True)(flat)
        if not hasattr(jax.lax, "pvary"):
            # pre-varying-axes jax: differentiating the replicated
            # ``flat`` inside the block has no pvary transpose to
            # insert the cross-device reduction, so g is only the
            # local share — reduce explicitly (current jax already
            # returns it summed; doing both would double-count)
            g = jax.lax.psum(g, (CLIENT_AXIS, SEQ_AXIS))
        # g is already Sum_c w_c * grad_c, replicated everywhere
        n_clients = jnp.maximum(
            jax.lax.psum(jnp.sum(w), CLIENT_AXIS), 1.0)
        # per-client reported losses, zeroed for non-participating
        # rows; identical on every seq shard (the lm report is
        # seq-psummed inside per_client), so a CLIENT_AXIS out-spec
        # reassembles the global (W,) vector
        return g / n_clients, losses * w

    tok = spec(CLIENT_AXIS, None, None, SEQ_AXIS)
    per_client = client_spec()
    fn = shard_map(
        block, mesh=mesh,
        in_specs=(replicated_spec(), tok, tok, tok, per_client,
                  per_client, per_client),
        out_specs=(replicated_spec(), per_client))

    def round_fn(flat_params, batch):
        return fn(flat_params, batch["input_ids"],
                  batch["token_type_ids"], batch["shifted_labels"],
                  batch["mc_token_ids"], batch["mc_labels"],
                  batch["mask"])

    return round_fn
