"""Server-side update: virtual momentum, virtual error feedback,
unsketching / top-k recovery.

Pure-functional counterpart of the reference's ``get_server_update``
dispatch and ``_server_helper_*`` family (fed_aggregator.py:471-615).
Because the whole server step is deterministic given the aggregated
gradient, it runs *replicated* on every device of the mesh — the
reference's parameter-server rank dissolves (SURVEY.md §2.9).

``gradient`` is the round's aggregated quantity: a flat (grad_size,)
vector, or an (r, c) sketch table in sketch mode — always the
client-transmit sum divided by the round's total datapoint count
(fed_aggregator.py:334).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from commefficient_tpu.config import Config
from commefficient_tpu.ops.sketch import CountSketch
from commefficient_tpu.ops.topk import topk_with_support


class ServerState(NamedTuple):
    """Virtual momentum & error buffers, dense or sketch-shaped
    (reference FedOptimizer.__init__, fed_aggregator.py:401-411)."""
    Vvelocity: jax.Array
    Verror: jax.Array

    @staticmethod
    def init(cfg: Config) -> "ServerState":
        shape = cfg.transmit_shape
        return ServerState(jnp.zeros(shape, jnp.float32),
                           jnp.zeros(shape, jnp.float32))


class ServerUpdate(NamedTuple):
    # subtract from ps_weights; None when ``sparse_update`` carries
    # the k-sparse form instead (large-d sketch mode: materialising a
    # dense (d,) update costs ~6 ms at d=124M for 50k real values)
    weight_update: Optional[jax.Array]
    state: ServerState
    # mask of coordinates transmitted to clients this round, used for
    # true_topk's momentum factor masking of *client* velocities
    # (fed_aggregator.py:530-535); None for other modes
    client_velocity_keep: Optional[jax.Array]
    # support of the update for download accounting, in one of two
    # forms: ((k,) indices, (k,) lr-scaled values) on the index path
    # (also consumed for the sparse k-sized weight scatter when
    # weight_update is None), or {"bitmap": packed uint8} of the
    # lr-scaled update's nonzeros on the threshold-select path. None
    # means dense (every coordinate may have changed). Either way the
    # host never needs the dense update shipped off device.
    support: Optional[Union[Tuple[jax.Array, jax.Array],
                            dict]] = None


def _use_threshold_select(cfg: Config) -> bool:
    """Exact dense-mode selections (true_topk) at large d go through
    the threshold-select mask instead of the lax.top_k sort — same
    selected set, no sort, no index scatter. Gating is the shared
    predicate in ops/topk.py."""
    from commefficient_tpu.ops.topk import use_threshold_select
    return use_threshold_select(min(cfg.k, cfg.grad_size),
                                cfg.grad_size, cfg.approx_topk)


def _lr_scaled_support(idx, vals, lr):
    """Support of the *weight* update: values scaled by the (scalar or
    per-coordinate) LR, so coordinates with an effective LR of 0 read
    as unchanged — matching a value-compare on ``update * lr``."""
    lr_arr = jnp.asarray(lr, jnp.float32)
    scale = lr_arr[idx] if lr_arr.ndim else lr_arr
    return idx, vals * scale


def server_update(cfg: Config,
                  gradient: jax.Array,
                  state: ServerState,
                  lr,
                  sketch: Optional[CountSketch] = None,
                  noise_rng: Optional[jax.Array] = None) -> ServerUpdate:
    """Dispatch on mode (reference get_server_update,
    fed_aggregator.py:471-483). ``lr`` may be a scalar or a
    (grad_size,) per-parameter vector (per-param-group LRs,
    fed_aggregator.py:413-429). For fedavg the caller passes lr=1 —
    the LR was already applied in the clients' local SGD
    (fed_aggregator.py:448-453)."""
    helper = {
        "sketch": _sketched,
        "local_topk": _local_topk,
        "true_topk": _true_topk,
        "fedavg": _fedavg,
        "uncompressed": _uncompressed,
    }[cfg.mode]
    return helper(cfg, gradient, state, lr, sketch, noise_rng)


def _fedavg(cfg, avg_update, state, lr, sketch, noise_rng):
    # (fed_aggregator.py:485-497) — avg_update is the data-weighted
    # mean of client weight *deltas*, LR already applied locally
    assert cfg.error_type == "none" and cfg.local_momentum == 0
    Vvel = avg_update + cfg.virtual_momentum * state.Vvelocity
    return ServerUpdate(Vvel, ServerState(Vvel, state.Verror), None)


def _uncompressed(cfg, gradient, state, lr, sketch, noise_rng):
    # (fed_aggregator.py:499-511)
    Vvel = gradient + cfg.virtual_momentum * state.Vvelocity
    if cfg.do_dp and cfg.dp_mode == "server" and cfg.noise_multiplier != 0:
        assert noise_rng is not None, \
            "server-mode DP with noise needs a noise_rng"
        # the reference adds the noise in place on Vvelocity
        # (``grad`` aliases it, fed_aggregator.py:506-510), so the
        # noise persists into the momentum buffer — keep that
        Vvel = Vvel + cfg.noise_multiplier * jax.random.normal(
            noise_rng, Vvel.shape, Vvel.dtype)
    return ServerUpdate(Vvel * lr, ServerState(Vvel, state.Verror), None)


def _true_topk(cfg, gradient, state, lr, sketch, noise_rng):
    # (fed_aggregator.py:513-544)
    assert cfg.error_type == "virtual"
    Vvel = gradient + cfg.virtual_momentum * state.Vvelocity
    Verr = state.Verror + Vvel

    k = min(cfg.k, cfg.grad_size)
    if _use_threshold_select(cfg):
        # exact selection without the large-d sort (ops/topk.py):
        # the update stays dense end-to-end and accounting takes the
        # bit-packed support of the LR-SCALED update — same value-
        # compare semantics as _lr_scaled_support (lr==0 coordinates
        # read as unchanged)
        from commefficient_tpu.ops.topk import threshold_topk_mask_1d
        mask = threshold_topk_mask_1d(jax.lax.square(Verr), k)
        update = jnp.where(mask, Verr, 0.0)
        support = {"bitmap": jnp.packbits((update * lr) != 0)}
    else:
        update, idx, vals = topk_with_support(
            Verr, k, approx=cfg.approx_topk, recall=cfg.approx_recall)
        support = _lr_scaled_support(idx, vals, lr)
    keep = update == 0
    # error feedback + momentum factor masking at transmitted coords
    Verr = jnp.where(keep, Verr, 0.0)
    Vvel = jnp.where(keep, Vvel, 0.0)
    # participating clients' *local* velocities are masked at the same
    # coords by the round engine (the reference does this from the
    # optimizer via globals; here the mask travels in the result —
    # avoiding the reference's latent unset-global bug, SURVEY.md §2.1)
    return ServerUpdate(update * lr, ServerState(Vvel, Verr), keep,
                        support)


def _local_topk(cfg, local_topk_grad, state, lr, sketch, noise_rng):
    # (fed_aggregator.py:546-568): momentum accumulation only; virtual
    # error is impossible (the transmitted quantity is already sparse)
    # and masking virtual momentum would zero all of it every round
    assert cfg.error_type in ("local", "none")
    Vvel = local_topk_grad + cfg.virtual_momentum * state.Vvelocity
    return ServerUpdate(Vvel * lr, ServerState(Vvel, state.Verror), None)


def _sketched(cfg, sketched_grad, state, lr, sketch, noise_rng):
    """FetchSGD server step (fed_aggregator.py:570-615): momentum and
    error accumulation happen in (r, c) sketch-table space; top-k
    recovery via unsketch; error feedback and momentum factor masking
    are applied in table space at the nonzero buckets of the re-sketch
    of the recovered update."""
    assert sketch is not None
    if cfg.error_type == "local":
        assert cfg.virtual_momentum == 0
    elif cfg.error_type == "virtual":
        assert cfg.local_momentum == 0

    Vvel = sketched_grad + cfg.virtual_momentum * state.Vvelocity
    if cfg.error_type == "local":
        Verr = Vvel
    elif cfg.error_type == "virtual":
        Verr = state.Verror + Vvel
    else:  # "none": Verror stays zero forever -> zero updates, exactly
        # like the reference (fed_aggregator.py:581-587 never assigns)
        Verr = state.Verror

    # At large d the k-sparse form wins everywhere: re-sketching the
    # recovered update costs O(r*k) scatter-adds instead of the O(d)
    # dense kernel (~8 ms -> ~1.5 ms at GPT-2 124M), and the dense
    # (d,) update itself is never materialised (with_dense=False).
    # In the dense regime, exact recovery uses the threshold-select
    # mask instead of the top-k sort (22.3 -> ~11 ms full round at
    # ResNet9 scale, BENCHMARKS.md).
    sparse = sketch.prefer_sparse_resketch(cfg.k)
    if sketch.prefer_threshold_unsketch(cfg.k):  # implies not sparse
        update, _ = sketch.unsketch_dense_mask(Verr, k=cfg.k)
        # bit-packed support of the LR-scaled update: same value-
        # compare semantics as _lr_scaled_support
        support = {"bitmap": jnp.packbits((update * lr) != 0)}
    else:
        update, idx, vals = sketch.unsketch(Verr, k=cfg.k,
                                            with_support=True,
                                            with_dense=not sparse)
        support = _lr_scaled_support(idx, vals, lr)

    # re-sketch the recovered update to find which table buckets it
    # occupies (fed_aggregator.py:595-597)
    if sparse:
        sketched_update = sketch.sketch_sparse(idx, vals)
    else:
        sketched_update = sketch.sketch(update)
    keep = sketched_update == 0

    if cfg.error_type == "virtual":
        Verr = jnp.where(keep, Verr, 0.0)
    # momentum factor masking in table space (both error types; with
    # error "local" this also masks Verror since they alias,
    # fed_aggregator.py:612-613)
    Vvel = jnp.where(keep, Vvel, 0.0)
    if cfg.error_type == "local":
        Verr = Vvel

    if sparse:
        # weight_update None: the server round applies the update as a
        # k-sized scatter of the (already lr-scaled) support instead
        # of materialising the dense (d,) vector
        return ServerUpdate(None, ServerState(Vvel, Verr), None,
                            support)
    return ServerUpdate(update * lr, ServerState(Vvel, Verr), None,
                        support)
