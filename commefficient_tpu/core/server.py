"""Server-side update: virtual momentum, virtual error feedback,
unsketching / top-k recovery.

Pure-functional counterpart of the reference's ``get_server_update``
dispatch and ``_server_helper_*`` family (fed_aggregator.py:471-615).
Because the whole server step is deterministic given the aggregated
gradient, it runs *replicated* on every device of the mesh — the
reference's parameter-server rank dissolves (SURVEY.md §2.9).

``gradient`` is the round's aggregated quantity: a flat (grad_size,)
vector, or an (r, c) sketch table in sketch mode — always the
client-transmit sum divided by the round's total datapoint count
(fed_aggregator.py:334).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from commefficient_tpu.config import Config
from commefficient_tpu.ops.sketch import CountSketch
from commefficient_tpu.ops.topk import topk_with_support


class ServerState(NamedTuple):
    """Virtual momentum & error buffers, dense or sketch-shaped
    (reference FedOptimizer.__init__, fed_aggregator.py:401-411)."""
    Vvelocity: jax.Array
    Verror: jax.Array

    @staticmethod
    def init(cfg: Config, sharding=None) -> "ServerState":
        """``sharding`` (a NamedSharding from
        parallel/mesh.server_state_sharding) places the buffers
        model-sharded on a 2D mesh so per-device server memory scales
        as 1/``model``; None keeps the replicated 1-D layout."""
        shape = cfg.transmit_shape

        def z():
            buf = jnp.zeros(shape, jnp.float32)
            return buf if sharding is None else jax.device_put(
                buf, sharding)

        return ServerState(z(), z())

    @staticmethod
    def restore(Vvelocity, Verror, sharding=None) -> "ServerState":
        """Rebuild from host arrays at checkpoint restore. The
        checkpoint always holds the FULL buffers, so ``sharding``
        (parallel/mesh.server_state_sharding for the CURRENT mesh)
        re-places them under whatever topology the resumed run has —
        a resize is a placement migration, values untouched, which is
        what keeps a resized resume bit-exact vs an unresized one
        (tests/test_elastic.py)."""
        def put(a):
            a = jnp.asarray(a, jnp.float32)
            return a if sharding is None else jax.device_put(
                a, sharding)

        return ServerState(put(Vvelocity), put(Verror))


def fold_row_chunks(chunks) -> jax.Array:
    """Chunk-ordered fold of the overlap pipeline's per-row-chunk
    collectives (``--overlap_depth``): reassemble the dequantized row
    chunks into the (r, c[/M]) table in emission order. The chunks
    cover disjoint row ranges, so the fold is pure concatenation — no
    summation — and is bit-exact regardless of which chunk's
    collective completed first on the wire."""
    return jnp.concatenate(list(chunks), axis=0)


class ServerUpdate(NamedTuple):
    # subtract from ps_weights; None when ``sparse_update`` carries
    # the k-sparse form instead (large-d sketch mode: materialising a
    # dense (d,) update costs ~6 ms at d=124M for 50k real values)
    weight_update: Optional[jax.Array]
    state: ServerState
    # mask of coordinates transmitted to clients this round, used for
    # true_topk's momentum factor masking of *client* velocities
    # (fed_aggregator.py:530-535); None for other modes
    client_velocity_keep: Optional[jax.Array]
    # support of the update for download accounting, in one of two
    # forms: ((k,) indices, (k,) lr-scaled values) on the index path
    # (also consumed for the sparse k-sized weight scatter when
    # weight_update is None), or {"bitmap": packed uint8} of the
    # lr-scaled update's nonzeros on the threshold-select path. None
    # means dense (every coordinate may have changed). Either way the
    # host never needs the dense update shipped off device.
    support: Optional[Union[Tuple[jax.Array, jax.Array],
                            dict]] = None
    # schema-v2 probe scalars (--probe_every): update/residual/momentum
    # norms + selection mass coverage, computed inside the compiled
    # step as O(d) reductions. None unless the caller opted in — the
    # probes-off program must stay HLO-identical to pre-probe builds.
    probes: Optional[dict] = None


def staleness_weights(staleness, alpha: float):
    """FedBuff-style staleness discount ``1/(1+s)^alpha`` for the
    buffered asynchronous fold (asyncfed/). ``alpha`` is a trace-time
    constant — the round builder skips the weighting branch entirely
    at alpha == 0, which is what makes the degenerate-sync
    configuration bit-exact. Applied to a client's transmit AND its
    datapoint count, so the fold stays a weighted per-datapoint mean
    and the server's virtual momentum / error feedback never absorbs
    unnormalised stale mass."""
    return (1.0 + staleness.astype(jnp.float32)) ** jnp.float32(-alpha)


def _use_threshold_select(cfg: Config) -> bool:
    """Exact dense-mode selections (true_topk) at large d go through
    the threshold-select mask instead of the lax.top_k sort — same
    selected set, no sort, no index scatter. Gating is the shared
    predicate in ops/topk.py."""
    from commefficient_tpu.ops.topk import use_threshold_select
    return use_threshold_select(min(cfg.k, cfg.grad_size),
                                cfg.grad_size, cfg.approx_topk)


def _lr_scaled_support(idx, vals, lr):
    """Support of the *weight* update: values scaled by the (scalar or
    per-coordinate) LR, so coordinates with an effective LR of 0 read
    as unchanged — matching a value-compare on ``update * lr``."""
    lr_arr = jnp.asarray(lr, jnp.float32)
    scale = lr_arr[idx] if lr_arr.ndim else lr_arr
    return idx, vals * scale


def _l2(x) -> jax.Array:
    return jnp.sqrt(jnp.sum(jax.lax.square(x)))


def _coverage(selected_mass, dense_mass) -> jax.Array:
    """‖selected‖² / ‖dense‖² — the fraction of the pre-selection
    vector's energy the transmitted top-k/threshold support carries.
    A zero denominator (cold-start buffers) reads as full coverage."""
    return jnp.where(dense_mass > 0,
                     selected_mass / jnp.maximum(dense_mass, 1e-30),
                     1.0)


def server_update(cfg: Config,
                  gradient: jax.Array,
                  state: ServerState,
                  lr,
                  sketch: Optional[CountSketch] = None,
                  noise_rng: Optional[jax.Array] = None,
                  probes: bool = False) -> ServerUpdate:
    """Dispatch on mode (reference get_server_update,
    fed_aggregator.py:471-483). ``lr`` may be a scalar or a
    (grad_size,) per-parameter vector (per-param-group LRs,
    fed_aggregator.py:413-429). For fedavg the caller passes lr=1 —
    the LR was already applied in the clients' local SGD
    (fed_aggregator.py:448-453).

    Under ``--robust_agg`` (core/robust.py) ``gradient`` is already
    the robust aggregate: mass the fold rejected (trimmed tails,
    clipped excess, off-median clients) never reaches this function,
    so it cannot leak into Vvelocity / Verror — the error-feedback
    residuals only ever accumulate what the server actually applied.
    No robust-specific handling belongs here.

    ``probes=True`` (a trace-time flag) additionally fills
    ``ServerUpdate.probes`` with the schema-v2 server diagnostics:
    ``update_norm`` (‖lr-scaled weight update‖), ``residual_norm``
    (‖post-mask Verror‖ — table-space in sketch mode),
    ``momentum_norm`` (‖post-mask Vvelocity‖) and, for the selecting
    modes, ``mass_coverage`` (‖selected‖²/‖dense‖² against the
    pre-selection residual, sketch mode estimating the denominator via
    ``l2estimate``)."""
    helper = {
        "sketch": _sketched,
        "local_topk": _local_topk,
        "true_topk": _true_topk,
        "fedavg": _fedavg,
        "uncompressed": _uncompressed,
    }[cfg.mode]
    return helper(cfg, gradient, state, lr, sketch, noise_rng, probes)


def _state_probes(update_norm, state: ServerState, extra=None) -> dict:
    pr = {"update_norm": update_norm,
          "momentum_norm": _l2(state.Vvelocity),
          "residual_norm": _l2(state.Verror)}
    if extra:
        pr.update(extra)
    return pr


def _fedavg(cfg, avg_update, state, lr, sketch, noise_rng,
            probes=False):
    # (fed_aggregator.py:485-497) — avg_update is the data-weighted
    # mean of client weight *deltas*, LR already applied locally
    assert cfg.error_type == "none" and cfg.local_momentum == 0
    Vvel = avg_update + cfg.virtual_momentum * state.Vvelocity
    new_state = ServerState(Vvel, state.Verror)
    pr = _state_probes(_l2(Vvel), new_state) if probes else None
    return ServerUpdate(Vvel, new_state, None, probes=pr)


def _uncompressed(cfg, gradient, state, lr, sketch, noise_rng,
                  probes=False):
    # (fed_aggregator.py:499-511)
    Vvel = gradient + cfg.virtual_momentum * state.Vvelocity
    if cfg.do_dp and cfg.dp_mode == "server" and cfg.noise_multiplier != 0:
        assert noise_rng is not None, \
            "server-mode DP with noise needs a noise_rng"
        # the reference adds the noise in place on Vvelocity
        # (``grad`` aliases it, fed_aggregator.py:506-510), so the
        # noise persists into the momentum buffer — keep that; the
        # draw routes through privacy/ (lint: noise-confinement)
        from commefficient_tpu.privacy import gaussian_noise
        Vvel = Vvel + gaussian_noise(noise_rng, Vvel.shape,
                                     Vvel.dtype,
                                     std=cfg.noise_multiplier)
    new_state = ServerState(Vvel, state.Verror)
    pr = _state_probes(_l2(Vvel * lr), new_state) if probes else None
    return ServerUpdate(Vvel * lr, new_state, None, probes=pr)


def _true_topk(cfg, gradient, state, lr, sketch, noise_rng,
               probes=False):
    # (fed_aggregator.py:513-544)
    assert cfg.error_type == "virtual"
    Vvel = gradient + cfg.virtual_momentum * state.Vvelocity
    Verr = state.Verror + Vvel

    k = min(cfg.k, cfg.grad_size)
    if _use_threshold_select(cfg):
        # exact selection without the large-d sort (ops/topk.py):
        # the update stays dense end-to-end and accounting takes the
        # bit-packed support of the LR-SCALED update — same value-
        # compare semantics as _lr_scaled_support (lr==0 coordinates
        # read as unchanged)
        from commefficient_tpu.ops.topk import threshold_topk_mask_1d
        mask = threshold_topk_mask_1d(jax.lax.square(Verr), k)
        update = jnp.where(mask, Verr, 0.0)
        support = {"bitmap": jnp.packbits((update * lr) != 0)}
    else:
        update, idx, vals = topk_with_support(
            Verr, k, approx=cfg.approx_topk, recall=cfg.approx_recall)
        support = _lr_scaled_support(idx, vals, lr)
    dense_mass = jnp.sum(jax.lax.square(Verr)) if probes else None
    keep = update == 0
    # error feedback + momentum factor masking at transmitted coords
    Verr = jnp.where(keep, Verr, 0.0)
    Vvel = jnp.where(keep, Vvel, 0.0)
    new_state = ServerState(Vvel, Verr)
    pr = None
    if probes:
        pr = _state_probes(
            _l2(update * lr), new_state,
            {"mass_coverage": _coverage(
                jnp.sum(jax.lax.square(update)), dense_mass)})
    # participating clients' *local* velocities are masked at the same
    # coords by the round engine (the reference does this from the
    # optimizer via globals; here the mask travels in the result —
    # avoiding the reference's latent unset-global bug, SURVEY.md §2.1)
    return ServerUpdate(update * lr, new_state, keep, support,
                        probes=pr)


def _local_topk(cfg, local_topk_grad, state, lr, sketch, noise_rng,
                probes=False):
    # (fed_aggregator.py:546-568): momentum accumulation only; virtual
    # error is impossible (the transmitted quantity is already sparse)
    # and masking virtual momentum would zero all of it every round
    assert cfg.error_type in ("local", "none")
    Vvel = local_topk_grad + cfg.virtual_momentum * state.Vvelocity
    new_state = ServerState(Vvel, state.Verror)
    pr = _state_probes(_l2(Vvel * lr), new_state) if probes else None
    return ServerUpdate(Vvel * lr, new_state, None, probes=pr)


def _sketched(cfg, sketched_grad, state, lr, sketch, noise_rng,
              probes=False):
    """FetchSGD server step (fed_aggregator.py:570-615): momentum and
    error accumulation happen in (r, c) sketch-table space; top-k
    recovery via unsketch; error feedback and momentum factor masking
    are applied in table space at the nonzero buckets of the re-sketch
    of the recovered update."""
    assert sketch is not None
    if cfg.error_type == "local":
        assert cfg.virtual_momentum == 0
    elif cfg.error_type == "virtual":
        assert cfg.local_momentum == 0

    Vvel = sketched_grad + cfg.virtual_momentum * state.Vvelocity
    if cfg.error_type == "local":
        Verr = Vvel
    elif cfg.error_type == "virtual":
        Verr = state.Verror + Vvel
    else:  # "none": Verror stays zero forever -> zero updates, exactly
        # like the reference (fed_aggregator.py:581-587 never assigns)
        Verr = state.Verror

    # At large d the k-sparse form wins everywhere: re-sketching the
    # recovered update costs O(r*k) scatter-adds instead of the O(d)
    # dense kernel (~8 ms -> ~1.5 ms at GPT-2 124M), and the dense
    # (d,) update itself is never materialised (with_dense=False).
    # In the dense regime, exact recovery uses the threshold-select
    # mask instead of the top-k sort (22.3 -> ~11 ms full round at
    # ResNet9 scale, BENCHMARKS.md).
    sparse = sketch.prefer_sparse_resketch(cfg.k)
    # pre-mask residual mass for the coverage probe: the true dense
    # residual never exists in sketch mode, so its energy comes from
    # the table's own l2estimate (unbiased median-of-rows)
    dense_mass = (jax.lax.square(CountSketch.l2estimate(Verr))
                  if probes else None)
    if sketch.prefer_threshold_unsketch(cfg.k):  # implies not sparse
        update, _ = sketch.unsketch_dense_mask(Verr, k=cfg.k)
        # bit-packed support of the LR-scaled update: same value-
        # compare semantics as _lr_scaled_support
        support = {"bitmap": jnp.packbits((update * lr) != 0)}
        sel_mass = (jnp.sum(jax.lax.square(update)) if probes
                    else None)
    else:
        update, idx, vals = sketch.unsketch(Verr, k=cfg.k,
                                            with_support=True,
                                            with_dense=not sparse)
        support = _lr_scaled_support(idx, vals, lr)
        sel_mass = jnp.sum(jax.lax.square(vals)) if probes else None

    # re-sketch the recovered update to find which table buckets it
    # occupies (fed_aggregator.py:595-597)
    if sparse:
        sketched_update = sketch.sketch_sparse(idx, vals)
    else:
        sketched_update = sketch.sketch(update)
    keep = sketched_update == 0

    if cfg.error_type == "virtual":
        Verr = jnp.where(keep, Verr, 0.0)
    # momentum factor masking in table space (both error types; with
    # error "local" this also masks Verror since they alias,
    # fed_aggregator.py:612-613)
    Vvel = jnp.where(keep, Vvel, 0.0)
    if cfg.error_type == "local":
        Verr = Vvel

    new_state = ServerState(Vvel, Verr)
    pr = None
    if probes:
        # update_norm from the lr-scaled support on the sparse path —
        # the dense update is never materialised there
        upd_norm = (_l2(support[1]) if sparse else _l2(update * lr))
        pr = _state_probes(
            upd_norm, new_state,
            {"mass_coverage": _coverage(sel_mass, dense_mass)})
    if sparse:
        # weight_update None: the server round applies the update as a
        # k-sized scatter of the (already lr-scaled) support instead
        # of materialising the dense (d,) vector
        return ServerUpdate(None, new_state, None, support,
                            probes=pr)
    return ServerUpdate(update * lr, new_state, None, support,
                        probes=pr)


def _psum_l2(x, axis_name) -> jax.Array:
    return jnp.sqrt(jax.lax.psum(jnp.sum(jax.lax.square(x)),
                                 axis_name))


def sketched_update_2d(cfg: Config, sketch: CountSketch,
                       sketched_grad_loc: jax.Array,
                       state: ServerState, lr,
                       axis_name: str, n_model: int,
                       probes: bool = False) -> ServerUpdate:
    """Shard-local FetchSGD server step for the 2D ``clients`` ×
    ``model`` mesh — runs INSIDE shard_map with the sketch table's
    columns sharded over ``axis_name`` (``n_model`` peers, c/M columns
    each). Momentum and error-feedback accumulation stay shard-local,
    so per-device server state and the accumulate FLOPs scale as 1/M.
    Recovery re-materialises the full (r, c) table once per round (one
    tiled all-gather, 4·r·c bytes on the wire) and then runs as a
    distributed select: each peer estimates only its own contiguous
    d/M coordinate slice (``estimates_at``, bit-identical per
    coordinate to the rolled ``estimates``), the global k-th value is
    agreed via psum'd radix histograms, and the k winners are gathered
    (``distributed_threshold_mask_1d``). The selected set — hence the
    dense update, the support, and the re-sketch keep mask — matches
    the 1-D ``_sketched`` selection (lowest-index tie-break, same set
    as ``lax.top_k``)."""
    assert cfg.error_type in ("none", "virtual", "local")
    if cfg.error_type == "local":
        assert cfg.virtual_momentum == 0
    elif cfg.error_type == "virtual":
        assert cfg.local_momentum == 0

    d = cfg.grad_size
    k = min(cfg.k, d)
    Vvel = sketched_grad_loc + cfg.virtual_momentum * state.Vvelocity
    if cfg.error_type == "local":
        Verr = Vvel
    elif cfg.error_type == "virtual":
        Verr = state.Verror + Vvel
    else:  # "none": zero updates forever, like the 1-D path
        Verr = state.Verror

    table = jax.lax.all_gather(Verr, axis_name, axis=1, tiled=True)

    # shard-local estimates over this peer's coordinate slice
    # [p·⌈d/M⌉, (p+1)·⌈d/M⌉); tail-shard padding slots are masked out
    # of the selection population, not zeroed into it
    p = jax.lax.axis_index(axis_name)
    n_loc = -(-d // n_model)
    start = (p * n_loc).astype(jnp.int32)
    gidx = start + jnp.arange(n_loc, dtype=jnp.int32)
    valid = gidx < d
    est = sketch.estimates_at(table, jnp.minimum(gidx, d - 1))
    est = jnp.where(valid, est, 0.0)

    from commefficient_tpu.ops.topk import distributed_threshold_mask_1d
    take = distributed_threshold_mask_1d(jax.lax.square(est), k,
                                         axis_name, valid=valid)
    # candidate extraction: pack this shard's winners into k slots
    # (index d = "empty"), gather all M·k slots, compact to exactly k —
    # the distributed mask selects exactly k coordinates globally
    pos = jnp.nonzero(take, size=k, fill_value=0)[0]
    n_take = jnp.sum(take.astype(jnp.int32))
    slot_ok = jnp.arange(k) < n_take
    cand_idx = jnp.where(slot_ok, start + pos.astype(jnp.int32), d)
    cand_val = jnp.where(slot_ok, est[pos], 0.0)
    cand_idx = jax.lax.all_gather(cand_idx, axis_name, tiled=True)
    cand_val = jax.lax.all_gather(cand_val, axis_name, tiled=True)
    sel = jnp.nonzero(cand_idx < d, size=k, fill_value=0)[0]
    idx = jnp.minimum(cand_idx[sel], d - 1)  # ascending global order
    vals = cand_val[sel]

    dense_mass = (jax.lax.square(CountSketch.l2estimate(table))
                  if probes else None)
    update = jnp.zeros(d, jnp.float32).at[idx].add(
        vals, mode="promise_in_bounds", unique_indices=True,
        indices_are_sorted=True)
    support = _lr_scaled_support(idx, vals, lr)

    # re-sketch the recovered update, slice this peer's columns, mask
    st = sketch.sketch_sparse(idx, vals)
    c_loc = Verr.shape[1]
    st_loc = jax.lax.dynamic_slice(st, (0, p * c_loc),
                                   (st.shape[0], c_loc))
    keep = st_loc == 0
    if cfg.error_type == "virtual":
        Verr = jnp.where(keep, Verr, 0.0)
    Vvel = jnp.where(keep, Vvel, 0.0)
    if cfg.error_type == "local":
        Verr = Vvel
    new_state = ServerState(Vvel, Verr)

    pr = None
    if probes:
        pr = {"update_norm": _l2(update * lr),
              "momentum_norm": _psum_l2(Vvel, axis_name),
              "residual_norm": _psum_l2(Verr, axis_name),
              "mass_coverage": _coverage(
                  jnp.sum(jax.lax.square(vals)), dense_mass)}
    return ServerUpdate(update * lr, new_state, None, support,
                        probes=pr)
