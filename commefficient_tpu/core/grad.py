"""Per-client gradient computation: microbatching, clipping, weight
decay, differential privacy, sketching.

Functional counterpart of the reference's ``forward_grad``
(fed_worker.py:251-337). A *loss function* here is

    loss_fn(params_flat, batch) -> (loss, aux_metrics_tuple)

where ``batch`` is a dict of arrays whose leading axis is the sample
axis, including a ``"mask"`` float array marking real (1.0) vs padded
(0.0) samples — padding is how ragged per-client batches become static
shapes under jit (SURVEY.md §7 "hard parts"). ``loss`` must be the
masked *mean* over real samples (like the reference's per-microbatch
mean loss), and metrics likewise.

Reference semantics kept bit-for-bit-in-spirit:
- with microbatching, the gradient is the **sum over microbatches of
  the per-microbatch mean gradient** (a deliberate reference quirk:
  loss.backward() accumulates mean-loss grads, fed_worker.py:268-289 —
  which is why its clip threshold scales by num_iters);
- grad-norm clipping to ``max_grad_norm * num_iters`` for non-sketch
  modes (fed_worker.py:292-294);
- fused weight decay ``g += (wd / num_workers) * weights``
  (utils.py:254-259);
- DP: L2-clip to ``l2_norm_clip``; in worker mode add Gaussian noise
  scaled by ``noise_multiplier * sqrt(num_workers)``
  (fed_worker.py:306-311);
- sketch mode: sketch the gradient, then clip the *sketch* by its
  l2estimate if max_grad_norm is set (fed_worker.py:314-322).
"""

from __future__ import annotations

import math
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from commefficient_tpu.config import Config
from commefficient_tpu.ops.sketch import CountSketch, clip_record
from commefficient_tpu.ops.vec import clip_by_l2


def _masked_count(batch) -> jax.Array:
    return jnp.maximum(jnp.sum(batch["mask"]), 1.0)


def make_forward_grad(cfg: Config,
                      loss_fn: Callable,
                      sketch: Optional[CountSketch],
                      padded_batch_size: int):
    """Returns ``forward_grad(params_flat, batch, noise_rng) ->
    (transmit_unit, metrics)`` where ``transmit_unit`` is the
    per-sample-mean (possibly sketched) gradient and ``metrics`` is a
    tuple of batch-mean scalars led by the loss."""

    if cfg.microbatch_size > 0:
        mb = min(cfg.microbatch_size, padded_batch_size)
        num_iters = math.ceil(padded_batch_size / mb)
        pad_to = num_iters * mb
    else:
        mb, num_iters, pad_to = padded_batch_size, 1, padded_batch_size

    grad_loss = jax.grad(
        lambda p, b: loss_fn(p, b)[0], argnums=0)

    def one_microbatch(params_flat, microbatch):
        loss, metrics = loss_fn(params_flat, microbatch)
        n = jnp.sum(microbatch["mask"])
        g = grad_loss(params_flat, microbatch)
        # an all-padding microbatch contributes nothing (the reference
        # never creates one; padding does)
        valid = n > 0
        g = jnp.where(valid, g, 0.0)
        weighted = tuple(jnp.where(valid, m, 0.0) * n
                         for m in (loss,) + tuple(metrics))
        return g, weighted

    def forward_grad(params_flat, batch, noise_rng=None):
        if num_iters == 1:
            g, weighted = one_microbatch(params_flat, batch)
        else:
            def pad(x):
                pad_width = [(0, pad_to - x.shape[0])] + \
                    [(0, 0)] * (x.ndim - 1)
                return jnp.pad(x, pad_width)

            chunked = {k: pad(v).reshape((num_iters, mb) + v.shape[1:])
                       for k, v in batch.items()}

            def body(carry, microbatch):
                g_acc, w_acc = carry
                g, weighted = one_microbatch(params_flat, microbatch)
                return (g_acc + g,
                        tuple(a + w for a, w in zip(w_acc, weighted))), None

            n_metrics = len(loss_fn(params_flat,
                                    jax.tree_util.tree_map(
                                        lambda v: v[:1], batch))[1]) + 1
            # zero init tied to the batch (x*0 of a batch-derived
            # scalar): under shard_map a plain-zeros carry lacks the
            # body output's varying mesh axes (the gradient depends on
            # the client-sharded batch) and trips the scan carry check
            z = 0.0 * _masked_count(batch)
            init = (jnp.zeros(cfg.grad_size, jnp.float32) + z,
                    tuple(jnp.zeros(()) + z for _ in range(n_metrics)))
            (g, weighted), _ = jax.lax.scan(body, init, chunked)

        batch_size = _masked_count(batch)
        metrics = tuple(w / batch_size for w in weighted)

        # per-worker grad clipping, non-sketch (fed_worker.py:292-294);
        # the reference's num_iters comes from the *real* batch size
        # (fed_worker.py:267), so derive it from the mask, not padding
        if cfg.max_grad_norm is not None and cfg.mode != "sketch":
            real_iters = jnp.ceil(batch_size / mb)
            g = clip_by_l2(g, cfg.max_grad_norm * real_iters)

        # fused weight decay (utils.py:254-259)
        if cfg.weight_decay != 0:
            g = g + (cfg.weight_decay / cfg.num_workers) * params_flat

        # differential privacy (fed_worker.py:306-311); the noise
        # draw routes through privacy/ — the one module allowed raw
        # jax.random noise (analysis/lint.py noise-confinement)
        if cfg.do_dp:
            from commefficient_tpu.privacy import gaussian_noise
            g = clip_by_l2(g, cfg.l2_norm_clip)
            if cfg.dp_mode == "worker":
                assert noise_rng is not None
                noise = gaussian_noise(noise_rng, g.shape, g.dtype,
                                       std=cfg.noise_multiplier)
                g = g + noise * jnp.sqrt(float(cfg.num_workers))

        # DP sketching (--dp sketch, privacy/): L2-clip the client's
        # SUMMED dense gradient — the microbatch-accumulated total,
        # never divided by batch_size, so --dp_clip is calibrated at
        # summed-gradient scale — BEFORE sketching. Sketching is
        # linear, so the aggregated table is the sketch of the
        # clipped sums and the calibrated table noise
        # (core/rounds.py) covers a sqrt(r)·dp_clip/W sensitivity.
        # Trace-time gate: "off" emits today's program bit-for-bit.
        if getattr(cfg, "dp", "off") == "sketch":
            from commefficient_tpu.privacy import dp_clip
            g = dp_clip(g, cfg.dp_clip)

        # compression (fed_worker.py:314-322)
        if cfg.mode == "sketch":
            assert sketch is not None
            table = sketch.sketch(g)
            if cfg.max_grad_norm is not None:
                table = clip_record(table, cfg.max_grad_norm,
                                    is_sketch=True)
            return table, metrics

        return g, metrics

    return forward_grad


def make_eval_metrics(loss_fn: Callable):
    """Validation pass: metrics only, no gradient
    (fed_worker.py:180-183 with compute_grad=False)."""

    def eval_metrics(params_flat, batch) -> Tuple[jax.Array, ...]:
        loss, metrics = loss_fn(params_flat, batch)
        return (loss,) + tuple(metrics)

    return eval_metrics
