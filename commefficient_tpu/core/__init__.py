from commefficient_tpu.core.client import (  # noqa: F401
    accumulate_and_compress,
    ClientUpdate,
)
from commefficient_tpu.core.server import server_update, ServerState  # noqa: F401
