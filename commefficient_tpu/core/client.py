"""Client-side update pipeline: momentum, error feedback, compression.

Pure-functional counterpart of the reference worker's ``local_step``
(fed_worker.py:186-232). Operates on whatever the client transmits —
the flat gradient vector, or its (r, c) count-sketch table — given the
per-sample-mean gradient already produced by the model's forward/
backward (see core/grad.py for that part).

Exact reference semantics reproduced:
- the transmitted quantity is the *sum*-of-gradients over the client's
  batch: ``g = g_mean * batch_size`` (fed_worker.py:192);
- local momentum: ``velocity = g + m * velocity`` (fed_worker.py:195-197);
- local error accumulation: ``error += velocity`` (or ``g`` when no
  momentum), transmit the error (fed_worker.py:200-204);
- local_topk: transmit ``topk(to_transmit)``, then error feedback
  (zero error at transmitted coords) and momentum factor masking (zero
  velocity at transmitted coords) (fed_worker.py:206-218).

State that a mode doesn't use is represented as ``None`` (the
reference only allocates the big per-client arrays for modes that need
them, fed_aggregator.py:123-129).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from commefficient_tpu.config import Config
from commefficient_tpu.ops.topk import topk


class ClientUpdate(NamedTuple):
    transmit: jax.Array                    # what this client uploads
    velocity: Optional[jax.Array]          # updated local momentum, or None
    error: Optional[jax.Array]             # updated local error, or None


def accumulate_and_compress(cfg: Config,
                            g_unit: jax.Array,
                            velocity: Optional[jax.Array],
                            error: Optional[jax.Array],
                            batch_size: jax.Array) -> ClientUpdate:
    """One client's momentum/error/compression step.

    ``g_unit`` is the client's per-sample-mean gradient — already
    weight-decayed, clipped, DP-noised and (in sketch mode) sketched,
    i.e. the output of the reference's ``forward_grad``
    (fed_worker.py:251-337). ``batch_size`` is the client's true
    (unpadded) number of samples this round.
    """
    has_velocity = cfg.local_momentum > 0
    has_error = cfg.error_type == "local"
    assert (velocity is not None) == has_velocity
    assert (error is not None) == has_error

    # sum-of-gradients semantics; scaling commutes with sketching
    # (linear), matching the reference's compress-then-scale order
    g = g_unit * batch_size

    if has_velocity:
        velocity = g + cfg.local_momentum * velocity

    if has_error:
        error = error + (velocity if has_velocity else g)
        to_transmit = error
    else:
        to_transmit = velocity if has_velocity else g

    if cfg.mode == "local_topk":
        assert cfg.error_type in ("local", "none")
        to_transmit = topk(to_transmit, k=cfg.k,
                           approx=cfg.approx_topk,
                           recall=cfg.approx_recall)
        kept = to_transmit != 0
        if has_error:
            error = jnp.where(kept, 0.0, error)      # error feedback
        if has_velocity:
            velocity = jnp.where(kept, 0.0, velocity)  # momentum masking

    # invariants the reference asserts in the hot path
    # (fed_worker.py:221-230)
    if has_error:
        assert cfg.mode not in ("sketch", "uncompressed")
    if has_velocity:
        assert cfg.mode != "sketch"

    return ClientUpdate(to_transmit, velocity, error)


def stale_weight_download(cfg: Config,
                          ps_weights: jax.Array,
                          client_weights: jax.Array) -> jax.Array:
    """Simulated download compression for ``--topk_down`` (reference
    ``get_new_worker_weights``, fed_worker.py:234-249): the client
    catches up to the server by applying only the top-k of the weight
    difference to its stale local weights."""
    diff = ps_weights - client_weights
    if cfg.do_topk_down:
        diff = topk(diff, k=cfg.k, approx=cfg.approx_topk,
                    recall=cfg.approx_recall)
    return client_weights + diff
