"""The federated round as a single SPMD program.

Where the reference runs a round as: queue batches to worker processes
→ each worker loops over its clients serially → NCCL-reduce the summed
transmit → server step on the PS rank (call stack in SURVEY.md §3.1),
here a round is two jitted functions over a ``clients`` mesh:

- ``client_round``: vmap of the per-client local step over the W
  participating clients (sharded across devices), returning the summed
  transmit (one XLA all-reduce), per-client metrics, and updated
  per-client momentum/error rows;
- ``server_round``: the deterministic server update, replicated.

They are split (rather than fused) to mirror the reference's
FedModel.__call__ / FedOptimizer.step protocol — the LR scheduler sits
between them on the host (cv_train.py:198) — but both stay on device;
only scalar metrics ever cross to the host.

Batch layout: a dict of (W, B, ...) arrays with a (W, B) float "mask"
marking real samples — ragged client batches become static shapes via
padding (SURVEY.md §7). ``client_ids`` is (W,) int32.
"""

from __future__ import annotations

import functools
from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp

from commefficient_tpu.config import Config
from commefficient_tpu.core.client import (accumulate_and_compress,
                                           stale_weight_download)
from commefficient_tpu.core.grad import make_eval_metrics, make_forward_grad
from commefficient_tpu.core.server import (ServerState, ServerUpdate,
                                           server_update,
                                           staleness_weights)
from commefficient_tpu.ops.sketch import CountSketch


class ClientStates(NamedTuple):
    """Per-client persistent state, rows sharded over the mesh
    (reference: host shared-memory tensors, fed_aggregator.py:105-129).
    Fields a mode doesn't use are None — never allocated."""
    velocities: Optional[jax.Array]  # (num_clients, *transmit_shape)
    errors: Optional[jax.Array]      # (num_clients, *transmit_shape)
    weights: Optional[jax.Array]     # (num_clients, grad_size), topk_down only

    @staticmethod
    def init(cfg: Config, num_clients: int,
             ps_weights: Optional[jax.Array] = None,
             sharding=None) -> "ClientStates":
        """``sharding`` (a NamedSharding over the client axis) creates
        the big (rows, ...) buffers directly sharded — at
        EMNIST/PERSONA scale a replicated allocation would not fit one
        device. NamedSharding requires the leading dim to divide the
        mesh, so rows are padded up to the next multiple; padded rows
        are never indexed (client ids < num_clients)."""
        rows = num_clients
        if sharding is not None:
            from commefficient_tpu.parallel.mesh import padded_rows
            rows = padded_rows(num_clients, sharding.mesh)
        shape = (rows,) + cfg.transmit_shape
        vel = (jnp.zeros(shape, jnp.float32, device=sharding)
               if cfg.local_momentum > 0 else None)
        err = (jnp.zeros(shape, jnp.float32, device=sharding)
               if cfg.error_type == "local" else None)
        wts = None
        if cfg.do_topk_down:
            assert ps_weights is not None
            wts = (jnp.zeros((rows, cfg.grad_size), jnp.float32,
                             device=sharding) + ps_weights[None, :])
        return ClientStates(vel, err, wts)


class RoundResult(NamedTuple):
    aggregated: jax.Array        # transmit-sum / total datapoints
    metrics: tuple               # per-client batch-mean metrics, each (W,)
    client_states: ClientStates
    # (stats_pytree, alive_scalar) when a stats_fn is configured —
    # the sample-weighted mean of participating clients' batch
    # statistics this round (BatchNorm running-stats parity mode)
    bn_stats: Optional[tuple] = None
    # schema-v2 client-pass probe scalars (--probe_every): aggregate
    # norm + NaN/Inf counts, per-client transmit-norm statistics
    # (paths that materialise per-client transmits), and — on probe
    # cadence rounds in sketch mode — the true recovery error against
    # the dense gradient. None unless the round was built with
    # ``probes=True``; probes-off builds stay HLO-identical.
    probes: Optional[dict] = None


_AUTO_ROT_LANES = 1024


def resolve_rot_lanes(cfg: Config) -> int:
    """Resolve ``--sketch_rot_lanes -1`` (auto, the default).

    Quantized rotations pay a heavier collision tail (rot_lanes/c for
    same-lane-offset pairs instead of 1/c) and buy a single sublane
    roll ONLY inside the Pallas TPU kernels — so auto engages 1024
    exactly where that trade was measured to win with no quality cost:
    a TPU default backend at a Pallas-supported, lane-aligned,
    large-d geometry (−44% on the sketch/estimates kernel pair at
    d=124M, −8% on the flagship GPT-2 federated round; 24-epoch
    anchor tail accuracy at parity with full-granularity rotations at
    both seeds — BENCHMARKS.md round-5 sections). Everywhere else
    auto resolves to 0 (full granularity). Explicit values pass
    through untouched. The default-backend probe lives here, NOT in
    CountSketch.__post_init__: round build runs after any
    jax.distributed initialization / platform selection."""
    lanes = getattr(cfg, "sketch_rot_lanes", 0)
    if lanes >= 0:
        return lanes
    from commefficient_tpu.ops.sketch_pallas import supported
    d, c, r = cfg.grad_size, cfg.num_cols, cfg.num_rows
    # c % 1024 == 0 also implies _pick_lanes(c) == 1024 (it probes
    # 1024 first), so the sublane fast path's rot_step % L == 0
    # precondition holds whenever the modulus check passes
    if (d < (1 << 20) or not supported(d, c, r)
            or c % _AUTO_ROT_LANES or c // _AUTO_ROT_LANES < 8):
        return 0
    return (_AUTO_ROT_LANES
            if jax.default_backend() in ("tpu", "axon") else 0)


def sketch_is_late(cfg: Config) -> bool:
    """Sketch-mode fast path predicate: sketching after the local
    dense sum (linearity) is legal whenever no per-client op touches
    the table — i.e. absent ``max_grad_norm``'s per-sketch clip.
    Robust folds need per-client sketches (median-of-sketches), so
    ``--robust_agg`` also forces the early-sketch path."""
    return (cfg.mode == "sketch" and cfg.max_grad_norm is None
            and getattr(cfg, "robust_agg", "none") == "none")


def fused_grad_eligible(cfg: Config) -> bool:
    """Fused-gradient fast path predicate: the aggregated quantity is
    exactly the gradient of the sample-weighted mean loss (one
    backward, no (W, d) buffer) when no per-client transform touches
    the gradient. Shared by ``build_client_round`` and
    ``round_plan`` so the telemetry meta record cannot drift from the
    program actually built."""
    return (cfg.mode in ("sketch", "uncompressed", "true_topk")
            and cfg.local_momentum == 0 and cfg.error_type != "local"
            and not cfg.do_topk_down and not cfg.do_dp
            and getattr(cfg, "dp", "off") == "off"
            and cfg.max_grad_norm is None and cfg.microbatch_size <= 0
            and getattr(cfg, "robust_agg", "none") == "none")


def round_plan(cfg: Config) -> dict:
    """Static description of the round program this Config builds —
    which fast paths engage, what one client transmits, what the
    geometry is. Logged once per run as the ledger's meta record
    (telemetry/record.py) so a ledger is interpretable without the
    launching command line."""
    plan = {
        "mode": cfg.mode,
        "error_type": cfg.error_type,
        "grad_size": int(cfg.grad_size),
        "num_workers": int(cfg.num_workers),
        "transmit_shape": list(cfg.transmit_shape),
        "upload_floats_per_client": int(cfg.upload_floats_per_client),
        "fused_grad": fused_grad_eligible(cfg),
        "robust_agg": getattr(cfg, "robust_agg", "none"),
        "pipeline_depth": int(getattr(cfg, "pipeline_depth", 1)),
        "client_chunk": int(getattr(cfg, "client_chunk", 0)),
        "overlap_depth": int(getattr(cfg, "overlap_depth", 1)),
        "clientstore": getattr(cfg, "clientstore", "device"),
        "async_buffer_size": int(getattr(cfg, "async_buffer_size", 0)
                                 or 0),
        "async_staleness_weight": float(
            getattr(cfg, "async_staleness_weight", 0.0) or 0.0),
    }
    plan["sketch_dtype"] = getattr(cfg, "sketch_dtype", "f32")
    plan["downlink_encoding"] = getattr(cfg, "downlink_encoding",
                                        "dense")
    if getattr(cfg, "dp", "off") != "off":
        # enough to re-derive the accountant (and the perf-gate's
        # p<eps> key fragment) from the ledger alone
        plan["dp"] = {"mode": str(cfg.dp),
                      "clip": float(cfg.dp_clip),
                      "noise_mult": float(cfg.dp_noise_mult),
                      "delta": float(cfg.dp_delta),
                      "epsilon_budget": float(cfg.dp_epsilon)}
    plan["upload_wire_bytes_per_client"] = float(
        cfg.upload_wire_bytes_per_client)
    if cfg.mode == "sketch":
        plan["sketch"] = {"rows": int(cfg.num_rows),
                          "cols": int(cfg.num_cols),
                          "blocks": int(cfg.num_blocks),
                          "k": int(cfg.k),
                          "late": sketch_is_late(cfg),
                          "rot_lanes": resolve_rot_lanes(cfg)}
    if cfg.mode in ("true_topk", "local_topk"):
        plan["k"] = int(cfg.k)
    if str(getattr(cfg, "autopilot", "off")) == "on":
        # knob-lattice walk parameters: enough to interpret (and
        # replay-check) a ledger whose rounds were dispatched through
        # the bucketed re-jit cache rather than one static program
        from commefficient_tpu.autopilot.lattice import (build_ladder,
                                                         key_of,
                                                         key_str)
        plan["autopilot"] = {
            "band": str(cfg.autopilot_band),
            "cooldown": int(cfg.autopilot_cooldown),
            "cache_size": int(cfg.autopilot_cache_size),
            "warm_ahead": bool(cfg.autopilot_warm_ahead),
            "pin": str(getattr(cfg, "autopilot_pin", "") or ""),
            "base": key_str(key_of(cfg)),
            "ladder": [key_str(k) for k in build_ladder(cfg)],
        }
    return plan


def args2sketch(cfg: Config) -> Optional[CountSketch]:
    """(reference fed_aggregator.py:466-469)"""
    if cfg.mode != "sketch":
        return None
    return CountSketch(d=cfg.grad_size, c=cfg.num_cols, r=cfg.num_rows,
                       num_blocks=cfg.num_blocks, seed=cfg.seed,
                       approx_topk=cfg.approx_topk,
                       approx_recall=cfg.approx_recall,
                       rot_lanes=resolve_rot_lanes(cfg))


def build_client_round(cfg: Config, loss_fn: Optional[Callable],
                       padded_batch_size: int,
                       mesh=None, stats_fn: Callable = None,
                       tree_loss: Callable = None,
                       unravel: Callable = None,
                       dense_rows: bool = False,
                       probes: bool = False,
                       probe_recovery: bool = False,
                       transmit_transform: Callable = None,
                       client_weights: bool = False) -> Callable:
    """Returns jit-able
    ``client_round(ps_weights, client_states, batch, client_ids, rng,
    fedavg_lr) -> RoundResult``.

    ``client_weights=True`` (the asyncfed buffered-arrival driver)
    appends a seventh argument — ``staleness``, (W,) float32 rounds
    each folded update waited in the arrival buffer — and compiles
    the staleness-weighted fold into the round: each client's
    transmit AND its datapoint count scale by
    ``1/(1+staleness)^{--async_staleness_weight}`` before the fold
    (core/server.staleness_weights), so the aggregate stays a
    weighted per-datapoint mean and stale mass never corrupts the
    server's virtual momentum/EF. At alpha == 0 the weighting branch
    is skipped at trace time (weights are identically 1), which is
    what makes the degenerate K == cohort configuration bit-exact
    against the synchronous round; the default ``False`` traces
    nothing and async-off builds stay HLO-identical.

    ``probes=True`` fills ``RoundResult.probes`` with the cheap O(d)
    diagnostics (aggregate norm/NaN/Inf, per-client transmit-norm
    stats where per-client transmits exist). ``probe_recovery=True``
    (sketch mode, the ``--probe_every`` cadence variant) additionally
    computes the TRUE recovery error ‖unsketch(S(g)) − g‖/‖g‖ against
    the dense aggregated gradient — paths where the dense aggregate
    doesn't naturally exist materialise it only in this variant (the
    clipped per-client-sketch path cannot and omits the key). Both are
    trace-time flags: with both False the emitted program is identical
    to a build without them.

    ``dense_rows``: host-clientstore mode (runtime/fed_model.py) — the
    ``client_states`` arrays hold ONLY the round's W participant rows
    (gathered host-side, ordered like ``client_ids``), so state rows
    are indexed by POSITION while the RNG folding below keeps the real
    client ids: every per-client stream is bit-identical to the
    device-resident path.

    Sketch-mode fast path: because sketching is linear and (absent
    ``max_grad_norm``'s per-sketch clip) no per-client op touches the
    table, each device sums its local clients' *dense* gradients and
    sketches **once**, then a single psum of (r, c) tables crosses the
    ICI — identical math to per-client sketching (the FetchSGD
    linearity identity), at 1/clients_per_device the sketch cost and
    with compressed inter-chip traffic. Pass ``mesh`` to enable; falls
    back to sketch-of-local-sum without one.

    ``transmit_transform``: optional traceable
    ``(transmit, batch, client_ids, rng) -> transmit`` applied to the
    materialised per-client transmit stack before the fold — the
    chaos harness's byzantine-attack hook (data/chaos.py; this module
    deliberately never imports chaos). Passing one forces the
    per-client path (the fused program has no per-client transmits);
    the default ``None`` is never traced, keeping the round program
    bit-identical to a build without the parameter.
    """
    cfg.validate_runtime()
    # recovery needs probes on and a sketch to recover from
    probe_recovery = bool(probes and probe_recovery
                          and cfg.mode == "sketch")
    if loss_fn is None:
        # flat loss derived from the tree loss: callers holding a
        # pytree-level loss need not duplicate the unravel closure
        assert tree_loss is not None and unravel is not None, \
            "need loss_fn, or tree_loss + unravel to derive it"

        def loss_fn(p, b):
            return tree_loss(unravel(p), b)

    sketch = args2sketch(cfg)
    sketch_late = sketch_is_late(cfg)
    # Trace-time gate: robust folds replace the mean over materialised
    # per-client transmits; at the default "none" the branch below is
    # never traced and the round program is bit-identical to today's
    # (pinned by test_probes_off_program_identical).
    robust = getattr(cfg, "robust_agg", "none") != "none"
    if transmit_transform is not None:
        assert getattr(cfg, "client_chunk", 0) == 0, \
            "transmit_transform needs the full per-client transmit " \
            "stack; incompatible with --client_chunk"
    # Staleness-weighted fold (asyncfed): a trace-time gate like
    # probes/robust. alpha == 0 means every weight is exactly 1, so
    # the branch is skipped and a K == cohort buffered fold is
    # bit-identical to the synchronous round.
    alpha = float(getattr(cfg, "async_staleness_weight", 0.0))
    weighted = client_weights and alpha != 0.0
    if client_weights:
        assert getattr(cfg, "client_chunk", 0) == 0, \
            "client_weights needs the full per-client transmit " \
            "stack; incompatible with --client_chunk"
    # Fused-gradient fast path: when no per-client transform touches
    # the gradient (no local momentum/error, clip, DP, topk_down or
    # microbatching), the aggregated quantity is exactly the gradient
    # of the sample-weighted mean loss over ALL clients' real samples
    # (+ the analytic weight-decay term). One backward pass then
    # accumulates straight into a single (d,) vector — the (W, d)
    # per-client gradient buffer, its dynamic-update-slices and the
    # cross-client reduction disappear from the program. On a mesh
    # (clients divisible across devices) each device runs the fused
    # backward over its local clients and ONE psum crosses the ICI —
    # of (r, c) sketch tables in sketch mode (compressed traffic, the
    # FetchSGD linearity identity), of the dense gradient otherwise.
    fused_grad = (fused_grad_eligible(cfg)
                  and transmit_transform is None)
    if cfg.mode == "fedavg":
        per_client = _build_fedavg_client_step(cfg, loss_fn,
                                               padded_batch_size)
    elif fused_grad:
        per_client = None
    else:
        step_cfg = cfg.replace(mode="uncompressed", error_type="none",
                               grad_size=cfg.grad_size) \
            if sketch_late else cfg
        per_client = _build_sgd_client_step(step_cfg, loss_fn,
                                            None if sketch_late else sketch,
                                            padded_batch_size)

    # Tree-space backward for the fused sketch path: differentiate
    # w.r.t. the PARAM PYTREE and sketch the leaf gradients directly
    # (CountSketch.sketch_from_leaves). Mathematically identical to
    # the flat-primal path — the flat gradient is exactly the
    # concatenation of the leaf gradients — but autodiff's
    # transpose-of-unravel (a d-sized concatenate) and sketch's pad
    # copy collapse into the kernel-input assembly, removing two
    # 124M-coord copies per round at GPT-2 scale (round-3 xplane
    # "concat/pad ~6 ms", VERDICT weak #5).
    tree_sketch = (cfg.mode == "sketch" and tree_loss is not None
                   and unravel is not None)

    # Quantized wire path (--sketch_dtype, ops/quant.py): a trace-time
    # gate like probes/robust — at the default "f32" none of the
    # branches below are traced and the round program stays
    # bit-identical (pinned by test_quant_f32_program_identical).
    wire = getattr(cfg, "sketch_dtype", "f32")
    quantized = cfg.mode == "sketch" and wire != "f32"

    # DP sketching (--dp sketch, privacy/): the calibrated Gaussian
    # noise lands on the f32 AGGREGATED table — after the fold's
    # datapoint normalisation, before any wire quantization — so the
    # released value is exactly what the accountant charges for and
    # the int8/fp8 qdq that follows is free post-processing. Inner
    # per-client / collective quantization is therefore disabled
    # under DP (tables cross at f32) and the round's one qdq runs on
    # the noisy table below. Trace-time gate: "off" traces nothing
    # and the program is bit-identical to a build without the flag.
    dp_on = getattr(cfg, "dp", "off") == "sketch"
    dp_qdq = quantized and dp_on
    if dp_on:
        quantized = False

    # Latency-hiding round pipeline (--overlap_depth, sketch mode):
    # emit and cross the table in min(depth, r) disjoint row chunks,
    # each chunk's collective issued as soon as its rows are quantized
    # so XLA's latency-hiding scheduler runs chunk i's wire crossing
    # under chunk i+1's compute. Per-row scales make every chunk's
    # quantize + harmonize exactly the row slice of the whole-table
    # algebra, so the folded table is bit-identical at any depth. A
    # trace-time gate like probes/robust: depth 1 traces none of the
    # chunked branches and the program stays bit-identical (pinned by
    # test_probes_off_program_identical).
    depth = int(getattr(cfg, "overlap_depth", 1))
    overlap = cfg.mode == "sketch" and depth > 1

    def _quantize_for_collective(t, axes, n_addends):
        """Local f32 table -> (wire-dtype table, shared scale) ready
        for a wire-dtype psum/psum_scatter (parallel/wire.py owns the
        mesh-facing crossing; ops/quant.py the algebra)."""
        from commefficient_tpu.parallel import wire as wirex
        return wirex.quantize_for_collective(t, wire, axes, n_addends)

    def _qdq_local(t):
        """Single-shard wire crossing: quantize at full range,
        immediately dequantize (n_addends=1 — harmonize is an exact
        identity, so this matches the NumPy mirror bit-for-bit)."""
        from commefficient_tpu.ops import quant
        q, scale = quant.quantize_table(t, wire)
        return quant.dequantize(q, scale)

    def _qdq_local_overlapped(t):
        """Single-shard crossing under --overlap_depth: per-row-chunk
        quantize-dequantize, folded in emission order. Scales are
        per-row, so each chunk's qdq IS the row slice of the
        whole-table qdq — bit-identical result, chunked program (the
        single-device mirror of the chunked collective pipeline)."""
        from commefficient_tpu.core.server import fold_row_chunks
        from commefficient_tpu.parallel.wire import row_chunks
        return fold_row_chunks(
            _qdq_local(jax.lax.slice_in_dim(t, off, off + cnt, axis=0))
            for off, cnt in row_chunks(t.shape[0], depth))

    def _partial_table_emit(g):
        """2D-mesh sketch emission for one model peer: sketch ONLY
        this peer's contiguous ⌈d/M⌉ coordinate slice of the dense
        gradient (slices are disjoint, so the model-axis SUM of the
        partial tables is the sketch of the full gradient — the same
        linearity identity the late-sketch path rests on), then one
        reduce-scatter leaves each peer holding its (r, c/M) column
        shard. Replaces replicate + all-reduce: per-link wire bytes
        drop from 4·r·c to 4·r·c/M and no device ever materialises
        the full table during emission. Tail-shard padding slots are
        zero-valued (a scatter-add of 0 at a clamped index is a
        no-op), so uneven d/M needs no special casing."""
        from commefficient_tpu.parallel.mesh import (MODEL_AXIS,
                                                     model_axis_size)
        M = model_axis_size(mesh)
        d = cfg.grad_size
        n_loc = -(-d // M)
        pad = n_loc * M - d
        gp = jnp.pad(g, (0, pad)) if pad else g
        start = (jax.lax.axis_index(MODEL_AXIS)
                 * n_loc).astype(jnp.int32)
        vals = jax.lax.dynamic_slice(gp, (start,), (n_loc,))
        idx = start + jnp.arange(n_loc, dtype=jnp.int32)
        vals = jnp.where(idx < d, vals, 0.0)
        partial = sketch.sketch_sparse(jnp.minimum(idx, d - 1), vals)
        if overlap:
            # chunked emission: slice the partial table into disjoint
            # row chunks and issue each chunk's model-axis
            # reduce-scatter as soon as its rows are quantized — the
            # unrolled interleaving is what lets the scheduler overlap
            # chunk i's collective with chunk i+1's quantize. Returns
            # the per-chunk results in row order; the client-axis
            # crossing (_client_psum) folds them back. Same headroom
            # algebra per chunk (C*M addends), same ledger bytes: N
            # collectives of cnt·c/M wire elements sum to one of
            # r·c/M.
            from commefficient_tpu.parallel import wire as wirex
            from commefficient_tpu.parallel.mesh import (
                CLIENT_AXIS, client_axis_size)
            C = client_axis_size(mesh)
            chunks = []
            for off, cnt in wirex.row_chunks(sketch.r, depth):
                part = jax.lax.slice_in_dim(partial, off, off + cnt,
                                            axis=0)
                if quantized:
                    q, scale = _quantize_for_collective(
                        part, (CLIENT_AXIS, MODEL_AXIS), C * M)
                    chunks.append(
                        (wirex.wire_reduce_scatter(q, MODEL_AXIS),
                         scale))
                else:
                    chunks.append(jax.lax.psum_scatter(
                        part, MODEL_AXIS, scatter_dimension=1,
                        tiled=True))
            return chunks
        if quantized:
            # quantize the shard-local partial BEFORE the collective:
            # the reduce-scatter moves wire-dtype bytes (r·c·wb per
            # link instead of 4·r·c) and the full-width f32 table
            # still never materialises. Headroom covers every addend
            # the downstream chain sums in wire dtype: M partials in
            # the scatter x C client shards in the following psum.
            from commefficient_tpu.parallel import wire as wirex
            from commefficient_tpu.parallel.mesh import (
                CLIENT_AXIS, client_axis_size)
            C = client_axis_size(mesh)
            q, scale = _quantize_for_collective(
                partial, (CLIENT_AXIS, MODEL_AXIS),
                C * M)
            return wirex.wire_reduce_scatter(q, MODEL_AXIS), scale
        return jax.lax.psum_scatter(partial, MODEL_AXIS,
                                    scatter_dimension=1, tiled=True)

    def _fused_local(ps_weights, batch, total, n_shards,
                     with_dense=False, emit=None, cw=None):
        """Fused backward over the clients in ``batch`` (all of them
        single-device; one device's shard under shard_map), already
        normalised by the GLOBAL datapoint total. The weight-decay
        term is split evenly across shards so the cross-shard sum
        reconstructs (wd/num_workers)·p exactly once — ``n_shards``
        is the number of CLIENT-axis shards (cross-shard sums are
        psums over ``clients``; on a 2D mesh the model peers hold
        coordinate-disjoint slices, never copies, so they must not
        enter the split).

        ``emit`` (2D mesh only) replaces the transmit construction on
        the dense flat gradient — the shard-local partial-sketch +
        reduce-scatter above. The tree-sketch path materialises the
        flat concatenation first in that case: coordinate slicing
        needs the flat layout. ``with_dense`` (probe cadence rounds
        only) appends the dense flat gradient to the return — the
        recovery-error probe's ground truth.

        ``cw`` (asyncfed, weighted builds only): this shard's (W,)
        per-client staleness weights. Each client's loss term scales
        by cw_i·n_i against the already-weighted global ``total``, so
        the fused gradient equals Σ cw_i·t_i / Σ cw_i·n_i — exactly
        the weighted per-client fold."""

        def make_local_loss(fn):
            def local_loss(p):
                def one(b, cwi=None):
                    loss, metrics = fn(p, b)
                    n = jnp.sum(b["mask"])
                    # guard all-padding clients: their (meaningless)
                    # loss must not poison the weighted sum (cf. the
                    # non-fused path's masking in core/grad.py)
                    w = jnp.where(n > 0, loss * n, 0.0)
                    if cwi is not None:
                        w = w * cwi
                    mets = tuple((n > 0) * m
                                 for m in (loss,) + tuple(metrics))
                    return w, mets

                if cw is None:
                    weighted_l, metrics = jax.vmap(one)(batch)
                else:
                    weighted_l, metrics = jax.vmap(one)(batch, cw)
                return jnp.sum(weighted_l) / total, metrics

            return local_loss

        # Weight-decay share of this shard. At the default (no
        # dropout) the even 1/n_shards split keeps today's program;
        # under --dropout_prob the share becomes this shard's alive-
        # datapoint fraction so the cross-shard sum matches the
        # per-client path exactly: full (wd/num_workers)·p while any
        # client survives, exact zero on a fully-dropped round (the
        # per-client path's dead transmits are zeros — the fused path
        # must not keep decaying weights on a round nobody joined).
        if cw is not None:
            # weighted build: the wd share is this shard's weighted
            # alive-datapoint fraction, matching the per-client
            # path's Σ cw_i·n_i·(wd/num_workers)·p / total exactly
            n_per = jax.vmap(lambda b: jnp.sum(b["mask"]))(batch)
            wd_frac = jnp.sum(cw * n_per) / total
        elif getattr(cfg, "dropout_prob", 0.0) > 0:
            wd_frac = jnp.sum(batch["mask"]) / total
        else:
            wd_frac = None  # even split — today's exact constants

        def _wd_coef():
            if wd_frac is None:
                return cfg.weight_decay / cfg.num_workers / n_shards
            return (cfg.weight_decay / cfg.num_workers) * wd_frac

        if tree_sketch:
            tree = unravel(ps_weights)
            (_, metrics), g_tree = jax.value_and_grad(
                make_local_loss(tree_loss), has_aux=True)(tree)
            if cfg.weight_decay != 0:
                coef = _wd_coef()
                # decay in f32 regardless of leaf dtype: the flat path
                # computes g + coef*p on the f32 flat vector, and
                # sketch_from_leaves casts leaves to f32 anyway — a
                # sub-f32 param_dtype must not make the tree path
                # accumulate the decay at lower precision than flat
                g_tree = jax.tree_util.tree_map(
                    lambda g, p: (g.astype(jnp.float32)
                                  + coef * p.astype(jnp.float32)),
                    g_tree, tree)
            leaves = jax.tree_util.tree_leaves(g_tree)
            if emit is not None:
                # 2D emission needs the flat coordinate layout (each
                # model peer sketches a contiguous slice) — the flat
                # concatenation comes back, but the per-link payload
                # still drops to (r, c/M)
                flat = jnp.concatenate(
                    [jnp.ravel(l).astype(jnp.float32)
                     for l in leaves])
                if with_dense:
                    return emit(flat), metrics, flat
                return emit(flat), metrics
            table = sketch.sketch_from_leaves(leaves)
            if with_dense:
                return table, metrics, jnp.concatenate(
                    [jnp.ravel(l).astype(jnp.float32)
                     for l in leaves])
            return table, metrics

        (_, metrics), g = jax.value_and_grad(
            make_local_loss(loss_fn), has_aux=True)(ps_weights)
        if cfg.weight_decay != 0:
            # Σ_i (wd/num_workers)·p·n_i / total = (wd/num_workers)·p
            g = g + _wd_coef() * ps_weights
        if emit is not None:
            t = emit(g)
        else:
            t = sketch.sketch(g) if cfg.mode == "sketch" else g
        if with_dense:
            return t, metrics, g
        return t, metrics

    def client_round_fused(ps_weights, client_states: ClientStates,
                           batch, client_ids, rng,
                           fedavg_lr=1.0, staleness=None) -> RoundResult:
        del rng, fedavg_lr
        W = client_ids.shape[0]
        if weighted:
            cw = staleness_weights(staleness, alpha)
            n_per = jax.vmap(lambda b: jnp.sum(b["mask"]))(batch)
            total = jnp.maximum(jnp.sum(cw * n_per), 1.0)
        else:
            cw = None
            total = jnp.maximum(jnp.sum(batch["mask"]), 1.0)
        from commefficient_tpu.parallel.mesh import (client_axis_size,
                                                     model_axis_size)
        ndev = mesh.devices.size if mesh is not None else 1
        C = client_axis_size(mesh)
        # 2D mesh sketch emission: partial-sketch + reduce-scatter
        # over ``model`` — the aggregated table leaves the round
        # column-sharded (parallel/mesh.table_shard_spec). Dense
        # modes keep the replicated emission on any mesh shape (their
        # server state shards under GSPMD instead, build_server_round)
        shard2d = model_axis_size(mesh) > 1 and cfg.mode == "sketch"
        # recovery probe needs the dense aggregate next to the table;
        # in non-sketch fused modes the aggregate IS dense and there
        # is no recovery to measure
        want_dense = probe_recovery and cfg.mode == "sketch"
        dense_g = None
        if ndev > 1 and W % C == 0:
            from commefficient_tpu.parallel.mesh import (
                CLIENT_AXIS, client_spec, replicated_spec, shard_map,
                table_shard_spec)

            def _client_psum(t):
                """The table's client-axis all-reduce — in wire dtype
                on the quantized path (the table crosses the ICI at
                wire width; dequantized right after, so the server
                only ever sees f32). Under --overlap_depth the
                crossing runs per row chunk, interleaved with the
                chunk quantizes, and the chunk-ordered fold
                (core/server.fold_row_chunks) reassembles the
                table."""
                if overlap:
                    from commefficient_tpu.core.server import \
                        fold_row_chunks
                    from commefficient_tpu.parallel import wire as wirex
                    if shard2d:
                        # emit handed back per-chunk reduce-scattered
                        # shards (quantized: with their scales)
                        if quantized:
                            return fold_row_chunks(
                                wirex.wire_allreduce(q, s, CLIENT_AXIS)
                                for q, s in t)
                        return fold_row_chunks(
                            jax.lax.psum(ch, CLIENT_AXIS) for ch in t)
                    return wirex.chunked_quantize_allreduce(
                        t, wire if quantized else "f32",
                        (CLIENT_AXIS,), C, CLIENT_AXIS, depth)
                if not quantized:
                    return jax.lax.psum(t, CLIENT_AXIS)
                from commefficient_tpu.parallel import wire as wirex
                if shard2d:
                    q, scale = t  # emit quantized + reduce-scattered
                else:
                    q, scale = _quantize_for_collective(
                        t, (CLIENT_AXIS,), C)
                return wirex.wire_allreduce(q, scale, CLIENT_AXIS)

            def block(p, local_batch, tot, *rest):
                # mark the replicated params as device-varying before
                # differentiating: otherwise shard_map's transpose
                # rule auto-psums the DENSE per-device gradient to
                # keep the cotangent replicated — a d-sized
                # all-reduce that defeats the compressed-table
                # traffic (and would double-count with ours)
                cw_loc = rest[0] if rest else None
                if hasattr(jax.lax, "pcast"):
                    p = jax.lax.pcast(p, CLIENT_AXIS, to="varying")
                else:
                    from commefficient_tpu.compat import pvary
                    p = pvary(p, CLIENT_AXIS)
                emit = _partial_table_emit if shard2d else None
                if want_dense:
                    # probed cadence round: the dense gradient crosses
                    # the ICI too — the one round where uncompressed
                    # traffic is the price of the ground-truth probe
                    t, metrics, g = _fused_local(p, local_batch, tot,
                                                 C, with_dense=True,
                                                 emit=emit, cw=cw_loc)
                    return (_client_psum(t),
                            jax.lax.psum(g, CLIENT_AXIS), metrics)
                t, metrics = _fused_local(p, local_batch, tot, C,
                                          emit=emit, cw=cw_loc)
                # the round's ONE all-reduce (reference
                # fed_worker.py:139-140 NCCL reduce): sketch tables in
                # sketch mode — inter-chip traffic stays compressed,
                # and on a 2D mesh it runs on the already
                # reduce-scattered (r, c/M) shard
                return _client_psum(t), metrics

            agg_spec = (table_shard_spec() if shard2d
                        else replicated_spec())
            # weighted builds shard the staleness weights along the
            # client axis next to the batch
            wex = (cw,) if cw is not None else ()
            wspec = (client_spec(),) if cw is not None else ()
            if want_dense:
                aggregated, dense_g, metrics = shard_map(
                    block, mesh=mesh,
                    in_specs=(replicated_spec(), client_spec(),
                              replicated_spec()) + wspec,
                    out_specs=(agg_spec, replicated_spec(),
                               client_spec()))(ps_weights, batch,
                                               total, *wex)
            else:
                aggregated, metrics = shard_map(
                    block, mesh=mesh,
                    in_specs=(replicated_spec(), client_spec(),
                              replicated_spec()) + wspec,
                    out_specs=(agg_spec, client_spec()))(ps_weights,
                                                         batch, total,
                                                         *wex)
        elif want_dense:
            aggregated, metrics, dense_g = _fused_local(
                ps_weights, batch, total, 1, with_dense=True, cw=cw)
            if quantized:
                aggregated = (_qdq_local_overlapped(aggregated)
                              if overlap else _qdq_local(aggregated))
        else:
            aggregated, metrics = _fused_local(ps_weights, batch,
                                               total, 1, cw=cw)
            if quantized:
                # single-shard wire crossing: quantize-dequantize the
                # aggregated table at full range (exactly the NumPy
                # mirror's np_quantize_table/np_dequantize_table)
                aggregated = (_qdq_local_overlapped(aggregated)
                              if overlap else _qdq_local(aggregated))
        pr = None
        if probes:
            pr = _agg_probes(aggregated)
            if dense_g is not None:
                pr["recovery_error"] = sketch.recovery_error(
                    aggregated, dense_g, cfg.k)
        return RoundResult(aggregated, metrics, client_states,
                           _round_bn_stats(stats_fn, ps_weights, batch),
                           probes=pr)

    def client_round(ps_weights, client_states: ClientStates, batch,
                     client_ids, rng, fedavg_lr=1.0,
                     staleness=None) -> RoundResult:
        W = client_ids.shape[0]
        real_ids = client_ids  # pre-sentinel ids for the chaos hook
        rngs = jax.vmap(lambda i: jax.random.fold_in(rng, i))(client_ids)

        # dead slots (the loader pads ragged rounds with id 0 and an
        # all-zero mask) must not touch client 0's state — and a real
        # client 0 in the same round would otherwise RACE the pad's
        # no-op row in the state scatter (duplicate indices, order
        # unspecified). Remap them to an out-of-range id: gathers
        # clamp (values unused), scatters drop. In dense_rows mode the
        # state arrays hold only this round's W rows, so state indices
        # are slot POSITIONS (same sentinel treatment); the rngs above
        # were already folded from the REAL ids.
        if dense_rows:
            client_ids = _state_ids(
                jnp.arange(W, dtype=client_ids.dtype), batch)
        else:
            client_ids = _state_ids(client_ids, batch)

        chunk = getattr(cfg, "client_chunk", 0)
        ndev = mesh.devices.size if mesh is not None else 1
        from commefficient_tpu.parallel.mesh import model_axis_size
        shard2d_late = (model_axis_size(mesh) > 1
                        and cfg.mode == "sketch" and sketch_late)
        if 0 < chunk < W and ndev == 1:
            return _client_round_chunked(ps_weights, client_states,
                                         batch, client_ids, rngs,
                                         fedavg_lr, chunk)

        vel_rows = (client_states.velocities[client_ids]
                    if client_states.velocities is not None else None)
        err_rows = (client_states.errors[client_ids]
                    if client_states.errors is not None else None)
        wt_rows = (client_states.weights[client_ids]
                   if client_states.weights is not None else None)

        transmit, metrics, new_vel, new_err, new_wts = jax.vmap(
            per_client, in_axes=(None, 0, 0, 0, 0, 0, None)
        )(ps_weights, _some(vel_rows, W), _some(err_rows, W),
          _some(wt_rows, W), batch, rngs, fedavg_lr)

        if transmit_transform is not None:
            transmit = transmit_transform(transmit, batch, real_ids,
                                          rng)

        if quantized and not sketch_late:
            # per-client uploads (the clipped / robust early-sketch
            # paths materialise per-client tables): each client's
            # table crosses the wire quantized at full range and the
            # server dequantizes before the fold — a dead client's
            # all-zero table survives exactly (scale guard in
            # ops/quant.py)
            transmit = jax.vmap(_qdq_local)(transmit)

        # Σ_clients transmit, ÷ total datapoints — one all-reduce
        # (reference fed_worker.py:131-140 + fed_aggregator.py:328-334)
        # Weighted (asyncfed) builds fold cw_i·transmit_i over
        # Σ cw_i·n_i instead: a weighted per-datapoint mean. The
        # probes below keep reading the UNWEIGHTED per-client
        # transmits — they report what clients sent, not how the
        # fold discounted it.
        # Under --dp sketch the denominator is the STATIC padded
        # datapoint capacity W·B (mask.size), not the alive total:
        # one client's transmit is its clipped gradient × its real
        # datapoint count n_i ≤ B, so its share of a capacity-
        # normalised fold is bounded by n_i/(W·B) ≤ 1/W — the
        # sqrt(r)·C/W sensitivity the accountant charges
        # (privacy/mechanism.py) — on EVERY round. A data-dependent
        # denominator breaks that bound two ways: a mostly-dead round
        # shrinks it below W·n_i (the survivor's share exceeds 1/W
        # against noise calibrated for W), and the weighted async
        # fold's Σ cw·n denominator cancels uniform staleness weights
        # out of the release entirely (no sensitivity shrink to
        # credit). With the fixed denominator the weights genuinely
        # scale the release, so the accountant's w·Δ staleness
        # discount is sound. Trace-time constant: dp-off builds are
        # bit-identical to before.
        if weighted:
            cw = staleness_weights(staleness, alpha)
            n_per = jnp.sum(batch["mask"],
                            axis=tuple(range(1, batch["mask"].ndim)))
            total = jnp.maximum(jnp.sum(cw * n_per), 1.0)
            t_fold = transmit * cw.reshape(
                (W,) + (1,) * (transmit.ndim - 1))
        else:
            cw = None
            total = jnp.maximum(jnp.sum(batch["mask"]), 1.0)
            t_fold = transmit
        if dp_on:
            total = jnp.float32(float(batch["mask"].size))
        fold_pr = None
        if robust:
            from commefficient_tpu.core.robust import robust_fold
            aggregated, fold_pr = robust_fold(cfg, transmit, batch,
                                              probes=probes,
                                              weights=cw)
        elif sketch_late:
            aggregated = _sketch_after_local_sum(
                sketch, t_fold, mesh,
                emit=_partial_table_emit if shard2d_late else None,
                wire="f32" if dp_on else wire,
                depth=depth if overlap else 1) / total
        else:
            aggregated = jnp.sum(t_fold, axis=0) / total

        if dp_on:
            # the release: one seeded Gaussian draw on the aggregated
            # table (the noise key is a distinguished fold of the
            # round key — disjoint from every per-client stream), then
            # the deferred wire qdq on the NOISY table. Same rng, same
            # round ⇒ bit-identical noise, including across resume.
            from commefficient_tpu.privacy import (add_table_noise,
                                                   round_noise_key,
                                                   table_noise_std)
            aggregated = add_table_noise(aggregated,
                                         round_noise_key(rng),
                                         table_noise_std(cfg))
            if dp_qdq:
                aggregated = (_qdq_local_overlapped(aggregated)
                              if overlap else _qdq_local(aggregated))

        pr = None
        if probes:
            pr = _agg_probes(aggregated)
            pr.update(_client_norm_probes(transmit, batch))
            if fold_pr:
                pr.update(fold_pr)
            if probe_recovery and sketch_late:
                # the dense transmits exist on this path anyway, so
                # the ground-truth aggregate is one extra sum; the
                # clipped per-client-sketch path (max_grad_norm set)
                # has no dense gradient to compare against and omits
                # the key
                dense_g = jnp.sum(t_fold, axis=0) / total
                pr["recovery_error"] = sketch.recovery_error(
                    aggregated, dense_g, cfg.k)
        states = ClientStates(
            _scatter(client_states.velocities, client_ids, new_vel),
            _scatter(client_states.errors, client_ids, new_err),
            _scatter(client_states.weights, client_ids, new_wts),
        )
        return RoundResult(aggregated, metrics, states,
                           _round_bn_stats(stats_fn, ps_weights, batch),
                           probes=pr)

    def _client_round_chunked(ps_weights, client_states, batch,
                              client_ids, rngs, fedavg_lr, chunk):
        """--client_chunk: scan over chunks of the round's client
        fan-out, capping live per-client intermediates at chunk x d
        instead of W x d. The reference gets this bound for free by
        running clients SERIALLY per worker process (fed_worker.py:
        59-133); the full vmap is that loop unrolled onto one chip,
        which at W=100, d=6.6M local_topk masking costs ~13 GB of HLO
        temps (measured OOM). Same math: transmits accumulate into the
        running sum chunk by chunk, per-client states scatter back as
        each chunk finishes. Single-device path — on a mesh the client
        axis is already divided across devices."""
        W = client_ids.shape[0]
        n_chunks = -(-W // chunk)
        pad = n_chunks * chunk - W

        def pad0(x):
            return jnp.pad(x, [(0, pad)] + [(0, 0)] * (x.ndim - 1)) \
                if pad else x

        # padded slots carry an OUT-OF-RANGE client id: their state
        # gathers clamp (values discarded — all-zero mask makes the
        # step a no-op) and their state scatters are DROPPED (JAX's
        # default out-of-bounds scatter semantics), so no real
        # client's row is ever touched by a pad slot. Padding with a
        # real id (e.g. 0) would both advance that client's topk_down
        # weights (new_wts has no alive guard) and race its update
        # when it shares the padded chunk.
        sentinel = jnp.iinfo(jnp.int32).max
        ids_p = (jnp.concatenate(
            [client_ids,
             jnp.full((pad,), sentinel, client_ids.dtype)])
            if pad else client_ids).reshape(n_chunks, chunk)
        rngs_p = pad0(rngs).reshape((n_chunks, chunk) +
                                    rngs.shape[1:])
        batch_p = {k: pad0(v).reshape((n_chunks, chunk) + v.shape[1:])
                   for k, v in batch.items()}
        total = jnp.maximum(jnp.sum(batch["mask"]), 1.0)

        def body(carry, inp):
            acc, states = carry
            ids_c, rngs_c, batch_c = inp
            vel_r = (states.velocities[ids_c]
                     if states.velocities is not None else None)
            err_r = (states.errors[ids_c]
                     if states.errors is not None else None)
            wt_r = (states.weights[ids_c]
                    if states.weights is not None else None)
            transmit, metrics, new_vel, new_err, new_wts = jax.vmap(
                per_client, in_axes=(None, 0, 0, 0, 0, 0, None)
            )(ps_weights, _some(vel_r, chunk), _some(err_r, chunk),
              _some(wt_r, chunk), batch_c, rngs_c, fedavg_lr)
            if quantized and not sketch_late:
                # same per-client wire crossing as the unchunked path
                transmit = jax.vmap(_qdq_local)(transmit)
            states = ClientStates(
                _scatter(states.velocities, ids_c, new_vel),
                _scatter(states.errors, ids_c, new_err),
                _scatter(states.weights, ids_c, new_wts),
            )
            ys = metrics
            if probes:
                # per-client transmit norms ride the scan's stacked
                # outputs like the metrics do
                norms = jnp.sqrt(jnp.sum(jax.lax.square(
                    transmit.reshape(chunk, -1)), axis=1))
                ys = (metrics, norms)
            return (acc + jnp.sum(transmit, axis=0), states), ys

        dense_g = None
        if sketch_late and not probe_recovery:
            # chunked + sketch-late: sketch each chunk's dense sum and
            # accumulate tables (linearity) — the (W, d) transmit
            # stack never exists
            def body_sketch(carry, inp):
                table_acc, states = carry
                (chunk_sum, states), ys = body(
                    (jnp.zeros(cfg.grad_size, jnp.float32), states),
                    inp)
                return (table_acc + sketch.sketch(chunk_sum),
                        states), ys

            (table, states), ys = jax.lax.scan(
                body_sketch,
                (jnp.zeros((sketch.r, sketch.c), jnp.float32),
                 client_states),
                (ids_p, rngs_p, batch_p))
            if quantized:
                table = (_qdq_local_overlapped(table)
                         if overlap else _qdq_local(table))
            aggregated = table / total
        else:
            # dense accumulator: transmit_shape covers both dense (d,)
            # transmits and the (r, c) tables of the clipped (non-late)
            # sketch path; the sketch-late PROBED variant accumulates
            # dense and sketches once at the end (linearity — same
            # table as per-chunk accumulation) so the recovery probe's
            # ground truth exists without a (W, d) stack
            init_shape = ((cfg.grad_size,) if sketch_late
                          else cfg.transmit_shape)
            (acc, states), ys = jax.lax.scan(
                body,
                (jnp.zeros(init_shape, jnp.float32), client_states),
                (ids_p, rngs_p, batch_p))
            if sketch_late:
                table = sketch.sketch(acc)
                if quantized:
                    table = (_qdq_local_overlapped(table)
                             if overlap else _qdq_local(table))
                aggregated = table / total
                dense_g = acc / total
            else:
                aggregated = acc / total

        if probes:
            metrics, norms = ys
        else:
            metrics = ys
        metrics = tuple(m.reshape(-1)[:W] for m in metrics)
        pr = None
        if probes:
            pr = _agg_probes(aggregated)
            pr.update(_client_norm_stats(norms.reshape(-1)[:W], batch))
            if dense_g is not None:
                pr["recovery_error"] = sketch.recovery_error(
                    aggregated, dense_g, cfg.k)
        return RoundResult(aggregated, metrics, states,
                           _round_bn_stats(stats_fn, ps_weights, batch),
                           probes=pr)

    return client_round_fused if fused_grad else client_round


def _agg_probes(aggregated) -> dict:
    """O(d) reductions over the round's aggregated transmit (dense
    vector or sketch table): its norm plus NaN/Inf element counts —
    the cheapest possible per-round health signal, compiled into the
    round program so no extra device round-trip is ever taken."""
    return {
        "agg_norm": jnp.sqrt(jnp.sum(jax.lax.square(aggregated))),
        "agg_nan": jnp.sum(jnp.isnan(aggregated)).astype(jnp.float32),
        "agg_inf": jnp.sum(jnp.isinf(aggregated)).astype(jnp.float32),
    }


def _client_norm_stats(norms, batch) -> dict:
    """Mean/max/std of per-client transmit norms over ALIVE clients
    (dead dropout/padding slots transmit zero and are excluded from
    mean/std; the max is alive-masked for the same reason). The
    dispersion is the population std — a sudden spread blow-up is the
    straggler/poisoned-client signature."""
    alive = jax.vmap(
        lambda b: jnp.sum(b["mask"]) > 0)(batch).astype(jnp.float32)
    n = jnp.maximum(jnp.sum(alive), 1.0)
    mean = jnp.sum(norms * alive) / n
    var = jnp.sum(alive * jax.lax.square(norms - mean)) / n
    return {"client_norm_mean": mean,
            "client_norm_max": jnp.max(norms * alive),
            "client_norm_std": jnp.sqrt(jnp.maximum(var, 0.0))}


def _client_norm_probes(transmit, batch) -> dict:
    W = transmit.shape[0]
    norms = jnp.sqrt(jnp.sum(jax.lax.square(
        transmit.reshape(W, -1)), axis=1))
    return _client_norm_stats(norms, batch)


def _round_bn_stats(stats_fn, ps_weights, batch):
    """Sample-weighted mean of participating clients' batch statistics
    (the federated replacement for per-worker torch running-stats
    updates): one extra forward per client, only in --batchnorm
    configs. Dropped/padded clients get zero weight; ``alive`` lets
    the server skip the blend on a fully-dropped round."""
    if stats_fn is None:
        return None
    n = jax.vmap(lambda b: jnp.sum(b["mask"]))(batch)   # (W,)
    total = jnp.maximum(jnp.sum(n), 1.0)
    per_client = jax.vmap(stats_fn, in_axes=(None, 0))(ps_weights,
                                                       batch)
    w = n / total
    mean_stats = jax.tree_util.tree_map(
        lambda s: jnp.tensordot(w.astype(s.dtype), s, axes=(0, 0)),
        per_client)
    return mean_stats, jnp.sum(n)


def _sketch_after_local_sum(sketch: CountSketch, transmit, mesh,
                            emit=None, wire="f32", depth=1):
    """(W, d) dense transmits -> (r, c) summed table: per-device local
    dense sum, one sketch per device, psum of tables over the mesh.
    ``emit`` (2D mesh, sketch mode) replaces the full per-device
    sketch with the partial-slice sketch + reduce-scatter over
    ``model`` (build_client_round._partial_table_emit); the returned
    table is then column-sharded (parallel/mesh.table_shard_spec).
    ``wire`` != "f32" quantizes the table before the collective
    (ops/quant.py — the collective payload drops to wire width) and
    dequantizes after; with an ``emit``, the emit closure already did
    the quantize + reduce-scatter and hands back ``(q, scale)``.
    ``depth`` > 1 (--overlap_depth) crosses the table in disjoint
    row chunks — collective i interleaved with chunk i+1's quantize —
    and folds the chunks back in row order (an ``emit`` then hands
    back the per-chunk list)."""
    from commefficient_tpu.parallel.mesh import (CLIENT_AXIS,
                                                 client_axis_size,
                                                 replicated_spec,
                                                 shard_map, spec,
                                                 table_shard_spec)
    W = transmit.shape[0]
    if mesh is not None and W % client_axis_size(mesh) == 0 \
            and mesh.devices.size > 1:
        C = client_axis_size(mesh)

        def block(local):  # (W/C, d) on each client-axis shard
            g = jnp.sum(local, axis=0)
            if depth > 1:
                from commefficient_tpu.core.server import \
                    fold_row_chunks
                from commefficient_tpu.parallel import wire as wirex
                if emit is not None:
                    chunks = emit(g)  # per-row-chunk scattered shards
                    if wire != "f32":
                        return fold_row_chunks(
                            wirex.wire_allreduce(q, s, CLIENT_AXIS)
                            for q, s in chunks)
                    return fold_row_chunks(
                        jax.lax.psum(ch, CLIENT_AXIS)
                        for ch in chunks)
                return wirex.chunked_quantize_allreduce(
                    sketch.sketch(g), wire, (CLIENT_AXIS,), C,
                    CLIENT_AXIS, depth)
            if wire != "f32":
                from commefficient_tpu.parallel import wire as wirex
                if emit is None:
                    q, scale = wirex.quantize_for_collective(
                        sketch.sketch(g), wire, (CLIENT_AXIS,), C)
                else:
                    q, scale = emit(g)
                return wirex.wire_allreduce(q, scale, CLIENT_AXIS)
            table = sketch.sketch(g) if emit is None else emit(g)
            return jax.lax.psum(table, CLIENT_AXIS)

        return shard_map(
            block, mesh=mesh,
            in_specs=spec(CLIENT_AXIS, None),
            out_specs=(replicated_spec() if emit is None
                       else table_shard_spec()))(transmit)
    table = sketch.sketch(jnp.sum(transmit, axis=0))
    if wire != "f32":
        from commefficient_tpu.ops import quant
        if depth > 1:
            # single-device mirror of the chunked crossing: per-chunk
            # qdq (per-row scales -> bit-identical, chunked program)
            from commefficient_tpu.core.server import fold_row_chunks
            from commefficient_tpu.parallel.wire import row_chunks
            return fold_row_chunks(
                quant.dequantize(*quant.quantize_table(
                    jax.lax.slice_in_dim(table, off, off + cnt,
                                         axis=0),
                    wire))
                for off, cnt in row_chunks(table.shape[0], depth))
        return quant.dequantize(*quant.quantize_table(table, wire))
    return table


def _state_ids(client_ids, batch):
    """Ids used for per-client STATE gathers/scatters: dead slots
    (all-zero mask) get an out-of-range sentinel so their scatters
    drop and they can never alias a live client's row. RNG folding
    keeps the original ids (dead slots' streams are unused)."""
    alive = jax.vmap(lambda b: jnp.sum(b["mask"]) > 0)(batch)
    return jnp.where(alive, client_ids,
                     jnp.iinfo(client_ids.dtype).max)


def _some(rows, W):
    """vmap can't map over None: use a zero-size placeholder."""
    return rows if rows is not None else jnp.zeros((W, 0))


def _scatter(arr, ids, rows):
    if arr is None or rows is None or rows.shape[-1] == 0:
        return arr
    return arr.at[ids].set(rows)


def _build_sgd_client_step(cfg, loss_fn, sketch, padded_batch_size):
    """One client's round for all non-fedavg modes
    (reference process_batch + local_step, fed_worker.py:142-232)."""
    forward_grad = make_forward_grad(cfg, loss_fn, sketch,
                                     padded_batch_size)

    def step(ps_weights, velocity, error, client_weights, batch, rng,
             fedavg_lr):
        del fedavg_lr
        batch_size = jnp.sum(batch["mask"])
        if cfg.do_topk_down:
            weights = stale_weight_download(cfg, ps_weights, client_weights)
            # dead slots (dropout / loader padding) did not download:
            # their stale-weight state must not advance (same
            # state-untouched semantics as velocity/error below)
            new_wts = jnp.where(batch_size > 0, weights, client_weights)
        else:
            weights = ps_weights
            new_wts = client_weights

        g_unit, metrics = forward_grad(weights, batch, noise_rng=rng)
        upd = accumulate_and_compress(
            cfg, g_unit,
            velocity if cfg.local_momentum > 0 else None,
            error if cfg.error_type == "local" else None,
            batch_size)
        # a dropped client (--dropout_prob zeroes its whole mask) ran
        # nothing: it transmits 0 and its momentum/error state stays
        # untouched — without this, local-momentum/-error modes would
        # still upload rho*velocity / accumulated error for it
        alive = (batch_size > 0).astype(jnp.float32)
        transmit = upd.transmit * alive

        def keep(new, old):
            if new is None:
                return old
            if old is None:
                return new
            return jnp.where(alive > 0, new, old)

        new_vel = keep(upd.velocity, velocity)
        new_err = keep(upd.error, error)
        return transmit, metrics, new_vel, new_err, new_wts

    return step


def _build_fedavg_client_step(cfg, loss_fn, padded_batch_size):
    """One client's FedAvg round: local SGD over its whole (padded)
    dataset, transmit the weighted weight delta
    (reference fed_worker.py:62-114)."""
    if cfg.fedavg_batch_size == -1:
        sub = padded_batch_size
    else:
        sub = min(cfg.fedavg_batch_size, padded_batch_size)
    n_batches = -(-padded_batch_size // sub)  # ceil
    pad_to = n_batches * sub
    forward_grad = make_forward_grad(cfg, loss_fn, None, sub)

    def step(ps_weights, velocity, error, client_weights, batch, rng,
             fedavg_lr):
        def pad(x):
            w = [(0, pad_to - x.shape[0])] + [(0, 0)] * (x.ndim - 1)
            return jnp.pad(x, w)

        chunked = {k: pad(v).reshape((n_batches, sub) + v.shape[1:])
                   for k, v in batch.items()}
        client_size = jnp.sum(batch["mask"])

        def local_sgd(carry, inp):
            w, step_i = carry
            microbatch, r = inp
            n = jnp.sum(microbatch["mask"])
            g_unit, metrics = forward_grad(w, microbatch, noise_rng=r)
            # skip all-padding chunks entirely: no weight change, no
            # step increment (the reference never creates such chunks)
            valid = n > 0
            decay = cfg.fedavg_lr_decay ** step_i
            w_new = w - g_unit * fedavg_lr * decay
            w = jnp.where(valid, w_new, w)
            step_i = step_i + valid.astype(jnp.int32)
            w_metrics = tuple(jnp.where(valid, m, 0.0) for m in metrics)
            return (w, step_i), w_metrics

        steps_per_epoch = n_batches
        rngs = jax.vmap(lambda i: jax.random.fold_in(rng, i))(
            jnp.arange(cfg.num_fedavg_epochs * steps_per_epoch))

        w = ps_weights
        step_i = jnp.zeros((), jnp.int32)
        all_metrics = []
        for ep in range(cfg.num_fedavg_epochs):
            ep_rngs = rngs[ep * steps_per_epoch:(ep + 1) * steps_per_epoch]
            (w, step_i), ms = jax.lax.scan(
                local_sgd, (w, step_i), (chunked, ep_rngs))
            all_metrics.append(ms)

        # metrics: mean over the local steps actually taken
        # (reference fed_worker.py:103-104)
        n_steps = jnp.maximum(step_i.astype(jnp.float32), 1.0)
        metrics = tuple(
            sum(jnp.sum(ms[i]) for ms in all_metrics) / n_steps
            for i in range(len(all_metrics[0])))

        # transmit = (w_orig - w_final) * |client data|
        # (fed_worker.py:105-109)
        transmit = (ps_weights - w) * client_size
        return transmit, metrics, velocity, error, client_weights

    return step


def build_val_fn(cfg: Config, loss_fn: Callable,
                 stateful: bool = False) -> Callable:
    """Validation shard evaluator: metrics only, batch-mean over the
    shard (reference _call_val + forward_grad(compute_grad=False),
    fed_aggregator.py:339-366). With ``stateful``, ``loss_fn`` takes
    an extra model-state pytree (BatchNorm running stats) that is
    passed per call — an argument, not a closure, so updated stats
    never trigger a re-trace."""
    if stateful:
        def val_shards_state(ps_weights, model_state, batch):
            def one(b):
                loss, metrics = loss_fn(ps_weights, b, model_state)
                return jnp.stack((loss,) + tuple(metrics))

            return jax.vmap(one)(batch)

        return val_shards_state

    eval_metrics = make_eval_metrics(loss_fn)

    def val_shards(ps_weights, batch):
        # batch: (S, B, ...) shards with (S, B) mask
        return jax.vmap(lambda b: jnp.stack(
            eval_metrics(ps_weights, b)))(batch)

    return val_shards


def build_server_round(cfg: Config, probes: bool = False,
                       mesh=None) -> Callable:
    """Returns jit-able ``server_round(ps_weights, server_state,
    aggregated, lr, client_velocities, client_ids, noise_rng) ->
    (new_ps_weights, new_server_state, new_client_velocities,
    weight_update, support)``. ``support`` is ((k,) indices, (k,)
    values) of the update on the index path, ``{"bitmap": packed
    uint8}`` on the exact threshold-select path (see ServerUpdate),
    None for dense modes — it lets the host-side download accounting
    avoid ever transferring the dense update. ``weight_update`` is
    None on the large-d sparse sketch path (prefer_sparse_resketch):
    the update was applied as a k-sized scatter and only ``support``
    (tuple form there) carries its values.

    ``probes=True`` appends a sixth output — the server-side probe
    dict (core/server.py server_update) — so the default arity stays
    five and probes-off callers build a bit-identical program.

    ``mesh`` with a ``model`` axis of size > 1 (parallel/mesh
    make_mesh2d) switches to the model-sharded server programs: the
    shard-mapped distributed-select step for sketch mode
    (core/server.py sketched_update_2d), GSPMD sharding constraints
    for uncompressed — same signature, same return arity. Any other
    mesh (None, 1-D, ``Cx1``) builds today's replicated program,
    HLO-identical to a build without the parameter.

    Covers FedOptimizer.step (fed_aggregator.py:431-460) including
    true_topk's masking of participating clients' local velocities at
    the global top-k coordinates (fed_aggregator.py:530-535) — done
    correctly here (the reference has a latent unset-global bug,
    SURVEY.md §2.1).
    """
    cfg.validate_runtime()
    sketch = args2sketch(cfg)
    from commefficient_tpu.parallel.mesh import model_axis_size
    if model_axis_size(mesh) > 1:
        if cfg.mode == "sketch":
            return _build_server_round_2d_sketch(cfg, sketch, mesh,
                                                 probes)
        assert cfg.mode == "uncompressed", cfg.mode  # config gate
        return _build_server_round_2d_dense(cfg, mesh, probes)

    def server_round(ps_weights, server_state: ServerState, aggregated,
                     lr, client_velocities=None, client_ids=None,
                     noise_rng=None):
        eff_lr = 1.0 if cfg.mode == "fedavg" else lr
        res: ServerUpdate = server_update(cfg, aggregated, server_state,
                                          eff_lr, sketch, noise_rng,
                                          probes=probes)
        if res.weight_update is None:
            # large-d k-sparse modes: the support already carries the
            # lr-scaled update values — apply them as a k-sized
            # scatter instead of materialising + subtracting a dense
            # (d,) vector (~6 ms saved per round at GPT-2's d=124M).
            # Sorting (free for the threshold path, a k-sized sort
            # otherwise) lets XLA take the in-place ordered-scatter
            # lowering instead of a d-sized rewrite fusion (measured
            # 4.4 ms in the round-4 xplane). unique_indices holds for
            # the exact/threshold selections but NOT for the big-d
            # approx path, whose degenerate-tie guard clamps
            # out-of-range slots to duplicate (d-1, 0) pairs that rely
            # on scatter-ADD semantics — one shared predicate with
            # ops/sketch.py unsketch, so the big-d gate cannot drift
            from commefficient_tpu.ops.topk import \
                selection_may_duplicate
            unique = not selection_may_duplicate(cfg.grad_size,
                                                 cfg.approx_topk)
            idx, scaled = res.support
            order = jnp.argsort(idx)
            new_ps = ps_weights.at[idx[order]].add(
                -scaled[order], mode="promise_in_bounds",
                unique_indices=unique, indices_are_sorted=True)
        else:
            new_ps = ps_weights - res.weight_update
        new_vel = client_velocities
        if (cfg.mode == "true_topk" and cfg.local_momentum > 0
                and client_velocities is not None):
            assert client_ids is not None
            rows = client_velocities[client_ids]
            rows = rows * res.client_velocity_keep.astype(rows.dtype)
            new_vel = client_velocities.at[client_ids].set(rows)
        out = (new_ps, res.state, new_vel, res.weight_update,
               res.support)
        return out + (res.probes,) if probes else out

    return server_round


def _build_server_round_2d_sketch(cfg: Config, sketch: CountSketch,
                                  mesh, probes: bool) -> Callable:
    """Model-sharded FetchSGD server round: shard_map over the full 2D
    mesh with the (r, c) state/aggregate column-sharded over ``model``
    (replicated over ``clients`` — the block is client-invariant).
    The body is core/server.py sketched_update_2d: shard-local
    momentum/error accumulation, one table all-gather, distributed
    threshold-select recovery. The dense weight update, support, and
    probe scalars come back identical on every peer (deterministic
    functions of all-gathered data), so they exit replicated; the new
    state exits on its column shards — per-device server state stays
    1/M across rounds."""
    from commefficient_tpu.core.server import sketched_update_2d
    from commefficient_tpu.parallel.mesh import (MODEL_AXIS,
                                                 model_axis_size,
                                                 replicated_spec,
                                                 shard_map,
                                                 table_shard_spec)
    M = model_axis_size(mesh)
    ts, rs = table_shard_spec(), replicated_spec()

    def body(state, agg, lr):
        res = sketched_update_2d(cfg, sketch, agg, state, lr,
                                 MODEL_AXIS, M, probes=probes)
        out = (res.weight_update, res.state, res.support)
        return out + ((res.probes,) if probes else ())

    out_specs = (rs, ServerState(ts, ts), (rs, rs))
    if probes:
        out_specs = out_specs + (rs,)
    step = shard_map(body, mesh=mesh,
                     in_specs=(ServerState(ts, ts), ts, rs),
                     out_specs=out_specs)

    def server_round(ps_weights, server_state: ServerState, aggregated,
                     lr, client_velocities=None, client_ids=None,
                     noise_rng=None):
        del client_ids, noise_rng  # sketch mode uses neither
        out = step(server_state, aggregated,
                   jnp.asarray(lr, jnp.float32))
        weight_update, new_state, support = out[:3]
        new_ps = ps_weights - weight_update
        ret = (new_ps, new_state, client_velocities, weight_update,
               support)
        return ret + (out[3],) if probes else ret

    return server_round


def _build_server_round_2d_dense(cfg: Config, mesh,
                                 probes: bool) -> Callable:
    """Model-sharded uncompressed server round: the 1-D math verbatim
    (it is elementwise in d) with GSPMD sharding constraints — the
    momentum buffer is pinned model-sharded so per-device server state
    stays 1/M, and the update is pinned replicated where it meets the
    replicated params. No shard_map needed: XLA partitions the
    elementwise chain along the constraint."""
    from commefficient_tpu.parallel.mesh import (replicated,
                                                 server_state_sharding)
    state_sh = server_state_sharding(mesh, cfg.transmit_shape)
    repl = replicated(mesh)

    def server_round(ps_weights, server_state: ServerState, aggregated,
                     lr, client_velocities=None, client_ids=None,
                     noise_rng=None):
        del client_ids
        res: ServerUpdate = server_update(cfg, aggregated, server_state,
                                          lr, None, noise_rng,
                                          probes=probes)
        new_state = jax.tree_util.tree_map(
            lambda x: jax.lax.with_sharding_constraint(x, state_sh),
            res.state)
        upd = jax.lax.with_sharding_constraint(res.weight_update, repl)
        out = (ps_weights - upd, new_state, client_velocities, upd,
               res.support)
        return out + (res.probes,) if probes else out

    return server_round
