"""Robust folds over per-client transmit vectors (--robust_agg).

The plain fold is a datapoint-weighted mean: Σ_clients transmit / Σ
datapoints, where transmit_i = g_unit_i * batch_size_i.  One
sign-flipped or rescaled client corrupts that mean — and through error
feedback the corruption is *remembered* by the server residuals.  The
estimators here replace the mean with a byzantine-tolerant statistic
computed over the round's materialised per-client transmit stack:

  median   coordinate-wise median of per-client (or grouped) sketch
           values — median-of-sketches preserves the count-sketch
           recovery guarantee (1903.04488 §3; groups trade breakdown
           point for variance)
  trimmed  coordinate-wise trimmed mean over per-client transmit
           vectors, discarding the top/bottom --robust_trim_frac tail
  clip     norm-clipped fold: each client's transmit is scaled down to
           a norm cap tau (--robust_clip_norm, or the median alive
           norm when 0) before the usual datapoint-weighted sum

Error-feedback correctness is by construction: the server only ever
sees the robust aggregate, so mass rejected by the estimator never
enters Vvelocity / Verror — there is no separate "put it back"
pathway to get wrong.

All estimators are mask-aware: padded / dropped client slots (all-zero
mask rows) carry no datapoints and are excluded from every statistic,
so a round that loses clients re-weights over the survivors instead
of averaging in zeros.  NumPy mirrors live in
tests/reference_mirror.py and must match to 1e-6.
"""

import jax
import jax.numpy as jnp

ROBUST_MODES = ("median", "trimmed", "clip")

# guards x/0 without perturbing any realistic norm
_TINY = 1e-12


def clip_factors(norms, tau):
    """Per-vector norm-clip scale: min(1, tau / max(norm, tiny)).

    The ONE clip algebra shared by the ``clip`` robust fold below and
    the DP per-client clip (privacy/mechanism.py) — the factor is
    exactly 1.0 for any vector already inside the cap, so clipping is
    a no-op there bit-for-bit, and the _TINY guard keeps an all-zero
    vector at zero instead of NaN. ``norms`` and ``tau`` broadcast.
    The NumPy mirror (tests/reference_mirror.py np_clip_factors)
    restates this formula with the same _TINY constant.
    """
    return jnp.minimum(1.0, tau / jnp.maximum(norms, _TINY))


def _masked_median(vals, alive):
    """Coordinate-wise median over the alive rows of vals (G, D).

    Dead rows sort to +inf past every alive value; the median of k
    alive rows is the mean of sorted ranks (k-1)//2 and k//2 (equal
    for odd k).  k is traced, so the ranks are gathered with a traced
    take.  All-dead input yields zeros.
    """
    G = vals.shape[0]
    s = jnp.sort(jnp.where(alive[:, None], vals, jnp.inf), axis=0)
    k = jnp.sum(alive.astype(jnp.int32))
    lo = jnp.clip((k - 1) // 2, 0, G - 1)
    hi = jnp.clip(k // 2, 0, G - 1)
    med = 0.5 * (jnp.take(s, lo, axis=0) + jnp.take(s, hi, axis=0))
    return jnp.where(k > 0, med, jnp.zeros_like(med))


def _masked_trimmed_mean(vals, alive, trim_frac):
    """Coordinate-wise trimmed mean over the alive rows of vals (G, D).

    Dead rows sort to +inf past the kept window.  t = floor(frac * k)
    is trimmed from each tail; trim_frac < 0.5 (validated in config)
    keeps the window non-empty for every k >= 1.  The where() guards
    the inf * 0 = nan a plain weighted sum would produce on dead rows.
    """
    G = vals.shape[0]
    s = jnp.sort(jnp.where(alive[:, None], vals, jnp.inf), axis=0)
    k = jnp.sum(alive.astype(jnp.int32))
    t = jnp.floor(trim_frac * k).astype(jnp.int32)
    ranks = jnp.arange(G, dtype=jnp.int32)[:, None]
    wm = (ranks >= t) & (ranks < k - t)
    kept = jnp.sum(jnp.where(wm, s, 0.0), axis=0)
    denom = jnp.maximum(jnp.sum(wm.astype(vals.dtype), axis=0), 1.0)
    return kept / denom


def _group_means(flatT, n, alive, groups):
    """Collapse W clients into `groups` contiguous groups.

    Returns (per-datapoint group means (G, D), group alive (G,)).
    W % groups == 0 is asserted at trace time (validated in config).
    A group is alive if any member is; its value is the datapoint-
    weighted mean over its members, so honest members dilute a
    byzantine one before the median sees the group.
    """
    W, D = flatT.shape
    assert W % groups == 0, (W, groups)
    gsum = flatT.reshape(groups, W // groups, D).sum(axis=1)
    gn = n.reshape(groups, W // groups).sum(axis=1)
    galive = jnp.any(alive.reshape(groups, W // groups), axis=1)
    return gsum / jnp.maximum(gn, 1.0)[:, None], galive


def robust_fold(cfg, transmit, batch, probes=False, weights=None):
    """Fold the per-client transmit stack robustly.

    transmit: (W, *transmit_shape) per-client transmits (already
    scaled by per-client batch size); batch["mask"] is the (W, B)
    aliveness mask.  Returns (aggregated, probes_dict) where
    aggregated has transmit.shape[1:] and matches the plain fold's
    per-datapoint-mean scale, and probes_dict carries
    fold_rejection_rate (deviation of the robust aggregate from the
    plain mean, relative to the plain mean's norm; None when probes
    is False).

    ``weights`` (asyncfed staleness weights, (W,) float > 0) scales
    each client's transmit AND its datapoint count before any
    statistic runs — algebraically the fold of w_i·transmit_i with
    w_i·n_i datapoints, so the NumPy mirror verifies a weighted fold
    by feeding the pre-scaled stack to the unweighted mirror.  The
    per-datapoint scale the estimators share is unchanged
    (w·T/(w·n) = T/n where n >= 1); the default None traces nothing
    extra.
    """
    W = transmit.shape[0]
    flatT = transmit.reshape(W, -1).astype(jnp.float32)
    n = jnp.sum(batch["mask"], axis=tuple(range(1, batch["mask"].ndim)))
    n = n.astype(jnp.float32)
    if weights is not None:
        w = weights.astype(jnp.float32)
        flatT = w[:, None] * flatT
        n = w * n
    alive = n > 0
    # --dp sketch normalises by the STATIC padded capacity W·B like
    # the plain fold (core/rounds.py, rationale there): each clipped
    # transmit is bounded by C·n_i, so only a data-independent
    # denominator ≥ W·n_i keeps every client's share within the
    # charged sqrt(r)·C/W sensitivity (privacy/mechanism.py).
    # Trace-time gate — dp-off folds keep the 1.0 guard unchanged.
    if getattr(cfg, "dp", "off") == "sketch":
        total = jnp.float32(float(batch["mask"].size))
    else:
        total = jnp.maximum(jnp.sum(n), 1.0)
    plain = jnp.sum(flatT, axis=0) / total
    # per-datapoint client means — the robust estimators operate on a
    # common scale so one big-batch client can't dominate by weight
    g = flatT / jnp.maximum(n, 1.0)[:, None]

    mode = cfg.robust_agg
    if mode == "median":
        groups = cfg.robust_median_groups
        if groups > 1 and groups < W:
            gv, galive = _group_means(flatT, n, alive, groups)
        else:
            gv, galive = g, alive
        agg = _masked_median(gv, galive)
    elif mode == "trimmed":
        agg = _masked_trimmed_mean(g, alive, cfg.robust_trim_frac)
    elif mode == "clip":
        norms = jnp.sqrt(jnp.sum(g * g, axis=1))
        if cfg.robust_clip_norm > 0:
            tau = jnp.float32(cfg.robust_clip_norm)
        else:
            tau = _masked_median(norms[:, None], alive)[0]
        scale = clip_factors(norms, tau)
        # weight-preserving: clipped transmits keep their datapoint
        # weights, so the fold stays the plain fold when nothing clips
        agg = jnp.sum(scale[:, None] * flatT, axis=0) / total
    else:  # pragma: no cover - config validates membership
        raise ValueError(f"unknown robust_agg {mode!r}")

    pr = None
    if probes:
        dev = jnp.linalg.norm(plain - agg)
        pr = {"fold_rejection_rate":
              dev / jnp.maximum(jnp.linalg.norm(plain), _TINY)}
    return agg.reshape(transmit.shape[1:]), pr
