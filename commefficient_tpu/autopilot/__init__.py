"""Adaptive compression autopilot.

A seeded, deterministic, replayable between-rounds controller
(controller.py) that reads the round's probe scalars and walks the
discrete knob lattice (lattice.py) toward the cheapest round program
whose sketch recovery error stays inside ``--autopilot_band LO:HI``,
dispatching through a bounded LRU of jitted round variants (cache.py)
so a revisited point never recompiles. ``lattice.apply_knobs`` is the
ONLY sanctioned way compression knobs change after construction — the
knob-mutation lint rule (analysis/lint.py) hard-fails direct writes
everywhere else.
"""

from commefficient_tpu.autopilot.cache import RoundVariantCache
from commefficient_tpu.autopilot.controller import (AutopilotController,
                                                    build_controller,
                                                    replay_record)
from commefficient_tpu.autopilot.lattice import (VariantKey,
                                                 apply_knobs,
                                                 band_str,
                                                 build_ladder, key_of,
                                                 key_str, parse_band,
                                                 parse_key,
                                                 variant_bytes)

__all__ = [
    "AutopilotController", "RoundVariantCache", "VariantKey",
    "apply_knobs", "band_str", "build_controller", "build_ladder",
    "key_of", "key_str", "parse_band", "parse_key", "replay_record",
    "variant_bytes",
]
