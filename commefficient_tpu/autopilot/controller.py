"""The between-rounds knob controller.

A deterministic, seeded, replayable policy over the knob lattice
(autopilot/lattice.py): every round whose probe dict carries a
recovery-error observation gets exactly one ``observe`` call, and the
controller either holds or moves one ladder step. The policy is pure
host-side state — no RNG is ever drawn (the seed is recorded purely so
a manifest names the stream the run's PROBES were computed under), so
replaying the recorded observations through a fresh controller
reproduces the knob sequence bit-exactly (autopilot/replay.py).

Policy (band ``LO:HI`` on relative sketch recovery error):

- error > HI        -> back off one step toward the expensive end,
                       immediately (safety beats cooldown), and lower
                       the cheap limit so the offending point is never
                       re-entered — the no-oscillation guarantee is a
                       monotone limit, not a timer;
- NaN/Inf observed  -> jump to the base (safest) point and freeze the
                       ladder (cheap limit 0);
- error < LO        -> after ``--autopilot_cooldown`` in-band rounds,
                       cheapen one step (never past the cheap limit);
- LO <= error <= HI -> hold (and pay down the cooldown).

The gap between LO and HI is the hysteresis band: a point whose error
sits inside it is stable by construction, and because the cheap limit
only ever decreases, the visited-point sequence is finite and the
controller converges on every input trace.
"""

from __future__ import annotations

from typing import List, Optional

from commefficient_tpu.autopilot.lattice import (VariantKey,
                                                 apply_knobs,
                                                 build_ladder,
                                                 key_of, key_str,
                                                 ladder_index,
                                                 parse_band, parse_key,
                                                 variant_bytes)
from commefficient_tpu.config import Config


class AutopilotController:
    def __init__(self, ladder: List[VariantKey], band, cooldown: int,
                 seed: int = 0, start: int = 0,
                 pinned: bool = False):
        assert ladder, "empty knob ladder"
        assert 0 <= start < len(ladder), (start, len(ladder))
        self.ladder = list(ladder)
        self.lo, self.hi = float(band[0]), float(band[1])
        self.cooldown = int(cooldown)
        self.seed = int(seed)
        self.pinned = bool(pinned)
        self.idx = int(start)
        self._cool = 0
        # cheapest index the controller may still enter; only ever
        # decreases (set one below any point whose error breached HI)
        self._cheap_limit = len(self.ladder) - 1
        self.trajectory: List[dict] = []

    @property
    def key(self) -> VariantKey:
        return self.ladder[self.idx]

    def observe(self, ridx: int, probes: dict) -> Optional[VariantKey]:
        """Feed one round's probe scalars; returns the new lattice
        point when the controller moves, None on hold. Deterministic in
        (constructor args, observation sequence) — nothing else."""
        err = probes.get("recovery_error")
        err = None if err is None else float(err)
        bad = (float(probes.get("agg_nan", 0.0)) > 0
               or float(probes.get("agg_inf", 0.0)) > 0)
        action, moved = "hold", None
        if self.pinned:
            action = "pinned"
        elif bad:
            # numeric blow-up: no band argument survives NaN — return
            # to the launch point and stop cheapening for good
            self._cheap_limit = 0
            if self.idx != 0:
                self.idx = 0
                action, moved = "panic", self.key
            self._cool = self.cooldown
        elif err is None:
            # off-cadence round (no recovery observation): hold
            # without paying down the cooldown — cooldown counts
            # OBSERVED in-band rounds, so a sparse probe cadence
            # cannot fast-forward it
            action = "blind"
        elif err > self.hi:
            self._cheap_limit = min(self._cheap_limit,
                                    max(self.idx - 1, 0))
            if self.idx > 0:
                self.idx -= 1
                action, moved = "backoff", self.key
            self._cool = self.cooldown
        elif err < self.lo and self.idx < self._cheap_limit:
            if self._cool > 0:
                self._cool -= 1
            else:
                self.idx += 1
                action, moved = "cheapen", self.key
                self._cool = self.cooldown
        else:
            self._cool = max(self._cool - 1, 0)
        self.trajectory.append({
            "round": int(ridx),
            "recovery_error": err,
            "nan": bool(bad),
            "action": action,
            "key": key_str(self.key),
        })
        return moved

    def record(self) -> dict:
        """Everything a manifest needs for bit-exact replay (plus the
        converged point for topology resolution — registry.run_band/
        run_wire_dtype read it)."""
        return {
            "band": [self.lo, self.hi],
            "cooldown": self.cooldown,
            "seed": self.seed,
            "pinned": self.pinned,
            "ladder": [key_str(k) for k in self.ladder],
            "initial": key_str(self.ladder[0]),
            "final": key_str(self.key),
            "final_wire_bytes": float(variant_bytes(self.key)),
            "initial_wire_bytes": float(
                variant_bytes(self.ladder[0])),
            "trajectory": list(self.trajectory),
        }


def _budget_feasible(cfg: Config):
    """``--dp sketch`` with a hard ε budget: a lattice point is
    feasible only if running the ENTIRE remaining run at it never
    exhausts the budget sooner than the launch point would —
    equivalently, its per-round RDP cost at the variant's
    (recalibrated) ``dp_noise_mult`` fits at least as many rounds
    under ``--dp_epsilon`` as the base σ does (privacy/accountant.py
    steps_to_budget on the composed curve). Returns the keep
    predicate; always-true when the constraint is off."""
    if (str(getattr(cfg, "dp", "off")) == "off"
            or float(getattr(cfg, "dp_epsilon", 0.0) or 0.0) <= 0
            or float(getattr(cfg, "dp_noise_mult", 0.0) or 0.0) <= 0):
        return lambda key: True
    from commefficient_tpu.privacy import (sample_rate_of,
                                           steps_to_budget)
    q = sample_rate_of(cfg)
    delta = float(cfg.dp_delta)
    budget = float(cfg.dp_epsilon)
    base_rounds = steps_to_budget(float(cfg.dp_noise_mult), q,
                                  delta, budget)

    def keep(key: VariantKey) -> bool:
        sigma = float(apply_knobs(cfg, key).dp_noise_mult)
        return steps_to_budget(sigma, q, delta, budget) >= base_rounds

    return keep


def build_controller(cfg: Config) -> Optional[AutopilotController]:
    """Controller for a Config, or None with the autopilot off. The
    ladder's base is the launch config's own lattice point;
    ``--autopilot_pin`` starts (and holds) at the named point, adding
    it as a one-point ladder when it is off the automatic walk.

    Under ``--dp sketch`` with a hard budget (``--dp_epsilon`` > 0)
    the ladder is pre-filtered to budget-feasible points — the
    controller can then NEVER visit a point that would exhaust ε
    before the launch plan would, by construction rather than by a
    runtime guard. A pinned point that violates the budget is a
    launch error, not a silent fallback."""
    if str(getattr(cfg, "autopilot", "off")) != "on":
        return None
    band = parse_band(cfg.autopilot_band)
    keep = _budget_feasible(cfg)
    ladder = [k for k in build_ladder(cfg) if keep(k)]
    # index 0 (the launch point) is feasible by definition — its σ IS
    # the budget plan's σ
    assert ladder, "budget filter removed the launch point"
    start, pinned = 0, False
    pin = str(getattr(cfg, "autopilot_pin", "") or "")
    if pin:
        pinned = True
        pin_key = parse_key(pin)
        if not keep(pin_key):
            raise ValueError(
                f"--autopilot_pin {pin} violates the ε budget: its "
                f"noise multiplier spends --dp_epsilon "
                f"{cfg.dp_epsilon:g} faster than the launch config")
        idx = ladder_index(ladder, pin_key)
        if idx is None:
            ladder = ladder + [pin_key]
            idx = len(ladder) - 1
        start = idx
    return AutopilotController(ladder, band,
                               int(cfg.autopilot_cooldown),
                               seed=int(cfg.seed), start=start,
                               pinned=pinned)


def replay_record(record: dict) -> List[str]:
    """Re-run the recorded observation sequence through a fresh
    controller and return the per-observation key strings — bit-exact
    replay means this list equals the recorded trajectory's ``key``
    column (autopilot/replay.py asserts exactly that)."""
    ladder = [parse_key(s) for s in record["ladder"]]
    start = ladder_index(ladder, parse_key(record["initial"]))
    if record.get("pinned"):
        start = ladder_index(ladder,
                             parse_key(record["trajectory"][0]["key"])
                             if record.get("trajectory")
                             else parse_key(record["final"]))
    ctl = AutopilotController(
        ladder, tuple(record["band"]), record["cooldown"],
        seed=record.get("seed", 0), start=start or 0,
        pinned=bool(record.get("pinned")))
    keys = []
    for entry in record["trajectory"]:
        probes = {}
        if entry.get("recovery_error") is not None:
            probes["recovery_error"] = entry["recovery_error"]
        if entry.get("nan"):
            probes["agg_nan"] = 1.0
        ctl.observe(entry["round"], probes)
        keys.append(key_str(ctl.key))
    return keys


def key_of_config(cfg: Config) -> VariantKey:
    return key_of(cfg)
