"""Bounded LRU of jitted round variants, keyed by the knob lattice.

The enabling refactor under the autopilot: round hyperparameters that
used to be compile-time constants become CACHE KEYS. The runtime asks
for the variant at the controller's current lattice point; a hit is a
dict lookup, a miss invokes the builder (which wraps jax.jit — still
LAZY, the XLA compile happens on the variant's first dispatch), and the
oldest untouched variant falls off once the bound is exceeded. The
cache is deliberately generic over entry type so tests can exercise it
with plain closures (tests/test_autopilot.py) exactly as the runtime
uses it with RoundVariant bundles.

Eviction drops the jit wrapper (and with it XLA's compiled executable
for that variant); a re-visit after eviction recompiles, which the
ledger stamping in runtime/fed_model.py makes visible as a fresh
``vcompile:*`` counter on that round's record.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Optional


class RoundVariantCache:
    """``builder(key) -> entry``; entries are opaque to the cache."""

    def __init__(self, builder: Callable, max_size: int = 4,
                 on_evict: Optional[Callable] = None):
        assert max_size >= 1, "cache bound must be >= 1"
        self._builder = builder
        self._max = int(max_size)
        self._on_evict = on_evict
        self._entries: "OrderedDict" = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, key) -> bool:
        return key in self._entries

    def keys(self):
        """LRU -> MRU order."""
        return list(self._entries.keys())

    def get(self, key):
        entry = self._entries.get(key)
        if entry is not None:
            self.hits += 1
            self._entries.move_to_end(key)
            return entry
        self.misses += 1
        entry = self._builder(key)
        self._entries[key] = entry
        while len(self._entries) > self._max:
            old_key, old = self._entries.popitem(last=False)
            self.evictions += 1
            if self._on_evict is not None:
                self._on_evict(old_key, old)
        return entry

    def peek(self, key):
        """Entry without touching recency or building — None on
        absence. The warm-ahead path uses this to stay side-effect-free
        on points it merely inspects."""
        return self._entries.get(key)

    def counters(self) -> dict:
        return {"hits": self.hits, "misses": self.misses,
                "evictions": self.evictions, "size": len(self)}
