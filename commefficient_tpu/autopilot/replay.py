"""Bit-exact controller replay from a run-registry manifest.

    python -m commefficient_tpu.autopilot.replay runs/manifests/run_*.json

Loads the manifest's recorded autopilot block (band, cooldown, ladder,
observation trajectory), re-runs the observations through a FRESH
controller (autopilot/controller.py replay_record — no model, no JAX),
and verifies the replayed knob sequence equals the recorded one
entry-for-entry. Exit 0 on exact match, 1 on divergence — the REPRO
§17 recipe and tests/test_autopilot.py both go through here, so the
CLI is the contract.
"""

from __future__ import annotations

import argparse
import json
import sys

from commefficient_tpu.autopilot.controller import replay_record


def load_autopilot_record(manifest_path: str) -> dict:
    with open(manifest_path) as f:
        manifest = json.load(f)
    rec = (manifest.get("autopilot")
           or manifest.get("extra", {}).get("autopilot"))
    if not rec:
        raise SystemExit(
            f"{manifest_path}: no autopilot record in manifest "
            "(was the run launched with --autopilot on?)")
    return rec


def verify(rec: dict, verbose: bool = True) -> bool:
    recorded = [e["key"] for e in rec.get("trajectory", [])]
    replayed = replay_record(rec)
    ok = replayed == recorded
    if verbose:
        lo, hi = rec["band"]
        print(f"band {lo}:{hi}  cooldown {rec['cooldown']}  "
              f"ladder {' > '.join(rec['ladder'])}")
        last = None
        for e, rk in zip(rec.get("trajectory", []), replayed):
            mark = "" if rk == e["key"] else "  <-- DIVERGES"
            if e["key"] != last or mark:
                err = e.get("recovery_error")
                err_s = "-" if err is None else f"{err:.4f}"
                print(f"  round {e['round']:>4}  err {err_s:>8}  "
                      f"{e['action']:<8} {e['key']}{mark}")
            last = e["key"]
        print(f"replay: {'EXACT' if ok else 'DIVERGED'} "
              f"({len(recorded)} observations, "
              f"final {rec.get('final', '?')})")
    return ok


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="replay + verify an autopilot trajectory from a "
                    "run-registry manifest")
    p.add_argument("manifest", help="runs/manifests/run_*.json")
    p.add_argument("-q", "--quiet", action="store_true")
    args = p.parse_args(argv)
    rec = load_autopilot_record(args.manifest)
    return 0 if verify(rec, verbose=not args.quiet) else 1


if __name__ == "__main__":
    sys.exit(main())
