"""The discrete compression-knob lattice.

The autopilot never touches a continuous knob: every runtime move is a
step between points of a small discrete lattice — wire dtype × unsketch
k × sketch rows × sketch cols × recall bucket — so each visited point
maps to exactly one jitted round variant in the re-jit cache
(autopilot/cache.py) and revisiting a point can never recompile.

``apply_knobs`` is the ONE sanctioned way a Config's compression knobs
change after construction (the knob-mutation lint rule in
analysis/lint.py hard-fails direct writes outside this package): it
returns the SAME object when the key already matches — the autopilot-off
and pinned-at-base paths therefore build from the identical Config
instance and stay HLO-fingerprint-identical to a build without the
feature.
"""

from __future__ import annotations

import math
from typing import List, NamedTuple, Optional, Tuple

from commefficient_tpu.config import Config

# recall is a float flag; the lattice stores it in basis points so keys
# stay exact, hashable ints end to end (the "recall bucket")
RECALL_SCALE = 10000

# descending wire width (accounting.dtype_bytes: 4 / 2 / 1). fp8 costs
# the same bytes as int8, so it is never an automatic cheapening step —
# it enters a ladder only when the launch config already starts there.
_DTYPE_LADDER = ("f32", "bf16", "int8")

# geometry floor for automatic column-halving steps: below this the
# sketch is too collision-dense for any band to hold and the step is
# wasted lattice surface
_MIN_COLS = 64


class VariantKey(NamedTuple):
    """One lattice point == one jitted round variant (cache key)."""
    dtype: str     # sketch wire dtype: f32 | bf16 | int8 | fp8
    k: int         # unsketch top-k
    rows: int      # sketch rows
    cols: int      # sketch cols
    recall_bp: int # approx_recall in basis points (recall bucket)


def key_of(cfg: Config) -> VariantKey:
    """The lattice point a Config currently sits at."""
    return VariantKey(str(cfg.sketch_dtype), int(cfg.k),
                      int(cfg.num_rows), int(cfg.num_cols),
                      int(round(float(cfg.approx_recall)
                                * RECALL_SCALE)))


def key_str(key: VariantKey) -> str:
    """Compact stable spelling used for ledger compile stamps, the
    manifest trajectory and --autopilot_pin:
    ``int8-k50000-r5-c500000-re9500``."""
    return (f"{key.dtype}-k{key.k}-r{key.rows}-c{key.cols}"
            f"-re{key.recall_bp}")


def parse_key(s: str) -> VariantKey:
    """Inverse of ``key_str`` (raises ValueError on malformed input)."""
    parts = s.strip().split("-")
    if len(parts) != 5 or not all(
            p.startswith(tag) for p, tag in
            zip(parts[1:], ("k", "r", "c", "re"))):
        raise ValueError(f"malformed variant key {s!r} "
                         "(want dtype-kK-rR-cC-reBP)")
    return VariantKey(parts[0], int(parts[1][1:]), int(parts[2][1:]),
                      int(parts[3][1:]), int(parts[4][2:]))


def variant_bytes(key: VariantKey) -> float:
    """Uplink wire bytes/round/client at this lattice point — the cost
    the controller minimises (identical to
    Config.upload_wire_bytes_per_client for the equivalent config)."""
    from commefficient_tpu import accounting
    return accounting.sketch_wire_bytes(key.rows, key.cols, key.dtype)


def apply_knobs(cfg: Config, key: VariantKey) -> Config:
    """The sanctioned re-plan API: a Config moved to ``key``.

    Returns ``cfg`` itself (same object) when the knobs already match,
    so the base variant's round build is bit-for-bit the build a
    feature-less runtime performs. The replaced copy keeps every
    non-knob field — including the runtime-populated ``grad_size``.

    Under ``--dp sketch`` a rows-changing move recalibrates
    ``dp_noise_mult`` by sqrt(rows_base/rows_new): the mechanism's
    table noise std is σ·sqrt(rows)·clip/W (privacy/mechanism.py), so
    the rescale holds the ABSOLUTE noise at the launch calibration —
    the variant's σ is what the accountant charges that round
    (runtime/fed_model.py _charge_privacy)."""
    if key_of(cfg) == key:
        return cfg
    knobs = dict(sketch_dtype=key.dtype, k=key.k,
                 num_rows=key.rows, num_cols=key.cols,
                 approx_recall=key.recall_bp / RECALL_SCALE)
    if (str(getattr(cfg, "dp", "off")) != "off"
            and key.rows != int(cfg.num_rows)):
        knobs["dp_noise_mult"] = float(cfg.dp_noise_mult) * math.sqrt(
            int(cfg.num_rows) / key.rows)
    return cfg.replace(**knobs)


def parse_band(band: str) -> Tuple[float, float]:
    """``--autopilot_band LO:HI`` -> (lo, hi) recovery-error band."""
    try:
        lo_s, hi_s = band.split(":")
        lo, hi = float(lo_s), float(hi_s)
    except ValueError:
        raise ValueError(
            f"--autopilot_band must be LO:HI (got {band!r})") from None
    if not (0.0 <= lo < hi):
        raise ValueError(
            f"--autopilot_band needs 0 <= LO < HI (got {band!r})")
    return lo, hi


def band_str(band: Tuple[float, float]) -> str:
    """Canonical compact spelling, shared with the perf-gate topology
    fragment: ``(0.2, 0.6) -> "0.2-0.6"`` (``:`` is not filename- or
    key-safe)."""
    def fmt(x: float) -> str:
        s = f"{x:g}"
        return s
    return f"{fmt(band[0])}-{fmt(band[1])}"


def build_ladder(cfg: Config) -> List[VariantKey]:
    """Cost-ordered lattice walk for this run, most expensive (safest)
    first. Index 0 is always the launch config's own point; each later
    entry is strictly cheaper on the wire, so the controller's
    "cheapen" move is always index + 1 and "back off" index - 1.

    The default ladder walks the dtype axis only — those moves preserve
    every state shape (sketch geometry, hence ServerState momentum/EF
    tables, is untouched). ``--autopilot_geometry`` appends
    column-halving steps at the cheapest dtype; a geometry move resets
    server momentum/error (runtime/fed_model.py documents the trade).
    """
    base = key_of(cfg)
    keys = [base]
    if base.dtype in _DTYPE_LADDER:
        start = _DTYPE_LADDER.index(base.dtype)
        for dt in _DTYPE_LADDER[start + 1:]:
            keys.append(base._replace(dtype=dt))
    if bool(getattr(cfg, "autopilot_geometry", False)):
        axis = max(1, int(getattr(cfg, "model_axis", 1)))
        tail = keys[-1]
        cols = tail.cols
        while (cols % 2 == 0 and cols // 2 >= _MIN_COLS
               and (cols // 2) % axis == 0):
            cols //= 2
            keys.append(tail._replace(cols=cols))
    # strict cost monotonicity: drop any step that fails to cheapen
    # (e.g. an fp8 base has no cheaper dtype) — the controller's
    # ordering invariant must hold by construction
    ladder = [keys[0]]
    for key in keys[1:]:
        if variant_bytes(key) < variant_bytes(ladder[-1]):
            ladder.append(key)
    return ladder


def ladder_index(ladder: List[VariantKey],
                 key: VariantKey) -> Optional[int]:
    try:
        return ladder.index(key)
    except ValueError:
        return None
