"""Double-buffered background gather for the host client store.

The trainer knows round N+1's participant ids one round ahead
(``FedSampler.peek_next_client_ids``), so a single worker thread can
stage their rows while round N's jitted compute runs, hiding the
host gather + H2D behind device time — the same overlap the C++
dataplane's ring gets for batches.

Two staging buffer sets alternate between consecutive submits, so
the consumer can still be uploading buffer A while the worker fills
buffer B.  Correctness does not depend on the prediction: ``take``
verifies the ids match, patches any row written after the async
gather's snapshot (store write-versions), and returns ``None`` on a
miss so the caller falls back to a synchronous gather.
"""

from __future__ import annotations

import logging
import queue
import random
import threading
import time

import numpy as np

from commefficient_tpu.telemetry import clock

logger = logging.getLogger("commefficient_tpu.clientstore.prefetch")

#: transient shard-read retry policy: GATHER_TRIES total attempts,
#: exponential backoff with +-50% jitter between them. A one-off NFS
#: hiccup or page-cache miss recovers invisibly; a persistent failure
#: still surfaces (as the per-job error on take()) after
#: GATHER_TRIES attempts, so a dead disk cannot silently stall a run.
GATHER_TRIES = 3
GATHER_BACKOFF_S = 0.05


class StorePrefetcher:
    def __init__(self, store, name="clientstore-prefetch"):
        self._store = store
        self._jobs: "queue.Queue" = queue.Queue()
        self._done: "queue.Queue" = queue.Queue()
        self._stop = threading.Event()
        self._pending = 0
        self._buffers = [{}, {}]
        self._buf_i = 0
        self.hits = 0
        self.misses = 0
        # exception that killed the worker LOOP (vs a per-job gather
        # error, which rides the done-queue): re-raised on the main
        # thread at the next submit/take — the next round boundary —
        # instead of the thread dying silently and every later take()
        # stalling out its timeout
        self._failure = None
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=name)
        self._thread.start()

    # ------------------------------------------------------------------
    def _run(self):
        try:
            while not self._stop.is_set():
                try:
                    job = self._jobs.get(timeout=0.1)
                except queue.Empty:
                    continue
                if job is None:
                    return
                ids, buf = job
                try:
                    rows, version = self._gather_with_retry(ids, buf)
                    self._done.put((ids, rows, version, None))
                except BaseException as exc:  # surfaced by take()
                    self._done.put((ids, None, 0, exc))
        except BaseException as exc:
            self._failure = exc

    def _gather_with_retry(self, ids, buf):
        """``store.gather`` with bounded retry: transient shard-read
        failures (OSError/IOError from a file-backed store) get
        GATHER_TRIES attempts with jittered exponential backoff
        before the error rides the done-queue to the caller.
        Non-I/O errors (a real bug) are never retried."""
        delay = GATHER_BACKOFF_S
        for attempt in range(GATHER_TRIES):
            try:
                return self._store.gather(ids, out=buf)
            except OSError as exc:
                if attempt + 1 >= GATHER_TRIES:
                    raise
                jittered = delay * (0.5 + random.random())
                logger.warning(
                    "transient clientstore gather failure "
                    "(attempt %d/%d, retrying in %.3fs): %s",
                    attempt + 1, GATHER_TRIES, jittered, exc)
                time.sleep(jittered)
                delay *= 2

    def _fail_for_test(self, exc):
        """Chaos-harness hook (data/chaos.kill_prefetch_worker):
        mark the worker loop dead exactly as an escaped exception
        would, so tests can exercise the death-surfacing path
        without racing a real thread crash."""
        self._failure = exc
        self._stop.set()
        self._jobs.put(None)

    def _check_failure(self):
        if self._failure is not None:
            raise RuntimeError(
                "clientstore prefetch worker died; round state may be "
                "stale") from self._failure

    # ------------------------------------------------------------------
    def submit(self, ids):
        """Stage an async gather for next round's participant ids."""
        self._check_failure()
        if self._stop.is_set():
            return
        ids = np.array(ids, dtype=np.int64).reshape(-1)
        buf = self._buffers[self._buf_i]
        self._buf_i ^= 1
        self._pending += 1
        self._jobs.put((ids, buf))

    def take(self, ids, timeout=60.0):
        """Rows for ``ids`` if a staged gather matches, else ``None``.

        Drains stale jobs (mispredicted or skipped rounds) until a
        matching one is found; patches rows the store wrote after the
        job's version snapshot so the result is always current.
        """
        self._check_failure()  # a dead worker surfaces even with an
        ids = np.asarray(ids, dtype=np.int64).reshape(-1)  # empty backlog
        deadline = clock.tick() + timeout
        while self._pending > 0:
            self._check_failure()
            try:
                # short poll, not one big blocking get: a dead worker
                # must surface within ~0.1s, not after `timeout`
                job_ids, rows, version, exc = self._done.get(
                    timeout=0.1)
            except queue.Empty:
                if not self._thread.is_alive():
                    self._check_failure()
                    return None  # worker exited cleanly (close())
                if clock.tick() >= deadline:
                    return None  # worker wedged: fall back sync
                continue
            self._pending -= 1
            if exc is not None:
                raise exc
            if len(job_ids) != len(ids) or \
                    not np.array_equal(job_ids, ids):
                self.misses += 1
                continue
            stale = [i for i, cid in enumerate(job_ids)
                     if self._store.row_version(int(cid)) > version]
            if stale:
                fresh, _ = self._store.gather(job_ids[stale])
                for name in rows:
                    rows[name][stale] = fresh[name]
            self.hits += 1
            return rows
        return None

    # ------------------------------------------------------------------
    def close(self, timeout=5.0):
        """Stop the worker and join it; idempotent, never hangs the
        caller past ``timeout`` even with staged jobs un-taken."""
        self._stop.set()
        self._jobs.put(None)
        if self._thread.is_alive():
            self._thread.join(timeout=timeout)

    def __del__(self):
        try:
            self.close(timeout=0.5)
        except Exception:
            pass
