"""Host-resident client-state store.

The device-resident path keeps every client's local-mode rows
(momentum velocity, error feedback, fedavg/topk-down stale weights)
as dense ``(num_clients, *transmit_shape)`` device arrays, so HBM —
not the interconnect — caps the simulated population at a few
thousand clients even though each round only ever touches the W
sampled participants.  ``HostClientStore`` moves those rows off the
accelerator: a fixed-budget NumPy arena holds the hot rows, colder
rows spill to an ``np.memmap`` tier, and only the participating
clients' rows are materialized on device each round
(gather -> H2D -> jitted round -> D2H -> write-back).

Multi-host: each process owns a contiguous block of client ids
(``shard_range``).  ``gather`` returns zeros for rows the process
does not own, so the cross-process exchange is a single
allgather-sum over the (W, ...) participant rows; ``write`` silently
drops rows outside the owned range.

The store is thread-safe (a single re-entrant lock) so the
``StorePrefetcher`` worker can gather round N+1's rows while the
main thread writes back round N's.
"""

from __future__ import annotations

import os
import tempfile
import threading
from collections import OrderedDict
from typing import Dict, Optional, Tuple

import numpy as np

_DTYPE = np.float32


class _Field:
    """One named per-client state row: shape, optional init row."""

    def __init__(self, name, shape, init_row=None):
        self.name = name
        self.shape = tuple(int(s) for s in shape)
        self.elems = int(np.prod(self.shape)) if self.shape else 1
        self.init_row = None
        if init_row is not None:
            self.set_init(init_row)

    def set_init(self, row):
        row = np.asarray(row, dtype=_DTYPE).reshape(self.shape)
        self.init_row = np.array(row, copy=True)

    def default_row(self):
        if self.init_row is not None:
            return self.init_row
        return np.zeros(self.shape, dtype=_DTYPE)


class HostClientStore:
    """Shard-per-process client-state store with an mmap spill tier.

    Parameters
    ----------
    num_clients: total simulated population (global, all processes).
    fields: mapping ``name -> (row_shape, init_row_or_None)``.
    budget_bytes: arena budget for the in-memory (hot) tier.  Rows
        beyond the budget are evicted LRU-first to the memmap tier.
        A budget smaller than one row still works: every write goes
        straight to the spill tier.
    spill_dir: directory for the memmap files.  Defaults to a private
        temp dir removed on ``close()``.
    owned: half-open ``(lo, hi)`` range of client ids this process
        persists.  Defaults to the full population.
    """

    def __init__(self, num_clients, fields, budget_bytes=1 << 30,
                 spill_dir=None, owned=None):
        self.num_clients = int(num_clients)
        self.fields = OrderedDict(
            (name, _Field(name, shape, init_row))
            for name, (shape, init_row) in fields.items())
        self.owned = (0, self.num_clients) if owned is None else (
            int(owned[0]), int(owned[1]))
        if not (0 <= self.owned[0] <= self.owned[1] <= self.num_clients):
            raise ValueError(f"owned range {self.owned} outside "
                             f"[0, {self.num_clients})")
        self.budget_bytes = int(budget_bytes)

        self.row_bytes = sum(f.elems for f in self.fields.values()) * \
            np.dtype(_DTYPE).itemsize
        n_owned = self.owned[1] - self.owned[0]
        arena_rows = (self.budget_bytes // self.row_bytes
                      if self.row_bytes else 0)
        self.arena_rows = int(min(arena_rows, n_owned))

        # hot tier: one (arena_rows, *shape) array per field; slots are
        # shared across fields (slot i of every field belongs to the
        # same client).  np.zeros is lazily paged-in on Linux, so a
        # large budget costs no RSS until rows are actually written.
        self._arena = {name: np.zeros((self.arena_rows,) + f.shape, _DTYPE)
                       for name, f in self.fields.items()}
        self._lru: "OrderedDict[int, int]" = OrderedDict()  # cid -> slot
        self._free = list(range(self.arena_rows - 1, -1, -1))
        self._in_spill: set = set()   # cids whose current row is mmap'd
        self._spill = None            # name -> memmap, created lazily
        self._spill_dir = spill_dir
        self._tmpdir = None
        self._spill_paths = []

        self._lock = threading.RLock()
        self._version = 0
        self._row_version: Dict[int, int] = {}
        # asyncfed issue stamps: client id -> round index at which its
        # participant snapshot was issued into the arrival queue.
        # Bookkeeping only (no row data): lets tests/telemetry check a
        # buffered fold consumed the snapshot version it was issued
        # with, not a later write-back's.
        self._issue_round: Dict[int, int] = {}
        self._closed = False

        self.stats = {
            "evictions": 0,
            "spill_rows": 0,        # rows currently in the mmap tier
            "resident_rows": 0,     # rows currently in the arena
            "resident_rows_max": 0,
            "gathers": 0,
            "writes": 0,
        }

    # ------------------------------------------------------------------
    @property
    def field_names(self):
        return list(self.fields)

    def owns(self, cid):
        return self.owned[0] <= int(cid) < self.owned[1]

    def row_version(self, cid):
        with self._lock:
            return self._row_version.get(int(cid), 0)

    def stamp_rounds(self, ids, round_index):
        """Version-stamp participant snapshots at issue time: the
        asyncfed driver records which round issued each client into
        the arrival queue (the snapshot the buffered fold will
        replay)."""
        r = int(round_index)
        with self._lock:
            for cid in np.asarray(ids).reshape(-1):
                self._issue_round[int(cid)] = r

    def stamped_round(self, cid):
        """The round index that last issued ``cid`` (-1 = never)."""
        with self._lock:
            return self._issue_round.get(int(cid), -1)

    def export_stamps(self):
        """``(ids, rounds)`` int64 arrays of every issue-round stamp,
        for checkpointing: the asyncfed staleness bookkeeping must
        survive a resume along with the arrival backlog it audits.
        Stamps cover the full issued cohort on every process (the
        driver stamps before ownership filtering), so one process's
        export is the global view."""
        with self._lock:
            ids = np.asarray(sorted(self._issue_round), np.int64)
            rounds = np.asarray([self._issue_round[int(i)]
                                 for i in ids], np.int64)
        return ids, rounds

    def import_stamps(self, ids, rounds):
        """Inverse of :meth:`export_stamps` (checkpoint restore)."""
        with self._lock:
            self._issue_round = {
                int(i): int(r)
                for i, r in zip(np.asarray(ids).reshape(-1),
                                np.asarray(rounds).reshape(-1))}

    @property
    def version(self):
        with self._lock:
            return self._version

    def set_init_row(self, name, row):
        """(Re)define a field's unwritten-row value — used on resume so
        never-participating clients keep the ORIGINAL run's init."""
        with self._lock:
            self.fields[name].set_init(row)

    # ------------------------------------------------------------------
    def _ensure_spill(self):
        if self._spill is not None:
            return
        if self._spill_dir:
            os.makedirs(self._spill_dir, exist_ok=True)
            base = self._spill_dir
        else:
            self._tmpdir = tempfile.mkdtemp(prefix="clientstore_")
            base = self._tmpdir
        n_owned = max(1, self.owned[1] - self.owned[0])
        self._spill = {}
        for name, f in self.fields.items():
            path = os.path.join(base, f"spill_{name}.dat")
            # sparse until rows are actually evicted
            self._spill[name] = np.memmap(
                path, dtype=_DTYPE, mode="w+",
                shape=(n_owned,) + f.shape)
            self._spill_paths.append(path)

    def _evict_one(self):
        """Push the LRU arena row to the spill tier; return its slot."""
        cid, slot = self._lru.popitem(last=False)
        self._ensure_spill()
        off = cid - self.owned[0]
        for name in self.fields:
            self._spill[name][off] = self._arena[name][slot]
        self._in_spill.add(cid)
        self.stats["evictions"] += 1
        return slot

    def _read_row_into(self, cid, out, i):
        """Copy client ``cid``'s current row of every field into
        ``out[name][i]``.  Caller holds the lock."""
        slot = self._lru.get(cid)
        if slot is not None:
            self._lru.move_to_end(cid)
            for name in self.fields:
                out[name][i] = self._arena[name][slot]
        elif cid in self._in_spill:
            off = cid - self.owned[0]
            for name in self.fields:
                out[name][i] = self._spill[name][off]
        else:
            for name, f in self.fields.items():
                out[name][i] = f.default_row()

    # ------------------------------------------------------------------
    def gather(self, ids, out=None):
        """Materialize rows for ``ids`` (host-side).

        Returns ``(rows, version)`` where ``rows`` maps field name to a
        ``(len(ids), *shape)`` f32 array and ``version`` is the store's
        write version at snapshot time (used by the prefetcher to patch
        rows written after an async gather started).  Ids outside the
        owned range come back as zeros — the multi-host exchange sums
        the per-process gathers, so exactly one process contributes
        each row's real value.
        """
        ids = np.asarray(ids, dtype=np.int64).reshape(-1)
        with self._lock:
            if self._closed:
                raise RuntimeError("HostClientStore is closed")
            n = len(ids)
            rows = {}
            for name, f in self.fields.items():
                buf = None if out is None else out.get(name)
                if (buf is None or buf.shape != (n,) + f.shape
                        or buf.dtype != _DTYPE):
                    buf = np.empty((n,) + f.shape, dtype=_DTYPE)
                rows[name] = buf
            for i, cid in enumerate(ids):
                cid = int(cid)
                if not self.owns(cid):
                    for name in self.fields:
                        rows[name][i] = 0.0
                else:
                    self._read_row_into(cid, rows, i)
            self.stats["gathers"] += 1
            return rows, self._version

    def write(self, ids, rows):
        """Write back rows for ``ids``; non-owned ids are dropped.

        ``rows`` maps field name to a ``(len(ids), *shape)`` array.
        """
        ids = np.asarray(ids, dtype=np.int64).reshape(-1)
        with self._lock:
            if self._closed:
                raise RuntimeError("HostClientStore is closed")
            self._version += 1
            for i, cid in enumerate(ids):
                cid = int(cid)
                if not self.owns(cid):
                    continue
                slot = self._lru.get(cid)
                if slot is None and self.arena_rows:
                    slot = (self._free.pop() if self._free
                            else self._evict_one())
                    self._lru[cid] = slot
                elif slot is not None:
                    self._lru.move_to_end(cid)
                if slot is not None:
                    for name in self.fields:
                        self._arena[name][slot] = rows[name][i]
                    self._in_spill.discard(cid)
                else:  # zero-row arena: straight to the spill tier
                    self._ensure_spill()
                    off = cid - self.owned[0]
                    for name in self.fields:
                        self._spill[name][off] = rows[name][i]
                    self._in_spill.add(cid)
                self._row_version[cid] = self._version
            self.stats["writes"] += 1
            self.stats["spill_rows"] = len(self._in_spill)
            self.stats["resident_rows"] = len(self._lru)
            self.stats["resident_rows_max"] = max(
                self.stats["resident_rows_max"], len(self._lru))

    # ------------------------------------------------------------------
    def written_ids(self):
        with self._lock:
            return np.array(sorted(set(self._lru) | self._in_spill),
                            dtype=np.int64)

    def export_shard(self):
        """Sparse snapshot of this process's shard for checkpointing:
        ``{"ids": (n,), "<field>": (n, *shape), "init:<field>": row}``
        (init rows only for fields that have one)."""
        with self._lock:
            ids = self.written_ids()
            rows, _ = self.gather(ids)
            shard = {"ids": ids}
            for name, arr in rows.items():
                shard[name] = arr
            for name, f in self.fields.items():
                if f.init_row is not None:
                    shard["init:" + name] = np.array(f.init_row)
            return shard

    def import_shard(self, shard):
        """Restore a snapshot produced by ``export_shard`` (owned rows
        only; foreign ids in a mismatched shard are dropped by
        ``write``)."""
        with self._lock:
            for name in self.fields:
                key = "init:" + name
                if key in shard:
                    self.fields[name].set_init(shard[key])
            ids = np.asarray(shard["ids"], dtype=np.int64)
            if len(ids):
                self.write(ids, {name: np.asarray(shard[name], _DTYPE)
                                 for name in self.fields})

    # ------------------------------------------------------------------
    def close(self):
        with self._lock:
            if self._closed:
                return
            self._closed = True
            if self._spill is not None:
                for mm in self._spill.values():
                    del mm
                self._spill = None
            for path in self._spill_paths:
                try:
                    os.remove(path)
                except OSError:
                    pass
            if self._tmpdir is not None:
                try:
                    os.rmdir(self._tmpdir)
                except OSError:
                    pass
                self._tmpdir = None
            self._arena = {}
            self._lru.clear()
            self._in_spill.clear()

    def __del__(self):  # best-effort temp cleanup
        try:
            self.close()
        except Exception:
            pass


# ----------------------------------------------------------------------
# Config plumbing


def state_fields(cfg, init_weights=None):
    """Which per-client fields the mode/config combination needs, as a
    ``HostClientStore`` fields mapping.  Mirrors
    ``core.rounds.ClientStates.init``: velocities for local momentum,
    errors for local error feedback, stale weights for topk_down
    (initialized to the server weights)."""
    fields = OrderedDict()
    shape = tuple(int(s) for s in cfg.transmit_shape)
    if cfg.local_momentum > 0:
        fields["velocities"] = (shape, None)
    if cfg.error_type == "local":
        fields["errors"] = (shape, None)
    if getattr(cfg, "do_topk_down", False):
        fields["weights"] = ((int(cfg.grad_size),), init_weights)
    return fields


def state_row_bytes(cfg):
    """Bytes of per-client state one client costs under ``cfg``."""
    return sum(int(np.prod(shape)) if shape else 1
               for shape, _ in state_fields(cfg).values()) * \
        np.dtype(_DTYPE).itemsize


def resolve_clientstore(cfg, num_clients):
    """Resolve ``--clientstore auto`` to a concrete placement, the same
    build-time pattern as ``resolve_rot_lanes``/``resolve_fused_ce``:
    keep state in HBM while the dense population fits the byte budget,
    spill to the host store beyond it."""
    mode = getattr(cfg, "clientstore", "device")
    if mode != "auto":
        return mode
    rb = state_row_bytes(cfg)
    if rb == 0:
        return "device"   # stateless combo: nothing to store
    budget = int(getattr(cfg, "clientstore_bytes", 1 << 30))
    return "host" if int(num_clients) * rb > budget else "device"


def shard_range(num_clients, process_index=None, process_count=None):
    """Contiguous client-id block ``[lo, hi)`` owned by a process."""
    if process_index is None or process_count is None:
        import jax
        process_index = jax.process_index()
        process_count = jax.process_count()
    per = -(-int(num_clients) // max(1, int(process_count)))
    lo = min(int(process_index) * per, int(num_clients))
    return lo, min(lo + per, int(num_clients))
