"""Host-backed per-client state store (see store.py for the design).

Public surface:
  HostClientStore     — budgeted NumPy arena + mmap spill tier
  StorePrefetcher     — double-buffered async gather thread
  state_fields        — which fields a Config needs
  state_row_bytes     — per-client state footprint under a Config
  resolve_clientstore — build-time resolution of --clientstore auto
  shard_range         — contiguous multi-host client-id ownership
"""

from commefficient_tpu.clientstore.prefetch import StorePrefetcher
from commefficient_tpu.clientstore.store import (HostClientStore,
                                                 resolve_clientstore,
                                                 shard_range,
                                                 state_fields,
                                                 state_row_bytes)

__all__ = [
    "HostClientStore",
    "StorePrefetcher",
    "resolve_clientstore",
    "shard_range",
    "state_fields",
    "state_row_bytes",
]
