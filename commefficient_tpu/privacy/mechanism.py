"""In-round DP primitives: per-client clip, aggregated-table noise.

The ``--dp sketch`` mechanism (FedSKETCH, PAPERS.md):

1. each participating client's SUMMED dense gradient — the
   microbatch-accumulated total, never divided by the batch size
   (core/grad.py), so ``--dp_clip`` is calibrated at summed-gradient
   scale and grows with the local batch — is L2-clipped to
   ``--dp_clip`` (``dp_clip`` below — the shared clip algebra from
   core/robust.py, so the robust ``clip`` fold and the DP clip
   cannot drift);
2. the round's *aggregated* sketch table — after the fold and its
   capacity normalisation, BEFORE any wire quantization — receives
   one Gaussian noise draw with std ``table_noise_std(cfg)``. The
   released value is therefore exactly what the accountant charges
   for; the int8/fp8 wire qdq that follows is post-processing (free).

Sensitivity: the transmitted quantity is the CLIPPED gradient times
the client's real datapoint count — core/client.py scales the
clipped unit by ``n_i ≤ B`` after the clip — and every count-sketch
row receives the full vector, so a client's table has L2 norm
≤ sqrt(num_rows)·dp_clip·n_i. DP folds divide by the STATIC padded
capacity ``W·B`` (core/rounds.py / core/robust.py), never by the
data-dependent alive total, so one client's share of the released
aggregate is ≤ sqrt(r)·C·n_i/(W·B) ≤ sqrt(r)·C/W on EVERY round —
tight at ``n_i = B``, conservative for smaller batches, and immune
to mostly-dead rounds (a shrinking alive total would otherwise hand
a survivor a share above sqrt(r)·C/W against noise calibrated for
W). Noise std is ``dp_noise_mult`` times that bound, so the
accountant's per-round noise multiplier is exactly
``cfg.dp_noise_mult``. Because the denominator is weight- and
data-independent, asyncfed staleness weights genuinely scale each
client's release (cw_i·t_i/(W·B)) and earn the accountant's
``weight_scale`` sensitivity discount (runtime/fed_model.py,
accountant.py).

Replayability: the one noise key per round is a distinguished
``fold_in`` of the round key already threaded through
core/rounds.py — per-client streams fold in client ids (< 2^31-1),
so the tag below can never collide with them. Same seed, same round
index ⇒ bit-identical noise, including across elastic resume.

This module is the ONLY place raw ``jax.random`` noise draws are
allowed (analysis/lint.py ``noise-confinement``); everything else —
the legacy reference-parity worker/server DP in core/grad.py /
core/server.py included — routes through ``noise_stream`` /
``gaussian_noise``.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from commefficient_tpu.core.robust import _TINY, clip_factors

DP_MODES_NEW = ("off", "sketch")

# out-of-range for any client id (ids are int32 client indices), so
# the round's noise stream can never collide with a per-client stream
_NOISE_TAG = 0x7FFFFFFF


def noise_stream(seed: int):
    """A dedicated noise PRNG root. The one sanctioned way to mint a
    noise key chain outside this package (lint: noise-confinement)."""
    return jax.random.PRNGKey(seed)


def round_noise_key(rng):
    """The round's single table-noise key, derived from the round key
    that core/rounds.py already threads — disjoint from every
    per-client stream by the out-of-range fold tag."""
    return jax.random.fold_in(rng, _NOISE_TAG)


def gaussian_noise(rng, shape, dtype=jnp.float32, std=1.0):
    """std · N(0, 1) of the given shape — the shared draw primitive
    (legacy worker/server DP noise routes through here too)."""
    return std * jax.random.normal(rng, shape, dtype)


def dp_clip(g, cap):
    """L2-clip one client's dense gradient — the microbatch-
    accumulated SUM, not a per-datapoint mean (core/grad.py) — to
    ``cap``, with the same min(1, cap/max(norm, tiny)) factor as the
    robust clip fold (core/robust.clip_factors), exact identity
    inside the cap."""
    norm = jnp.sqrt(jnp.sum(jax.lax.square(g)))
    return g * clip_factors(norm, jnp.float32(cap))


def table_sensitivity(num_rows: int, clip: float,
                      num_workers: int) -> float:
    """One client's max L2 contribution to the aggregated table:
    sqrt(r)·C/W (every sketch row carries the full clipped vector,
    the transmit scales it by n_i ≤ B, and DP-mode folds divide by
    the static W·B capacity — core/rounds.py — so the bound holds on
    padded / mostly-dead rounds too, tight at n_i = B)."""
    return math.sqrt(num_rows) * float(clip) / float(num_workers)


def table_noise_std(cfg) -> float:
    """The mechanism's noise std: dp_noise_mult × sensitivity. A
    trace-time Python float — the compiled round bakes it in."""
    return float(cfg.dp_noise_mult) * table_sensitivity(
        cfg.num_rows, cfg.dp_clip, cfg.num_workers)


def add_table_noise(table, noise_key, std: float):
    """The release: aggregated table + N(0, std²). Called before any
    wire quantization so the accountant's charged value is exactly
    what leaves the round."""
    return table + gaussian_noise(noise_key, table.shape, table.dtype,
                                  std=std)


# ---------------------------------------------------------------- #
# NumPy mirrors (tests/reference_mirror.py discipline: restate the  #
# algebra independently; must match the jitted path to 1e-6 — the   #
# clip exactly, the noise to ulp level given the same key: the      #
# threefry bits are identical, only the uniform->normal tail may    #
# fuse differently inside the round jit).                           #
# ---------------------------------------------------------------- #

def np_dp_clip(g: np.ndarray, cap: float) -> np.ndarray:
    """Mirror of ``dp_clip``: same formula, same _TINY guard, norm
    taken in f32 like the jitted path."""
    norm = np.float32(np.sqrt(np.sum(np.square(
        g.astype(np.float32)))))
    scale = np.float32(min(1.0, float(cap) / max(float(norm), _TINY)))
    return g.astype(np.float32) * scale


def np_dp_noise(noise_key, shape, std: float) -> np.ndarray:
    """Mirror of the table noise draw. The std calibration is
    restated host-side by the caller (np mirror of table_noise_std);
    the N(0,1) stream itself is *defined* as JAX's threefry draw for
    the given key — the mirror pins the scaling and placement, and
    the draw is evaluated outside jit so any jit-only transform of
    the noise would be caught."""
    return np.asarray(
        std * jax.random.normal(noise_key, shape, jnp.float32))
