"""Differentially-private sketching (--dp) and its accountant.

Two halves, matching the FedSKETCH recipe (PAPERS.md — clip-then-
noise *inside* the count-sketch costs no extra wire bytes):

- ``mechanism``: the in-round DP primitives — per-client L2 clipping
  (the shared clip algebra from core/robust.py) and calibrated
  Gaussian noise on the *aggregated* sketch table, drawn from seeded
  per-round PRNG keys so runs replay bit-exactly. Every noise draw in
  the codebase routes through here (analysis/lint.py
  ``noise-confinement`` makes raw draws elsewhere an audit failure).
- ``accountant``: Rényi-DP composition of the subsampled Gaussian
  mechanism with an ε(δ) conversion — client subsampling, staleness-
  weighted folds (weights scale sensitivity), and quantization
  post-processing (free) are all accounted; state round-trips JSON-
  exactly through elastic checkpoints.
"""

from commefficient_tpu.privacy.accountant import (  # noqa: F401
    PrivacyAccountant, build_accountant, eps_from_rdp,
    rdp_subsampled_gaussian, sample_rate_of, steps_to_budget)
from commefficient_tpu.privacy.mechanism import (  # noqa: F401
    add_table_noise, dp_clip, gaussian_noise, noise_stream,
    np_dp_clip, np_dp_noise, round_noise_key, table_noise_std)
