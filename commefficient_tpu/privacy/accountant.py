"""Rényi-DP accountant for the subsampled Gaussian mechanism.

Pure host-side math (no jax import): the accountant composes one RDP
curve per round and converts to (ε, δ) on demand, so it can run in
telemetry/report contexts without touching a device.

Per round the mechanism (privacy/mechanism.py) releases the
aggregated sketch table + N(0, (σ·Δ)²) where Δ bounds one client's
contribution and σ = ``--dp_noise_mult``. When a round's cohort is
genuinely Poisson-sampled at rate q (every client tossed
independently), the round is the sampled Gaussian mechanism; its RDP
at integer order α is the exact Mironov–Talwar–Zhang closed form

    ε_α = log( Σ_{k=0}^{α} C(α,k) (1-q)^{α-k} q^k
               · exp(k(k-1)/(2σ²)) ) / (α-1)

(q=1 degenerates to the plain Gaussian α/(2σ²)). RDP composes by
addition over rounds; ε(δ) is the order-minimised conversion

    ε = min_α  ε_α_total + log((α-1)/α) − (log δ + log α)/(α-1)

(the tightened Canonne–Kamath–Steinke bound). The repo's own runs
charge q = 1 — NO subsampling amplification: the FedSampler cohort
is ``num_workers`` non-exhausted clients drawn without replacement,
and every client participates in ~data_i/batch rounds per epoch
until its data is spent, so participation is neither Poisson nor
independent across rounds and the amplified curve would under-report
ε (``sample_rate_of``). The subsampled closed form stays available
for callers that do Poisson-sample. Two round features and what they
are charged:

- **staleness weights** (asyncfed) earn a sensitivity discount
  because DP folds normalise by the STATIC padded capacity W·B
  (core/rounds.py), never by the weighted datapoint total: a
  client's released contribution is cw_i·t_i/(W·B), genuinely
  scaled by its fold weight, so a round whose largest alive weight
  is w has sensitivity w·Δ and is charged ``step(weight_scale=w)``
  — the effective noise multiplier σ/w (runtime/fed_model.py).
  The discount is sound ONLY against a weight-independent
  normaliser; against the weight-preserving Σ cw_i·n_i denominator
  uniform weights would cancel out of the release and the
  discounted curve would under-report ε.
- **quantization**: the int8/fp8 wire qdq runs *after* the noise
  (core/rounds.py ordering) — post-processing, charged nothing.

State (per-order RDP totals + step count) is a flat JSON dict of
Python floats, so checkpoint round-trips are bit-exact
(runtime/checkpoint.py stores it in the meta record).
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

# integer orders: dense low range where the minimum usually lands,
# sparse tail for tiny-q / huge-σ regimes
DEFAULT_ORDERS = tuple(range(2, 64)) + (72, 96, 128, 192, 256, 512)


def _log_comb(n: int, k: int) -> float:
    return (math.lgamma(n + 1) - math.lgamma(k + 1)
            - math.lgamma(n - k + 1))


def rdp_gaussian(sigma: float, alpha: int) -> float:
    """RDP of the (unsampled) Gaussian mechanism at order alpha."""
    return float(alpha) / (2.0 * sigma * sigma)


def rdp_subsampled_gaussian(q: float, sigma: float,
                            alpha: int) -> float:
    """RDP at integer order alpha ≥ 2 of the Poisson-sampled Gaussian
    with sampling rate q and noise multiplier sigma."""
    assert alpha >= 2 and alpha == int(alpha), alpha
    if sigma <= 0.0:
        return math.inf
    if q <= 0.0:
        return 0.0
    if q >= 1.0:
        return rdp_gaussian(sigma, alpha)
    # log-sum-exp over the binomial expansion
    log_terms = []
    for k in range(alpha + 1):
        lt = (_log_comb(alpha, k)
              + (alpha - k) * math.log1p(-q)
              + (k * math.log(q) if k else 0.0)
              + k * (k - 1) / (2.0 * sigma * sigma))
        log_terms.append(lt)
    m = max(log_terms)
    return (m + math.log(sum(math.exp(t - m) for t in log_terms))) \
        / (alpha - 1)


def eps_from_rdp(orders: Sequence[int], rdp: Sequence[float],
                 delta: float) -> float:
    """Order-minimised RDP → (ε, δ) conversion (CKS tightening).
    Returns inf when every order is inf (σ = 0)."""
    assert 0.0 < delta < 1.0, delta
    best = math.inf
    for alpha, r in zip(orders, rdp):
        if not math.isfinite(r):
            continue
        eps = (r + math.log((alpha - 1) / alpha)
               - (math.log(delta) + math.log(alpha)) / (alpha - 1))
        best = min(best, max(eps, 0.0))
    return best


class PrivacyAccountant:
    """Composes per-round RDP; converts to ε(δ) on demand.

    One instance per run. ``step()`` after every released round;
    ``epsilon()`` is the spent budget so far; ``state_dict`` /
    ``load_state`` round-trip bit-exactly through JSON.
    """

    def __init__(self, noise_multiplier: float, sample_rate: float,
                 delta: float,
                 orders: Sequence[int] = DEFAULT_ORDERS):
        assert noise_multiplier >= 0.0, noise_multiplier
        assert 0.0 <= sample_rate <= 1.0, sample_rate
        assert 0.0 < delta < 1.0, delta
        self.noise_multiplier = float(noise_multiplier)
        self.sample_rate = float(sample_rate)
        self.delta = float(delta)
        self.orders = tuple(int(a) for a in orders)
        self._rdp = [0.0] * len(self.orders)
        self.steps = 0

    # ------------------------------------------------------------ #

    def round_rdp(self, weight_scale: float = 1.0,
                  sigma: Optional[float] = None) -> list:
        """One round's RDP curve. ``weight_scale=w`` charges the
        effective noise multiplier σ/w — sound ONLY for a mechanism
        that scales every client's contribution by ≤ w against a
        weight-independent normaliser. The shipped DP folds qualify:
        they divide by the static W·B capacity, so the runtime
        charges the round's largest alive staleness weight (module
        docstring). ``sigma`` overrides the base noise
        multiplier for the round — the autopilot's active variant may
        run a different ``dp_noise_mult`` than the launch config
        (geometry moves rescale it; autopilot/lattice.py)."""
        assert 0.0 < weight_scale <= 1.0, weight_scale
        base = self.noise_multiplier if sigma is None else float(sigma)
        eff = base / weight_scale if base > 0 else 0.0
        return [rdp_subsampled_gaussian(self.sample_rate, eff, a)
                for a in self.orders]

    def step(self, weight_scale: float = 1.0,
             sigma: Optional[float] = None) -> None:
        """Charge one released round."""
        for i, r in enumerate(self.round_rdp(weight_scale, sigma)):
            self._rdp[i] += r
        self.steps += 1

    def epsilon(self, delta: Optional[float] = None) -> float:
        """ε spent so far at the accountant's δ (or an override)."""
        if self.steps == 0:
            return 0.0
        return eps_from_rdp(self.orders, self._rdp,
                            self.delta if delta is None else delta)

    def epsilon_after(self, extra_steps: int,
                      weight_scale: float = 1.0,
                      sigma: Optional[float] = None) -> float:
        """Projected ε after ``extra_steps`` more rounds at the given
        weight scale (and optional per-round σ override) — the
        autopilot's budget-feasibility check and the alarm's
        predicted-exhaustion round, without mutating state."""
        if extra_steps <= 0:
            return self.epsilon()
        per = self.round_rdp(weight_scale, sigma)
        total = [a + extra_steps * b for a, b in zip(self._rdp, per)]
        return eps_from_rdp(self.orders, total, self.delta)

    def rounds_left(self, eps_budget: float,
                    weight_scale: float = 1.0,
                    sigma: Optional[float] = None,
                    max_steps: int = 1 << 20) -> int:
        """How many MORE rounds fit under ``eps_budget`` from the
        current spent state — bisection on ``epsilon_after`` (ε is
        monotone in the step count). 0 when the budget is already
        spent; ``max_steps`` when it is never reached inside it."""
        assert eps_budget > 0.0, eps_budget
        if self.epsilon() >= eps_budget:
            return 0
        if self.epsilon_after(max_steps, weight_scale,
                              sigma) <= eps_budget:
            return max_steps
        lo, hi = 0, max_steps  # eps_after(lo) < budget < eps_after(hi)
        while hi - lo > 1:
            mid = (lo + hi) // 2
            if self.epsilon_after(mid, weight_scale,
                                  sigma) <= eps_budget:
                lo = mid
            else:
                hi = mid
        return lo

    # ------------------------------------------------------------ #

    def state_dict(self) -> dict:
        """JSON-exact state: floats round-trip bit-for-bit."""
        return {
            "noise_multiplier": self.noise_multiplier,
            "sample_rate": self.sample_rate,
            "delta": self.delta,
            "orders": list(self.orders),
            "rdp": list(self._rdp),
            "steps": int(self.steps),
        }

    @classmethod
    def load_state(cls, state: dict) -> "PrivacyAccountant":
        acc = cls(state["noise_multiplier"], state["sample_rate"],
                  state["delta"], orders=state["orders"])
        rdp = [float(x) for x in state["rdp"]]
        assert len(rdp) == len(acc.orders), (len(rdp), len(acc.orders))
        acc._rdp = rdp
        acc.steps = int(state["steps"])
        return acc


def steps_to_budget(noise_multiplier: float, sample_rate: float,
                    delta: float, eps_budget: float,
                    max_steps: int = 1 << 20,
                    orders: Sequence[int] = DEFAULT_ORDERS) -> int:
    """How many rounds fit inside ``eps_budget``? Exact bisection on
    the composed curve (ε is monotone in the step count). 0 when even
    one round exceeds the budget; ``max_steps`` when the budget is
    never reached inside it (σ large / q tiny)."""
    assert eps_budget > 0.0, eps_budget
    per = [rdp_subsampled_gaussian(sample_rate, noise_multiplier, a)
           for a in orders]

    def eps_at(n):
        return eps_from_rdp(orders, [n * r for r in per], delta)

    if eps_at(1) > eps_budget:
        return 0
    if eps_at(max_steps) <= eps_budget:
        return max_steps
    lo, hi = 1, max_steps  # eps_at(lo) <= budget < eps_at(hi)
    while hi - lo > 1:
        mid = (lo + hi) // 2
        if eps_at(mid) <= eps_budget:
            lo = mid
        else:
            hi = mid
    return lo


def sample_rate_of(cfg) -> float:
    """The accountant's per-round sampling rate for this config:
    1.0 — NO subsampling amplification. Poisson amplification needs
    every client tossed independently at rate q each round; the
    FedSampler cohort is ``num_workers`` non-exhausted clients drawn
    WITHOUT replacement, with every client participating until its
    epoch data is spent, so charging q = num_workers/num_clients
    would under-report ε (module docstring). The subsampled curve
    stays available to callers that genuinely Poisson-sample
    (``rdp_subsampled_gaussian`` / ``PrivacyAccountant(sample_rate=
    q)``). Shared by the accountant, the autopilot's budget
    pre-filter and the selftest's closed-form check so all three
    price the same mechanism."""
    del cfg
    return 1.0


def build_accountant(cfg) -> Optional[PrivacyAccountant]:
    """The run's accountant, or None when ``--dp off``."""
    if str(getattr(cfg, "dp", "off")) == "off":
        return None
    return PrivacyAccountant(float(cfg.dp_noise_mult),
                             sample_rate_of(cfg),
                             float(cfg.dp_delta))
