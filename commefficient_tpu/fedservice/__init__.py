"""Multi-tenant federation service: one long-lived daemon owning one
elastic pod, multiplexing many independent federated jobs over it.

Everything below the daemon is the ordinary single-job stack — each
admitted job gets its own :class:`~commefficient_tpu.runtime.fed_model.
FedModel` (own telemetry ledger shard, own alarm engine, own DP
accountant, own RNG stream), so a single job driven through the daemon
is bit-identical to driving the model directly. The daemon adds only
the control plane on top:

- :class:`JobSpec` manifests + admission control (``FedService.admit``)
- the scheduler (spatial sub-meshes carved by ``parallel/mesh.py``
  and/or round-robin time-slicing over the shared pod)
- per-job isolation (ledger shards, checkpoints, disjoint seeds)
- fairness observability (occupancy / backlog / starvation probes in
  the service's own ledger; ``job_starvation`` and
  ``admission_rejected`` alarm rules)

Importing ``fedservice`` from other ``commefficient_tpu`` modules is a
lint violation (``fedservice-confinement`` in ``analysis/lint.py``) —
the service sits ON TOP of the runtime, never underneath it.
"""

from commefficient_tpu.fedservice.job import AdmissionError, JobSpec
from commefficient_tpu.fedservice.service import FedService

__all__ = ["AdmissionError", "FedService", "JobSpec"]
