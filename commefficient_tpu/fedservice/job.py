"""JobSpec manifests for the federation service.

A :class:`JobSpec` is everything the daemon needs to admit and run one
federated job: the job's :class:`~commefficient_tpu.config.Config`, a
builder that constructs the job's ``(FedModel, FedOptimizer)`` pair
under a mesh the SERVICE chooses, and a batch source. The spec never
touches devices itself — mesh carving stays in ``parallel/mesh.py``
and model construction stays in the builder, so admission can reason
about capacity before anything is allocated.
"""

import dataclasses
from typing import Callable, Optional, Tuple


class AdmissionError(ValueError):
    """A JobSpec the pod cannot (or must not) run: oversubscribed
    mesh demand, colliding job id, or a seed collision that would
    alias two jobs' RNG streams. Raised by ``FedService.admit`` AFTER
    the rejection has been counted in the service ledger, so the
    ``admission_rejected`` alarm fires even when the caller swallows
    the exception."""


@dataclasses.dataclass
class JobSpec:
    """One tenant's manifest.

    ``builder(cfg, mesh)`` must return ``(model, opt)`` constructed
    from exactly the ``cfg`` and ``mesh`` it is handed: the service
    rewrites ``cfg.ledger`` to the job's ``.job<j>.jsonl`` shard
    (ledger paths are excluded from ``config_hash``, so lineage is
    unaffected) and carves ``mesh`` from the pod when the spec asks
    for spatial partitioning. A builder that ignores its arguments
    breaks per-job isolation and determinism-parity with solo runs.

    ``batch_fn(round_index)`` returns the next round batch for the
    job, or ``None`` when the job is out of work; the scheduler also
    retires the job after ``rounds`` completed rounds.

    ``mesh_demand=(C, M)`` requests a dedicated ``CxM`` sub-mesh
    (spatial partitioning); ``None`` time-slices the whole pod
    through the jitted-variant cache instead.
    """

    job_id: str
    cfg: object
    builder: Callable
    batch_fn: Callable
    rounds: int
    mesh_demand: Optional[Tuple[int, int]] = None

    def validate(self):
        """Spec-local admission checks (no pod state needed)."""
        if not str(self.job_id):
            raise AdmissionError("JobSpec.job_id must be non-empty")
        if int(self.rounds) < 1:
            raise AdmissionError(
                f"job {self.job_id}: rounds must be >= 1, "
                f"got {self.rounds}")
        if self.mesh_demand is not None:
            c, m = self.mesh_demand
            if int(c) < 1 or int(m) < 1:
                raise AdmissionError(
                    f"job {self.job_id}: mesh_demand {c}x{m} "
                    "must be positive")
        if not callable(self.builder) or not callable(self.batch_fn):
            raise AdmissionError(
                f"job {self.job_id}: builder and batch_fn must be "
                "callable")

    def demand_devices(self) -> int:
        """Devices a spatial spec reserves (0 for time-sliced)."""
        if self.mesh_demand is None:
            return 0
        c, m = self.mesh_demand
        return int(c) * int(m)
