"""FedService: the long-lived multi-tenant federation daemon.

One service instance owns one pod and runs J admitted jobs over it.
Each job is the ordinary single-job stack — its own FedModel (own
ledger shard, alarm engine, DP accountant, RNG stream keyed by its
own seed) — so the daemon's value-add is purely control-plane:
admission, scheduling, fairness observability, and elastic migration.
A single job driven through the daemon is bit-identical (ledger
records and final server state) to driving the model directly;
``tests/test_fedservice.py`` and ``scripts/tpu_selftest.py
service_smoke`` pin that.

Scheduling
----------
``policy="fair"`` round-robins: every runnable job steps one round
per tick. ``policy="backlog"`` greedily steps only the job with the
largest remaining backlog each tick — deliberately starvable, which
is what the ``job_starvation`` alarm drill exercises.

Telemetry
---------
The service writes its OWN ledger at the base ``cfg.ledger`` path —
one record per scheduler tick carrying the fairness probes
(occupancy, backlog, starvation, admission rejections). Job records
go to ``<ledger>.job<j>.jsonl`` shards (``telemetry.job_ledger_path``)
that stay byte-equivalent to solo-run ledgers; ``scripts/
ledger_merge.py`` joins both shard families.
"""

import dataclasses
import os
import tempfile
import threading

import numpy as np

from commefficient_tpu.fedservice.job import AdmissionError, JobSpec
from commefficient_tpu.parallel.mesh import (carve_submeshes,
                                             mesh_shape_dict)
from commefficient_tpu.runtime.checkpoint import (RoundAutosaver,
                                                  load_checkpoint,
                                                  save_checkpoint)
from commefficient_tpu.telemetry import (build_telemetry, clock,
                                         job_ledger_path,
                                         recover_ledger_shards)
from commefficient_tpu.telemetry import registry
from commefficient_tpu.telemetry.alarms import (AlarmEngine,
                                                DivergenceAbort)
from commefficient_tpu.telemetry.causal import (SEQ_ADMIT, SEQ_GRANT,
                                                SEQ_ROOT,
                                                build_causal_tracer,
                                                span_id, trace_id)
from commefficient_tpu.telemetry.live import attach_live_plane
from commefficient_tpu.telemetry.slo import build_slo_engine

#: lock-confinement declarations (flowlint ``lock-confinement``): the
#: scheduler state is read by probe/admission paths that outlive the
#: tick loop — an HTTP scrape asking ``active_jobs`` or an operator
#: admitting a tenant while a tick runs must not iterate ``_jobs``
#: while ``admit`` appends, and the device free-list carve must be
#: atomic. ``_ticks``/``_admitted``/``_rejected`` are plain counters
#: touched only by the single scheduler thread — deliberately not
#: declared.
_LOCK_MAP = {"_jobs": "_lock", "_by_id": "_lock", "_free": "_lock"}


class _Job:
    """Internal per-tenant record: spec + live runtime objects +
    scheduler bookkeeping. ``mesh`` is the carved sub-mesh (None for
    time-sliced jobs — their FedModel spans the whole pod and shares
    it through the jitted-variant cache)."""

    def __init__(self, spec, index, cfg, mesh, devices):
        self.spec = spec
        self.index = int(index)
        self.cfg = cfg          # ledger rewritten to the job shard
        self.mesh = mesh
        self.devices = devices  # reserved pod devices (spatial only)
        self.model = None
        self.opt = None
        self.autosaver = None
        self.rounds_done = 0
        self.ran_ticks = 0
        self.starved_ticks = 0
        self.done = False
        self.final_state = None
        # --causal_trace bookkeeping: monotonic instant the job last
        # became runnable (admission / previous grant) — the begin of
        # its next round's sched_grant span
        self.wait_since = None

    def backlog(self) -> int:
        return max(0, int(self.spec.rounds) - self.rounds_done)


class FedService:
    """The daemon. ``cfg`` is the SERVICE's Config — its ``ledger``
    is the base path the job shards hang off, and its alarm knobs
    (``--alarm_job_starvation``, ``--on_divergence``) arm the
    service's own AlarmEngine. Jobs bring their own Configs inside
    their :class:`JobSpec`.

    ``runs_dir`` (optional) stamps one registry manifest per admitted
    job (``job_id`` + ``service_run`` lineage keys). ``ckpt_dir``
    holds migration checkpoints (a tempdir by default).
    """

    POLICIES = ("fair", "backlog")

    def __init__(self, cfg, *, policy: str = "fair", runs_dir: str = "",
                 ckpt_dir: str = "", devices=None):
        assert policy in self.POLICIES, policy
        import jax
        self.cfg = cfg
        self.policy = policy
        self.runs_dir = runs_dir
        self._ckpt_dir = ckpt_dir
        self._devices = list(devices) if devices is not None \
            else list(jax.devices())
        self._lock = threading.Lock()
        self._free = list(self._devices)
        self._jobs = []
        self._by_id = {}
        self._ticks = 0
        self._admitted = 0
        self._rejected = 0
        # restart hygiene: a daemon SIGKILLed mid-write leaves a torn
        # tail on whichever shard was flushing — and a tenant that is
        # never re-admitted would leave it there forever, poisoning
        # ledger_merge. Sweep the base path and EVERY sibling shard
        # (.p<k>, .job<j>, and job shards' process shards) before any
        # sink reopens them.
        base = getattr(cfg, "ledger", "") or ""
        if base:
            for shard, n in recover_ledger_shards(base).items():
                print(f"WARNING: recovered torn ledger tail "
                      f"({n} bytes) at {shard}")
        self.telemetry = build_telemetry(cfg)
        # constructed directly (not build_alarm_engine) so the
        # always-armed admission_rejected rule fires even when no
        # threshold knob is set on the service cfg
        self.engine = AlarmEngine(cfg, self.telemetry)
        # live operations plane: the daemon's own fairness/SLO series
        # export under job="service"; each admitted job's FedModel
        # attaches its own sink (job=<j> labels) to the same process
        # registry, so one scrape endpoint carries the whole pod
        self.live_sink, self.flightrec = attach_live_plane(
            self.telemetry, cfg, labels={"job": "service"},
            runs_dir=runs_dir)
        # service-level SLO engine (starvation objective, typically):
        # observed once per scheduler tick; None with no target set
        self._slo = build_slo_engine(cfg)
        # causal tracer (--causal_trace on the service cfg): tick
        # records carry the daemon's own span DAGs, and admission /
        # scheduler-grant spans are stamped INTO each tenant's round
        # trace by deterministic id (they ride the next tick record
        # with a trace override; ledger_merge stitches them)
        self.telemetry.set_causal_tracer(
            build_causal_tracer(cfg, job="service"))
        self._causal = self.telemetry.causal

    # ------------------------------------------------------------ admission

    def admit(self, spec: JobSpec) -> int:
        """Validate ``spec`` against the pod and bring the job up.

        Returns the job index ``j`` (its ledger shard is
        ``<ledger>.job<j>.jsonl``). Raises :class:`AdmissionError`
        after counting the rejection in the service ledger, so the
        ``admission_rejected`` alarm fires even when the caller
        swallows the exception."""
        try:
            spec.validate()
            if str(spec.job_id) in self._by_id:
                raise AdmissionError(
                    f"job id {spec.job_id!r} already admitted")
            with self._lock:
                for other in self._jobs:
                    if int(other.cfg.seed) == int(spec.cfg.seed):
                        raise AdmissionError(
                            f"job {spec.job_id}: seed {spec.cfg.seed}"
                            f" collides with job "
                            f"{other.spec.job_id!r} — per-job RNG "
                            "streams must be disjoint")
            need = spec.demand_devices()
            if need > len(self._free):
                raise AdmissionError(
                    f"job {spec.job_id}: mesh demand "
                    f"{spec.mesh_demand[0]}x{spec.mesh_demand[1]} "
                    f"needs {need} devices, pod has "
                    f"{len(self._free)} free of {len(self._devices)}")
            if str(getattr(spec.cfg, "dp", "off")) != "off" and \
                    float(getattr(spec.cfg, "dp_epsilon", 0.0)
                          or 0.0) <= 0:
                raise AdmissionError(
                    f"job {spec.job_id}: DP mode needs a positive "
                    "epsilon budget for the per-job accountant")
        except AdmissionError:
            self._count_rejection()
            raise
        admit_b = clock.tick()

        burning = self.slo_burning_jobs()
        if burning:
            # admission flag, not refusal: a tenant burning its error
            # budget means the pod is already failing someone — the
            # operator should know BEFORE a new job compounds the
            # load. The meta record and per-job manifest carry the
            # flag; the admission itself proceeds.
            print(f"WARNING: admitting {spec.job_id!r} while job(s) "
                  f"{burning} are burning their SLO error budget")
            self.telemetry.emit_meta(
                slo_burning_at_admission=burning,
                admitted_job=str(spec.job_id))

        index = self._admitted
        self._admitted += 1
        mesh, devices = None, None
        if need:
            with self._lock:
                devices = self._free[:need]
                self._free = self._free[need:]
            mesh = carve_submeshes([spec.mesh_demand],
                                   devices=devices)[0]
        base = getattr(self.cfg, "ledger", "") or ""
        shard = job_ledger_path(base, index) if base else ""
        # the operations plane is pod-scoped: a daemon with
        # --live_port / --flightrec_rounds arms every tenant's sink
        # on the shared process registry too (a job cfg's own setting
        # wins). Both knobs are config-hash-excluded, so the shard
        # stays bit-identical to a solo run's ledger.
        plane = {}
        if getattr(self.cfg, "live_port", 0) \
                and not getattr(spec.cfg, "live_port", 0):
            plane["live_port"] = self.cfg.live_port
        if getattr(self.cfg, "flightrec_rounds", 0) \
                and not getattr(spec.cfg, "flightrec_rounds", 0):
            plane["flightrec_rounds"] = self.cfg.flightrec_rounds
            plane["postmortem_dir"] = self.cfg.postmortem_dir
        if getattr(self.cfg, "causal_trace", False) \
                and not getattr(spec.cfg, "causal_trace", False):
            plane["causal_trace"] = True
        cfg = dataclasses.replace(spec.cfg, ledger=shard, **plane)
        job = _Job(spec, index, cfg, mesh, devices)
        job.model, job.opt = spec.builder(cfg, mesh)
        if int(getattr(cfg, "checkpoint_every_rounds", 0) or 0) > 0:
            os.makedirs(cfg.checkpoint_path, exist_ok=True)
            job.autosaver = RoundAutosaver(
                cfg, job.model, job.opt, None, None, None,
                tag=f"job{index}")
        with self._lock:
            self._jobs.append(job)
            self._by_id[str(spec.job_id)] = job
        job.wait_since = clock.tick()
        if self._causal is not None:
            # the tenant's round-0 trace gets the admission span;
            # parent=None makes it a root anchor (it precedes the
            # round root in time and may sit on another clock)
            self._causal.add_event(
                "admission", admit_b, job.wait_since,
                trace=trace_id(index, 0),
                sid=span_id(index, 0, SEQ_ADMIT), parent=None)
        if self.runs_dir:
            registry.write_manifest(
                self.runs_dir, args=cfg, ledger=shard,
                mesh_shape=mesh_shape_dict(mesh if mesh is not None
                                           else job.model.mesh),
                extra={"job_id": str(spec.job_id),
                       "service_run": True,
                       "config_hash": registry.config_hash(cfg),
                       **({"slo_burning_at_admission": burning}
                          if burning else {})})
        return index

    def _count_rejection(self):
        """One service-ledger tick per rejection: the record carries
        the ``admission_rejected`` probe and the (always-armed) alarm
        rule flags it. An ``abort`` divergence action is swallowed —
        the AdmissionError the caller gets IS the abort."""
        self._rejected += 1
        t = self._ticks
        self._ticks += 1
        probes = {"admission_rejected": 1.0,
                  "job_active": float(self.active_jobs())}
        self.telemetry.begin_round(t)
        self.telemetry.merge_round_probes(t, probes)
        self.telemetry.set_round_bytes(t, 0, 0)
        try:
            self.engine.check(t, probes)
        except DivergenceAbort:
            pass

    # ------------------------------------------------------------ plumbing

    def _job(self, job_id) -> _Job:
        try:
            return self._by_id[str(job_id)]
        except KeyError:
            with self._lock:
                have = sorted(self._by_id)
            raise KeyError(f"no admitted job {job_id!r}; have "
                           f"{have}") from None

    def attach_arrival_process(self, job_id, fn):
        """Per-job arrival relay: forwards ``fn`` to the job's async
        driver. (Named ``attach_arrival_process`` on purpose — this
        is a sanctioned arrival-confinement relay range.)"""
        self._job(job_id).model.attach_arrival_process(fn)

    def active_jobs(self) -> int:
        with self._lock:
            return sum(1 for job in self._jobs if not job.done)

    def job_state(self, job_id):
        """The job's current (or final) replicated server weights."""
        job = self._job(job_id)
        if job.final_state is not None:
            return job.final_state
        return np.asarray(job.model.ps_weights)

    def job_rounds(self, job_id) -> int:
        return self._job(job_id).rounds_done

    def slo_burning_jobs(self) -> list:
        """Job ids currently burning their SLO error budget (their
        own FedModel SLO engine reads burn >= 1), plus "service" when
        the daemon's own engine is. Admission consults this."""
        burning = []
        with self._lock:
            jobs = list(self._jobs)
        for job in jobs:
            if job.done or job.model is None:
                continue
            slo = getattr(job.model, "_slo", None)
            if slo is not None and slo.burning:
                burning.append(str(job.spec.job_id))
        if self._slo is not None and self._slo.burning:
            burning.append("service")
        return burning

    # ------------------------------------------------------------ scheduler

    def tick(self):
        """One scheduler quantum: pick jobs per the policy, step each
        chosen job one round, then write the fairness record to the
        service ledger and evaluate the alarm rules on it. Returns
        the fired alarms (``abort`` raises DivergenceAbort instead)."""
        with self._lock:
            runnable = [job for job in self._jobs if not job.done]
        if not runnable:
            return []
        if self.policy == "fair":
            chosen = list(runnable)
        else:  # backlog: greedy, deliberately starvable
            chosen = [max(runnable,
                          key=lambda j: (j.backlog(), -j.index))]
        for job in chosen:
            self._run_round(job)
        for job in runnable:
            if job in chosen:
                job.ran_ticks += 1
                job.starved_ticks = 0
            else:
                job.starved_ticks += 1
        t = self._ticks
        self._ticks += 1
        probes = self._fairness_probes(runnable, chosen)
        self.telemetry.begin_round(t)
        if self._slo is not None:
            # the service's SLO objectives read the fairness probes
            # (starvation ticks); the burn probes merge INTO the tick
            # record's probe dict so the slo_burn rule fires through
            # the single engine.check below — the daemon path never
            # needs check_slo
            probes.update(self._slo.observe(
                t, starved_ticks=probes.get("job_starved_rounds")))
            self.telemetry.set_round_slo(t, self._slo.stamp())
        self.telemetry.merge_round_probes(t, probes)
        self.telemetry.set_round_bytes(t, 0, 0)
        return self.engine.check(t, probes)

    def run(self, max_ticks=None):
        """Drive ticks until every job drains (or the budget runs
        out). Returns the number of ticks executed."""
        n = 0
        while self.active_jobs() and (max_ticks is None
                                      or n < max_ticks):
            self.tick()
            n += 1
        return n

    def _run_round(self, job: _Job):
        batch = job.spec.batch_fn(job.rounds_done)
        if batch is None:
            self._finish(job)
            return
        if self._causal is not None:
            # grant span: runnable-since -> now, stitched into the
            # tenant's round trace by deterministic id (parent is the
            # tenant's round root — minted by the tenant, never by
            # us). Emitted only for rounds that actually run.
            now = clock.tick()
            r = job.rounds_done
            self._causal.add_event(
                "sched_grant",
                job.wait_since if job.wait_since is not None else now,
                now, trace=trace_id(job.index, r),
                sid=span_id(job.index, r, SEQ_GRANT),
                parent=span_id(job.index, r, SEQ_ROOT))
        job.model(batch)
        job.opt.step()
        job.rounds_done += 1
        if job.autosaver is not None:
            if job.model.telemetry.causal is not None:
                # round r's record is still current: the checkpoint
                # lands in its flush bucket. Off-path untouched so a
                # service-driven ledger stays byte-identical to solo.
                with job.model.telemetry.span("checkpoint"):
                    job.autosaver(0)
            else:
                job.autosaver(0)
        job.wait_since = clock.tick()
        if job.rounds_done >= int(job.spec.rounds):
            self._finish(job)

    def _finish(self, job: _Job):
        if job.done:
            return
        job.final_state = np.array(job.model.ps_weights)
        job.model.finalize()
        job.done = True
        if job.devices:
            with self._lock:
                self._free.extend(job.devices)
            job.devices = None

    def _fairness_probes(self, runnable, chosen) -> dict:
        still = [job for job in runnable if not job.done]
        probes = {
            "job_active": float(len(still)),
            "job_ran": float(len(chosen)),
            "job_backlog_total": float(sum(j.backlog()
                                           for j in runnable)),
            "job_backlog_max": float(max(j.backlog()
                                         for j in runnable)),
        }
        if still:
            starved = max(still, key=lambda j: j.starved_ticks)
            probes["job_starved_rounds"] = float(starved.starved_ticks)
            probes["job_starved_index"] = float(starved.index)
            occ = [j.ran_ticks / max(1, j.ran_ticks + j.starved_ticks)
                   for j in still]
            probes["job_occupancy_min"] = float(min(occ))
        return probes

    # ------------------------------------------------------------ elasticity

    def migrate(self, job_id, mesh_demand=None):
        """Elastic migration: checkpoint the job, rebuild its model
        under a freshly carved mesh (``mesh_demand=(C, M)`` for a new
        spatial footprint, ``None`` to fall back to time-slicing the
        whole pod), and restore — the PR 12 topology-free checkpoint
        format makes the restore bit-exact across mesh shapes. The
        job's ledger shard survives: the old sink closes before the
        rebuilt model reopens it, and round ids continue where they
        left off."""
        job = self._job(job_id)
        if job.done:
            raise ValueError(f"job {job_id!r} already finished")
        ckpt_dir = self._ckpt_dir or tempfile.mkdtemp(
            prefix="fedservice_migrate_")
        os.makedirs(ckpt_dir, exist_ok=True)
        path = os.path.join(ckpt_dir, f"migrate_job{job.index}.npz")
        save_checkpoint(path, job.model, job.opt)
        job.model.finalize()
        if job.devices:
            with self._lock:
                self._free.extend(job.devices)
            job.devices = None
        mesh, devices = None, None
        if mesh_demand is not None:
            c, m = mesh_demand
            need = int(c) * int(m)
            if need > len(self._free):
                raise AdmissionError(
                    f"job {job_id}: migration demand {c}x{m} needs "
                    f"{need} devices, {len(self._free)} free")
            with self._lock:
                devices = self._free[:need]
                self._free = self._free[need:]
            mesh = carve_submeshes([mesh_demand],
                                   devices=devices)[0]
        job.mesh, job.devices = mesh, devices
        job.model, job.opt = job.spec.builder(job.cfg, mesh)
        load_checkpoint(path, job.model, job.opt)
        if job.autosaver is not None:
            job.autosaver = RoundAutosaver(
                job.cfg, job.model, job.opt, None, None, None,
                tag=f"job{job.index}")
        return job.index

    # ------------------------------------------------------------ teardown

    def close(self):
        """Drain-free shutdown: finalize still-live jobs, stamp the
        service meta record, close the service ledger."""
        with self._lock:
            jobs = list(self._jobs)
        for job in jobs:
            if not job.done:
                job.final_state = np.array(job.model.ps_weights)
                job.model.finalize()
                job.done = True
        self.telemetry.emit_meta(
            service_jobs=self._admitted,
            service_policy=self.policy,
            service_ticks=self._ticks,
            service_rejected=self._rejected,
            pod_devices=len(self._devices))
        self.telemetry.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
