"""Version-compatibility shims for jax APIs the runtime uses.

The SPMD round code targets current jax (`jax.lax.axis_size`,
`jax.lax.pvary`), but the library must also run on the 0.4.x line
where those names don't exist yet. Each shim prefers the real API and
falls back to the semantically-equivalent old-jax spelling, so call
sites stay single-path.
"""

from __future__ import annotations

import jax


def axis_size(axis_name) -> int:
    """``jax.lax.axis_size`` with a pre-0.5 fallback.

    ``psum(1, axis)`` over a manual (shard_map) axis constant-folds to
    the static mesh extent on the 0.4.x line, so loop bounds built
    from it stay Python ints.
    """
    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    return jax.lax.psum(1, axis_name)


def pvary(x, axis_name):
    """``jax.lax.pvary`` with a pre-0.5 identity fallback.

    Old jax has no varying-axes type system, so there is nothing to
    mark: values are implicitly device-varying inside shard_map and
    grad transposes don't insert the replication psum the marker
    exists to suppress.
    """
    fn = getattr(jax.lax, "pvary", None)
    if fn is not None:
        return fn(x, axis_name)
    return x
