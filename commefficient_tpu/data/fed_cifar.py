"""Federated CIFAR10/100: natural partition = one class per client.

Counterpart of reference data_utils/fed_cifar.py:13-100. On first use,
reads the standard python-pickle CIFAR archives from ``dataset_dir``
(no download — this environment has zero egress; place
``cifar-10-batches-py/`` or ``cifar-100-python/`` there) and writes
per-client ``client{i}.npy`` files + ``test.npz`` + ``stats.json``.
Non-iid CIFAR means "each client holds one class", subdivided among
``--num_clients`` by ``data_per_client`` (fed_dataset.py:40-48).
"""

from __future__ import annotations

import json
import os
import pickle

import numpy as np

from commefficient_tpu.data.fed_dataset import FedDataset

__all__ = ["FedCIFAR10", "FedCIFAR100"]


class FedCIFAR10(FedDataset):
    num_classes = 10
    _archive = "cifar-10-batches-py"
    _train_files = [f"data_batch_{i}" for i in range(1, 6)]
    _test_file = "test_batch"
    _label_key = b"labels"

    def prepare_datasets(self, download=False):
        src = os.path.join(self.dataset_dir, self._archive)
        if not os.path.exists(src):
            raise FileNotFoundError(
                f"{src} not found; place the CIFAR archive there "
                "(no download in this environment)")
        xs, ys = [], []
        for fn in self._train_files:
            with open(os.path.join(src, fn), "rb") as f:
                d = pickle.load(f, encoding="bytes")
            xs.append(d[b"data"])
            ys.append(np.array(d[self._label_key]))
        x = np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(
            0, 2, 3, 1)  # NHWC
        y = np.concatenate(ys)

        images_per_client = []
        for c in range(self.num_classes):
            idx = np.where(y == c)[0]
            images_per_client.append(len(idx))
            np.save(os.path.join(self.dataset_dir, f"client{c}.npy"),
                    x[idx])
        with open(os.path.join(src, self._test_file), "rb") as f:
            d = pickle.load(f, encoding="bytes")
        tx = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        ty = np.array(d[self._label_key])
        np.savez(os.path.join(self.dataset_dir, "test.npz"),
                 x=tx, y=ty)
        with open(self.stats_fn(), "w") as f:
            json.dump({"images_per_client": images_per_client,
                       "num_val_images": len(ty)}, f)

    def _load_meta(self, train):
        super()._load_meta(train)
        if train:
            self._clients = [
                np.load(os.path.join(self.dataset_dir, f"client{c}.npy"))
                for c in range(self.num_classes)]
        else:
            d = np.load(os.path.join(self.dataset_dir, "test.npz"))
            self._test_x, self._test_y = d["x"], d["y"]

    def _get_train_item(self, client_id, idx_within_client):
        # label == natural client id (one class per client,
        # fed_cifar.py:80)
        return self._clients[client_id][idx_within_client], int(client_id)

    def dense_train_view(self):
        cached = getattr(self, "_dense_view_cache", None)
        if cached is None:
            imgs = np.concatenate(self._clients)
            tgts = np.repeat(np.arange(len(self._clients), dtype=np.int32),
                             [len(c) for c in self._clients])
            self._dense_view_cache = (imgs, tgts)
        return self._dense_view_cache

    def _get_val_item(self, idx):
        return self._test_x[idx], int(self._test_y[idx])


class FedCIFAR100(FedCIFAR10):
    num_classes = 100
    _archive = "cifar-100-python"
    _train_files = ["train"]
    _test_file = "test"
    _label_key = b"fine_labels"
