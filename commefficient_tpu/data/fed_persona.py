"""Federated PersonaChat: client = distinct personality.

Counterpart of reference data_utils/fed_persona.py. Same on-disk
layout (per-client ``client{i}.json`` + ``validation.json`` +
``stats.json`` split from the personachat archive), same item
semantics:

- an item is one utterance: ``num_candidates`` candidate sequences
  (gold last), built as
  ``[bos persona] [<speaker1/2> turn]... [<speaker2> reply eos]``
  with speaker-alternating token types, LM labels only on the gold
  reply, mc_token_id at the last position, mc_label = gold index
  (fed_persona.py:330-358);
- history truncated to ``2*max_history + 1`` turns;
- ``personality_permutations`` random persona shufflings per item.

Differences by design: no S3 download (zero-egress environment — place
``personachat_self_original.json`` in the dataset dir, or use
``generate_synthetic_personachat`` for offline runs), and the collate
pads to a **static** ``max_seq_len`` so the jitted round never
recompiles on batch shape (the reference pads per-batch,
fed_persona.py:360-392 — a dynamic shape the TPU runtime must avoid).
"""

from __future__ import annotations

import json
import os
import random
from collections import defaultdict

import numpy as np

from commefficient_tpu.data.fed_dataset import FedDataset
from commefficient_tpu.data.tokenizer import SPECIAL_TOKENS

__all__ = ["FedPERSONA", "persona_collate",
           "generate_synthetic_personachat",
           "generate_learnable_personachat"]

MODEL_INPUTS = ["input_ids", "mc_token_ids", "lm_labels", "mc_labels",
                "token_type_ids"]

RAW_NAME = "personachat_self_original.json"


class FedPERSONA(FedDataset):
    def __init__(self, tokenizer, num_candidates, max_history,
                 personality_permutations, *args, **kwargs):
        self.tokenizer = tokenizer
        self.num_candidates = num_candidates
        self.max_history = max_history
        self.personality_permutations = personality_permutations
        super().__init__(*args, **kwargs)
        if self.type == "val":
            with open(self.validation_fn()) as f:
                self.raw_val_set = json.load(f)
        self._rng = random.Random(kwargs.get("seed", 0))
        self._client_cache = {}

    # --- partitioning (reference fed_persona.py:46-75) -------------------

    @property
    def data_per_client(self):
        # cached: at natural scale (17,568 clients) this is an
        # O(#dialogs) reduction, and __getitem__ consults it per item
        # in iid mode
        if self._dpc_cache is not None:
            return self._dpc_cache
        if self.do_iid:
            n = len(self)
            upc = (np.ones(self.num_clients, dtype=int) * n
                   // self.num_clients)
            extra = n % self.num_clients
            if extra:
                upc[self.num_clients - extra:] += 1
            self._dpc_cache = upc
            return upc
        # utterances per client = segmented sum of utterances-per-
        # dialog over each client's dialog span
        upd_cumsum = np.hstack(
            [[0], np.cumsum(self.train_utterances_per_dialog)])
        spans = np.hstack([[0], np.cumsum(self.dialogs_per_client)])
        self._dpc_cache = np.diff(upd_cumsum[spans])
        return self._dpc_cache

    @property
    def num_clients(self):
        if self.do_iid:
            return (self._num_clients if self._num_clients is not None
                    else len(self.dialogs_per_client))
        return len(self.dialogs_per_client)

    def _load_meta(self, train):
        with open(self.stats_fn()) as f:
            stats = json.load(f)
        self.dialogs_per_client = stats["dialogs_per_client"]
        self.train_utterances_per_dialog = \
            stats["train_utterances_per_dialog"]
        self.val_utterances_per_dialog = \
            stats["val_utterances_per_dialog"]
        # index->dialog->client lookups are done per __getitem__; at
        # 17,568 clients / 130k dialogs the cumsums must not be
        # recomputed per access (round-1 review, "host-side scale")
        self._train_upd_cumsum = np.cumsum(
            self.train_utterances_per_dialog)
        self._dialog_cumsum = np.cumsum(self.dialogs_per_client)
        self._val_upd_cumsum = np.cumsum(
            self.val_utterances_per_dialog)
        self._dpc_cache = None
        self._iid_dpc_cumsum = None

    def __len__(self):
        if self.type == "train":
            return int(sum(self.train_utterances_per_dialog))
        return int(sum(self.val_utterances_per_dialog))

    # --- split (reference fed_persona.py:87-167) -------------------------

    def prepare_datasets(self, download=False):
        os.makedirs(self.dataset_dir, exist_ok=True)
        raw_path = os.path.join(self.dataset_dir, RAW_NAME)
        if not os.path.exists(raw_path):
            raise FileNotFoundError(
                f"{raw_path} not found (no download in this "
                "environment); place the personachat archive there or "
                "use generate_synthetic_personachat()")
        with open(raw_path) as f:
            raw = json.load(f)

        val_set = raw["valid"]
        val_upd = [len(d["utterances"]) for d in val_set]

        client_datasets = defaultdict(list)
        for dialog in raw["train"]:
            client_datasets[tuple(dialog["personality"])].append(dialog)

        personalities = list(client_datasets.keys())
        dialogs_per_client, train_upd = [], []
        for p in personalities:
            dialogs = client_datasets[p]
            dialogs_per_client.append(len(dialogs))
            train_upd.extend(len(d["utterances"]) for d in dialogs)

        for cid, p in enumerate(personalities):
            with open(self.client_fn(cid), "w") as f:
                json.dump(client_datasets[p], f)
        with open(self.validation_fn(), "w") as f:
            json.dump(val_set, f)
        with open(self.stats_fn(), "w") as f:
            json.dump({"dialogs_per_client": dialogs_per_client,
                       "train_utterances_per_dialog": train_upd,
                       "val_utterances_per_dialog": val_upd}, f)

    # --- items (reference fed_persona.py:180-260) ------------------------

    def __getitem__(self, idx):
        if self.type == "train":
            return self._get_train_item_full(idx)
        return self._get_val_item_full(idx)

    def _get_train_item_full(self, idx):
        orig_idx = idx
        if self.do_iid:
            idx = self.iid_shuffle[idx]

        cumsum = self._train_upd_cumsum
        dialog_id = int(np.searchsorted(cumsum, idx, side="right"))
        idx_within_dialog = int(idx - (cumsum[dialog_id - 1]
                                       if dialog_id else 0))

        cumsum = self._dialog_cumsum
        client_id = int(np.searchsorted(cumsum, dialog_id,
                                        side="right"))
        idx_within_client = int(dialog_id - (cumsum[client_id - 1]
                                             if client_id else 0))

        dataset = self._load_client(client_id)
        dialog = dataset[idx_within_client]
        personality = list(dialog["personality"])
        utterance = dialog["utterances"][idx_within_dialog]

        # the reference shuffles P times and returns only the last
        # tokenization (fed_persona.py:231-241 — model_inputs is built
        # then discarded); same semantics, but tokenize just once
        for _ in range(self.personality_permutations):
            self._rng.shuffle(personality)
        model_input = self.utterance_to_input(personality, utterance)

        if self.do_iid:
            if self._iid_dpc_cumsum is None:
                self._iid_dpc_cumsum = np.cumsum(self.data_per_client)
            client_id = int(np.searchsorted(self._iid_dpc_cumsum,
                                            orig_idx, side="right"))
        return (client_id,) + model_input

    def _get_val_item_full(self, idx):
        cumsum = self._val_upd_cumsum
        dialog_id = int(np.searchsorted(cumsum, idx, side="right"))
        idx_within = int(idx - (cumsum[dialog_id - 1]
                                if dialog_id else 0))
        dialog = self.raw_val_set[dialog_id]
        return (-1,) + self.utterance_to_input(
            list(dialog["personality"]),
            dialog["utterances"][idx_within])

    def _load_client(self, client_id):
        if client_id not in self._client_cache:
            if len(self._client_cache) > 256:
                self._client_cache.clear()
            with open(self.client_fn(client_id)) as f:
                self._client_cache[client_id] = json.load(f)
        return self._client_cache[client_id]

    def utterance_to_input(self, personality, utterance):
        history = utterance["history"][-(2 * self.max_history + 1):]
        candidates = utterance["candidates"]
        num_candidates = len(candidates)
        if self.num_candidates > 0 and self.type == "train":
            num_candidates = min(self.num_candidates, num_candidates)
        candidates = candidates[-num_candidates:]
        return raw_to_input(self.tokenizer, personality, history,
                            candidates)

    def client_fn(self, client_id):
        return os.path.join(self.dataset_dir,
                            f"client{client_id}.json")

    def validation_fn(self):
        return os.path.join(self.dataset_dir, "validation.json")


def tokenize_obj(obj, tokenizer):
    if isinstance(obj, str):
        return tokenizer.encode(obj)
    if isinstance(obj, dict):
        return {n: tokenize_obj(o, tokenizer) for n, o in obj.items()}
    return [tokenize_obj(o, tokenizer) for o in obj]


def raw_to_input(tokenizer, personality, history, candidates):
    """strings -> per-candidate model inputs
    (reference fed_persona.py:283-316)."""
    personality = tokenize_obj(personality, tokenizer)
    history = tokenize_obj(history, tokenizer)
    candidates = tokenize_obj(candidates, tokenizer)

    model_input = defaultdict(list)
    n = len(candidates)
    for j, candidate in enumerate(candidates):
        instance = build_input_from_segments(
            personality, history, candidate, tokenizer,
            lm_labels=(j == n - 1))
        for name, arr in instance.items():
            model_input[name].append(arr)
    model_input["mc_labels"] = n - 1
    return tuple(model_input[name] for name in MODEL_INPUTS)


def build_input_from_segments(persona, history, reply, tokenizer,
                              lm_labels=False, with_eos=True):
    """Serialize one (persona, history, reply) triple into the flat
    GPT-2 double-heads token protocol. The token streams must match
    the reference's (fed_persona.py:330-358 *semantics*; golden-tested
    in tests/test_gpt2.py) exactly, since checkpoints and eval numbers
    depend on them. Protocol, accumulated segment by segment:

    - header: ``<bos>`` + all persona sentences flattened, token type
      ``speaker1``;
    - one segment per dialog turn (history turns, then the reply, with
      ``<eos>`` appended when ``with_eos``). Each is prefixed with a
      speaker token chosen so the *reply* is always ``speaker2`` and
      speakers alternate backwards from it. The token *type* of turn t
      is ``speaker2`` for even t — by turn index, not by the prefixed
      speaker, so the two disagree for odd history lengths (the
      reference's index-parity quirk, kept as-is);
    - ``mc_token_ids``: index of the final token, where the MC head
      reads its summary;
    - ``lm_labels``: -1 (ignore) everywhere except, on the gold
      candidate (``lm_labels=True``), the reply tokens and eos — each
      predicted from its predecessor, so the speaker prefix gets -1.
    """
    bos, eos, speaker1, speaker2 = tokenizer.convert_tokens_to_ids(
        SPECIAL_TOKENS[:-1])

    input_ids = [bos]
    for sentence in persona:
        input_ids.extend(sentence)
    token_types = [speaker1] * len(input_ids)
    labels = [-1] * len(input_ids)

    turns = list(history)
    turns.append(list(reply) + ([eos] if with_eos else []))
    gold = len(turns) - 1
    for t, turn in enumerate(turns):
        prefix = speaker2 if (gold - t) % 2 == 0 else speaker1
        input_ids.append(prefix)
        input_ids.extend(turn)
        ttype = speaker2 if t % 2 == 0 else speaker1
        token_types.extend([ttype] * (len(turn) + 1))
        if lm_labels and t == gold:
            labels.append(-1)          # the speaker prefix
            labels.extend(turn)
        else:
            labels.extend([-1] * (len(turn) + 1))

    return {"input_ids": input_ids,
            "token_type_ids": token_types,
            "mc_token_ids": len(input_ids) - 1,
            "lm_labels": labels}


def persona_collate(records, num_candidates, max_seq_len, pad_id=0):
    """List of (client_id,)+MODEL_INPUTS tuples -> static-shape arrays:
    input_ids/token_type_ids/lm_labels (B, N, T), mc_token_ids (B, N),
    mc_labels (B,). Sequences beyond ``max_seq_len`` are truncated
    from the *front* (keeps the reply + eos, which carry the LM
    labels); lm_labels pad with -1 (reference pad values,
    fed_persona.py:379)."""
    B, N, T = len(records), num_candidates, max_seq_len
    out = {
        "input_ids": np.full((B, N, T), pad_id, np.int32),
        "token_type_ids": np.full((B, N, T), pad_id, np.int32),
        "lm_labels": np.full((B, N, T), -1, np.int32),
        "mc_token_ids": np.zeros((B, N), np.int32),
        "mc_labels": np.zeros((B,), np.int32),
        # 1.0 on real candidate slots; val consumers mask the MC
        # argmax with this so padded slots can never be predicted
        "cand_mask": np.zeros((B, N), np.float32),
    }
    client_ids = np.zeros((B,), np.int32)
    for b, rec in enumerate(records):
        cid, input_ids, mc_tok, lm_lab, mc_lab, tt = rec
        client_ids[b] = cid
        # if the record has more candidates than N (val items carry all
        # ~20), keep the LAST N — the gold candidate is always last by
        # construction (fed_persona.py:305), so the label stays N-1
        if len(input_ids) > N:
            input_ids, mc_tok = input_ids[-N:], mc_tok[-N:]
            lm_lab, tt = lm_lab[-N:], tt[-N:]
            mc_lab = N - 1
        out["mc_labels"][b] = mc_lab
        for j in range(min(N, len(input_ids))):
            seq = input_ids[j][-T:]
            ttj = tt[j][-T:]
            lab = lm_lab[j][-T:]
            L = len(seq)
            out["input_ids"][b, j, :L] = seq
            out["token_type_ids"][b, j, :L] = ttj
            out["lm_labels"][b, j, :L] = lab
            out["mc_token_ids"][b, j] = min(mc_tok[j], L - 1)
            out["cand_mask"][b, j] = 1.0
    return client_ids, out


def generate_learnable_personachat(path, word_list,
                                   num_personalities=1000,
                                   dialogs_per_personality=4,
                                   utterances_per_dialog=5,
                                   num_candidates=5,
                                   signature_size=24,
                                   num_val_dialogs=100,
                                   seed=0,
                                   val_from_train_sigs=False,
                                   distractor_disjoint=False):
    """Write a personachat-format archive with *learnable* structure,
    for convergence evidence where the real archive is unavailable
    (zero egress; reference fed_persona.py:23 downloads it from S3).

    Each personality draws a signature set of ``signature_size`` words
    from ``word_list``; its persona sentences, dialog turns, and gold
    replies all use only signature words, while distractor candidates
    are sentences from a *different* personality's signature. So:

    - the LM can cut NLL from ~ln(|word_list|) to ~ln(signature_size)
      by conditioning on the persona/history prefix;
    - the MC head is above chance iff it learns "the gold reply shares
      the prefix's vocabulary" — a relation, not a memorized string:
      validation dialogs use personalities (signature sets) never seen
      in training, so val PPL/accuracy measure the learned rule.

    ``val_from_train_sigs=True`` instead draws validation dialogs
    (fresh sentences) from the TRAINING personalities — the easier
    seen-persona tier: persona-vocabulary associations absorbed during
    training suffice, no cross-persona rule needed. Useful as a
    second evaluation split for a model trained on the default corpus
    (same word list + seed ⇒ identical train signatures).

    ``distractor_disjoint=True`` rejection-samples each distractor's
    source personality so its signature shares NO words with the gold
    signature (falls back to the least-overlapping candidate after 64
    tries). Without it, random signature collisions put gold-vocabulary
    words inside distractors, diluting the lexical-overlap signal the
    MC head must learn; with it the task's Bayes accuracy is 1.0 by a
    pure "candidate vocabulary ⊆ prefix vocabulary" rule. Off by
    default so pre-existing seeds regenerate byte-identically.

    Gold candidate is last (reference convention, fed_persona.py:305).
    """
    rng = random.Random(seed)

    def make_persona():
        return rng.sample(word_list, signature_size)

    def sentence(sig):
        return " ".join(rng.choice(sig)
                        for _ in range(rng.randint(4, 8)))

    def pick_distractor_sig(gold_set, all_sigs):
        if not distractor_disjoint:
            return rng.choice(all_sigs)
        best, best_overlap = None, None
        for _ in range(64):
            cand = rng.choice(all_sigs)
            overlap = len(gold_set.intersection(cand))
            if overlap == 0:
                return cand
            if best_overlap is None or overlap < best_overlap:
                best, best_overlap = cand, overlap
        return best

    def dialog(sig, all_sigs):
        gold_set = set(sig)
        utterances = []
        history = [sentence(sig)]
        for _ in range(utterances_per_dialog):
            cands = [sentence(pick_distractor_sig(gold_set, all_sigs))
                     for _ in range(num_candidates - 1)]
            cands.append(sentence(sig))  # gold last
            utterances.append({"history": list(history),
                               "candidates": cands})
            history.append(sentence(sig))
            history.append(sentence(sig))
        return utterances

    data = {"train": [], "valid": []}
    train_sigs = [make_persona() for _ in range(num_personalities)]
    for sig in train_sigs:
        personality = [sentence(sig) for _ in range(3)]
        others = [s for s in train_sigs if s is not sig] or [sig]
        for _ in range(dialogs_per_personality):
            data["train"].append({"personality": personality,
                                  "utterances": dialog(sig, others)})
    n_val_sigs = max(1, num_val_dialogs // 4)
    if val_from_train_sigs:
        val_sigs = [train_sigs[rng.randrange(len(train_sigs))]
                    for _ in range(n_val_sigs)]
    else:
        val_sigs = [make_persona() for _ in range(n_val_sigs)]
    for i in range(num_val_dialogs):
        sig = val_sigs[i % len(val_sigs)]
        others = [s for s in val_sigs if s is not sig] or [sig]
        data["valid"].append({
            "personality": [sentence(sig) for _ in range(3)],
            "utterances": dialog(sig, others)})
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, RAW_NAME), "w") as f:
        json.dump(data, f)


def generate_synthetic_personachat(path, num_personalities=8,
                                   dialogs_per_personality=2,
                                   utterances_per_dialog=3,
                                   num_candidates=2, seed=0):
    """Write a tiny synthetic personachat-format archive for offline
    tests/smoke (same JSON schema as the S3 original)."""
    rng = random.Random(seed)
    words = ["i", "like", "cats", "dogs", "music", "food", "sports",
             "reading", "travel", "coding", "you", "me", "the", "a"]

    def sentence():
        return " ".join(rng.choice(words)
                        for _ in range(rng.randint(3, 7)))

    def dialog():
        utterances = []
        history = [sentence()]
        for _ in range(utterances_per_dialog):
            utterances.append({
                "history": list(history),
                "candidates": [sentence()
                               for _ in range(num_candidates)],
            })
            history.append(sentence())
            history.append(sentence())
        return utterances

    data = {"train": [], "valid": []}
    for p in range(num_personalities):
        personality = [f"persona {p} " + sentence() for _ in range(3)]
        for _ in range(dialogs_per_personality):
            data["train"].append({"personality": personality,
                                  "utterances": dialog()})
    for _ in range(4):
        data["valid"].append({
            "personality": [sentence() for _ in range(3)],
            "utterances": dialog()})
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, RAW_NAME), "w") as f:
        json.dump(data, f)
