"""Synthetic federated image dataset — class-conditional Gaussian
blobs, one class per natural client (mirroring CIFAR's partition
shape). Used by tests, the ``--test`` smoke mode and offline benches;
no reference equivalent (the reference assumes datasets on disk)."""

from __future__ import annotations

import numpy as np

from commefficient_tpu.data.fed_dataset import FedDataset

__all__ = ["FedSynthetic"]


class FedSynthetic(FedDataset):
    """``classes_per_client`` sets the heterogeneity dial: 1 (default)
    is the pathological one-class-per-client split that defeats local
    state at low participation (the paper's FedAvg-degradation story);
    c > 1 gives each natural client an even mix of c consecutive
    classes — the milder non-iid regime where fedavg/local_topk are
    expected to learn."""

    def __init__(self, *args, num_classes=10, image_shape=(32, 32, 3),
                 per_class=64, num_val=128, gen_seed=0,
                 classes_per_client=1, separation=1.0, **kw):
        self.num_classes = num_classes
        self.image_shape = image_shape
        self.per_class = per_class
        self.num_val = num_val
        self.gen_seed = gen_seed
        self.classes_per_client = classes_per_client
        # class-overlap dial: scales the class means against the fixed
        # 0.5 noise std. 1.0 (default) is trivially separable (the
        # saturating regime); small values give a computable sub-1.0
        # Bayes ceiling (bayes_accuracy), making long-horizon anchors
        # accuracy-DISCRIMINATING instead of stability-only (round-3
        # review weak #1).
        self.separation = separation
        super().__init__(*args, **kw)

    # entirely in-memory: no disk prep
    def prepare_datasets(self, download=False):
        pass

    def stats_fn(self):
        return ""  # never consulted

    def _gen(self):
        rng = np.random.RandomState(self.gen_seed)
        # one mean per class, scaled by the overlap dial
        self._means = (self.separation
                       * rng.randn(self.num_classes,
                                   *self.image_shape)).astype(np.float32)

        vx, vy = [], []
        for c in range(self.num_classes):
            n = self.num_val // self.num_classes
            vx.append(self._means[c] + 0.5 * rng.randn(
                n, *self.image_shape).astype(np.float32))
            vy.append(np.full(n, c))
        self._val_x = np.concatenate(vx)
        self._val_y = np.concatenate(vy)

    def _load_meta(self, train):
        self.images_per_client = np.full(self.num_classes,
                                         self.per_class)
        self._gen()
        self.num_val_images = len(self._val_y)

    def _get_train_item(self, client_id, idx_within_client):
        rng = np.random.RandomState(
            self.gen_seed + 17 + int(client_id) * 100003
            + int(idx_within_client))
        # client c holds classes {c, c+1, ..., c+cpc-1} (mod K),
        # cycled over its items so the per-class counts stay even
        label = (int(client_id)
                 + int(idx_within_client) % self.classes_per_client) \
            % self.num_classes
        img = (self._means[label]
               + 0.5 * rng.randn(*self.image_shape).astype(np.float32))
        return img, label

    def _get_val_item(self, idx):
        return self._val_x[idx], int(self._val_y[idx])

    def bayes_accuracy(self):
        """Empirical Bayes-optimal (true-means nearest-class under the
        isotropic noise) accuracy on THIS val split — the anchor's
        ceiling. Equal covariances: the Bayes rule is the max class
        log-likelihood = nearest mean."""
        x = self._val_x.reshape(len(self._val_y), -1)
        mu = self._means.reshape(self.num_classes, -1)
        d2 = ((x[:, None, :] - mu[None, :, :]) ** 2).sum(-1)
        return float((np.argmin(d2, 1) == self._val_y).mean())
