"""Round-batch construction: sampler output -> fixed-shape padded
engine batches.

The reference ships a flat concatenated tensor batch to the server,
which re-groups rows by client id and queues them to worker processes
(fed_aggregator.py:214-238). Here the loaders themselves emit the
static (W, B, ...) layout the jitted round wants — client axis first,
a (W, B) mask for ragged clients — so the device never sees a dynamic
shape (SURVEY.md §7).

``_RoundLoaderBase`` holds the shared mechanics (B/W resolution,
incomplete-round skipping, epoch length); subclasses provide only
``collate``. Same split for the sharded validation loaders.
"""

from __future__ import annotations

from typing import Iterator, Optional

import numpy as np

__all__ = ["FedLoader", "ValLoader", "PersonaFedLoader",
           "PersonaValLoader", "NativeFedLoader", "make_fed_loader"]


class _RoundLoaderBase:
    """Iterate federated train rounds. Rounds with fewer than
    ``num_workers`` distinct clients are skipped, matching the
    reference's run_batches guard (cv_train.py:205-219).

    ``dropout_prob`` injects client failures: each sampled client
    independently drops with that probability — its mask rows are
    zeroed, the engine excludes its transmit and leaves its
    momentum/error state untouched, and the aggregate renormalises
    over survivors (fault injection the reference lacks, SURVEY §5).
    A fully-dropped round still executes with a zero aggregate (the
    server's momentum coasts), keeping round counts, RNG streams and
    the LR schedule identical across the Python and native loaders."""

    def __init__(self, dataset, sampler,
                 max_batch_size: Optional[int] = None,
                 dropout_prob: float = 0.0, dropout_seed: int = 0):
        self.dataset = dataset
        self.sampler = sampler
        if max_batch_size is not None:
            self.B = max_batch_size
        elif sampler.local_batch_size != -1:
            self.B = sampler.local_batch_size
        else:
            self.B = int(np.max(dataset.data_per_client))
        self.W = sampler.num_workers
        self.dropout_prob = dropout_prob
        self._dropout_rng = np.random.RandomState(dropout_seed)

    def _apply_dropout(self, batch: dict) -> dict:
        """Zero dropped clients' mask rows."""
        if self.dropout_prob <= 0.0:
            return batch
        drop = self._dropout_rng.rand(self.W) < self.dropout_prob
        if drop.any():
            batch = dict(batch)
            mask = batch["mask"].copy()
            mask[drop] = 0.0
            batch["mask"] = mask
        return batch

    def __iter__(self) -> Iterator[dict]:
        for round_spec in self.sampler:
            if len(round_spec) < self.W:
                continue  # incomplete round: skip
            yield self._apply_dropout(self.collate(round_spec))

    def peek_next_client_ids(self):
        """Next round's participant ids one round ahead (the
        client-store prefetch feed, runtime/fed_model.py). None when
        the sampler can't see ahead or the peeked round is incomplete
        (it would be skipped above) — the consumer then falls back to
        a synchronous gather, so a miss costs latency, never
        correctness."""
        peek = getattr(self.sampler, "peek_next_client_ids", None)
        ids = peek() if peek is not None else None
        if ids is None or len(ids) < self.W:
            return None
        return ids

    def collate(self, round_spec) -> dict:
        raise NotImplementedError

    def __len__(self):
        from commefficient_tpu.utils import steps_per_epoch
        return steps_per_epoch(self.sampler.local_batch_size,
                               self.dataset, self.W)


class FedLoader(_RoundLoaderBase):
    """CV rounds: ``client_ids`` (W,), ``x`` (W, B, ...) f32, ``y``
    (W, B) i32, ``mask`` (W, B) f32."""

    _img_shape = None

    def _probe_shape(self, idx):
        if self._img_shape is None:
            self._img_shape = np.asarray(self.dataset[int(idx)][1]).shape
        return self._img_shape

    def collate(self, round_spec) -> dict:
        W, B = self.W, self.B
        img_shape = self._probe_shape(round_spec[0][1][0])
        x = np.zeros((W, B) + img_shape, np.float32)
        y = np.zeros((W, B), np.int32)
        mask = np.zeros((W, B), np.float32)
        ids = np.zeros((W,), np.int32)
        for i, (cid, idxs) in enumerate(round_spec):
            ids[i] = cid
            for j, idx in enumerate(idxs[:B]):
                client_id, img, target = self.dataset[int(idx)]
                assert client_id == cid, (client_id, cid)
                x[i, j] = img
                y[i, j] = target
                mask[i, j] = 1.0
        return {"client_ids": ids, "x": x, "y": y, "mask": mask}


class NativeFedLoader(_RoundLoaderBase):
    """CV rounds assembled by the C++ data-plane with threaded
    prefetch (commefficient_tpu/native): gather + reflect-pad random
    crop + flip + normalize run GIL-free while the device steps.

    Same batch dict contract as FedLoader. Augmentation RNG is the
    native splitmix64 stream (deterministic per seed, a different
    stream than the numpy transforms); with augmentation off the
    output matches FedLoader bit-for-bit — tested in
    tests/test_native_dataplane.py.

    Raises RuntimeError when the toolchain/transform/dataset don't
    support the native path — use :func:`make_fed_loader` for the
    auto-fallback.
    """

    def __init__(self, dataset, sampler,
                 max_batch_size: Optional[int] = None,
                 seed: int = 0, depth: int = 4, n_threads: int = 2,
                 dropout_prob: float = 0.0, dropout_seed: int = 0):
        super().__init__(dataset, sampler, max_batch_size,
                         dropout_prob=dropout_prob,
                         dropout_seed=dropout_seed)
        from commefficient_tpu import native

        if not native.available():
            raise RuntimeError("native dataplane unavailable (no g++?)")
        spec = native.native_transform_spec(dataset.transform)
        if spec is None:
            raise RuntimeError("transform not native-representable")
        images, targets = dataset.dense_train_view()
        if images.ndim != 4 or images.shape[1] != images.shape[2]:
            raise RuntimeError(
                "native path needs square (N, H, H, C) storage, got "
                f"{images.shape}")
        if spec["crop_size"] is not None \
                and spec["crop_size"] != images.shape[1]:
            # the native kernel crops back to the image's own size
            raise RuntimeError("crop size != image size")
        self.plane = native.NativeDataplane(
            images, targets, self.W, self.B,
            spec["mean"], spec["std"],
            crop_pad=spec["crop_pad"], do_flip=spec["do_flip"])
        self.seed = seed
        self.depth, self.n_threads = depth, n_threads
        self._round_counter = 0

    def _spec_to_indices(self, round_spec):
        idx = np.full((self.W, self.B), -1, np.int64)
        ids = np.zeros((self.W,), np.int32)
        for i, (cid, idxs) in enumerate(round_spec):
            ids[i] = cid
            rows = [self.dataset.storage_row(int(ix))
                    for ix in idxs[: self.B]]
            idx[i, : len(rows)] = rows
        return ids, idx

    def __iter__(self):
        from commefficient_tpu import native

        with native.Prefetcher(self.plane, self.depth,
                               self.n_threads) as pf:
            pending: list = []
            for round_spec in self.sampler:
                if len(round_spec) < self.W:
                    continue
                ids, idx = self._spec_to_indices(round_spec)
                pf.submit(idx, self.seed + self._round_counter)
                self._round_counter += 1
                pending.append(ids)
                if len(pending) > self.depth:
                    yield self._pop(pf, pending)
            while pending:
                yield self._pop(pf, pending)

    def _pop(self, pf, pending):
        ids = pending.pop(0)
        x, y, m = pf.pop()
        return self._apply_dropout(
            {"client_ids": ids, "x": x, "y": y, "mask": m})


def make_fed_loader(dataset, sampler, max_batch_size=None, seed=0,
                    prefer_native=True, dropout_prob=0.0):
    """NativeFedLoader when the C++ path applies, FedLoader otherwise.
    The fallback is logged (once per call site reason) so a silently
    slow data path is visible; genuine bugs (TypeError etc.) still
    propagate."""
    if prefer_native:
        try:
            return NativeFedLoader(dataset, sampler, max_batch_size,
                                   seed=seed,
                                   dropout_prob=dropout_prob,
                                   dropout_seed=seed)
        except RuntimeError as e:
            import warnings
            warnings.warn(f"native data-plane unavailable ({e}); "
                          "using the Python loader")
    return FedLoader(dataset, sampler, max_batch_size,
                     dropout_prob=dropout_prob, dropout_seed=seed)


class PersonaFedLoader(_RoundLoaderBase):
    """PersonaChat rounds: adds the double-heads arrays
    input_ids/token_type_ids/lm_labels (W, B, N, T), mc_token_ids
    (W, B, N), mc_labels (W, B).

    ``prefetch_depth`` > 1 runs tokenization/collation on ONE
    background thread, up to that many rounds ahead of the consumer —
    host item prep overlaps the device round (the reference gets this
    from its mp.Queue worker topology, fed_aggregator.py:137-158).
    A single in-order producer keeps every RNG stream (sampler,
    dataset ``_rng`` personality shuffles, dropout) byte-identical to
    the synchronous path, so batches — and checkpointed RNG state at
    epoch end — are deterministic per seed (tested in
    tests/test_gpt2.py TestPersonaPrefetch)."""

    def __init__(self, dataset, sampler, num_candidates: int,
                 max_seq_len: int, pad_id: int = 0,
                 max_batch_size: Optional[int] = None,
                 dropout_prob: float = 0.0, dropout_seed: int = 0,
                 prefetch_depth: int = 2):
        super().__init__(dataset, sampler, max_batch_size,
                         dropout_prob=dropout_prob,
                         dropout_seed=dropout_seed)
        self.N, self.T, self.pad_id = num_candidates, max_seq_len, pad_id
        self.prefetch_depth = prefetch_depth

    def __iter__(self) -> Iterator[dict]:
        if self.prefetch_depth <= 1:
            yield from super().__iter__()
            return
        import queue
        import threading

        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch_depth)
        stop = threading.Event()

        def put_or_stop(item) -> bool:
            # every producer put is stop-aware and bounded: an
            # abandoning consumer (finally-drain racing a concurrent
            # put) can never leave this thread blocked past the 5s
            # join holding dataset/sampler references
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def produce():
            try:
                # the synchronous path's own iterator: skip-guard,
                # collate and dropout stay defined in ONE place
                for batch in _RoundLoaderBase.__iter__(self):
                    if stop.is_set() or not put_or_stop(("batch",
                                                         batch)):
                        return
            except BaseException as e:  # surface in the consumer
                put_or_stop(("error", e))
                return
            put_or_stop(("done", None))

        t = threading.Thread(target=produce, daemon=True,
                             name="persona-prefetch")
        t.start()
        try:
            while True:
                kind, val = q.get()
                if kind == "batch":
                    yield val
                elif kind == "error":
                    raise val
                else:
                    break
        finally:
            # consumer abandoned mid-epoch (NaN abort): unblock and
            # retire the producer so it can't race a later epoch's
            # iteration of the same sampler
            stop.set()
            while not q.empty():
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            t.join(timeout=5.0)

    def collate(self, round_spec) -> dict:
        from commefficient_tpu.data.fed_persona import persona_collate
        W, B, N, T = self.W, self.B, self.N, self.T
        batch = {
            "input_ids": np.zeros((W, B, N, T), np.int32),
            "token_type_ids": np.zeros((W, B, N, T), np.int32),
            "lm_labels": np.full((W, B, N, T), -1, np.int32),
            "mc_token_ids": np.zeros((W, B, N), np.int32),
            "mc_labels": np.zeros((W, B), np.int32),
            "mask": np.zeros((W, B), np.float32),
        }
        ids = np.zeros((W,), np.int32)
        for i, (cid, idxs) in enumerate(round_spec):
            ids[i] = cid
            records = [self.dataset[int(ix)] for ix in idxs[:self.B]]
            assert all(r[0] == cid for r in records)
            _, arrs = persona_collate(records, N, T, self.pad_id)
            n = len(records)
            for k in ("input_ids", "token_type_ids", "lm_labels",
                      "mc_token_ids", "mc_labels"):
                batch[k][i, :n] = arrs[k]
            batch["mask"][i, :n] = 1.0
        batch["client_ids"] = ids
        return batch


class _ShardedValBase:
    """Validation shards: (S, B, ...) stacked shards of
    ``valid_batch_size`` each — the reference's _call_val splitting
    (fed_aggregator.py:339-350) without the queue plumbing. Final
    partial/empty shards are padded and masked; consumers weight
    per-shard metrics by the mask counts the runtime returns."""

    def __init__(self, dataset, valid_batch_size: int,
                 shards_per_step: int = 8):
        self.dataset = dataset
        self.B = valid_batch_size
        self.S = shards_per_step

    def _shard_indices(self):
        n = len(self.dataset)
        step = self.B * self.S
        for start in range(0, n, step):
            yield np.arange(start, min(start + step, n))

    def __len__(self):
        return int(np.ceil(len(self.dataset) / (self.B * self.S)))


class ValLoader(_ShardedValBase):
    _img_shape = None

    def __iter__(self):
        for idxs in self._shard_indices():
            if self._img_shape is None:
                self._img_shape = np.asarray(
                    self.dataset[int(idxs[0])][1]).shape
            x = np.zeros((self.S, self.B) + self._img_shape, np.float32)
            y = np.zeros((self.S, self.B), np.int32)
            mask = np.zeros((self.S, self.B), np.float32)
            for pos, idx in enumerate(idxs):
                s, j = divmod(pos, self.B)
                _, img, target = self.dataset[int(idx)]
                x[s, j] = img
                y[s, j] = target
                mask[s, j] = 1.0
            yield {"x": x, "y": y, "mask": mask}


class PersonaValLoader(_ShardedValBase):
    def __init__(self, dataset, valid_batch_size: int,
                 num_candidates: int, max_seq_len: int,
                 pad_id: int = 0, shards_per_step: int = 8):
        super().__init__(dataset, valid_batch_size, shards_per_step)
        self.N, self.T, self.pad_id = num_candidates, max_seq_len, pad_id

    def __iter__(self):
        from commefficient_tpu.data.fed_persona import persona_collate
        for idxs in self._shard_indices():
            batch = {
                "input_ids": np.zeros((self.S, self.B, self.N, self.T),
                                      np.int32),
                "token_type_ids": np.zeros(
                    (self.S, self.B, self.N, self.T), np.int32),
                "lm_labels": np.full((self.S, self.B, self.N, self.T),
                                     -1, np.int32),
                "mc_token_ids": np.zeros((self.S, self.B, self.N),
                                         np.int32),
                "mc_labels": np.zeros((self.S, self.B), np.int32),
                "cand_mask": np.zeros((self.S, self.B, self.N),
                                      np.float32),
                "mask": np.zeros((self.S, self.B), np.float32),
            }
            for s in range(self.S):
                rows = idxs[s * self.B:(s + 1) * self.B]
                if len(rows) == 0:
                    break
                records = [self.dataset[int(ix)] for ix in rows]
                _, arrs = persona_collate(records, self.N, self.T,
                                          self.pad_id)
                n = len(records)
                for k in ("input_ids", "token_type_ids", "lm_labels",
                          "mc_token_ids", "mc_labels", "cand_mask"):
                    batch[k][s, :n] = arrs[k]
                batch["mask"][s, :n] = 1.0
            yield batch