"""Round-batch construction: sampler output -> fixed-shape padded
engine batches.

The reference ships a flat concatenated tensor batch to the server,
which re-groups rows by client id and queues them to worker processes
(fed_aggregator.py:214-238). Here the loader itself emits the static
(W, B, ...) layout the jitted round wants — client axis first, a
(W, B) mask for ragged clients — so the device never sees a dynamic
shape (SURVEY.md §7).
"""

from __future__ import annotations

from typing import Iterator, Optional

import numpy as np

__all__ = ["FedLoader", "ValLoader"]


class FedLoader:
    """Iterate federated train rounds.

    Yields dicts: ``client_ids`` (W,) int32, ``x`` (W, B, ...) f32,
    ``y`` (W, B) i32, ``mask`` (W, B) f32. Rounds with fewer than
    ``num_workers`` distinct clients are skipped, matching the
    reference's run_batches guard (cv_train.py:205-219).
    """

    def __init__(self, dataset, sampler, max_batch_size: Optional[int] = None):
        self.dataset = dataset
        self.sampler = sampler
        if max_batch_size is not None:
            self.B = max_batch_size
        elif sampler.local_batch_size != -1:
            self.B = sampler.local_batch_size
        else:
            self.B = int(np.max(dataset.data_per_client))
        self.W = sampler.num_workers

    def __iter__(self) -> Iterator[dict]:
        for round_spec in self.sampler:
            if len(round_spec) < self.W:
                continue  # incomplete round: skip
            yield self.collate(round_spec)

    def collate(self, round_spec) -> dict:
        W, B = self.W, self.B
        first = self.dataset[int(round_spec[0][1][0])]
        img_shape = np.asarray(first[1]).shape
        x = np.zeros((W, B) + img_shape, np.float32)
        y = np.zeros((W, B), np.int32)
        mask = np.zeros((W, B), np.float32)
        ids = np.zeros((W,), np.int32)
        for i, (cid, idxs) in enumerate(round_spec):
            ids[i] = cid
            for j, idx in enumerate(idxs[:B]):
                client_id, img, target = self.dataset[int(idx)]
                assert client_id == cid, (client_id, cid)
                x[i, j] = img
                y[i, j] = target
                mask[i, j] = 1.0
        return {"client_ids": ids, "x": x, "y": y, "mask": mask}

    def __len__(self):
        from commefficient_tpu.utils import steps_per_epoch
        return steps_per_epoch(self.sampler.local_batch_size,
                               self.dataset, self.W)


class ValLoader:
    """Validation shards: yields (S, B, ...) stacked shards of
    ``valid_batch_size`` each — the reference's _call_val splitting
    (fed_aggregator.py:339-350) without the queue plumbing. The final
    partial shard is padded and masked."""

    def __init__(self, dataset, valid_batch_size: int,
                 shards_per_step: int = 8):
        self.dataset = dataset
        self.B = valid_batch_size
        self.S = shards_per_step

    def __iter__(self):
        n = len(self.dataset)
        step = self.B * self.S
        for start in range(0, n, step):
            idxs = np.arange(start, min(start + step, n))
            first = self.dataset[0]
            img_shape = np.asarray(first[1]).shape
            x = np.zeros((self.S, self.B) + img_shape, np.float32)
            y = np.zeros((self.S, self.B), np.int32)
            mask = np.zeros((self.S, self.B), np.float32)
            for pos, idx in enumerate(idxs):
                s, j = divmod(pos, self.B)
                _, img, target = self.dataset[int(idx)]
                x[s, j] = img
                y[s, j] = target
                mask[s, j] = 1.0
            yield {"x": x, "y": y, "mask": mask}

    def __len__(self):
        return int(np.ceil(len(self.dataset) / (self.B * self.S)))
