from commefficient_tpu.data.fed_dataset import FedDataset  # noqa: F401
from commefficient_tpu.data.fed_cifar import FedCIFAR10, FedCIFAR100  # noqa: F401
from commefficient_tpu.data.synthetic import FedSynthetic  # noqa: F401
from commefficient_tpu.data.fed_sampler import FedSampler  # noqa: F401
from commefficient_tpu.data.loader import (  # noqa: F401
    FedLoader,
    NativeFedLoader,
    ValLoader,
    make_fed_loader,
)

DATASET_REGISTRY = {
    "CIFAR10": FedCIFAR10,
    "CIFAR100": FedCIFAR100,
    "Synthetic": FedSynthetic,
}


def get_dataset_cls(name: str):
    """Dataset registry — the reference resolves ``globals()["Fed" +
    name]`` (cv_train.py:262); EMNIST/ImageNet/PERSONA register here
    when their modules land."""
    try:
        from commefficient_tpu.data.fed_emnist import FedEMNIST
        DATASET_REGISTRY.setdefault("EMNIST", FedEMNIST)
    except ImportError:
        pass
    try:
        from commefficient_tpu.data.fed_imagenet import FedImageNet
        DATASET_REGISTRY.setdefault("ImageNet", FedImageNet)
    except ImportError:
        pass
    try:
        from commefficient_tpu.data.fed_persona import FedPERSONA
        DATASET_REGISTRY.setdefault("PERSONA", FedPERSONA)
    except ImportError:
        pass
    return DATASET_REGISTRY[name]
