"""Tokenizers for the GPT-2/PersonaChat path.

The reference uses pytorch_transformers' GPT2Tokenizer plus 5 added
special tokens (gpt2_train.py:26-32, 101-112). Here:

- ``GPT2BPETokenizer`` implements GPT-2's byte-level BPE, loading the
  standard ``vocab.json`` + ``merges.txt`` files from disk (this
  environment has zero egress, so no hub download);
- ``ByteTokenizer`` is an offline fallback (byte values as ids) with
  the same interface, used by tests and smoke runs.

Both expose the reference's special-token protocol:
SPECIAL_TOKENS = <bos>, <eos>, <speaker1>, <speaker2>, <pad>.
"""

from __future__ import annotations

import json
import os
from functools import lru_cache
from typing import Dict, List

SPECIAL_TOKENS = ["<bos>", "<eos>", "<speaker1>", "<speaker2>", "<pad>"]


def _read_special(save_dir: str) -> Dict[str, int]:
    path = os.path.join(save_dir, "special_tokens.json")
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        return {k: int(v) for k, v in json.load(f).items()}


def _write_special(save_dir: str, special: Dict[str, int]) -> None:
    os.makedirs(save_dir, exist_ok=True)
    with open(os.path.join(save_dir, "special_tokens.json"), "w") as f:
        json.dump(special, f)


@lru_cache()
def _bytes_to_unicode() -> Dict[int, str]:
    """GPT-2's reversible byte<->unicode table."""
    bs = (list(range(ord("!"), ord("~") + 1))
          + list(range(ord("\xa1"), ord("\xac") + 1))
          + list(range(ord("\xae"), ord("\xff") + 1)))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, [chr(c) for c in cs]))


def _get_pairs(word):
    pairs = set()
    prev = word[0]
    for ch in word[1:]:
        pairs.add((prev, ch))
        prev = ch
    return pairs


class GPT2BPETokenizer:
    """Byte-level BPE (GPT-2). Load with
    ``GPT2BPETokenizer(dir_with_vocab_json_and_merges_txt)``."""

    def __init__(self, vocab_dir: str):
        with open(os.path.join(vocab_dir, "vocab.json")) as f:
            self.encoder: Dict[str, int] = json.load(f)
        with open(os.path.join(vocab_dir, "merges.txt"),
                  encoding="utf-8") as f:
            merges = f.read().split("\n")
        merges = [tuple(m.split()) for m in merges
                  if m and not m.startswith("#version")]
        self.bpe_ranks = dict(zip(merges, range(len(merges))))
        self.byte_encoder = _bytes_to_unicode()
        self.byte_decoder = {v: k for k, v in self.byte_encoder.items()}
        self.decoder = {v: k for k, v in self.encoder.items()}
        self.cache: Dict[str, str] = {}
        self.special: Dict[str, int] = _read_special(vocab_dir)

    def __len__(self):
        return len(self.encoder) + len(self.special)

    def add_special_tokens(self, tokens: List[str]) -> int:
        """Returns number added (reference add_special_tokens_,
        gpt2_train.py:101-112)."""
        added = 0
        for t in tokens:
            if t not in self.special and t not in self.encoder:
                self.special[t] = len(self.encoder) + len(self.special)
                added += 1
        return added

    def convert_tokens_to_ids(self, tokens):
        if isinstance(tokens, str):
            tokens = [tokens]
        out = []
        for t in tokens:
            if t in self.special:
                out.append(self.special[t])
            else:
                out.append(self.encoder.get(t, 0))
        return out

    def _bpe(self, token: str) -> str:
        if token in self.cache:
            return self.cache[token]
        word = tuple(token)
        pairs = _get_pairs(word) if len(word) > 1 else set()
        while pairs:
            bigram = min(pairs,
                         key=lambda p: self.bpe_ranks.get(p, 1e10))
            if bigram not in self.bpe_ranks:
                break
            first, second = bigram
            new_word = []
            i = 0
            while i < len(word):
                try:
                    j = word.index(first, i)
                except ValueError:
                    new_word.extend(word[i:])
                    break
                new_word.extend(word[i:j])
                i = j
                if (i < len(word) - 1 and word[i] == first
                        and word[i + 1] == second):
                    new_word.append(first + second)
                    i += 2
                else:
                    new_word.append(word[i])
                    i += 1
            word = tuple(new_word)
            if len(word) == 1:
                break
            pairs = _get_pairs(word)
        out = " ".join(word)
        self.cache[token] = out
        return out

    def _split_words(self, text: str) -> List[str]:
        """GPT-2's regex split, approximated without the `regex`
        module: contractions, letter runs, digit runs, symbol runs,
        with leading-space attachment."""
        import re
        pat = (r"'s|'t|'re|'ve|'m|'ll|'d"
               r"| ?[A-Za-z]+| ?[0-9]+| ?[^\sA-Za-z0-9]+|\s+(?!\S)|\s+")
        return re.findall(pat, text)

    def encode(self, text: str) -> List[int]:
        ids = []
        for word in self._split_words(text):
            word = "".join(self.byte_encoder[b]
                           for b in word.encode("utf-8"))
            ids.extend(self.encoder[t] for t in self._bpe(word).split(" ")
                       if t in self.encoder)
        return ids

    def decode(self, ids) -> str:
        toks = []
        inv_special = {v: k for k, v in self.special.items()}
        for i in ids:
            i = int(i)
            if i in inv_special:
                toks.append(inv_special[i])
            else:
                toks.append(self.decoder.get(i, ""))
        text = "".join(toks)
        return bytearray(
            self.byte_decoder.get(ch, 32) for ch in text
        ).decode("utf-8", errors="replace")

    def save_pretrained(self, save_dir: str):
        """Write vocab.json / merges.txt / special_tokens.json so the
        saved run directory is self-contained (the reference saves its
        tokenizer to the logdir, gpt2_train.py:278-283)."""
        os.makedirs(save_dir, exist_ok=True)
        with open(os.path.join(save_dir, "vocab.json"), "w") as f:
            json.dump(self.encoder, f)
        merges = sorted(self.bpe_ranks, key=self.bpe_ranks.get)
        with open(os.path.join(save_dir, "merges.txt"), "w",
                  encoding="utf-8") as f:
            f.write("#version: 0.2\n")
            # trailing newline: HF loaders split("\n")[1:-1] and would
            # otherwise drop the last merge
            f.write("\n".join(" ".join(m) for m in merges) + "\n")
        _write_special(save_dir, self.special)


def fabricate_bpe_vocab(save_dir: str, vocab_size: int = 50257,
                        num_words: int = 8000, seed: int = 0):
    """Write a full-size GPT-2-layout ``vocab.json``/``merges.txt``
    whose *geometry* matches the real GPT-2 vocabulary (default
    50257 entries — the reference fine-tunes this exact shape,
    gpt2_train.py:262-285) without needing the real files (zero-egress
    environment). Returns the list of ``num_words`` synthetic words,
    each of which encodes to exactly ONE token through
    :class:`GPT2BPETokenizer`, both bare and with a leading space.

    Construction: words are two consonant-vowel syllables
    ("bade", "kilu", ...). Merges are layered so greedy BPE resolves
    deterministically: char-pair -> syllable, syllable-pair -> word,
    "Ġ"+word -> spaced word. Ids are shuffled so the reachable tokens
    spread across the whole [0, vocab_size) range (embedding/softmax
    rows are exercised across the full table, not a dense prefix).
    Remaining ids are filler entries, unreachable by the merge rules —
    the real vocabulary likewise has ids rare text never produces.
    """
    rng = __import__("random").Random(seed)
    consonants = "bcdfghjklmnprstvwz"
    vowels = "aeiou"
    syllables = [c + v for c in consonants for v in vowels]  # 90
    if num_words > len(syllables) ** 2:
        raise ValueError("num_words exceeds 2-syllable combinations")
    pairs = [(a, b) for a in syllables for b in syllables]
    rng.shuffle(pairs)
    words = [a + b for a, b in pairs[:num_words]]

    byte_tokens = list(_bytes_to_unicode().values())  # 256
    tokens = list(byte_tokens) + list(syllables)
    merges = [(s[0], s[1]) for s in syllables]
    for a, b in pairs[:num_words]:
        merges.append((a, b))
        tokens.append(a + b)
    for w in words:
        merges.append(("Ġ", w))
        tokens.append("Ġ" + w)
    n_filler = vocab_size - len(tokens)
    if n_filler < 0:
        raise ValueError(f"vocab_size {vocab_size} < {len(tokens)} "
                         "constructed tokens")
    tokens.extend(f"<unused{i}>" for i in range(n_filler))

    ids = list(range(vocab_size))
    rng.shuffle(ids)
    encoder = {t: i for t, i in zip(tokens, ids)}

    os.makedirs(save_dir, exist_ok=True)
    with open(os.path.join(save_dir, "vocab.json"), "w") as f:
        json.dump(encoder, f)
    with open(os.path.join(save_dir, "merges.txt"), "w",
              encoding="utf-8") as f:
        f.write("#version: 0.2\n")
        f.write("\n".join(" ".join(m) for m in merges) + "\n")
    return words


class ByteTokenizer:
    """Offline fallback with the same interface: ids = byte values."""

    def __init__(self):
        self.special: Dict[str, int] = {}

    def __len__(self):
        return 256 + len(self.special)

    def add_special_tokens(self, tokens: List[str]) -> int:
        added = 0
        for t in tokens:
            if t not in self.special:
                self.special[t] = 256 + len(self.special)
                added += 1
        return added

    def convert_tokens_to_ids(self, tokens):
        if isinstance(tokens, str):
            tokens = [tokens]
        return [self.special.get(t, ord(t[0]) % 256) for t in tokens]

    def encode(self, text: str) -> List[int]:
        return list(text.encode("utf-8"))

    def decode(self, ids) -> str:
        inv = {v: k for k, v in self.special.items()}
        out = []
        buf = []
        for i in ids:
            i = int(i)
            if i in inv:
                if buf:
                    out.append(bytes(buf).decode("utf-8", "replace"))
                    buf = []
                out.append(inv[i])
            elif i < 256:
                buf.append(i)
        if buf:
            out.append(bytes(buf).decode("utf-8", "replace"))
        return "".join(out)

    def save_pretrained(self, save_dir: str):
        _write_special(save_dir, self.special)


def load_tokenizer(model_checkpoint: str):
    """GPT-2 BPE if vocab files exist at the checkpoint path, else the
    byte fallback (restoring saved special-token ids if present)."""
    if (os.path.isdir(model_checkpoint)
            and os.path.exists(os.path.join(model_checkpoint,
                                            "vocab.json"))):
        return GPT2BPETokenizer(model_checkpoint)
    tok = ByteTokenizer()
    if os.path.isdir(model_checkpoint):
        tok.special = _read_special(model_checkpoint)
    return tok
