"""Deterministic fault + adversary injection (the chaos harness).

Everything here is SEEDED and REPLAYABLE: the same ChaosConfig
produces the same byzantine client set, the same dropout trace and
the same host-fault schedule on every run, so a chaos test failure is
a plain repro, not a flake. Three fault families:

Byzantine clients
    A seeded subset of client ids turns adversarial. ``label_flip``
    poisons the DATA (y -> (num_classes-1) - y on the byzantine rows
    of each round batch, applied by :meth:`ChaosInjector.wrap_loader`).
    The gradient-level attacks — ``sign_flip`` (transmit x -1),
    ``scale`` (transmit x C), ``noise`` (transmit replaced by
    N(0, noise_std²) scaled by the client's datapoint count) — act on
    the per-client transmit inside the jitted round via the traceable
    function from :meth:`ChaosInjector.transmit_transform`, passed to
    ``build_client_round(..., transmit_transform=...)``. With the
    default ``transmit_transform=None`` the hook is never traced and
    the round program is bit-identical to a chaos-free build (pinned
    by the HLO-identity test).

Dropout traces
    Beyond the loader's i.i.d. ``dropout_prob``: a seeded two-state
    Markov chain (calm/burst) drops a CORRELATED subset of the
    round's client slots for the whole burst — the "rack went dark
    for a few rounds" shape i.i.d. drops can't produce.

Host faults
    :class:`FlakyStore` wraps a clientstore so ``gather`` fails (or
    stalls) on a seeded schedule — the fixture behind the prefetch
    retry/backoff tests. :meth:`ChaosInjector.straggler_sleep`
    simulates slow input lanes by sleeping before designated rounds'
    batches are released, and :func:`kill_prefetch_worker` murders a
    StorePrefetcher's thread mid-run to exercise the worker-death
    surfacing path.

Import policy: production modules must NOT import this file — chaos
is reachable only from tests, benches and scripts (enforced by the
``chaos-confinement`` lint rule in analysis/lint.py). The
engine-side hook is a generic parameter; only the harness that builds
the attack lives here.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import time
from typing import Iterator, Optional, Sequence

import numpy as np

__all__ = ["ATTACKS", "ArrivalSchedule", "ChaosConfig",
           "ChaosInjector", "FlakyStore", "PreemptionDrill",
           "kill_prefetch_worker"]

ATTACKS = ("none", "label_flip", "sign_flip", "scale", "noise")


@dataclasses.dataclass
class ChaosConfig:
    """One replayable fault scenario. All schedules derive from
    ``seed``; a field's zero value disables that fault family."""

    seed: int = 0
    # -- byzantine clients ------------------------------------------
    attack: str = "none"
    byzantine_frac: float = 0.0        # fraction of the client pool
    byzantine_ids: Optional[Sequence[int]] = None  # explicit override
    attack_scale: float = 10.0         # C for the "scale" attack
    noise_std: float = 1.0             # sigma for the "noise" attack
    num_classes: int = 0               # required for label_flip
    # -- correlated dropout trace -----------------------------------
    burst_start_prob: float = 0.0      # calm -> burst per round
    burst_stop_prob: float = 0.5       # burst -> calm per round
    burst_drop_frac: float = 0.5       # slots dropped during a burst
    # -- host faults ------------------------------------------------
    shard_fail_prob: float = 0.0       # FlakyStore transient failures
    shard_fail_streak: int = 1         # consecutive failures per hit
    shard_delay_s: float = 0.0         # FlakyStore read latency
    straggler_every: int = 0           # every Nth round is a straggler
    straggler_delay_s: float = 0.0     # how long the slow lane sleeps

    def __post_init__(self):
        assert self.attack in ATTACKS, self.attack
        if self.attack == "label_flip":
            assert self.num_classes > 1, \
                "label_flip needs ChaosConfig.num_classes"


class ChaosInjector:
    """Materialises one ChaosConfig against a client pool."""

    def __init__(self, cfg: ChaosConfig, num_clients: int):
        self.cfg = cfg
        self.num_clients = int(num_clients)
        rng = np.random.RandomState(cfg.seed)
        if cfg.byzantine_ids is not None:
            ids = np.asarray(sorted(set(int(i) for i
                                        in cfg.byzantine_ids)),
                             np.int32)
        elif cfg.attack != "none" and cfg.byzantine_frac > 0:
            k = max(1, int(round(cfg.byzantine_frac * num_clients)))
            ids = np.sort(rng.choice(num_clients, size=min(
                k, num_clients), replace=False)).astype(np.int32)
        else:
            ids = np.zeros((0,), np.int32)
        self.byzantine = ids
        # independent streams so toggling one fault family never
        # perturbs another's schedule
        self._drop_rng = np.random.RandomState(cfg.seed + 1)
        self._noise_seed = cfg.seed + 2
        self._in_burst = False
        self._burst_slots: Optional[np.ndarray] = None
        self._round = 0

    # -- byzantine side ---------------------------------------------

    def is_byzantine(self, client_ids) -> np.ndarray:
        return np.isin(np.asarray(client_ids), self.byzantine)

    def poison_batch(self, batch: dict) -> dict:
        """label_flip: y -> (num_classes-1) - y on byzantine rows.
        Other attacks act on transmits, not data — no-op here."""
        if self.cfg.attack != "label_flip" or "y" not in batch:
            return batch
        bad = self.is_byzantine(batch["client_ids"])
        if not bad.any():
            return batch
        batch = dict(batch)
        y = batch["y"].copy()
        y[bad] = (self.cfg.num_classes - 1) - y[bad]
        batch["y"] = y
        return batch

    def transmit_transform(self):
        """A traceable (transmit, batch, client_ids, rng) -> transmit
        for ``build_client_round``, or None when the configured attack
        lives at the data level. Byzantine membership is tested inside
        the trace (jnp.isin against the seeded id set), so one
        compiled round serves every round's client draw."""
        if self.cfg.attack not in ("sign_flip", "scale", "noise"):
            return None
        import jax
        import jax.numpy as jnp

        byz = jnp.asarray(self.byzantine)
        attack = self.cfg.attack
        C = float(self.cfg.attack_scale)
        sigma = float(self.cfg.noise_std)
        noise_seed = self._noise_seed

        def transform(transmit, batch, client_ids, rng):
            if byz.size == 0:
                return transmit
            bad = jnp.isin(client_ids, byz)
            badx = bad.reshape((-1,) + (1,) * (transmit.ndim - 1))
            if attack == "sign_flip":
                evil = -transmit
            elif attack == "scale":
                evil = C * transmit
            else:  # noise: transmit = sigma*N(0,1) * datapoint count,
                # matching the honest transmit's batch-size scaling
                n = jnp.sum(batch["mask"],
                            axis=tuple(range(1, batch["mask"].ndim)))
                nx = n.reshape(badx.shape)
                nrng = jax.random.fold_in(
                    jax.random.fold_in(rng, noise_seed), 7)
                evil = sigma * jax.random.normal(
                    nrng, transmit.shape, transmit.dtype) * nx
            return jnp.where(badx, evil, transmit)

        return transform

    # -- dropout trace ----------------------------------------------

    def _advance_burst(self, W: int):
        c = self.cfg
        if self._in_burst:
            if self._drop_rng.rand() < c.burst_stop_prob:
                self._in_burst, self._burst_slots = False, None
        elif c.burst_start_prob > 0 \
                and self._drop_rng.rand() < c.burst_start_prob:
            self._in_burst = True
            k = max(1, int(round(c.burst_drop_frac * W)))
            self._burst_slots = self._drop_rng.choice(
                W, size=min(k, W), replace=False)

    def drop_slots(self, W: int) -> Optional[np.ndarray]:
        """This round's correlated-drop slot indices (None when calm).
        The same subset holds for the burst's whole lifetime."""
        self._advance_burst(W)
        return self._burst_slots if self._in_burst else None

    # -- loader wrapping --------------------------------------------

    def wrap_loader(self, loader) -> Iterator[dict]:
        """Iterate ``loader`` with data poisoning, the correlated
        dropout trace and straggler sleeps applied, in round order.
        len() and peek_next_client_ids pass through untouched on the
        wrapper object returned by :meth:`wrap`."""
        c = self.cfg
        for batch in loader:
            self._round += 1
            if c.straggler_every > 0 and c.straggler_delay_s > 0 \
                    and self._round % c.straggler_every == 0:
                time.sleep(c.straggler_delay_s)
            batch = self.poison_batch(batch)
            slots = self.drop_slots(batch["mask"].shape[0])
            if slots is not None and len(slots):
                batch = dict(batch)
                mask = batch["mask"].copy()
                mask[slots] = 0.0
                batch["mask"] = mask
            yield batch

    def wrap(self, loader):
        return _ChaosLoader(self, loader)


class _ChaosLoader:
    """Loader facade: chaos-wrapped iteration, everything else
    delegated (len, W/B, peek_next_client_ids for the prefetch
    feed)."""

    def __init__(self, injector: ChaosInjector, loader):
        self._injector = injector
        self._loader = loader

    def __iter__(self):
        return self._injector.wrap_loader(self._loader)

    def __len__(self):
        return len(self._loader)

    def __getattr__(self, name):
        return getattr(self._loader, name)


class ArrivalSchedule:
    """Seeded, replayable per-client ARRIVAL process — when each
    issued client's update actually lands, in fold-step units.

    This is the arrival-side twin of the dropout trace above,
    promoted out of ``scripts/host_scale_bench.py`` so benches,
    tests and the asyncfed driver all replay the same schedule from
    one seed. Three kinds:

    ``uniform``
        Every client arrives the round it was issued (delay 0) —
        the punctual barrier world; with ``--async_buffer_size`` at
        the cohort size this is the degenerate-sync configuration.
    ``churny``
        Independent per-client lag: each client is late with
        probability ``churn_frac``, by 1..``max_delay`` rounds.
    ``bursty``
        The correlated-dropout shape: a two-state Markov chain
        (calm/burst, same transition logic as
        :meth:`ChaosInjector.drop_slots`) delays a correlated
        ``drop_frac`` subset of each issued cohort by ``max_delay``
        rounds for the burst's whole lifetime ("rack went dark").

    Delays are drawn from one sequential ``RandomState(seed)``
    stream, so a schedule replays exactly: ``reset()`` then the same
    sequence of :meth:`delays` calls yields the same trace (pinned
    by the golden-trace test). Instances are callable with the
    ``(round_index, n) -> delays`` signature the asyncfed driver's
    ``attach_arrival_process`` hook expects.

    Import policy: like the rest of this module, production code
    never imports this — the asyncfed driver defaults to punctual
    arrival internally and schedules are injected only from tests,
    benches and scripts (``arrival-confinement`` lint rule).
    """

    KINDS = ("uniform", "churny", "bursty")

    def __init__(self, kind: str = "uniform", seed: int = 0,
                 max_delay: int = 4, churn_frac: float = 0.5,
                 burst_start_prob: float = 0.15,
                 burst_stop_prob: float = 0.5,
                 drop_frac: float = 0.5):
        assert kind in self.KINDS, kind
        assert max_delay >= 1, "max_delay must be >= 1"
        self.kind = kind
        self.seed = int(seed)
        self.max_delay = int(max_delay)
        self.churn_frac = float(churn_frac)
        self.burst_start_prob = float(burst_start_prob)
        self.burst_stop_prob = float(burst_stop_prob)
        self.drop_frac = float(drop_frac)
        self.reset()

    def reset(self) -> None:
        """Rewind to round 0 of the trace."""
        self._rng = np.random.RandomState(self.seed)
        self._in_burst = False
        self._burst_slots: Optional[np.ndarray] = None
        self._round = 0

    def delays(self, n: int) -> np.ndarray:
        """Arrival delays (int64, >= 0) for the next issued cohort of
        ``n`` clients. Consumes the stream — call in round order."""
        self._round += 1
        if self.kind == "uniform":
            return np.zeros((n,), np.int64)
        if self.kind == "churny":
            late = self._rng.rand(n) < self.churn_frac
            lag = self._rng.randint(1, self.max_delay + 1, size=n)
            return np.where(late, lag, 0).astype(np.int64)
        # bursty: advance the calm/burst chain, then stall the
        # burst's correlated slot subset by the full max_delay
        if self._in_burst:
            if self._rng.rand() < self.burst_stop_prob:
                self._in_burst, self._burst_slots = False, None
        elif self.burst_start_prob > 0 \
                and self._rng.rand() < self.burst_start_prob:
            self._in_burst = True
            k = max(1, int(round(self.drop_frac * n)))
            self._burst_slots = self._rng.choice(
                n, size=min(k, n), replace=False)
        out = np.zeros((n,), np.int64)
        if self._in_burst and self._burst_slots is not None:
            out[self._burst_slots[self._burst_slots < n]] = \
                self.max_delay
        return out

    def __call__(self, round_index: int, n: int) -> np.ndarray:
        return self.delays(n)

    @staticmethod
    def replay_stats(alive: Sequence[float], cohort: int) -> dict:
        """Burst statistics of a replayed trace, from the per-round
        alive fractions a run observed. Exactly the summary
        ``host_scale_bench`` reports (the bench now calls this)."""
        alive = [float(a) for a in alive]
        ragged = [a for a in alive if a < 1.0]
        burst_rounds, bursts, in_burst = 0, 0, False
        longest, cur = 0, 0
        for a in alive:
            if a < 1.0:
                burst_rounds += 1
                cur += 1
                if not in_burst:
                    bursts += 1
                in_burst = True
                longest = max(longest, cur)
            else:
                in_burst, cur = False, 0
        return {
            "burst_count": bursts,
            "burst_rounds": burst_rounds,
            "longest_burst": longest,
            "alive_frac_min": round(min(alive), 3) if alive else 1.0,
            "alive_frac_mean": round(
                sum(alive) / max(len(alive), 1), 3),
            "dropped_client_rounds": round(
                sum(1.0 - a for a in ragged) * cohort),
        }


class PreemptionDrill:
    """Seeded self-preemption: kill THIS process mid-round, once.

    The elastic-restore drill's first act. A seeded RandomState picks
    the kill round from ``[min_round, max_round]`` and the signal from
    ``signals`` (SIGTERM for the graceful-shutdown path, SIGKILL for
    the torn-write path), so the same seed always dies at the same
    point — a failed drill is a repro, not a flake. The driving test
    calls :meth:`should_kill` each round at the chosen fault point
    (between forward and fold, after the autosave, wherever it wants
    the cut) and :meth:`execute` delivers the signal to ``os.getpid``.

    Like everything in this module the drill is test/bench-only; the
    survivor half of the story (restart on fewer hosts, resume from
    the last valid autosave, converge-or-alarm) lives in the chaos
    tests, not here.
    """

    def __init__(self, seed: int = 0, min_round: int = 1,
                 max_round: int = 4,
                 signals: Sequence[int] = (signal.SIGTERM,
                                           signal.SIGKILL)):
        assert 0 <= min_round <= max_round
        rng = np.random.RandomState(seed)
        self.kill_round = int(rng.randint(min_round, max_round + 1))
        self.signal = int(signals[int(rng.randint(len(signals)))])
        self.fired = False

    def should_kill(self, round_index: int) -> bool:
        """True once ``round_index`` reaches the drawn kill round (and
        the drill has not fired yet)."""
        return not self.fired and int(round_index) >= self.kill_round

    def execute(self) -> None:
        """Deliver the drawn signal to this process. SIGKILL never
        returns; SIGTERM returns to let the harness's handler (e.g.
        ``sigterm_raises``) unwind the run."""
        self.fired = True
        os.kill(os.getpid(), self.signal)


class FlakyStore:
    """Clientstore wrapper whose ``gather`` transiently fails and/or
    stalls on a seeded schedule — the fixture behind the prefetch
    retry/backoff tests. A scheduled hit raises for
    ``shard_fail_streak`` consecutive attempts, then succeeds: with
    bounded retry (3 tries) a streak of 2 recovers invisibly and a
    streak of 3+ surfaces as the worker-death RuntimeError."""

    def __init__(self, store, cfg: ChaosConfig):
        self._store = store
        self._cfg = cfg
        self._rng = np.random.RandomState(cfg.seed + 3)
        self._streak_left = 0
        self.attempts = 0
        self.failures = 0

    def gather(self, ids, out=None):
        self.attempts += 1
        if self._cfg.shard_delay_s > 0:
            time.sleep(self._cfg.shard_delay_s)
        if self._streak_left == 0 \
                and self._cfg.shard_fail_prob > 0 \
                and self._rng.rand() < self._cfg.shard_fail_prob:
            self._streak_left = max(1, int(self._cfg.shard_fail_streak))
        if self._streak_left > 0:
            self._streak_left -= 1
            self.failures += 1
            raise OSError("chaos: transient shard read failure")
        return self._store.gather(ids, out=out)

    def __getattr__(self, name):
        return getattr(self._store, name)


def kill_prefetch_worker(prefetcher) -> None:
    """Simulate a prefetch-worker crash: poison the work queue so the
    worker thread exits its loop as if it had died mid-run. The next
    ``take``/``submit`` must surface the PR-2 worker-death
    RuntimeError rather than hang."""
    fail = getattr(prefetcher, "_fail_for_test", None)
    if callable(fail):
        fail(RuntimeError("chaos: prefetch worker killed"))
        return
    raise RuntimeError("prefetcher exposes no kill hook")
