"""Image transforms — numpy re-implementations of the torchvision
stacks the reference uses (data_utils/transforms.py:1-75). All operate
on HWC float arrays; normalization constants are identical."""

from __future__ import annotations

import numpy as np

CIFAR10_MEAN = np.array([0.4914, 0.4822, 0.4465], np.float32)
CIFAR10_STD = np.array([0.2470, 0.2435, 0.2616], np.float32)
CIFAR100_MEAN = np.array([0.5071, 0.4865, 0.4409], np.float32)
CIFAR100_STD = np.array([0.2673, 0.2564, 0.2762], np.float32)
EMNIST_MEAN = np.array([0.1307], np.float32)
EMNIST_STD = np.array([0.3081], np.float32)
IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class ToFloat:
    """uint8 HWC -> float32 in [0, 1]."""

    def __call__(self, x):
        if x.dtype == np.uint8:
            return x.astype(np.float32) / 255.0
        return x.astype(np.float32)


class Normalize:
    def __init__(self, mean, std):
        self.mean, self.std = mean, std

    def __call__(self, x):
        return (x - self.mean) / self.std


class RandomCrop:
    """Pad by ``padding`` then random-crop back to ``size``."""

    def __init__(self, size, padding=4, rng=None, fill=None):
        self.size, self.padding, self.fill = size, padding, fill
        self.rng = rng or np.random

    def __call__(self, x):
        p = self.padding
        if self.fill is None:
            x = np.pad(x, ((p, p), (p, p), (0, 0)), mode="reflect")
        else:
            x = np.pad(x, ((p, p), (p, p), (0, 0)), mode="constant",
                       constant_values=self.fill)
        i = self.rng.randint(0, x.shape[0] - self.size + 1)
        j = self.rng.randint(0, x.shape[1] - self.size + 1)
        return x[i:i + self.size, j:j + self.size]


class RandomHorizontalFlip:
    def __init__(self, rng=None):
        self.rng = rng or np.random

    def __call__(self, x):
        if self.rng.rand() < 0.5:
            return x[:, ::-1].copy()
        return x


def cifar_train_transform(mean=CIFAR10_MEAN, std=CIFAR10_STD):
    return Compose([ToFloat(), RandomCrop(32, 4),
                    RandomHorizontalFlip(), Normalize(mean, std)])


def cifar_val_transform(mean=CIFAR10_MEAN, std=CIFAR10_STD):
    return Compose([ToFloat(), Normalize(mean, std)])


FEMNIST_MEAN = np.array([0.9637], np.float32)
FEMNIST_STD = np.array([0.1597], np.float32)


class RandomRotation:
    """Small-angle rotation with constant fill (femnist augmentation,
    reference transforms.py:50-51). Nearest-neighbor on HWC arrays."""

    def __init__(self, degrees, fill=1.0, rng=None):
        self.degrees, self.fill = degrees, fill
        self.rng = rng or np.random

    def __call__(self, x):
        ang = np.deg2rad(self.rng.uniform(-self.degrees, self.degrees))
        h, w = x.shape[:2]
        cy, cx = (h - 1) / 2.0, (w - 1) / 2.0
        yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
        c, s = np.cos(ang), np.sin(ang)
        sy = cy + (yy - cy) * c - (xx - cx) * s
        sx = cx + (yy - cy) * s + (xx - cx) * c
        syi = np.round(sy).astype(int)
        sxi = np.round(sx).astype(int)
        valid = (syi >= 0) & (syi < h) & (sxi >= 0) & (sxi < w)
        out = np.full_like(x, self.fill, dtype=np.float32)
        out[valid] = x[syi[valid], sxi[valid]]
        return out


def _pil_resize(x, nh, nw):
    """PIL-bilinear resize of an HWC array to (nh, nw), preserving the
    input dtype convention (uint8 stays uint8; float in [0,1] is
    clipped, round-tripped via uint8, and returned as float32).
    Handles (H, W, 1) grayscale on both paths."""
    from PIL import Image
    dtype = x.dtype
    if dtype == np.uint8:
        arr = np.asarray(x)
    else:
        arr = np.asarray(np.clip(x, 0, 1) * 255, np.uint8)
    if arr.ndim == 3 and arr.shape[-1] == 1:
        arr = arr[..., 0]
    im = Image.fromarray(arr).resize((nw, nh), Image.BILINEAR)
    out = np.asarray(im)
    if out.ndim == 2:
        out = out[..., None]
    if dtype != np.uint8:
        out = out.astype(np.float32) / 255.0
    return out


class Resize:
    """Shorter side -> ``size`` (PIL bilinear), HWC uint8/float."""

    def __init__(self, size):
        self.size = size

    def __call__(self, x):
        h, w = x.shape[:2]
        if h < w:
            nh, nw = self.size, max(1, round(w * self.size / h))
        else:
            nh, nw = max(1, round(h * self.size / w)), self.size
        return _pil_resize(x, nh, nw)


class CenterCrop:
    def __init__(self, size):
        self.size = size

    def __call__(self, x):
        h, w = x.shape[:2]
        i = max(0, (h - self.size) // 2)
        j = max(0, (w - self.size) // 2)
        return x[i:i + self.size, j:j + self.size]


class RandomResizedCrop:
    """Random area/aspect crop resized to ``size`` (reference
    transforms.py:49, 67)."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3. / 4., 4. / 3.),
                 rng=None):
        self.size, self.scale, self.ratio = size, scale, ratio
        self.rng = rng or np.random

    def __call__(self, x):
        h, w = x.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * self.rng.uniform(*self.scale)
            ar = np.exp(self.rng.uniform(np.log(self.ratio[0]),
                                         np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                i = self.rng.randint(0, h - ch + 1)
                j = self.rng.randint(0, w - cw + 1)
                x = x[i:i + ch, j:j + cw]
                break
        else:
            s = min(h, w)
            x = CenterCrop(s)(x)
        return _pil_resize(x, self.size, self.size)


def femnist_train_transform(rng=None):
    """reference transforms.py:47-53 (crop/resize/rotate with white
    fill — LEAF femnist is white-background floats in [0,1])."""
    return Compose([ToFloat(),
                    RandomCrop(28, 2, rng=rng, fill=1.0),
                    RandomResizedCrop(28, scale=(0.8, 1.2),
                                      ratio=(4. / 5., 5. / 4.), rng=rng),
                    RandomRotation(5, fill=1.0, rng=rng),
                    Normalize(FEMNIST_MEAN, FEMNIST_STD)])


def femnist_val_transform():
    return Compose([ToFloat(), Normalize(FEMNIST_MEAN, FEMNIST_STD)])


def imagenet_train_transform(rng=None):
    return Compose([RandomResizedCrop(224, rng=rng),
                    RandomHorizontalFlip(rng=rng), ToFloat(),
                    Normalize(IMAGENET_MEAN, IMAGENET_STD)])


def imagenet_val_transform():
    return Compose([Resize(256), CenterCrop(224), ToFloat(),
                    Normalize(IMAGENET_MEAN, IMAGENET_STD)])
