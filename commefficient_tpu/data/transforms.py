"""Image transforms — numpy re-implementations of the torchvision
stacks the reference uses (data_utils/transforms.py:1-75). All operate
on HWC float arrays; normalization constants are identical."""

from __future__ import annotations

import numpy as np

CIFAR10_MEAN = np.array([0.4914, 0.4822, 0.4465], np.float32)
CIFAR10_STD = np.array([0.2470, 0.2435, 0.2616], np.float32)
CIFAR100_MEAN = np.array([0.5071, 0.4865, 0.4409], np.float32)
CIFAR100_STD = np.array([0.2673, 0.2564, 0.2762], np.float32)
EMNIST_MEAN = np.array([0.1307], np.float32)
EMNIST_STD = np.array([0.3081], np.float32)
IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class ToFloat:
    """uint8 HWC -> float32 in [0, 1]."""

    def __call__(self, x):
        if x.dtype == np.uint8:
            return x.astype(np.float32) / 255.0
        return x.astype(np.float32)


class Normalize:
    def __init__(self, mean, std):
        self.mean, self.std = mean, std

    def __call__(self, x):
        return (x - self.mean) / self.std


class RandomCrop:
    """Pad by ``padding`` then random-crop back to ``size``."""

    def __init__(self, size, padding=4, rng=None):
        self.size, self.padding = size, padding
        self.rng = rng or np.random

    def __call__(self, x):
        p = self.padding
        x = np.pad(x, ((p, p), (p, p), (0, 0)), mode="reflect")
        i = self.rng.randint(0, x.shape[0] - self.size + 1)
        j = self.rng.randint(0, x.shape[1] - self.size + 1)
        return x[i:i + self.size, j:j + self.size]


class RandomHorizontalFlip:
    def __init__(self, rng=None):
        self.rng = rng or np.random

    def __call__(self, x):
        if self.rng.rand() < 0.5:
            return x[:, ::-1].copy()
        return x


def cifar_train_transform(mean=CIFAR10_MEAN, std=CIFAR10_STD):
    return Compose([ToFloat(), RandomCrop(32, 4),
                    RandomHorizontalFlip(), Normalize(mean, std)])


def cifar_val_transform(mean=CIFAR10_MEAN, std=CIFAR10_STD):
    return Compose([ToFloat(), Normalize(mean, std)])
