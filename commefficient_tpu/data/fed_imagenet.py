"""Federated ImageNet: natural partition = one wnid (class) per client.

Counterpart of reference data_utils/fed_imagenet.py:12-76:
``prepare_datasets`` refuses to download and only writes ``stats.json``
over an existing extracted tree::

    dataset_dir/
      train/<wnid>/<image>.JPEG ...
      val/<wnid>/<image>.JPEG ...

Unlike the reference (which wraps ``torchvision.datasets.ImageNet``),
the tree is indexed directly — wnids sorted lexicographically define
client ids, matching torchvision's class ordering. Images decode
lazily per item via PIL; the transform stack (data/transforms.py)
handles resize/crop/normalize.
"""

from __future__ import annotations

import json
import os

import numpy as np

from commefficient_tpu.data.fed_dataset import FedDataset

__all__ = ["FedImageNet"]

_EXTS = (".jpeg", ".jpg", ".png")


def _index_split(split_dir: str):
    """[(path, class_idx)] sorted by (wnid, filename), plus counts."""
    wnids = sorted(d for d in os.listdir(split_dir)
                   if os.path.isdir(os.path.join(split_dir, d)))
    samples, counts = [], []
    for ci, wnid in enumerate(wnids):
        cdir = os.path.join(split_dir, wnid)
        files = sorted(f for f in os.listdir(cdir)
                       if f.lower().endswith(_EXTS))
        samples.extend((os.path.join(cdir, f), ci) for f in files)
        counts.append(len(files))
    return samples, counts


class FedImageNet(FedDataset):
    num_classes = 1000

    def prepare_datasets(self, download=False):
        if download:
            raise RuntimeError("Can't download ImageNet "
                               "(reference fed_imagenet.py:15-16)")
        if os.path.exists(self.stats_fn()):
            raise RuntimeError("won't overwrite existing stats file")
        _, counts = _index_split(os.path.join(self.dataset_dir, "train"))
        val_samples, _ = _index_split(os.path.join(self.dataset_dir,
                                                   "val"))
        with open(self.stats_fn(), "w") as f:
            json.dump({"images_per_client": counts,
                       "num_val_images": len(val_samples)}, f)

    def _load_meta(self, train):
        super()._load_meta(train)
        split = "train" if train else "val"
        self._samples, counts = _index_split(
            os.path.join(self.dataset_dir, split))
        # trust the fresh walk over the frozen stats.json snapshot —
        # a re-extracted tree would otherwise silently desync indices
        if train:
            self.images_per_client = np.asarray(counts)
        else:
            self.num_val_images = len(self._samples)

    def _decode(self, path):
        from PIL import Image
        with Image.open(path) as im:
            return np.asarray(im.convert("RGB"))

    def _get_train_item(self, client_id, idx_within_client):
        cumsum = self._ipc_cumsum
        start = int(cumsum[client_id - 1]) if client_id else 0
        path, target = self._samples[start + int(idx_within_client)]
        return self._decode(path), int(target)

    def _get_val_item(self, idx):
        path, target = self._samples[int(idx)]
        return self._decode(path), int(target)
