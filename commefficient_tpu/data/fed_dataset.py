"""Federated dataset base class.

Host-side numpy counterpart of reference data_utils/fed_dataset.py:9-98:
a dataset is a natural partition of records over clients
(``images_per_client``); ``--iid`` applies a global permutation while
keeping synthetic client ids; ``--num_clients`` re-splits natural
partitions. Items are ``(client_id, image, target)`` with client_id -1
for validation records (fed_dataset.py:68-95).

Data feeding is host-side numpy end to end — the TPU only ever sees
the fixed-shape padded round batches built by ``FedLoader``.
"""

from __future__ import annotations

import json
import os

import numpy as np

__all__ = ["FedDataset"]


class FedDataset:
    def __init__(self, dataset_dir, dataset_name, transform=None,
                 do_iid=False, num_clients=None, train=True,
                 download=False, seed=None):
        self.dataset_dir = dataset_dir
        self.dataset_name = dataset_name
        self.transform = transform
        self.do_iid = do_iid
        self._num_clients = num_clients
        self.type = "train" if train else "val"

        if not do_iid and num_clients == 1:
            raise ValueError("can't have 1 client when non-iid")

        if not os.path.exists(self.stats_fn()):
            self.prepare_datasets(download=download)

        self._load_meta(train)

        if self.do_iid:
            rng = (np.random if seed is None
                   else np.random.RandomState(seed))
            self.iid_shuffle = rng.permutation(len(self))

    @property
    def data_per_client(self):
        """(reference fed_dataset.py:31-48); cached — immutable after
        _load_meta, and the sampler/__getitem__ hot paths consult it
        per item."""
        cached = getattr(self, "_dpc_cache", None)
        if cached is not None:
            return cached
        if self.do_iid:
            num_data = len(self)
            ipc = (np.ones(self.num_clients, dtype=int)
                   * num_data // self.num_clients)
            extra = num_data % self.num_clients
            if extra:
                ipc[self.num_clients - extra:] += 1
        elif self._num_clients is None:
            # natural partition: one client per natural unit
            ipc = np.asarray(self.images_per_client)
        else:
            if self._num_clients < len(self.images_per_client):
                raise ValueError(
                    f"non-iid needs num_clients >= "
                    f"{len(self.images_per_client)} natural partitions "
                    f"(got {self._num_clients}); pass --iid to re-split")
            n_natural = len(self.images_per_client)
            if self._num_clients % n_natural:
                # the even split below would yield
                # n_natural * (num_clients // n_natural) clients and
                # the sampler would crash on the length mismatch —
                # fail with the actual constraint instead
                raise ValueError(
                    f"non-iid re-split divides clients evenly over "
                    f"the {n_natural} natural partitions: "
                    f"--num_clients must be a multiple of {n_natural} "
                    f"(got {self._num_clients}); pass --iid for an "
                    f"arbitrary client count")
            new_ipc = []
            for num_images in self.images_per_client:
                n_per_class = self._num_clients // n_natural
                extra = num_images % n_per_class
                split = [num_images // n_per_class
                         for _ in range(n_per_class)]
                split[-1] += extra
                new_ipc.extend(split)
            ipc = np.array(new_ipc)
        self._dpc_cache = ipc
        self._dpc_cumsum = np.cumsum(ipc)
        return ipc

    @property
    def num_clients(self):
        return (self._num_clients if self._num_clients is not None
                else len(self.images_per_client))

    def _load_meta(self, train):
        with open(self.stats_fn(), "r") as f:
            stats = json.load(f)
            self.images_per_client = np.array(stats["images_per_client"])
            self.num_val_images = stats["num_val_images"]

    @property
    def _ipc_cumsum(self):
        cached = getattr(self, "_ipc_cumsum_cache", None)
        if cached is None:
            cached = np.cumsum(self.images_per_client)
            self._ipc_cumsum_cache = cached
        return cached

    def __len__(self):
        if self.type == "train":
            return int(sum(self.images_per_client))
        return int(self.num_val_images)

    def __getitem__(self, idx):
        if self.type == "train":
            orig_idx = idx
            if self.do_iid:
                idx = self.iid_shuffle[idx]
            cumsum = self._ipc_cumsum
            natural_client = np.searchsorted(cumsum, idx, side="right")
            start = cumsum[natural_client - 1] if natural_client else 0
            idx_within = idx - start
            image, target = self._get_train_item(natural_client,
                                                 idx_within)
            # the *reported* client id comes from data_per_client over
            # the original index (fed_dataset.py:84-85)
            self.data_per_client  # ensure _dpc_cumsum
            client_id = int(np.searchsorted(self._dpc_cumsum, orig_idx,
                                            side="right"))
        else:
            image, target = self._get_val_item(idx)
            client_id = -1

        if self.transform is not None:
            image = self.transform(image)
        return client_id, image, target

    def dense_train_view(self):
        """(images (N, ...), targets (N,) int32) in global *pre-iid*
        train-index order — the storage the native C++ data-plane
        gathers from (commefficient_tpu/native). Raw records, no
        transform. Subclasses with contiguous storage should override
        (FedCIFAR does); this generic path materialises once."""
        cached = getattr(self, "_dense_view_cache", None)
        if cached is not None:
            return cached
        cumsum = self._ipc_cumsum
        n = int(sum(self.images_per_client))
        imgs, tgts = None, np.zeros(n, np.int32)
        for idx in range(n):
            nat = int(np.searchsorted(cumsum, idx, side="right"))
            start = cumsum[nat - 1] if nat else 0
            img, t = self._get_train_item(nat, idx - start)
            img = np.asarray(img)
            if imgs is None:
                imgs = np.zeros((n,) + img.shape, img.dtype)
            imgs[idx] = img
            tgts[idx] = t
        self._dense_view_cache = (imgs, tgts)
        return self._dense_view_cache

    def storage_row(self, idx):
        """Map a sampled global train index to its dense_train_view
        row (identity unless --iid permuted)."""
        return self.iid_shuffle[idx] if self.do_iid else idx

    def stats_fn(self):
        return os.path.join(self.dataset_dir, "stats.json")

    # subclass API
    def prepare_datasets(self, download=False):
        raise NotImplementedError

    def _get_train_item(self, client_id, idx_within_client):
        raise NotImplementedError

    def _get_val_item(self, idx):
        raise NotImplementedError
