"""Federated round scheduler — same semantics as reference
data_utils/fed_sampler.py:5-71: shuffle within each client, then each
round sample ``num_workers`` non-exhausted clients without replacement
and take up to ``local_batch_size`` records from each (-1 = the
client's whole remaining data); epoch ends when every client is
exhausted."""

from __future__ import annotations

import numpy as np

__all__ = ["FedSampler"]


class _Lookahead:
    """Iterator that buffers ONE item ahead so the round spec the
    consumer will receive next is peekable — the client-store prefetch
    thread (runtime/fed_model.py) needs round N+1's participant ids
    while round N computes. Each underlying draw happens one ``next``
    earlier than it would unbuffered, but the draw ORDER (and hence
    the sampler RNG stream a checkpoint captures) is unchanged."""

    def __init__(self, it):
        self._it = it
        self._buf = None
        self._has = False
        self._advance()

    def _advance(self):
        try:
            self._buf = next(self._it)
            self._has = True
        except StopIteration:
            self._buf = None
            self._has = False

    def peek(self):
        return self._buf if self._has else None

    def __iter__(self):
        return self

    def __next__(self):
        if not self._has:
            raise StopIteration
        out = self._buf
        self._advance()
        return out


class FedSampler:
    def __init__(self, dataset, num_workers, local_batch_size,
                 shuffle_clients=True, seed=None):
        self.dataset = dataset
        self.num_workers = num_workers
        self.local_batch_size = local_batch_size
        self.shuffle_clients = shuffle_clients
        self.rng = (np.random if seed is None
                    else np.random.RandomState(seed))
        self._lookahead = None
        # live epoch arrays (set by __iter__) — what export_state
        # captures for mid-epoch checkpointing
        self._permuted = None
        self._cur = None
        self._resume_state = None

    def peek_next_client_ids(self):
        """Participant ids of the round the active iterator will yield
        NEXT, or None (no active iterator / epoch exhausted)."""
        la = self._lookahead
        spec = la.peek() if la is not None else None
        if spec is None:
            return None
        return [cid for cid, _ in spec]

    def export_state(self):
        """Mid-epoch snapshot for the round-cadence autosaver
        (runtime/checkpoint.py). Captures the live epoch arrays, the
        RNG (AFTER the lookahead's one-ahead draw) and the buffered
        round spec, so a resumed iterator replays the remaining
        rounds bit-exactly: the buffered spec is re-yielded first,
        then the generator continues from the restored cursor/RNG.
        None when no epoch iterator is active (epoch boundary — the
        plain end-of-epoch RNG capture suffices there)."""
        if self._lookahead is None or self._permuted is None:
            return None
        spec = self._lookahead.peek()
        state = {
            "permuted": np.asarray(self._permuted).copy(),
            "cur": np.asarray(self._cur).copy(),
        }
        if isinstance(self.rng, np.random.RandomState):
            state["rng_state"] = self.rng.get_state()
        if spec is not None:
            state["spec_workers"] = np.asarray(
                [cid for cid, _ in spec], np.int64)
            state["spec_sizes"] = np.asarray(
                [len(ix) for _, ix in spec], np.int64)
            state["spec_idx"] = (np.concatenate(
                [np.asarray(ix, np.int64) for _, ix in spec])
                if spec else np.zeros((0,), np.int64))
        return state

    def import_state(self, state):
        """Arm the NEXT ``__iter__`` to continue the exported epoch
        instead of starting a fresh one (one-shot)."""
        self._resume_state = state

    def _consume_resume(self):
        state = self._resume_state
        self._resume_state = None
        if isinstance(self.rng, np.random.RandomState) \
                and state.get("rng_state") is not None:
            self.rng.set_state(state["rng_state"])
        permuted = np.asarray(state["permuted"])
        cur = np.asarray(state["cur"]).copy()
        pending = None
        if state.get("spec_workers") is not None \
                and len(state["spec_workers"]):
            workers = [int(w) for w in state["spec_workers"]]
            sizes = [int(s) for s in state["spec_sizes"]]
            idx = np.asarray(state["spec_idx"])
            lists, off = [], 0
            for s in sizes:
                lists.append(idx[off:off + s])
                off += s
            pending = (workers, sizes, list(zip(workers, lists)))
        return permuted, cur, pending

    def __iter__(self):
        data_per_client = np.asarray(self.dataset.data_per_client)
        cumsum = np.hstack([[0], np.cumsum(data_per_client)])
        pending = None
        if self._resume_state is not None:
            permuted, cur, pending = self._consume_resume()
        else:
            permuted = np.hstack([
                s + self.rng.permutation(u)
                for s, u in zip(cumsum, data_per_client)])
            cur = np.zeros(self.dataset.num_clients, dtype=int)
        self._permuted, self._cur = permuted, cur

        def sampler():
            if pending is not None:
                p_workers, p_sizes, p_spec = pending
                yield p_spec
                cur[p_workers] += p_sizes
            while True:
                alive = np.where(cur < data_per_client)[0]
                if len(alive) == 0:
                    break
                n = min(self.num_workers, len(alive))
                workers = self.rng.choice(alive, n, replace=False)
                remaining = data_per_client[workers] - cur[workers]
                if self.local_batch_size == -1:
                    sizes = remaining
                else:
                    sizes = np.clip(remaining, 0, self.local_batch_size)
                # per-client index lists (the engine wants them grouped,
                # unlike the reference's flat concatenation which the
                # server re-groups, fed_aggregator.py:219-225)
                idx_lists = [
                    permuted[s:s + sizes[i]]
                    for i, s in enumerate(cumsum[workers] + cur[workers])]
                yield list(zip(workers.tolist(), idx_lists))
                cur[workers] += sizes

        self._lookahead = _Lookahead(sampler())
        return self._lookahead

    def __len__(self):
        return len(self.dataset)
