"""Federated EMNIST (LEAF FEMNIST): natural partition = one writer per
client (3500 writers; reference fed_aggregator.py:69).

Counterpart of reference data_utils/fed_emnist.py:36-138. The LEAF
preprocessing pipeline (the reference's ``leaf`` git submodule) emits
json shards with keys ``users`` / ``user_data`` where
``user_data[u] = {"x": [flat 784-pixel images], "y": [labels]}``;
``prepare_datasets`` parses those once and repacks them as **packed
``.npy`` memmaps** — concatenated ``(N, 28, 28)`` float32 images +
targets + client offsets. A handful of mmap-able files instead of 3500
tiny ``.pt`` files solves the same fd-limit problem the reference
works around at runtime (fed_emnist.py:42-59), and items slice out of
the memmap without loading the ~GB image array into RAM.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List

import numpy as np

from commefficient_tpu.data.fed_dataset import FedDataset

__all__ = ["FedEMNIST", "read_leaf_dir"]


def read_leaf_dir(data_dir: str) -> Dict[str, dict]:
    """Parse every ``*.json`` LEAF shard in ``data_dir`` into one
    ``{user: {"x": [...], "y": [...]}}`` dict (reference
    fed_emnist.py:11-34)."""
    data: Dict[str, dict] = {}
    for f in sorted(os.listdir(data_dir)):
        if not f.endswith(".json"):
            continue
        with open(os.path.join(data_dir, f), "rb") as inf:
            cdata = json.loads(inf.read())
        data.update(cdata["user_data"])
    return data


def _pack(user_data: Dict[str, dict]):
    images: List[np.ndarray] = []
    targets: List[np.ndarray] = []
    offsets = [0]
    for u, cdata in user_data.items():
        x = np.asarray(cdata["x"], np.float32).reshape(-1, 28, 28)
        y = np.asarray(cdata["y"], np.int32)
        images.append(x)
        targets.append(y)
        offsets.append(offsets[-1] + len(y))
    return (np.concatenate(images), np.concatenate(targets),
            np.asarray(offsets, np.int64))


class FedEMNIST(FedDataset):
    num_classes = 62

    def prepare_datasets(self, download=False):
        if download:
            raise RuntimeError(
                "FEMNIST comes from LEAF preprocessing; no download "
                "(reference fed_emnist.py:40)")
        if os.path.exists(self.stats_fn()):
            raise RuntimeError("won't overwrite existing stats file")
        train_dir = os.path.join(self.dataset_dir, "train")
        test_dir = os.path.join(self.dataset_dir, "test")

        x, y, offsets = _pack(read_leaf_dir(train_dir))
        np.save(self._fn("train_x"), x)
        np.save(self._fn("train_y"), y)
        np.save(self._fn("train_offsets"), offsets)
        images_per_client = np.diff(offsets).tolist()

        tx, ty, _ = _pack(read_leaf_dir(test_dir))
        np.save(self._fn("test_x"), tx)
        np.save(self._fn("test_y"), ty)

        with open(self.stats_fn(), "w") as f:
            json.dump({"images_per_client": images_per_client,
                       "num_val_images": int(len(ty))}, f)

    def _load_meta(self, train):
        super()._load_meta(train)
        if train:
            # .npy memmaps: zero-copy per-item slices (npz would load
            # the whole array — numpy ignores mmap_mode for archives)
            self._x = np.load(self._fn("train_x"), mmap_mode="r")
            self._y = np.load(self._fn("train_y"), mmap_mode="r")
            self._offsets = np.load(self._fn("train_offsets"))
        else:
            self._test_x = np.load(self._fn("test_x"), mmap_mode="r")
            self._test_y = np.load(self._fn("test_y"), mmap_mode="r")

    def _get_train_item(self, client_id, idx_within_client):
        i = int(self._offsets[client_id]) + int(idx_within_client)
        return self._x[i][..., None], int(self._y[i])

    def _get_val_item(self, idx):
        return self._test_x[idx][..., None], int(self._test_y[idx])

    def _fn(self, name):
        return os.path.join(self.dataset_dir, f"{name}_packed.npy")
