"""Native (C++) federated data-plane bindings.

Builds ``fed_dataplane.cpp`` on first use with the in-image g++ (no
pybind11 — plain C ABI via ctypes; ctypes releases the GIL around
calls, so ring pops block without stalling Python). Falls back cleanly
when no toolchain is available: callers must check :func:`available`.

Counterpart of the reference's native data plumbing (multiprocessing
queues + torchvision C++ transform kernels, SURVEY.md §2.9).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "fed_dataplane.cpp")
_BUILD_DIR = os.path.join(os.path.dirname(__file__), "_build")
_LOCK = threading.Lock()
_lib_handle = None
_build_failed = False


def _compile() -> Optional[str]:
    so = os.path.join(_BUILD_DIR, "libfed_dataplane.so")
    try:
        if (os.path.exists(so)
                and os.path.getmtime(so) >= os.path.getmtime(_SRC)):
            return so
        os.makedirs(_BUILD_DIR, exist_ok=True)
        subprocess.run(
            ["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
             "-pthread", _SRC, "-o", so + ".tmp"],
            check=True, capture_output=True)
        os.replace(so + ".tmp", so)
        return so
    except (OSError, subprocess.CalledProcessError):
        return None


def _lib():
    global _lib_handle, _build_failed
    with _LOCK:
        if _lib_handle is not None or _build_failed:
            return _lib_handle
        so = _compile()
        if so is None:
            _build_failed = True
            return None
        lib = ctypes.CDLL(so)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        f32p = ctypes.POINTER(ctypes.c_float)
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        ci = ctypes.c_int
        i64 = ctypes.c_int64
        lib.cet_assemble_round.argtypes = [
            u8p, f32p, i32p, i64, ci, ci, ci, ci, ci, ci, ci,
            f32p, f32p, i64p, ctypes.c_uint64, f32p, i32p, f32p]
        lib.cet_assemble_round.restype = ctypes.c_int
        lib.cet_ring_create.argtypes = [
            u8p, f32p, i32p, i64, ci, ci, ci, ci, ci, ci, ci,
            f32p, f32p, ci, ci]
        lib.cet_ring_create.restype = ctypes.c_void_p
        lib.cet_ring_submit.argtypes = [ctypes.c_void_p, i64p,
                                        ctypes.c_uint64]
        lib.cet_ring_submit.restype = None
        lib.cet_ring_pop.argtypes = [ctypes.c_void_p, f32p, i32p, f32p]
        lib.cet_ring_pop.restype = ctypes.c_int64
        lib.cet_ring_oob.argtypes = [ctypes.c_void_p]
        lib.cet_ring_oob.restype = ctypes.c_longlong
        lib.cet_ring_destroy.argtypes = [ctypes.c_void_p]
        lib.cet_ring_destroy.restype = None
        _lib_handle = lib
        return lib


def available() -> bool:
    return _lib() is not None


def _ptr(arr, ctype):
    if arr is None:
        return ctypes.cast(None, ctypes.POINTER(ctype))
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


class NativeDataplane:
    """Round assembly over a dense in-memory image store.

    ``images``: (N, H, W, C) uint8 (raw, scaled by 1/255 natively) or
    float32 in [0, 1]. ``targets``: (N,) int32. Augmentation =
    reflect-pad random crop (``crop_pad``) + horizontal flip
    (``do_flip``) + per-channel normalize — the CIFAR/FEMNIST stacks.
    """

    def __init__(self, images: np.ndarray, targets: np.ndarray,
                 slots: int, B: int, mean, std,
                 crop_pad: int = 0, do_flip: bool = False):
        lib = _lib()
        if lib is None:
            raise RuntimeError("native dataplane unavailable")
        if images.ndim != 4:
            raise RuntimeError(
                f"need (N, H, W, C) images, got {images.shape}")
        self._lib = lib
        # keep alive: the C side borrows these buffers
        self.images = np.ascontiguousarray(images)
        self.targets = np.ascontiguousarray(targets, dtype=np.int32)
        self.slots, self.B = slots, B
        _, self.H, self.W, self.C = self.images.shape
        assert self.C <= 8
        self.mean = np.ascontiguousarray(
            np.broadcast_to(np.asarray(mean, np.float32), (self.C,)))
        self.std = np.ascontiguousarray(
            np.broadcast_to(np.asarray(std, np.float32), (self.C,)))
        self.crop_pad, self.do_flip = crop_pad, int(do_flip)
        if self.images.dtype == np.uint8:
            self._u8, self._f32 = self.images, None
        elif self.images.dtype == np.float32:
            self._u8, self._f32 = None, self.images
        else:
            raise RuntimeError(
                f"unsupported image dtype {self.images.dtype} "
                "(uint8 or float32)")

    def _common_args(self):
        return (_ptr(self._u8, ctypes.c_uint8),
                _ptr(self._f32, ctypes.c_float),
                _ptr(self.targets, ctypes.c_int32),
                ctypes.c_int64(self.images.shape[0]),
                self.H, self.W, self.C, self.slots, self.B,
                self.crop_pad, self.do_flip,
                _ptr(self.mean, ctypes.c_float),
                _ptr(self.std, ctypes.c_float))

    def _alloc_out(self):
        x = np.empty((self.slots, self.B, self.H, self.W, self.C),
                     np.float32)
        y = np.empty((self.slots, self.B), np.int32)
        m = np.empty((self.slots, self.B), np.float32)
        return x, y, m

    def assemble(self, indices: np.ndarray, seed: int):
        """indices: (slots, B) int64 storage rows, -1 = padding."""
        idx = np.ascontiguousarray(indices, dtype=np.int64)
        assert idx.shape == (self.slots, self.B), idx.shape
        x, y, m = self._alloc_out()
        oob = self._lib.cet_assemble_round(
            *self._common_args(), _ptr(idx, ctypes.c_int64),
            ctypes.c_uint64(seed & (2**64 - 1)),
            _ptr(x, ctypes.c_float), _ptr(y, ctypes.c_int32),
            _ptr(m, ctypes.c_float))
        if oob:
            raise IndexError(
                f"{oob} indices out of range for {self.images.shape[0]}"
                " stored rows")
        return x, y, m


class Prefetcher:
    """Bounded ring of pre-assembled rounds, filled by C++ worker
    threads; pops arrive strictly in submission order (deterministic
    regardless of thread scheduling)."""

    def __init__(self, plane: NativeDataplane, depth: int = 4,
                 n_threads: int = 2):
        self.plane = plane
        self._handle = plane._lib.cet_ring_create(
            *plane._common_args(), depth, n_threads)
        assert self._handle

    def submit(self, indices: np.ndarray, seed: int):
        idx = np.ascontiguousarray(indices, dtype=np.int64)
        assert idx.shape == (self.plane.slots, self.plane.B)
        self.plane._lib.cet_ring_submit(
            self._handle, _ptr(idx, ctypes.c_int64),
            ctypes.c_uint64(seed & (2**64 - 1)))

    def pop(self):
        x, y, m = self.plane._alloc_out()
        seq = self.plane._lib.cet_ring_pop(
            self._handle, _ptr(x, ctypes.c_float),
            _ptr(y, ctypes.c_int32), _ptr(m, ctypes.c_float))
        assert seq >= 0, "ring stopped"
        oob = self.plane._lib.cet_ring_oob(self._handle)
        if oob:
            raise IndexError(
                f"{oob} out-of-range indices submitted to the ring")
        return x, y, m

    def close(self):
        if self._handle:
            self.plane._lib.cet_ring_destroy(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):  # best-effort
        try:
            self.close()
        except Exception:
            pass


def native_transform_spec(transform) -> Optional[dict]:
    """Map a data/transforms.py Compose onto the native augmentation
    pipeline, which is exactly ``ToFloat -> [RandomCrop(reflect)] ->
    [RandomHorizontalFlip] -> Normalize`` in that order (the CIFAR /
    FEMNIST-val stacks). Anything else — different order, missing
    ToFloat (the native path always scales uint8 by 1/255), extra
    ops — returns None and the caller falls back to the Python
    loader, so the two paths can never silently diverge."""
    from commefficient_tpu.data import transforms as T

    if not isinstance(transform, T.Compose):
        return None
    ts = list(transform.transforms)
    if not ts or not isinstance(ts.pop(0), T.ToFloat):
        return None
    crop_pad, do_flip, crop_size = 0, False, None
    if ts and isinstance(ts[0], T.RandomCrop):
        t = ts.pop(0)
        if t.fill is not None:
            return None
        crop_pad, crop_size = t.padding, t.size
    if ts and isinstance(ts[0], T.RandomHorizontalFlip):
        ts.pop(0)
        do_flip = True
    if len(ts) != 1 or not isinstance(ts[0], T.Normalize):
        return None
    norm = ts[0]
    return {"crop_pad": crop_pad, "do_flip": do_flip,
            "crop_size": crop_size,  # must equal image H/W (checked
            "mean": norm.mean, "std": norm.std}  # by the loader)
