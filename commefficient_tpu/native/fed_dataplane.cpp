// Native federated data-plane: round-batch assembly + threaded
// prefetch ring.
//
// The reference's host data path is worker processes fed by
// multiprocessing queues (fed_aggregator.py:137-158, SURVEY.md §2.9);
// its per-sample transform work rides torchvision's C++ kernels. This
// is the TPU build's equivalent native component: the per-round
// gather/augment/pad of (W, B, H, W, C) client batches runs here in
// C++ (GIL-free, off the Python hot loop), with a bounded ring of
// pre-assembled rounds so host data prep overlaps device steps.
//
// Augmentations implemented (the CIFAR/FEMNIST stacks,
// data/transforms.py): uint8->float scaling, reflect-pad random crop,
// horizontal flip, per-channel normalize. Randomness is splitmix64 on
// (seed, slot, sample) — deterministic regardless of thread schedule.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this image).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <vector>

namespace {

struct DataplaneCfg {
  const uint8_t* img_u8;   // one of img_u8 / img_f32 non-null
  const float* img_f32;    // values already in [0,1]
  const int32_t* targets;
  int64_t n_rows;          // dataset size (bounds-checked gathers)
  int H, W, C;             // per-image shape (HWC)
  int slots, B;            // round geometry: slots x B samples
  int crop_pad;            // 0 = no random crop
  int do_flip;             // 0/1 horizontal flip
  float mean[8], stdev[8]; // per-channel (C <= 8)
};

inline uint64_t splitmix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ULL;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ULL;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBULL;
  return x ^ (x >> 31);
}

inline int reflect_idx(int v, int n) {
  // numpy "reflect" (no edge duplication)
  if (v < 0) v = -v;
  if (v >= n) v = 2 * n - 2 - v;
  return v;
}

inline float load_px(const DataplaneCfg& c, int64_t row, int y, int x,
                     int ch) {
  int64_t off =
      ((row * c.H + y) * (int64_t)c.W + x) * c.C + ch;
  return c.img_u8 ? (float)c.img_u8[off] * (1.0f / 255.0f)
                  : c.img_f32[off];
}

// Assemble one (slots, B, H, W, C) round into out_x/out_y/out_mask.
// indices: int64[slots*B], -1 marks padding. Returns the count of
// out-of-range (row >= n_rows) indices, which are emitted as padding
// — callers treat nonzero as an error (the Python loader would have
// raised IndexError; silence here would mean garbage heap reads).
int fill_round(const DataplaneCfg& c, const int64_t* indices,
               uint64_t seed, float* out_x, int32_t* out_y,
               float* out_m) {
  const int H = c.H, W = c.W, C = c.C, p = c.crop_pad;
  const int64_t img_elems = (int64_t)H * W * C;
  int oob = 0;
  for (int s = 0; s < c.slots; ++s) {
    for (int b = 0; b < c.B; ++b) {
      const int64_t row = indices[(int64_t)s * c.B + b];
      float* dst = out_x + ((int64_t)s * c.B + b) * img_elems;
      int32_t* ydst = out_y + (int64_t)s * c.B + b;
      float* mdst = out_m + (int64_t)s * c.B + b;
      if (row < 0 || row >= c.n_rows) {
        if (row >= c.n_rows) ++oob;
        std::memset(dst, 0, sizeof(float) * img_elems);
        *ydst = 0;
        *mdst = 0.0f;
        continue;
      }
      *ydst = c.targets[row];
      *mdst = 1.0f;
      uint64_t r =
          splitmix64(seed ^ splitmix64(((uint64_t)s << 32) | (uint64_t)b));
      int ci = 0, cj = 0, flip = 0;
      if (p > 0) {
        ci = (int)(r % (uint64_t)(2 * p + 1));
        r = splitmix64(r);
        cj = (int)(r % (uint64_t)(2 * p + 1));
        r = splitmix64(r);
      }
      if (c.do_flip) flip = (int)(r & 1u);
      for (int y = 0; y < H; ++y) {
        const int sy = p > 0 ? reflect_idx(y + ci - p, H) : y;
        for (int x = 0; x < W; ++x) {
          int xx = flip ? (W - 1 - x) : x;
          const int sx = p > 0 ? reflect_idx(xx + cj - p, W) : xx;
          float* px = dst + ((int64_t)y * W + x) * C;
          for (int ch = 0; ch < C; ++ch) {
            px[ch] = (load_px(c, row, sy, sx, ch) - c.mean[ch]) /
                     c.stdev[ch];
          }
        }
      }
    }
  }
  return oob;
}

struct Spec {
  uint64_t seq;
  uint64_t seed;
  std::vector<int64_t> indices;
};

struct Ring {
  DataplaneCfg cfg;
  int depth;
  int64_t round_elems;  // floats in x per round
  int64_t round_n;      // slots*B
  std::vector<float> x;
  std::vector<int32_t> y;
  std::vector<float> m;
  std::vector<uint64_t> slot_seq;
  std::vector<int> state;  // 0 free, 1 filling, 2 ready
  std::deque<Spec> specs;
  uint64_t submit_seq = 0;
  uint64_t pop_seq = 0;
  bool stop = false;
  std::mutex mu;
  std::condition_variable cv_work, cv_ready, cv_space;
  std::vector<std::thread> workers;
  std::atomic<long long> oob{0};
};

void worker_loop(Ring* rg) {
  for (;;) {
    Spec spec;
    int slot;
    {
      std::unique_lock<std::mutex> lk(rg->mu);
      rg->cv_work.wait(lk, [&] {
        if (rg->stop) return true;
        if (rg->specs.empty()) return false;
        int sl = (int)(rg->specs.front().seq % (uint64_t)rg->depth);
        return rg->state[sl] == 0;
      });
      if (rg->stop) return;
      spec = std::move(rg->specs.front());
      rg->specs.pop_front();
      slot = (int)(spec.seq % (uint64_t)rg->depth);
      rg->state[slot] = 1;
      rg->slot_seq[slot] = spec.seq;
    }
    rg->cv_space.notify_all();
    int oob = fill_round(
        rg->cfg, spec.indices.data(), spec.seed,
        rg->x.data() + (int64_t)slot * rg->round_elems,
        rg->y.data() + (int64_t)slot * rg->round_n,
        rg->m.data() + (int64_t)slot * rg->round_n);
    if (oob) rg->oob += oob;
    {
      std::lock_guard<std::mutex> lk(rg->mu);
      rg->state[slot] = 2;
    }
    rg->cv_ready.notify_all();
  }
}

}  // namespace

extern "C" {

// ---- one-shot API ----------------------------------------------------

// Returns the number of out-of-range indices (0 = success).
int cet_assemble_round(const uint8_t* img_u8, const float* img_f32,
                       const int32_t* targets, int64_t n_rows,
                       int H, int W, int C,
                       int slots, int B, int crop_pad, int do_flip,
                       const float* mean, const float* stdev,
                       const int64_t* indices, uint64_t seed,
                       float* out_x, int32_t* out_y, float* out_m) {
  DataplaneCfg c{};
  c.img_u8 = img_u8;
  c.img_f32 = img_f32;
  c.targets = targets;
  c.n_rows = n_rows;
  c.H = H; c.W = W; c.C = C;
  c.slots = slots; c.B = B;
  c.crop_pad = crop_pad; c.do_flip = do_flip;
  for (int i = 0; i < C && i < 8; ++i) {
    c.mean[i] = mean[i];
    c.stdev[i] = stdev[i];
  }
  return fill_round(c, indices, seed, out_x, out_y, out_m);
}

// ---- prefetch ring ---------------------------------------------------

void* cet_ring_create(const uint8_t* img_u8, const float* img_f32,
                      const int32_t* targets, int64_t n_rows,
                      int H, int W, int C,
                      int slots, int B, int crop_pad, int do_flip,
                      const float* mean, const float* stdev, int depth,
                      int n_threads) {
  Ring* rg = new Ring();
  rg->cfg.img_u8 = img_u8;
  rg->cfg.img_f32 = img_f32;
  rg->cfg.targets = targets;
  rg->cfg.n_rows = n_rows;
  rg->cfg.H = H; rg->cfg.W = W; rg->cfg.C = C;
  rg->cfg.slots = slots; rg->cfg.B = B;
  rg->cfg.crop_pad = crop_pad; rg->cfg.do_flip = do_flip;
  for (int i = 0; i < C && i < 8; ++i) {
    rg->cfg.mean[i] = mean[i];
    rg->cfg.stdev[i] = stdev[i];
  }
  rg->depth = depth;
  rg->round_n = (int64_t)slots * B;
  rg->round_elems = rg->round_n * H * W * C;
  rg->x.resize((size_t)depth * rg->round_elems);
  rg->y.resize((size_t)depth * rg->round_n);
  rg->m.resize((size_t)depth * rg->round_n);
  rg->slot_seq.assign(depth, 0);
  rg->state.assign(depth, 0);
  if (n_threads < 1) n_threads = 1;
  for (int i = 0; i < n_threads; ++i)
    rg->workers.emplace_back(worker_loop, rg);
  return rg;
}

// Blocks while the spec backlog is >= 2*depth (bounded memory).
void cet_ring_submit(void* h, const int64_t* indices, uint64_t seed) {
  Ring* rg = (Ring*)h;
  Spec spec;
  spec.seed = seed;
  spec.indices.assign(indices, indices + rg->round_n);
  {
    std::unique_lock<std::mutex> lk(rg->mu);
    rg->cv_space.wait(lk, [&] {
      return rg->stop ||
             rg->specs.size() < (size_t)(2 * rg->depth);
    });
    if (rg->stop) return;
    spec.seq = rg->submit_seq++;
    rg->specs.push_back(std::move(spec));
  }
  rg->cv_work.notify_all();
}

// Pops rounds strictly in submission order. Returns the seq popped,
// or -1 if the ring was stopped.
int64_t cet_ring_pop(void* h, float* out_x, int32_t* out_y,
                     float* out_m) {
  Ring* rg = (Ring*)h;
  int slot;
  uint64_t seq;
  {
    std::unique_lock<std::mutex> lk(rg->mu);
    seq = rg->pop_seq;
    slot = (int)(seq % (uint64_t)rg->depth);
    rg->cv_ready.wait(lk, [&] {
      return rg->stop ||
             (rg->state[slot] == 2 && rg->slot_seq[slot] == seq);
    });
    if (rg->stop) return -1;
  }
  std::memcpy(out_x, rg->x.data() + (int64_t)slot * rg->round_elems,
              sizeof(float) * rg->round_elems);
  std::memcpy(out_y, rg->y.data() + (int64_t)slot * rg->round_n,
              sizeof(int32_t) * rg->round_n);
  std::memcpy(out_m, rg->m.data() + (int64_t)slot * rg->round_n,
              sizeof(float) * rg->round_n);
  {
    std::lock_guard<std::mutex> lk(rg->mu);
    rg->state[slot] = 0;
    rg->pop_seq++;
  }
  rg->cv_work.notify_all();
  return (int64_t)seq;
}

// Cumulative out-of-range index count across all assembled rounds.
long long cet_ring_oob(void* h) {
  return ((Ring*)h)->oob.load();
}

void cet_ring_destroy(void* h) {
  Ring* rg = (Ring*)h;
  {
    std::lock_guard<std::mutex> lk(rg->mu);
    rg->stop = true;
  }
  rg->cv_work.notify_all();
  rg->cv_ready.notify_all();
  rg->cv_space.notify_all();
  for (auto& t : rg->workers) t.join();
  delete rg;
}

}  // extern "C"
