"""Cross-cutting utilities: LR schedules, loggers, timers.

Functional parity with reference utils.py:14-99 (Logger, PiecewiseLinear,
Exp, TableLogger, TSVLogger, Timer, make_logdir).
"""

from __future__ import annotations

import os
import signal
import threading
from collections import namedtuple
from contextlib import contextmanager
from datetime import datetime

import numpy as np

from commefficient_tpu.telemetry import clock


class GracefulShutdown(Exception):
    """Raised in the main thread when a termination signal arrives
    (``sigterm_raises``). Unwinds the round loop so the trainer can run
    crash-safety cleanup (``FedModel.interrupted`` + ``finalize``)
    instead of dying mid-write; the last round-cadence autosave plus
    the ledger's torn-tail recovery make the run resumable."""

    def __init__(self, signum: int):
        super().__init__(f"received signal {signum}")
        self.signum = signum


@contextmanager
def sigterm_raises(signums=(signal.SIGTERM,)):
    """Install handlers that raise ``GracefulShutdown``; priors are
    restored on exit. Degrades to a no-op outside the main thread
    (where ``signal.signal`` is illegal) so tests can call trainer
    main()s from worker threads."""
    if threading.current_thread() is not threading.main_thread():
        yield
        return

    def _handler(signum, frame):
        raise GracefulShutdown(signum)

    prev = {}
    for s in signums:
        prev[s] = signal.signal(s, _handler)
    try:
        yield
    finally:
        for s, h in prev.items():
            signal.signal(s, h)


class Logger:
    """print-based logger (reference utils.py:14-24)."""

    def debug(self, msg, args=None):
        print(msg.format(args))

    info = warn = error = critical = debug


class PiecewiseLinear(namedtuple("PiecewiseLinear", ("knots", "vals"))):
    """Piecewise-linear schedule; e.g. the triangular CIFAR LR schedule
    PiecewiseLinear([0, pivot_epoch, num_epochs], [0, lr_scale, 0])
    (reference utils.py:26-28, cv_train.py:394-397)."""

    def __call__(self, t):
        return float(np.interp([t], self.knots, self.vals)[0])


class Exp(namedtuple("Exp", ("warmup_epochs", "amplitude", "decay_len"))):
    """Linear warmup then exponential decay (reference utils.py:30-35)."""

    def __call__(self, t):
        if t < self.warmup_epochs:
            return float(np.interp([t], [0, self.warmup_epochs],
                                   [0, self.amplitude])[0])
        return float(self.amplitude
                     * 10 ** (-(t - self.warmup_epochs) / self.decay_len))


def make_logdir(args) -> str:
    """runs/<time>_<workers>/<clients>_<mode>... (reference utils.py:51-64)."""
    rows, cols, k, mode = args.num_rows, args.num_cols, args.k, args.mode
    sketch_str = f"{mode}: {rows} x {cols}" if mode == "sketch" else f"{mode}"
    k_str = f"k: {k}" if mode in ["sketch", "true_topk", "local_topk"] else ""
    clients_str = f"{args.num_workers}/{args.num_clients}"
    current_time = datetime.now().strftime("%b%d_%H-%M-%S")
    return os.path.join(
        "runs", current_time + "_" + clients_str + "_" + sketch_str + "_" + k_str)


class TableLogger:
    """Fixed-width stdout table (reference utils.py:66-74)."""

    def append(self, output):
        if not hasattr(self, "keys"):
            self.keys = output.keys()
            print(*("{:>12s}".format(k) for k in self.keys))
        filtered = [output[k] for k in self.keys]
        print(*("{:12.4f}".format(v)
                if isinstance(v, (float, np.floating)) else "{:12}".format(v)
                for v in filtered))


class TSVLogger:
    """epoch,hours,top1Accuracy TSV accumulator (reference utils.py:76-85)."""

    def __init__(self):
        self.log = ["epoch,hours,top1Accuracy"]

    def append(self, output):
        epoch = output["epoch"]
        hours = output["total_time"] / 3600
        acc = output["test_acc"] * 100
        self.log.append("{},{:.8f},{:.2f}".format(epoch, hours, acc))

    def __str__(self):
        return "\n".join(self.log)


union = lambda *dicts: {k: v for d in dicts for (k, v) in d.items()}  # noqa: E731


class Timer:
    """Wall-clock phase timer (reference utils.py:89-99)."""

    def __init__(self):
        self.times = [clock.wall()]
        self.total_time = 0.0

    def __call__(self, include_in_total=True):
        self.times.append(clock.wall())
        delta_t = self.times[-1] - self.times[-2]
        if include_in_total:
            self.total_time += delta_t
        return delta_t


def steps_per_epoch(local_batch_size: int, dataset, num_workers: int) -> int:
    """Rounds per epoch (reference utils.py:315-321): when the local
    batch is the client's whole dataset, an epoch is num_clients /
    num_workers rounds; otherwise ceil(len(ds) / (lbs * num_workers))."""
    if local_batch_size == -1:
        return int(dataset.num_clients // num_workers)
    batch_size = local_batch_size * num_workers
    return int(np.ceil(len(dataset) / batch_size))
