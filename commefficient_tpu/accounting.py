"""Dtype-aware wire-byte accounting.

One home for every byte-width decision the ledger, cost model and
auditor make. Before the quantized wire path every accounting site
hardcoded ``* 4`` (f32); now the uplink table, its per-row scales and
the downlink payload each carry their own dtype, so the arithmetic
lives here and the callers say *what* crossed the wire, not how wide
a float is. ``analysis/lint.py``'s ``byte-literal`` rule keeps inline
byte-width literals out of the accounting code paths.

Wire dtypes are named by the ``--sketch_dtype`` flag surface
(``f32``/``bf16``/``int8``/``fp8``), not by numpy names, because the
name keys perf baselines and audit programs — ``fp8`` pins e4m3fn.
"""

from __future__ import annotations

from typing import Iterable, Union

import numpy as np

# wire name -> (jnp dtype name, bytes per element, carries per-row scales)
# fp8 is e4m3fn: the wider-mantissa variant — sketch tables want
# resolution, the shared row scale absorbs range.
WIRE_DTYPES = {
    "f32": ("float32", 4, False),
    "bf16": ("bfloat16", 2, False),
    "int8": ("int8", 1, True),
    "fp8": ("float8_e4m3fn", 1, True),
}

# the per-row dequantization scales ride the wire as f32
SCALE_WIRE_BYTES = 4

# numpy has no bfloat16/float8; resolve those by name before asking
# np.dtype for the rest
_NAMED_WIDTHS = {
    "bfloat16": 2,
    "bf16": 2,
    "float8_e4m3fn": 1,
    "float8_e5m2": 1,
    "float8_e4m3": 1,
    "fp8": 1,
    "f32": 4,
    "int8": 1,
}


def dtype_bytes(dtype: Union[str, np.dtype, type]) -> int:
    """Bytes per element of ``dtype``.

    Accepts wire names (``f32``/``bf16``/``int8``/``fp8``), jnp dtype
    names (``bfloat16``, ``float8_e4m3fn``), numpy dtypes and scalar
    types.
    """
    name = getattr(dtype, "name", None) or (
        dtype if isinstance(dtype, str) else None)
    if name is not None and name in _NAMED_WIDTHS:
        return _NAMED_WIDTHS[name]
    if name is not None and name in WIRE_DTYPES:
        return WIRE_DTYPES[name][1]
    return int(np.dtype(dtype).itemsize)


def bytes_of(shape: Union[int, Iterable[int]], dtype) -> float:
    """Wire bytes of an array of ``shape`` and ``dtype``.

    The single source of truth for ``elements x width`` accounting
    math; returns float because the ledger's byte counters are f64
    accumulators.
    """
    if isinstance(shape, (int, np.integer)):
        n = int(shape)
    else:
        n = 1
        for s in shape:
            n *= int(s)
    return float(n) * float(dtype_bytes(dtype))


def wire_dtype_name(wire: str) -> str:
    """jnp dtype name for a wire name (validates the wire name)."""
    return WIRE_DTYPES[wire][0]


def wire_has_scales(wire: str) -> bool:
    """True when the wire format carries per-row f32 scales
    (int8/fp8); bf16 and f32 ride scale-free."""
    return WIRE_DTYPES[wire][2]


def sketch_wire_bytes(num_rows: int, num_cols: int, wire: str) -> float:
    """Uplink bytes for one quantized sketch table: the table at wire
    width plus, for the scaled dtypes, one f32 row-scale per row (the
    pmax'd rowmax that rides with the table)."""
    body = bytes_of((num_rows, num_cols), wire_dtype_name(wire))
    if wire_has_scales(wire):
        body += bytes_of((num_rows,), "f32")
    return body


def delta_downlink_bytes(changed: float, repeated: float,
                         prev_support: float, wire: str,
                         have_prev: bool = True) -> float:
    """Downlink bytes for one client under ``--downlink_encoding
    delta``: every changed coordinate ships its value at wire width;
    indices ship as int32 only for coordinates NOT repeated from the
    round the client last saw; repeats are named by a bitmap over the
    previous round's support (1 bit per previous index, byte-padded).

    ``have_prev`` is False when the client missed the previous
    broadcast (its cached support is stale) — then nothing can be
    delta-coded and every changed coordinate ships (idx, val).
    """
    if not have_prev:
        repeated = 0.0
        prev_support = 0.0
    vals = float(changed) * dtype_bytes(wire)
    idxs = (float(changed) - float(repeated)) * dtype_bytes(np.int32)
    bitmap = float(np.ceil(prev_support / 8.0)) if prev_support else 0.0
    return vals + idxs + bitmap
