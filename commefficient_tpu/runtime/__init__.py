from commefficient_tpu.runtime.fed_model import (  # noqa: F401
    FedModel,
    drain_rounds,
    FedOptimizer,
    LambdaLR,
)
