from commefficient_tpu.runtime.fed_model import (  # noqa: F401
    FedModel,
    FedOptimizer,
    LambdaLR,
)
