"""High-level federated runtime: FedModel + FedOptimizer.

API-parity layer over the SPMD round engine, mirroring the reference's
FedModel/FedOptimizer protocol (fed_aggregator.py:54-463) so the
training scripts keep the same shape:

    model = FedModel(module, params, compute_loss, args)
    opt   = FedOptimizer(optimizer_params, args)
    scheduler = LambdaLR(opt, lambda_fn)
    ...
    scheduler.step()
    metrics = model(batch)     # one federated round (client pass)
    opt.step()                 # server update

What dissolved relative to the reference: worker processes, queues,
shared-memory tensors and the NCCL process group (SURVEY.md §2.9) —
``model(batch)`` runs one jitted SPMD program over the device mesh and
``opt.step()`` a second, replicated one. Only metrics cross to host.

Per-client communication accounting (the reference's distinctive
observability feature, fed_aggregator.py:171-196, 240-300) is kept,
with one simplification: instead of a deque of historical weight
vectors, we track per-coordinate ``last_updated`` round indices (from
the server update's support), so a returning client's download bytes
cover #{coords updated since it last participated} at the configured
downlink width (``accounting.py``; dense f32, or ``--downlink_encoding
delta``'s (idx, val) pairs + repeat bitmap). Identical to the
reference's count except for exact value-reversion collisions
(measure-zero) and without the deque's staleness clamp approximation.
Uploads bill at the wire dtype: ``--sketch_dtype int8`` tables cost
r x c bytes + r f32 row scales, not 4 x r x c.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from commefficient_tpu import accounting
from commefficient_tpu.autopilot import (RoundVariantCache, apply_knobs,
                                         build_controller, key_of,
                                         key_str)
from commefficient_tpu.clientstore import (HostClientStore,
                                           StorePrefetcher,
                                           resolve_clientstore,
                                           shard_range, state_fields)
from commefficient_tpu.config import Config, NATURAL_NUM_CLIENTS
from commefficient_tpu.core.rounds import (ClientStates,
                                           build_client_round,
                                           build_server_round,
                                           build_val_fn, round_plan)
from commefficient_tpu.core.server import ServerState
from commefficient_tpu.privacy import build_accountant, noise_stream
from commefficient_tpu.telemetry import build_telemetry, clock, trace
from commefficient_tpu.telemetry.core import compile_delta, compile_mark
from commefficient_tpu.ops.vec import flatten_params
from commefficient_tpu.parallel import make_mesh, make_mesh2d
from commefficient_tpu.parallel.mesh import (client_sharding,
                                             model_axis_size,
                                             server_state_sharding,
                                             shard_batch)

# the most recently constructed FedModel; lets FedOptimizer(args) find
# its runtime without an explicit handle — honest parity with the
# reference's module-level globals (fed_aggregator.py:37-44)
_CURRENT_MODEL: Optional["FedModel"] = None


def _host(arr) -> np.ndarray:
    """Materialise a device array on the host, multi-process safe:
    arrays sharded across processes (per-client metrics on a
    multi-host mesh) are allgathered first — every process returns the
    same global value, preserving the replicated-server invariant."""
    if (getattr(arr, "is_fully_addressable", True)
            or getattr(arr, "is_fully_replicated", False)):
        return np.asarray(arr)
    from jax.experimental import multihost_utils
    return np.asarray(multihost_utils.process_allgather(arr,
                                                        tiled=True))


class _RoundVariant:
    """One lattice point's executable bundle: the knob-substituted
    Config plus its jitted round programs. jit is lazy, so building a
    variant costs a closure — XLA compiles on the variant's first
    dispatch (or under the autopilot's warm-ahead, which AOT-compiles
    into ``aot`` during the previous round's host phase). ``compiled``
    tracks which flavors have been charged to the ledger's per-variant
    ``vcompile_*:<key>`` counters."""

    __slots__ = ("key", "cfg", "round_fn", "round_probed", "server_fn",
                 "aot", "compiled")

    def __init__(self, key, cfg, round_fn, round_probed):
        self.key = key
        self.cfg = cfg
        self.round_fn = round_fn
        self.round_probed = round_probed
        self.server_fn = None   # built by FedOptimizer on first use
        self.aot = {}           # flavor -> AOT-compiled executable
        self.compiled = set()   # flavors already compile-stamped


class FedModel:
    """One federated model + its client-side runtime.

    ``compute_loss(params_pytree, batch, args) -> (loss, metrics...)``
    with masked-mean semantics over ``batch["mask"]`` (the per-task
    callbacks of cv_train.py:67-83 / gpt2_train.py:77-99).
    """

    def __init__(self, module, params, compute_loss: Callable,
                 args: Config, compute_loss_val: Optional[Callable] = None,
                 padded_batch_size: Optional[int] = None,
                 mesh=None, stats_fn: Optional[Callable] = None,
                 init_model_state=None):
        global _CURRENT_MODEL
        args.validate_runtime()
        self.module = module
        self.args = args
        self.compute_loss_train = compute_loss
        self.compute_loss_val = compute_loss_val or compute_loss
        # BatchNorm running-stats parity mode: ``stats_fn(params,
        # client_batch) -> stats_pytree`` records each participating
        # client's batch statistics; the server blends their round
        # average into ``model_state`` (torch momentum 0.1) and eval
        # reads it — so eval metrics don't depend on eval batch
        # composition (reference models/resnet9.py BN eval). When set,
        # ``compute_loss_val`` must take (params, batch, args, state).
        self.stats_fn = stats_fn
        self.model_state = (jax.tree_util.tree_map(jnp.asarray,
                                                   init_model_state)
                            if stats_fn is not None else None)

        flat, unravel = flatten_params(params)
        args.grad_size = int(flat.size)
        self.unravel = unravel
        if mesh is None:
            devices = jax.devices()
            if args.num_devices > 0:
                if args.num_devices > len(devices):
                    raise ValueError(
                        f"--num_devices {args.num_devices} > "
                        f"{len(devices)} available devices")
                if jax.process_count() > 1:
                    raise ValueError(
                        "--num_devices is a single-host knob; on "
                        "multi-host pods the mesh must span every "
                        "process's devices (leave it at -1)")
                devices = devices[: args.num_devices]
            mesh2d = getattr(args, "mesh2d", None)
            # --mesh CxM: the pod-scale 2D mesh (clients × model).
            # Cx1 shapes behave exactly like the 1-D mesh (every 2D
            # code path gates on model_axis_size > 1); 1x1 compiles
            # the single-device program
            mesh = (make_mesh2d(*mesh2d, devices) if mesh2d
                    else make_mesh(devices))
        self.mesh = mesh

        num_clients = args.num_clients
        if num_clients is None:
            num_clients = NATURAL_NUM_CLIENTS.get(args.dataset_name)
        assert num_clients is not None, "num_clients unresolved"
        self.num_clients = num_clients

        self.ps_weights = flat
        # per-client state placement (commefficient_tpu/clientstore):
        # device = dense (num_clients, ...) HBM arrays (below); host =
        # budgeted host arena + mmap spill, with only the round's W
        # participant rows materialised on device (gather -> H2D ->
        # round -> D2H -> write-back)
        self.clientstore = resolve_clientstore(args, num_clients)
        self.client_store = None
        self._prefetcher = None
        self._participant_feed = None
        self._store_pending = None
        self._prefetch_after_writeback = False
        if self.clientstore == "host":
            if int(getattr(args, "pipeline_depth", 1)) > 1:
                raise ValueError(
                    "--clientstore host requires --pipeline_depth 1: "
                    "round N's write-back must land before round "
                    "N+1's gather reads the store")
            fields = state_fields(
                args, init_weights=(np.asarray(flat)
                                    if getattr(args, "do_topk_down",
                                               False) else None))
            self.client_store = HostClientStore(
                num_clients, fields,
                budget_bytes=args.clientstore_bytes,
                spill_dir=(args.clientstore_dir or None),
                owned=shard_range(num_clients))
            self.client_states = ClientStates(None, None, None)
            # gather/H2D overlap thread: single-process only — the
            # multi-host row exchange is a collective and must stay on
            # the main thread
            if fields and jax.process_count() == 1:
                self._prefetcher = StorePrefetcher(self.client_store)
        else:
            # big per-client buffers created directly sharded over the
            # client axis, row-padded to the mesh size — never
            # materialised replicated (see ClientStates.init)
            self.client_states = ClientStates.init(
                args, num_clients, flat,
                sharding=client_sharding(self.mesh))

        # --async_buffer_size K: buffered-arrival front end
        # (commefficient_tpu/asyncfed). The driver issues each sampled
        # cohort into an arrival queue and hands back a fold batch of
        # up to K arrived updates (dead-padded to the compiled cohort
        # width) plus the per-slot staleness vector the weighted fold
        # consumes. Host store participants get issue-round stamps so
        # the snapshot a buffered fold replays is auditable.
        self.async_k = int(getattr(args, "async_buffer_size", 0) or 0)
        self._async_driver = None
        if self.async_k > 0:
            from commefficient_tpu.asyncfed import AsyncRoundDriver
            stamp = (self.client_store.stamp_rounds
                     if self.client_store is not None else None)
            self._async_driver = AsyncRoundDriver(args, stamp=stamp)

        if padded_batch_size is None:
            padded_batch_size = (args.local_batch_size
                                 if args.local_batch_size > 0 else 1)
        self.padded_batch_size = padded_batch_size

        stats_fn_flat = None
        if stats_fn is not None:
            def stats_fn_flat(flat_params, batch):
                return stats_fn(self.unravel(flat_params), batch)

            def loss_flat_val_state(flat_params, batch, model_state):
                return self.compute_loss_val(
                    self.unravel(flat_params), batch, args,
                    model_state)
        else:
            def loss_flat_val(flat_params, batch):
                return self.compute_loss_val(self.unravel(flat_params),
                                             batch, args)

        # donate the per-client state buffers: the round returns their
        # updated versions and the stale ones are never read again —
        # halves peak memory for local-momentum/-error modes at scale
        def loss_tree(params_tree, batch, loss=compute_loss):
            return loss(params_tree, batch, args)

        # --probe_every/--probe_full: algorithm probes compile INTO
        # the round program (core/rounds.py). Two jitted variants when
        # the expensive recovery probe applies: the cheap one runs
        # off-cadence rounds, the recovery one every probe_period-th
        # round. jit is lazy, so a variant never dispatched never
        # compiles (probe_period == 1 only ever compiles the full one).
        self.probe_period = int(getattr(args, "probe_period", 0) or 0)
        probes_on = self.probe_period > 0

        def _build_round(cfg, with_probes, with_recovery):
            return jax.jit(
                build_client_round(
                    cfg, None, padded_batch_size,
                    mesh=self.mesh, stats_fn=stats_fn_flat,
                    tree_loss=loss_tree,
                    unravel=self.unravel,
                    dense_rows=(self.clientstore == "host"),
                    probes=with_probes,
                    probe_recovery=with_recovery,
                    client_weights=(self.async_k > 0)),
                donate_argnums=(1,))

        # bucketed re-jit cache: round programs live in a bounded LRU
        # keyed by the discrete knob lattice point they were built for
        # (autopilot/). The base variant's config IS ``args`` itself
        # (apply_knobs returns the same object at the base key), so
        # with the autopilot off the dispatched program — and its HLO —
        # is byte-identical to building jax.jit(build_client_round(
        # args, ...)) directly.
        def _build_variant(key):
            cfg = apply_knobs(args, key)
            return _RoundVariant(
                key, cfg, _build_round(cfg, probes_on, False),
                (_build_round(cfg, True, True)
                 if probes_on and cfg.mode == "sketch" else None))

        self._variants = RoundVariantCache(
            _build_variant,
            max_size=int(getattr(args, "autopilot_cache_size", 4) or 4))
        self._variant_key = key_of(args)
        self._autopilot = build_controller(args)
        if self._autopilot is not None:
            # --autopilot_pin starts (and holds) at the pinned point
            self._variant_key = self._autopilot.key
            if self._variant_key != key_of(args):
                self.args = args = apply_knobs(args, self._variant_key)
        self.pending_variant_key = self._variant_key
        # abstract round-call signature (ShapeDtypeStructs incl.
        # shardings), captured at the first dispatch; warm-ahead AOT
        # compiles against it. Input shapes are knob-independent — the
        # lattice only moves the sketch geometry/wire INSIDE the round.
        self._round_abstract = None
        if stats_fn is not None:
            self._val_fn = jax.jit(build_val_fn(
                args, loss_flat_val_state, stateful=True))
        else:
            self._val_fn = jax.jit(build_val_fn(args, loss_flat_val))

        # pending round state consumed by FedOptimizer.step
        self.pending_aggregated = None
        self.pending_client_ids = None
        self.round_index = 0
        self.training = True
        self.diverged = False  # set by trainers on NaN abort
        # fedavg local-SGD LR: ZERO until the first FedOptimizer.step
        # sets it, like the reference's shared g_lr tensor
        # (fed_aggregator.py:98-101, torch.zeros) — clients read the
        # value set by the *previous* round's step, and the trainer's
        # LR==0 "HACK STEP" aligns the schedule. Initialising to 1.0
        # made round 0 take full-gradient local steps (diverges
        # instantly at ResNet9 scale).
        self.fedavg_lr = 0.0
        # round-key stream genesis (data order / client sampling), not
        # a noise source — noise streams live in privacy/mechanism.py
        self._rng = jax.random.PRNGKey(args.seed)  # audit: allow(noise-confinement)

        # communication accounting
        self.last_updated = np.full(args.grad_size, -1, np.int64)
        self.client_last_seen = np.full(num_clients, -1, np.int64)
        self._update_round = 0
        self._rebuild_round_counts()
        # --downlink_encoding delta bookkeeping: the latest update's
        # support indices (None = dense/all coords), how many of them
        # repeat the update before it, and that previous update's
        # support size (the bitmap a round-fresh client holds)
        self._prev_support_idx: Optional[np.ndarray] = np.zeros(
            0, np.int64)
        self._repeat_count = 0
        self._bitmap_bits = 0

        # --pipeline_depth > 1: rounds are dispatched without waiting
        # for their metrics/accounting; the host runs ahead of the
        # device by up to `depth` rounds and materialises in batches
        # via flush() (per-round math is unchanged — only when results
        # cross to the host changes)
        self.pipeline_depth = max(1, int(getattr(args,
                                                 "pipeline_depth", 1)))
        self._inflight = []   # per round: device metric arrays
        self._oplog = []      # ordered ("account", ids, mask, ridx) /
        #                       ("note", support) deferred host ops

        # round-ledger telemetry (commefficient_tpu/telemetry): spans
        # around each host-side round stage, byte totals unified with
        # the accounting above, memory/compile watermarks. Disabled
        # (no --ledger/--telemetry_console) it's a no-op fast path.
        self.telemetry = build_telemetry(args)
        # probe bookkeeping: _probe_host holds materialised client-
        # pass values until the server pass completes the round's dict
        # (sync path); _probe_log holds DEVICE scalars for pipelined
        # rounds, materialised at flush replay. The alarm engine is
        # None with probes off; it evaluates even without sinks, so
        # --on_divergence abort works ledgerless.
        self._probe_host = {}
        self._probe_log = {}
        self._prev_residual = None
        from commefficient_tpu.telemetry.alarms import build_alarm_engine
        self.alarm_engine = build_alarm_engine(args, self.telemetry)
        if self.alarm_engine is not None:
            # trace-derived skew escalates like any probe alarm: the
            # profiler's bucket merge calls straight into the engine
            self.telemetry.on_device_time = \
                self.alarm_engine.check_device_time
        # --dp sketch: the run's RDP accountant (privacy/). Charged
        # once per DISPATCHED round — the round program releases the
        # noised table whether or not its metrics ever materialise —
        # so pipelined rounds spend budget in dispatch order too. Its
        # cumulative ε lands on the schema-v5 ledger keys and feeds
        # the privacy_budget_exhausted alarm. None with --dp off.
        self._accountant = build_accountant(args)
        # roofline cost model (analysis/cost.py), computed lazily at
        # the first --profile'd round from the lowered round program
        self._cost_model = None
        from commefficient_tpu.parallel import mesh as mesh_lib
        topo = mesh_lib.topology_summary()
        # live operations plane (telemetry/live.py + flightrec.py):
        # exporter sink + flight recorder must attach BEFORE the meta
        # record below is emitted — the live sink derives clients/s
        # from the plan, the recorder stamps the bundle's meta. Both
        # stay None with the knobs unset (disabled fast path
        # untouched). Labels: the job index is parsed off the ledger
        # shard path (the shard IS the job identity under a
        # fedservice daemon); registry lineage arms only when the run
        # writes a ledger, matching maybe_write_manifest.
        from commefficient_tpu.telemetry.live import attach_live_plane
        from commefficient_tpu.telemetry.registry import config_hash
        from commefficient_tpu.telemetry.sinks import \
            job_index_of_ledger
        ledger = str(getattr(args, "ledger", "") or "")
        job = job_index_of_ledger(ledger)
        labels = {"process": topo["process_index"],
                  "run": config_hash(args)[:8]}
        if job is not None:
            labels["job"] = job
        self.live_sink, self.flightrec = attach_live_plane(
            self.telemetry, args, labels=labels,
            runs_dir="runs" if ledger else "")
        # per-run SLO engine (telemetry/slo.py): None unless a target
        # is set; observed once per synchronous round in step()
        from commefficient_tpu.telemetry.slo import build_slo_engine
        self._slo = build_slo_engine(args)
        # causal round tracer (telemetry/causal.py): None unless
        # --causal_trace — every telemetry span then also records a
        # causal frame, and the asyncfed driver adds cohort-issue /
        # arrival-dequeue spans through the same tracer. The job
        # index keys the deterministic trace ids, so daemon-side
        # grant spans stitch in by id across the process boundary.
        from commefficient_tpu.telemetry.causal import \
            build_causal_tracer
        self.telemetry.set_causal_tracer(
            build_causal_tracer(args, job=job))
        if self._async_driver is not None:
            self._async_driver.causal = self.telemetry.causal
        self.telemetry.emit_meta(
            num_clients=num_clients,
            num_devices=int(np.prod(self.mesh.devices.shape)),
            process_index=topo["process_index"],
            process_count=topo["process_count"],
            clientstore=self.clientstore,
            mesh_shape={str(k): int(v)
                        for k, v in dict(self.mesh.shape).items()},
            plan=round_plan(args))

        _CURRENT_MODEL = self

    # --- reference API surface ------------------------------------------

    def train(self, training: bool):
        self.training = training

    def __call__(self, batch):
        return (self._call_train(batch) if self.training
                else self._call_val(batch))

    def finalize(self):
        """Shutdown protocol parity (fed_aggregator.py:197-204): a
        device barrier, plus host client-store teardown (prefetch
        thread join, final write-back, spill-file removal)."""
        trace.end_round_marker()
        # audit: allow(host-sync) — the shutdown barrier IS the sync
        jax.block_until_ready(self.ps_weights)
        if self._prefetcher is not None:
            self._prefetcher.close()
            self._prefetcher = None
        self._store_writeback()
        if self.client_store is not None:
            self.client_store.close()
            self.client_store = None
        self.telemetry.close()

    def interrupted(self):
        """Crash-safety cleanup after a mid-round SIGTERM/exception:
        discard every partially-dispatched round's host-side state so
        ``finalize()`` (device barrier, store teardown, telemetry
        close) runs cleanly. Server state and residuals are left
        untouched — the last round-cadence autosave is the consistent
        restore point, and dropping the in-flight rounds keeps both
        the ledger and the client store free of rounds the checkpoint
        never saw (a half-written-back round would desync store rows
        from the checkpointed server state)."""
        self._inflight = []
        self._oplog = []
        self._probe_log = {}
        self._probe_host = {}
        self.pending_aggregated = None
        self.pending_client_ids = None
        self._store_pending = None

    # --- host client store (commefficient_tpu/clientstore) ---------------

    def attach_participant_feed(self, feed: Callable):
        """``feed() -> next round's participant client ids (or None)``
        — wires the sampler's one-round lookahead
        (data/fed_sampler.py peek_next_client_ids) into the prefetch
        thread so round N+1's gather/H2D overlaps round N's compute."""
        self._participant_feed = feed

    def attach_arrival_process(self, fn):
        """Inject a seeded arrival schedule into the async driver
        (tests/benches/scripts only — the arrival-confinement lint
        rule keeps injection out of package modules, so production
        keeps the punctual default). Requires --async_buffer_size."""
        assert self._async_driver is not None, \
            "attach_arrival_process needs --async_buffer_size > 0"
        self._async_driver.attach_arrival_process(fn)

    def _gather_rows(self, ids_np):
        """Host-side rows for this round's participants, prefetched
        when the lookahead predicted them, synchronous otherwise."""
        ids64 = np.asarray(ids_np, np.int64)
        rows = None
        if self._prefetcher is not None:
            rows = self._prefetcher.take(ids64)
            self.telemetry.count("prefetch_hit" if rows is not None
                                 else "prefetch_miss")
        if rows is None:
            rows, _ = self.client_store.gather(ids64)
        if jax.process_count() > 1 and rows:
            # every process contributed its owned rows (zeros
            # elsewhere): one allgather-sum rebuilds each participant
            # row everywhere. Main thread only — it's a collective.
            from jax.experimental import multihost_utils
            rows = {k: np.asarray(multihost_utils.process_allgather(
                        v, tiled=False)).sum(axis=0, dtype=np.float32)
                    for k, v in rows.items()}
        return rows

    def _rows_to_states(self, rows) -> ClientStates:
        def put(name):
            v = rows.get(name)
            return (None if v is None
                    else shard_batch(self.mesh, jnp.asarray(v)))

        return ClientStates(put("velocities"), put("errors"),
                            put("weights"))

    def _submit_prefetch(self):
        if self._prefetcher is None:
            return
        # buffered arrival: the driver beats the sampler — when the
        # backlog already holds the next fold's full buffer, its ids
        # (in fold-slot order, dead-padded) are known exactly. The
        # sampler lookahead covers the punctual/underfull case; a
        # wrong guess is just a prefetch miss (synchronous fallback).
        ids = (self._async_driver.peek_next_ids()
               if self._async_driver is not None else None)
        if ids is None and self._participant_feed is not None:
            ids = self._participant_feed()
        if ids is not None:
            self._prefetcher.submit(np.asarray(ids, np.int64))

    def _store_writeback(self):
        """D2H the pending round's updated participant rows into the
        store. Runs from FedOptimizer.step (after the server round's
        velocity rewrite, so true_topk's momentum-factor masking is
        captured), and defensively before the next gather, at
        checkpoint save and at shutdown. Dead slots (dropout/padding)
        are excluded, matching the device path's dropped scatters."""
        if self.client_store is None or self._store_pending is None:
            return
        with self.telemetry.span("writeback"):
            ids_np, alive = self._store_pending
            self._store_pending = None
            cs = self.client_states
            self.client_states = ClientStates(None, None, None)
            rows = {}
            for name, val in (("velocities", cs.velocities),
                              ("errors", cs.errors),
                              ("weights", cs.weights)):
                if val is not None:
                    rows[name] = np.asarray(_host(val), np.float32)
            if rows and alive.any():
                self.client_store.write(
                    ids_np[alive],
                    {k: v[alive] for k, v in rows.items()})

    def params(self):
        """Current weights as the module's pytree (the reference's
        lazy state_dict sync, fed_aggregator.py:374-378)."""
        return self.unravel(self.ps_weights)

    def save_pretrained(self, save_dir: str, hf_format: bool = False,
                        torch_format: bool = False):
        """HF-style final-model save (reference fed_aggregator.py:
        205-212 / gpt2_train.py:146): current server weights as a flax
        msgpack blob plus the module's config as JSON.

        ``hf_format=True`` (GPT-2 modules only) additionally writes
        ``pytorch_model.bin`` + an HF-`transformers` ``config.json`` so
        the directory loads with ``GPT2DoubleHeadsModel/GPT2LMHeadModel
        .from_pretrained`` — the model goes back to the torch/HF
        ecosystem the reference lives in. The HF config's field names
        are a superset of GPT2Config's, so this framework's own reload
        path (gpt2_train.build_model_and_tokenizer) reads the same dir
        too.

        ``torch_format=True`` (CV families) additionally writes
        ``state_dict.pt``: a torch ``state_dict`` with the reference
        torch modules' own key names and layouts
        (models/torch_export.py) — the reference's final CV artifact
        is exactly ``torch.save(model.state_dict(), ...)``
        (cv_train.py:420-423), including running BN stats when the
        model tracks them."""
        import dataclasses
        import json
        import os

        from flax import serialization

        os.makedirs(save_dir, exist_ok=True)
        # config first: a dir with weights but no config would rebuild
        # the wrong architecture on reload (gpt2_train reload path)
        cfg = getattr(self.module, "cfg", None)
        if torch_format:
            from commefficient_tpu.models.torch_export import \
                save_torch_state_dict
            save_torch_state_dict(
                self.module, self.params(),
                getattr(self, "model_state", None),
                os.path.join(save_dir, "state_dict.pt"))
        if hf_format:
            import torch

            from commefficient_tpu.models.gpt2 import (GPT2Config,
                                                       convert_gpt2_to_hf)
            if not isinstance(cfg, GPT2Config):
                raise ValueError("hf_format export is defined for "
                                 "GPT-2 modules only")
            sd, hf_cfg = convert_gpt2_to_hf(self.params(), cfg)
            with open(os.path.join(save_dir, "config.json"), "w") as f:
                json.dump(hf_cfg, f, indent=2)
            torch.save({k: torch.from_numpy(
                            np.array(v, copy=True))
                        for k, v in sd.items()},
                       os.path.join(save_dir, "pytorch_model.bin"))
        elif cfg is not None and dataclasses.is_dataclass(cfg):
            blob = {k: v for k, v in dataclasses.asdict(cfg).items()
                    if isinstance(v, (int, float, str, bool,
                                      type(None)))}
            with open(os.path.join(save_dir, "config.json"), "w") as f:
                json.dump(blob, f, indent=2)
        with open(os.path.join(save_dir, "flax_model.msgpack"),
                  "wb") as f:
            f.write(serialization.msgpack_serialize(
                jax.tree_util.tree_map(np.asarray, self.params())))

    # --- rounds ----------------------------------------------------------

    def _call_train(self, batch):
        args = self.args
        tel = self.telemetry
        ridx = self.round_index
        tel.begin_round(ridx)
        # device-timeline marker, same lifecycle as the ledger record
        # (closed by the next round's begin): a flag check when no
        # profiler trace window is open
        trace.begin_round_marker(ridx)
        eng = self.alarm_engine
        step_t0 = (clock.tick()
                   if eng is not None and eng.step_time_ratio > 0
                   and self.pipeline_depth <= 1 else None)
        # SLO latency samples need a wall clock on every synchronous
        # round (pipelined dispatch times measure the host, not the
        # round — same exclusion as step_time_regression)
        slo_t0 = (clock.tick()
                  if self._slo is not None and self.pipeline_depth <= 1
                  else None)
        staleness = None
        if self._async_driver is not None:
            # issue the sampled cohort into the arrival queue, then
            # fold what has actually arrived: the batch the round runs
            # is the buffer's head, dead-padded to the cohort width
            with tel.span("async_fold"):
                batch, staleness = self._async_driver.step(batch)
        ids_np = np.asarray(batch["client_ids"])
        dev_batch = {k: v for k, v in batch.items()
                     if k != "client_ids"}
        with tel.span("h2d"), trace.phase("h2d"):
            dev_batch = shard_batch(self.mesh, jax.tree_util.tree_map(
                jnp.asarray, dev_batch))
            ids = jax.device_put(jnp.asarray(ids_np, jnp.int32))

        rng = jax.random.fold_in(self._rng, self.round_index)
        cs_in = self.client_states
        if self.client_store is not None:
            # normally a no-op: opt.step() already wrote round N-1's
            # rows back; covers trainers that skip the server step
            self._store_writeback()
            with tel.span("gather"):
                rows = self._gather_rows(ids_np)
            with tel.span("h2d_state"):
                cs_in = self._rows_to_states(rows)
        var = self._variants.get(self._variant_key)
        probed = (var.round_probed is not None
                  and ridx % self.probe_period == 0)
        flavor = "probed" if probed else "plain"
        jit_fn = var.round_probed if probed else var.round_fn
        # prefer the warm-ahead AOT executable when the switch compiled
        # one; otherwise the jit wrapper compiles lazily right here
        round_fn = var.aot.get(flavor, jit_fn)
        # the server pass must consume this aggregate with the SAME
        # variant's program — record the dispatch-time key, not
        # whatever the controller moves to afterwards
        self.pending_variant_key = var.key
        # staleness rides as a seventh positional arg only when the
        # async driver is on — the synchronous call site stays
        # byte-identical (and so does its compiled program)
        sargs = (() if staleness is None
                 else (shard_batch(self.mesh, jnp.asarray(staleness)),))
        rargs = (self.ps_weights, cs_in, dev_batch, ids, rng,
                 jnp.float32(self.fedavg_lr)) + sargs
        if (self._cost_model is None and tel.enabled
                and getattr(args, "do_profile", False)):
            # roofline expectation from this round's lowered program —
            # once per run, text-only (no second compile; always the
            # jit wrapper — AOT executables don't re-lower)
            self._emit_cost_model(jit_fn, rargs)
        if self._round_abstract is None:
            self._round_abstract = jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(
                    a.shape, a.dtype,
                    sharding=getattr(a, "sharding", None)), rargs)
        cmark = (compile_mark() if flavor not in var.compiled
                 else None)
        with tel.span("round_dispatch"), trace.phase("round_dispatch"):
            res = round_fn(*rargs)
        if cmark is not None:
            # ledger compile events carry the variant cache key — jit
            # compiles synchronously inside the dispatch, so the delta
            # around a variant's first call is its compile
            var.compiled.add(flavor)
            self._stamp_vcompile(var.key, cmark)
        self.client_states = res.client_states
        self.pending_aggregated = res.aggregated
        # dead slots (dropout / loader padding) must carry the
        # out-of-range sentinel into the SERVER round too: true_topk's
        # velocity masking scatters rows back at these ids, and a dead
        # client's momentum must stay untouched exactly like its
        # client-side state (core/rounds.py _state_ids; regression
        # found by tests/test_fuzz_modes.py)
        from commefficient_tpu.core.rounds import _state_ids
        if self.client_store is not None:
            # host mode: state rows are positional (dense_rows), so the
            # server round's velocity scatter needs slot positions —
            # dead slots keep the sentinel either way
            W = ids_np.shape[0]
            self.pending_client_ids = _state_ids(
                jnp.arange(W, dtype=jnp.int32), dev_batch)
            alive = np.asarray(batch["mask"]).reshape(W, -1) \
                .sum(axis=1) > 0
            self._store_pending = (np.asarray(ids_np, np.int64), alive)
            if int(getattr(args, "overlap_depth", 1)) > 1:
                # latency-hiding pipeline: a prefetch staged now
                # snapshots the store BEFORE opt.step()'s write-back,
                # so take() would synchronously re-gather every repeat
                # participant's row next round. Defer the submit to
                # step(), right after the write-back lands — the
                # background gather then overlaps the downlink
                # delta-encode bookkeeping (note_update /
                # _note_delta_support) instead of being undone by it.
                self._prefetch_after_writeback = True
            else:
                self._submit_prefetch()
        else:
            self.pending_client_ids = _state_ids(ids, dev_batch)
        if self._accountant is not None:
            self._charge_privacy(ridx, var.cfg, staleness,
                                 np.asarray(batch["mask"]))
        self.round_index += 1
        if res.bn_stats is not None:
            # running-stats blend (torch BN momentum 0.1); a fully
            # dropped round contributes nothing. Lazy device ops on
            # per-channel vectors — no host sync.
            new_stats, alive = res.bn_stats
            self.model_state = jax.tree_util.tree_map(
                lambda ra, s: jnp.where(alive > 0,
                                        0.9 * ra + 0.1 * s, ra),
                self.model_state, new_stats)

        if self.pipeline_depth > 1:
            # bytes for this round attach at flush() replay — the
            # ledger record stays buffered (round order preserved)
            # until then; probe scalars stay DEVICE arrays in
            # _probe_log (no sync) and materialise at the same replay
            self._oplog.append(("account", ids_np,
                                np.asarray(batch["mask"]), ridx,
                                var.cfg))
            self._inflight.append(list(res.metrics))
            if res.probes is not None:
                self._probe_log.setdefault(ridx, {}).update(res.probes)
            return None
        with tel.span("metrics_host"), trace.phase("metrics_host"):
            metrics = [_host(m) for m in res.metrics]
            probe_vals = (None if res.probes is None else
                          {k: float(_host(v))
                           for k, v in res.probes.items()})
        if probe_vals is not None:
            # merge now (so eval-only callers still get them on the
            # ledger); the server pass completes the dict and runs the
            # alarms via _finish_probes
            tel.merge_round_probes(ridx, probe_vals)
            self._probe_host[ridx] = probe_vals
        astats = None
        if self._async_driver is not None:
            # buffered-arrival probes (staleness histogram, buffer
            # occupancy, backlog) are host-side driver state: merged
            # onto the ledger record every round, and routed to the
            # alarm engine through the round's probe dict when probes
            # are compiled in (so _finish_probes checks once) or
            # directly when they are not
            astats = self._async_driver.round_stats()
            tel.merge_round_probes(ridx, astats)
            if probe_vals is not None:
                self._probe_host[ridx].update(astats)
            elif self.alarm_engine is not None:
                self.alarm_engine.check(ridx, astats)
        if step_t0 is not None:
            # wall step time through the metrics sync — evaluated
            # before set_round_bytes so an aborting alarm still lands
            # on the record telemetry.close() will flush
            eng.check_step_time(ridx, clock.tick() - step_t0)
        if slo_t0 is not None:
            self._observe_slo(ridx, clock.tick() - slo_t0, astats)
        acct_ids, acct_mask = ids_np, batch["mask"]
        if self._async_driver is not None:
            # dead pad slots (id 0, mask 0) are queue padding, not
            # participants — they must not bill client 0 a download.
            # Folded ids route through the regular accounting, so a
            # stale client's downlink is priced by how far
            # client_last_seen lags (incl. delta have_prev freshness).
            alive = np.asarray(acct_mask).reshape(
                len(ids_np), -1).sum(axis=1) > 0
            acct_ids = ids_np[alive]
            acct_mask = np.asarray(acct_mask)[alive]
        down, up = self._account_bytes(acct_ids, acct_mask,
                                       cfg=var.cfg)
        tel.set_round_bytes(ridx, float(down.sum()), float(up.sum()))
        return metrics + [down, up]

    def flush(self, force=True):
        """Materialise buffered pipelined rounds, replaying the
        deferred accounting ops in dispatch order. Returns the list of
        per-round outputs in the same format a synchronous
        ``model(batch)`` call returns; empty until ``pipeline_depth``
        rounds are buffered unless ``force``."""
        if self.pipeline_depth <= 1 or not self._inflight:
            return []
        if not force and len(self._inflight) < self.pipeline_depth:
            return []
        # the pipelined path's big blocking sync: every buffered
        # round's metrics materialise here, so ledger-attribute it
        # like the synchronous path does (the span lands on the
        # current record — the flush boundary — which is where the
        # wall-clock actually goes)
        with self.telemetry.span("metrics_host"):
            rounds = iter([[_host(m) for m in ms]
                           for ms in self._inflight])
        self._inflight = []
        oplog, self._oplog = self._oplog, []
        results = []
        for op in oplog:
            if op[0] == "account":
                # probes must land on the record BEFORE its bytes:
                # set_round_bytes makes the record emission-eligible
                pd = self._probe_log.pop(op[3], None)
                if pd is not None:
                    with self.telemetry.span("metrics_host"):
                        vals = {k: float(_host(v))
                                for k, v in pd.items()}
                    self._finish_probes(op[3], vals)
                down, up = self._account_bytes(op[1], op[2],
                                               cfg=op[4])
                self.telemetry.set_round_bytes(
                    op[3], float(down.sum()), float(up.sum()))
                results.append(next(rounds) + [down, up])
            else:
                self._apply_note(op[1])
        return results

    def _charge_privacy(self, ridx: int, cfg, staleness=None,
                        mask=None):
        """Charge round ``ridx``'s DP release to the accountant and
        stamp the schema-v5 ledger keys. σ is the DISPATCHED variant's
        ``dp_noise_mult`` (autopilot geometry moves recalibrate it so
        the absolute table noise holds — autopilot/lattice.py).

        Async staleness-weighted rounds charge the REDUCED
        sensitivity ``weight_scale = (1 + s_min)^{-alpha}`` — the
        largest fold weight among the round's ALIVE slots: DP folds
        normalise by the static W·B capacity (core/rounds.py), so a
        client's released contribution is cw_i·t_i/(W·B), genuinely
        scaled by its weight, and the round's worst-case release is
        the largest alive weight times the full sensitivity. (Against
        the data-dependent Σ cw_i·n_i denominator this discount would
        be unsound — uniform weights cancel out of that release.)
        Fully-dead rounds conservatively charge 1. With a hard budget
        (``--dp_epsilon`` > 0) the post-charge ε routes through the
        alarm engine, so ``--on_divergence abort`` stops the run AT
        the exhausting round."""
        acc = self._accountant
        sigma = float(cfg.dp_noise_mult)
        w = 1.0
        alpha = float(getattr(cfg, "async_staleness_weight", 0.0)
                      or 0.0)
        if staleness is not None and alpha > 0.0:
            s = np.asarray(staleness, np.float64)
            alive = np.asarray(mask).reshape(s.shape[0], -1) \
                .sum(axis=1) > 0
            if alive.any():
                w = float(min(
                    (1.0 + float(s[alive].min())) ** (-alpha), 1.0))
        acc.step(weight_scale=w, sigma=sigma)
        eps = acc.epsilon()
        # ledger σ is the round's effective noise-to-sensitivity
        # ratio σ/w — what the composed curve actually charged
        self.telemetry.set_round_privacy(ridx, eps, acc.delta,
                                         sigma / w)
        budget = float(getattr(cfg, "dp_epsilon", 0.0) or 0.0)
        if self.alarm_engine is not None and budget > 0:
            self.alarm_engine.check(ridx, {
                "dp_epsilon": eps,
                "dp_delta": acc.delta,
                "dp_sigma": sigma / w,
                # projection at full sensitivity: future rounds'
                # staleness weights are unknown, so predict
                # exhaustion at the conservative weight_scale=1
                "dp_rounds_left": acc.rounds_left(budget,
                                                  sigma=sigma)})

    def _observe_slo(self, ridx: int, round_s: float, astats=None):
        """One SLO observation per synchronous round: latency is the
        dispatch-through-metrics wall time, staleness comes from the
        async driver's round stats, ε from the accountant's
        post-charge curve. The returned burn probes ride the ledger
        record (where the live plane's ``slo_burn`` gauges read
        them), the per-objective stamp lands on the v6 ``slo`` key,
        and the slo_burn rule evaluates through ``check_slo`` — never
        ``check``, which is stateful and already ran this round."""
        slo = self._slo
        eps = (self._accountant.epsilon()
               if self._accountant is not None else None)
        smax = (astats or {}).get("async_staleness_max")
        probes = slo.observe(ridx, round_s=round_s,
                             staleness_max=smax, dp_epsilon=eps)
        self.telemetry.merge_round_probes(ridx, probes)
        self.telemetry.set_round_slo(ridx, slo.stamp())
        if self.alarm_engine is not None:
            self.alarm_engine.check_slo(ridx, probes)

    def _finish_probes(self, ridx: int, vals: dict):
        """Complete round ``ridx``'s probe dict host-side: fold in any
        stashed client-pass values, derive the residual growth ratio
        from the previous round's residual norm (rounds are finished
        in dispatch order on both the sync and flush-replay paths, so
        the ratio is always consecutive-round), merge onto the ledger
        record, and evaluate the alarm rules — which may raise
        DivergenceAbort under ``--on_divergence abort``."""
        full = self._probe_host.pop(ridx, {})
        full.update(vals)
        rn = full.get("residual_norm")
        if rn is not None:
            prev = self._prev_residual
            if prev is not None and prev > 0:
                full["residual_growth"] = rn / prev
            self._prev_residual = rn
        self.telemetry.merge_round_probes(ridx, full)
        if self.alarm_engine is not None:
            self.alarm_engine.check(ridx, full)
        if self._autopilot is not None:
            # between-rounds knob control: one observation per finished
            # round, in dispatch order on both the sync and
            # flush-replay paths — the controller (and so its manifest
            # trajectory) sees exactly the probe stream the run saw
            new_key = self._autopilot.observe(ridx, full)
            if new_key is not None:
                self._switch_variant(new_key)

    def _switch_variant(self, key):
        """Move the dispatch point to lattice point ``key``: fetch (or
        lazily build) its variant from the re-jit cache, optionally
        AOT-compile the flavor the NEXT round will dispatch — under the
        CURRENT round's host phase, so the compile hides behind work
        the host was doing anyway, and only ever for the point the
        controller just committed to visiting (warm-ahead never touches
        an unvisited lattice point) — and swap ``self.args`` to the
        variant's config so byte accounting reprices from the next
        round on."""
        tel = self.telemetry
        var = self._variants.get(key)
        self._variant_key = key
        nridx = self.round_index  # next round to dispatch
        probed = (var.round_probed is not None
                  and self.probe_period > 0
                  and nridx % self.probe_period == 0)
        flavor = "probed" if probed else "plain"
        if (getattr(self.args, "autopilot_warm_ahead", True)
                and self._round_abstract is not None
                and flavor not in var.compiled
                and flavor not in var.aot):
            fn = var.round_probed if probed else var.round_fn
            cmark = compile_mark()
            try:
                with tel.span("autopilot_warm"):
                    var.aot[flavor] = fn.lower(
                        *self._round_abstract).compile()
                var.compiled.add(flavor)
                self._stamp_vcompile(var.key, cmark)
            except Exception:
                # AOT lowering is best-effort: the lazy jit wrapper
                # compiles at first dispatch instead
                var.aot.pop(flavor, None)
        self.args = var.cfg
        tel.count("autopilot_moves")

    def _stamp_vcompile(self, key, mark):
        """Charge the compile activity since ``mark`` to lattice point
        ``key`` on the current ledger record: raw jax.monitoring event
        count + seconds, plus one ``vcompile_programs`` unit per
        actually-compiled executable (telemetry_report's per-variant
        compile table reads these)."""
        ev, secs = compile_delta(mark)
        if ev:
            ks = key_str(key)
            tel = self.telemetry
            tel.count(f"vcompile_events:{ks}", ev)
            tel.count(f"vcompile_secs:{ks}", round(secs, 6))
            tel.count(f"vcompile_programs:{ks}", 1)

    def autopilot_record(self):
        """The controller's replayable trajectory record (manifest
        ``autopilot`` block), or None with the autopilot off."""
        return (None if self._autopilot is None
                else self._autopilot.record())

    def _emit_cost_model(self, round_fn, round_args):
        """Roofline expectation for this run's round program
        (analysis/cost.py): lower the jitted round with the first
        profiled round's concrete arguments — text only, the XLA
        compile is NOT repeated — count its dot/conv FLOPs and emit
        the cost model as a ledger meta record. Registers
        ``expected_round_s`` on the telemetry so the trace window's
        device-time buckets carry ``roofline_utilization``. Any
        failure degrades to a warning; the marker stays set so it is
        not retried every round."""
        self._cost_model = {}
        try:
            from commefficient_tpu.analysis.cost import build_cost_model
            text = round_fn.lower(*round_args).as_text()
            n_dev = int(np.prod(self.mesh.devices.shape))
            dev0 = self.mesh.devices.flat[0]
            cost = build_cost_model(
                text,
                backend=jax.default_backend(),
                device_kind=getattr(dev0, "device_kind", ""),
                n_devices=n_dev,
                allreduce_payload_bytes=float(
                    self.args.upload_wire_bytes_per_client),
                wire_dtype=getattr(self.args, "sketch_dtype", "f32"),
                label=(f"{self.args.mode}/{self.clientstore}/"
                       f"{n_dev}dev"))
            self._cost_model = cost
            self.telemetry.expected_round_s = cost["expected_round_s"]
            self.telemetry.emit_meta(cost_model=cost)
        except Exception as e:  # noqa: BLE001 — observability only
            print(f"WARNING: roofline cost model skipped "
                  f"({type(e).__name__}: {e})")

    def _rebuild_round_counts(self):
        """Histogram of ``last_updated`` by round (index = round + 1).
        ``#coords changed since a client last synced at round s`` =
        the suffix sum from index s + 2 — O(k) to maintain per round
        and O(#rounds) to query, replacing the old O(W x grad_size)
        host compare (and, with sparse support, the dense update
        transfer) per round."""
        self._round_counts = np.bincount(
            self.last_updated + 1,
            minlength=self._update_round + 2).astype(np.int64)

    def _account_bytes(self, ids_np, mask=None, cfg=None):
        """Per-round download/upload byte accounting (see module
        docstring; reference fed_aggregator.py:171-196, 240-300).
        ``mask`` (W, B) derives which clients completed the round:
        dropped clients (--dropout_prob) downloaded weights but
        uploaded nothing. All byte widths route through
        ``accounting`` — uploads at the sketch wire dtype, downloads
        dense-f32 or delta-coded per --downlink_encoding. ``cfg`` is
        the config the round was DISPATCHED under (the dispatch-time
        round variant's) so autopilot knob moves reprice exactly from
        the round that first used them, even on pipelined replay."""
        if cfg is None:
            cfg = self.args
        download_bytes = np.zeros(self.num_clients)
        suffix = np.cumsum(self._round_counts[::-1])[::-1]
        q = self.client_last_seen[ids_np] + 2
        changed = np.where(
            q < len(suffix), suffix[np.minimum(q, len(suffix) - 1)], 0)
        if getattr(cfg, "downlink_encoding", "dense") == "delta":
            wire = getattr(cfg, "sketch_dtype", "f32")
            # a client that saw the PREVIOUS broadcast holds its
            # support list, so repeats delta-code against it; anyone
            # staler downloads every changed coord as (idx, val)
            fresh = (self.client_last_seen[ids_np]
                     == self._update_round - 1)
            download_bytes[ids_np] = [
                accounting.delta_downlink_bytes(
                    c, self._repeat_count, self._bitmap_bits, wire,
                    have_prev=bool(hp))
                for c, hp in zip(changed, fresh)]
        else:
            download_bytes[ids_np] = changed * accounting.bytes_of(
                1, "f32")
        self.client_last_seen[ids_np] = self._update_round
        upload_bytes = np.zeros(self.num_clients)
        up_ids = ids_np
        if mask is not None:
            up_ids = ids_np[np.asarray(mask).sum(axis=1) > 0]
        upload_bytes[up_ids] = float(
            cfg.upload_wire_bytes_per_client)
        return download_bytes, upload_bytes

    def _call_val(self, batch):
        dev_batch = shard_batch(self.mesh, jax.tree_util.tree_map(
            jnp.asarray, batch))
        # eval metrics cross to the host like train metrics do —
        # attribute the sync (a no-op span when no round is open)
        with self.telemetry.span("metrics_host"):
            if self.stats_fn is not None:
                out = _host(self._val_fn(self.ps_weights,
                                         self.model_state, dev_batch))
            else:
                out = _host(self._val_fn(self.ps_weights, dev_batch))
        # (S, n_metrics) -> per-shard metric arrays, like the
        # reference's split_results (fed_aggregator.py:617-618), plus
        # per-shard real-sample counts so callers can weight out the
        # padded/empty shards the fixed S-shard layout produces
        counts = np.asarray(batch["mask"]).reshape(
            batch["mask"].shape[0], -1).sum(axis=1)
        return [out[:, i] for i in range(out.shape[1])] + [counts]

    def note_update(self, support=None):
        """Record the server update's support for download accounting
        (deferred to flush() when pipelining).

        ``support`` forms:
        - ((k,) indices, (k,) values): sparse support of the weight
          update (values post-LR) — only ~k values cross to the host;
        - None: dense update, every coordinate marked changed with no
          device transfer (the only deviation from the reference's
          value-compare: dense-mode coordinates whose update is
          exactly 0.0 still count as changed — measure-zero under
          momentum);
        - {"bitmap": packed uint8}: device-side ``!= 0`` compare,
          bit-packed before crossing to the host (modes whose update
          is sparse with non-static support size, e.g. local_topk —
          its update's support is the union of past top-k
          selections; 1/32 the transfer of the dense form);
        - a dense update array: host-side ``!= 0`` compare (legacy
          form, kept for direct callers)."""
        if self.pipeline_depth > 1:
            self._oplog.append(("note", support))
            return
        self._apply_note(support)

    def _apply_note(self, support):
        self._update_round += 1
        r = self._update_round
        if len(self._round_counts) < r + 2:
            self._round_counts = np.concatenate(
                [self._round_counts,
                 np.zeros(r + 2 - len(self._round_counts) + 64,
                          np.int64)])
        if support is None:
            self.last_updated[:] = r
            self._round_counts[:] = 0
            self._round_counts[r + 1] = self.args.grad_size
            self._note_delta_support(None)
            return
        if isinstance(support, tuple):
            idx = np.asarray(support[0])
            vals = np.asarray(support[1])
            idx = idx[vals != 0]
        elif isinstance(support, dict):  # packed changed-coords bitmap
            bits = np.unpackbits(np.asarray(support["bitmap"]))
            idx = np.nonzero(bits[: self.args.grad_size])[0]
        else:
            idx = np.nonzero(np.asarray(support) != 0)[0]
        old = self.last_updated[idx] + 1
        np.subtract.at(self._round_counts, old, 1)
        self._round_counts[r + 1] += len(idx)
        self.last_updated[idx] = r
        self._note_delta_support(idx)

    def _note_delta_support(self, idx):
        """Roll the --downlink_encoding delta bookkeeping forward one
        update: how many of this update's support indices repeat the
        previous update's (those ship as bitmap bits, not int32
        indices, to a client that saw the previous broadcast), and
        the previous support's size (the bitmap's bit count).
        ``idx=None`` means a dense update (every coordinate)."""
        prev = self._prev_support_idx
        d = int(self.args.grad_size)
        prev_n = d if prev is None else len(prev)
        if idx is None:
            self._repeat_count = prev_n
        elif prev is None:
            self._repeat_count = len(idx)
        else:
            self._repeat_count = int(np.intersect1d(
                idx, prev, assume_unique=False).size)
        self._bitmap_bits = prev_n
        self._prev_support_idx = (None if idx is None
                                  else np.asarray(idx, np.int64))


def drain_rounds(model, pending, process, force):
    """Trainer-side pipeline drain: pop ``model.flush()`` results in
    dispatch order, pairing each with its queued dispatch-time context
    tuple from ``pending``. Returns False as soon as ``process`` does
    (divergence abort)."""
    for metrics in model.flush(force=force):
        if not process(metrics, *pending.pop(0)):
            return False
    return True


class FedOptimizer:
    """Server-side optimizer (reference FedOptimizer,
    fed_aggregator.py:385-463). ``param_groups`` is torch-shaped so LR
    schedulers port unchanged; per-group LRs become a concatenated LR
    vector (fed_aggregator.py:413-429) via each group's ``size``."""

    def __init__(self, param_groups=None, args: Config = None,
                 model: Optional[FedModel] = None):
        self.model = model or _CURRENT_MODEL
        assert self.model is not None, "construct FedModel first"
        self.args = args or self.model.args
        if param_groups is None:
            param_groups = [{"lr": 1.0}]
        if isinstance(param_groups, dict):
            param_groups = [param_groups]
        self.param_groups = param_groups
        # index-based groups: one device-resident indicator vector per
        # group, built once — get_lr then only ships scalars per step
        self._lr_indicators = None
        if len(param_groups) > 1 and \
                all("index" in g for g in param_groups):
            inds = []
            for group in param_groups:
                v = np.zeros(self.args.grad_size, np.float32)
                v[group["index"]] = 1.0
                inds.append(jnp.asarray(v))
            self._lr_indicators = inds
        # 2D mesh: server momentum/error buffers are created (and the
        # server round built) model-sharded — per-device server state
        # is 1/M from the first round, never resharded from a
        # replicated allocation. Cx1/1-D meshes keep today's exact
        # replicated construction.
        mesh = self.model.mesh
        sharded = model_axis_size(mesh) > 1
        self._mesh, self._sharded = mesh, sharded
        self.server_state = ServerState.init(
            self.args,
            sharding=(server_state_sharding(mesh,
                                            self.args.transmit_shape)
                      if sharded else None))
        # geometry the live server state was allocated for: a knob
        # move that changes transmit_shape (--autopilot_geometry)
        # re-inits the momentum/error tables at the new shape
        self._server_geom = tuple(self.args.transmit_shape)
        # donate weights + server state: both are replaced by the
        # round's outputs and the stale buffers are never read again —
        # at GPT-2 scale that's ~1 GB of peak HBM saved per step
        self._probes = int(getattr(self.args, "probe_period", 0)
                           or 0) > 0
        self._server_round = jax.jit(
            build_server_round(self.args, probes=self._probes,
                               mesh=mesh if sharded else None),
            donate_argnums=(0, 1))
        # legacy --do_dp server-mode noise stream: the seed+1 root key
        # comes from privacy/ (the one module allowed raw jax.random
        # noise — analysis/lint.py noise-confinement)
        self._noise_rng = noise_stream(self.args.seed + 1)
        self._step_count = 0

    def get_lr(self):
        if len(self.param_groups) == 1:
            return self.param_groups[0]["lr"]
        if self._lr_indicators is not None:
            # index-based groups (param_group_indices): per-coordinate
            # LRs aligned with the flat vector regardless of how the
            # group members interleave in parameter order
            return sum(float(g["lr"]) * ind for g, ind in
                       zip(self.param_groups, self._lr_indicators))
        lr_vec = []
        for group in self.param_groups:
            assert "size" in group, \
                "multi-group LR needs per-group 'index' or 'size'"
            lr_vec.append(np.full(group["size"], group["lr"],
                                  np.float32))
        return jnp.asarray(np.concatenate(lr_vec))

    def step(self):
        m = self.model
        assert m.pending_aggregated is not None, \
            "call model(batch) before opt.step()"
        lr = self.get_lr()
        # group scalars, so this also covers the vector-LR path
        if all(float(g["lr"]) == 0 for g in self.param_groups):
            print("WARNING: LR is 0")
        if self.args.mode == "fedavg":
            assert np.ndim(lr) == 0, "fedavg supports scalar lr only"
            m.fedavg_lr = float(lr)
            # NB: fedavg also takes the bitmap value-compare below —
            # its round-0 update is all-zero (clients ran at the
            # initial g_lr of 0), and the reference's
            # weight_update != 0 compare charges nothing for it

        self._step_count += 1
        noise_rng = jax.random.fold_in(self._noise_rng,
                                       self._step_count)
        server_fn, svar = self._server_round, None
        if getattr(m, "_autopilot", None) is not None:
            # the aggregate pending on the model was emitted by a
            # specific round variant — its server program (the wire
            # dequant and unsketch geometry are trace-time constants)
            # must match. Variants hold their own jitted server round;
            # the static self._server_round is never dispatched, so it
            # never compiles.
            svar = m._variants.get(m.pending_variant_key)
            if svar.server_fn is None:
                svar.server_fn = jax.jit(
                    build_server_round(svar.cfg, probes=self._probes,
                                       mesh=(self._mesh if self._sharded
                                             else None)),
                    donate_argnums=(0, 1))
            geom = tuple(svar.cfg.transmit_shape)
            if geom != self._server_geom:
                # geometry move: the sketch-shaped server tables are
                # re-seeded at the new shape (momentum restarts — the
                # controller's geometry steps are opt-in for exactly
                # this reason)
                self.server_state = ServerState.init(
                    svar.cfg,
                    sharding=(server_state_sharding(self._mesh, geom)
                              if self._sharded else None))
                self._server_geom = geom
            server_fn = svar.server_fn
        sfirst = svar is not None and "server" not in svar.compiled
        cmark = compile_mark() if sfirst else None
        # round ridx's ledger record is still current (the next
        # _call_train's begin_round closes it), so the server span
        # lands on the round whose aggregate it consumes
        with m.telemetry.span("server"), trace.phase("server"):
            out = server_fn(
                m.ps_weights, self.server_state,
                m.pending_aggregated,
                jnp.asarray(lr, jnp.float32),
                m.client_states.velocities, m.pending_client_ids,
                noise_rng)
        if cmark is not None:
            svar.compiled.add("server")
            m._stamp_vcompile(svar.key, cmark)
        sprobes = None
        if self._probes:
            new_ps, self.server_state, new_vel, update, support, \
                sprobes = out
        else:
            new_ps, self.server_state, new_vel, update, support = out
        m.ps_weights = new_ps
        if new_vel is not None:
            m.client_states = m.client_states._replace(
                velocities=new_vel)
        m.pending_aggregated = None
        # host client store: the round's participant rows (incl. any
        # server-side velocity rewrite above) go back to the host now
        m._store_writeback()
        if m._prefetch_after_writeback:
            # --overlap_depth > 1: the gather staged here sees the
            # post-write-back row versions, so next round's take() is
            # patch-free while the worker thread hides the gather
            # under the delta-encode host work below
            m._prefetch_after_writeback = False
            m._submit_prefetch()
        if support is None:
            # dense-update modes. fedavg/momentum updates touch every
            # coordinate; the exceptions that don't: a zero scalar LR
            # (nothing moved) and local_topk (even with virtual
            # momentum the update's support is only the union of past
            # top-k selections, ~W*k coords early on — the reference
            # value-compares weight_update != 0, so marking all
            # grad_size coords would overcount download bytes)
            lr_np = np.asarray(lr)
            if (self.args.mode != "fedavg" and lr_np.ndim == 0
                    and float(lr_np) == 0):
                support = (np.zeros(0, np.int64), np.zeros(0))
            elif self.args.mode in ("local_topk", "fedavg") \
                    or lr_np.ndim > 0:
                # != 0 compare, packed ON DEVICE: shipping the dense
                # f32 update to the host costs 4*d bytes per round
                # through the (slow) dispatch link — the bitmap is
                # 1/32 of that (measured: the dense transfer dominated
                # local_topk wall time at d=6.6M on the relay)
                support = {"bitmap": jnp.packbits(update != 0)}
        m.note_update(support)
        if sprobes is not None:
            # the round this server pass belongs to (round_index was
            # already advanced by _call_train)
            sridx = m.round_index - 1
            if m.pipeline_depth > 1:
                # stay on device: values cross at flush replay, in
                # round order, together with the client-pass probes
                m._probe_log.setdefault(sridx, {}).update(sprobes)
            else:
                with m.telemetry.span("metrics_host"):
                    svals = {k: float(_host(v))
                             for k, v in sprobes.items()}
                m._finish_probes(sridx, svals)

    def zero_grad(self):
        raise NotImplementedError(
            "functional runtime: there is no gradient to zero")


class LambdaLR:
    """Minimal torch-compatible LR scheduler: lr = base_lr *
    lr_lambda(step) (used as cv_train.py:394-406 uses torch's)."""

    def __init__(self, optimizer: FedOptimizer, lr_lambda,
                 base_lrs=None):
        self.optimizer = optimizer
        self.lr_lambda = lr_lambda
        self.base_lrs = base_lrs or [g["lr"]
                                     for g in optimizer.param_groups]
        self._step = 0

    def step(self):
        for g, base in zip(self.optimizer.param_groups, self.base_lrs):
            g["lr"] = base * self.lr_lambda(self._step)
        self._step += 1
